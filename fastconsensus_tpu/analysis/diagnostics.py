"""Shared diagnostic model for the fcheck static-analysis suite.

Every layer (AST lint, jaxpr audit, recompile guard) reports through one
:class:`Diagnostic` record so the CLI can merge them into a single
machine-readable JSON report plus ``file:line``-style human output.

Suppression: a line carrying ``# fcheck: ok=<rule>[,<rule>...]`` (or the
line directly above it) suppresses those rules there.  ``# fcheck: ok``
with no rule list suppresses everything on that line.  Pragmas are how
deliberate violations stay deliberate — each one should carry a reason in
the trailing comment text, and the JSON report counts them so CI can spot
pragma creep.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, List, Optional, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

_PRAGMA_RE = re.compile(r"#\s*fcheck:\s*ok(?:\s*=\s*([A-Za-z0-9_,\- ]+))?")


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: ``rule`` is a stable kebab-case id, ``line`` 1-based."""

    rule: str
    message: str
    file: str = "<memory>"
    line: int = 0
    col: int = 0
    severity: str = SEVERITY_ERROR

    def format(self) -> str:
        loc = f"{self.file}:{self.line}:{self.col}" if self.line else self.file
        return f"{loc}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def parse_pragmas(source: str) -> Dict[int, Optional[Tuple[str, ...]]]:
    """Map line number -> suppressed rule names (None = all rules).

    A trailing pragma suppresses its own line.  A comment-only pragma
    line suppresses the next *code* line (further comment/blank lines in
    between stay covered too, so multi-line reason comments work).
    """
    lines = source.splitlines()
    out: Dict[int, Optional[Tuple[str, ...]]] = {}

    def add(ln: int, rules: Optional[Tuple[str, ...]]) -> None:
        if rules is None or out.get(ln, ()) is None:
            out[ln] = None
        else:
            out[ln] = tuple(out.get(ln, ())) + rules

    for i, text in enumerate(lines, start=1):
        m = _PRAGMA_RE.search(text)
        if not m:
            continue
        rules: Optional[Tuple[str, ...]] = None
        if m.group(1):
            rules = tuple(r.strip() for r in m.group(1).split(",")
                          if r.strip())
        add(i, rules)
        if text.strip().startswith("#"):
            # standalone pragma comment: cover through the next code line
            j = i + 1
            while j <= len(lines):
                add(j, rules)
                stripped = lines[j - 1].strip()
                if stripped and not stripped.startswith("#"):
                    break
                j += 1
    return out


def apply_pragmas(diags: List[Diagnostic], source: str
                  ) -> Tuple[List[Diagnostic], int]:
    """Drop suppressed diagnostics; returns (kept, n_suppressed)."""
    pragmas = parse_pragmas(source)
    kept: List[Diagnostic] = []
    suppressed = 0
    for d in diags:
        rules = pragmas.get(d.line, ())
        if rules is None or (rules and d.rule in rules):
            suppressed += 1
        else:
            kept.append(d)
    return kept, suppressed


@dataclasses.dataclass
class Report:
    """Aggregated result of one analyzer invocation."""

    diagnostics: List[Diagnostic] = dataclasses.field(default_factory=list)
    n_files: int = 0
    n_suppressed: int = 0
    # per-entry-point jaxpr audit summaries (entrypoint -> primitive
    # counts, plus the liveness-sweep "peak_bytes" entry)
    jaxpr_summary: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    # the footprint block (analysis/footprint.py module docstring
    # documents the schema); None when the footprint pass did not run
    footprint: Optional[dict] = None
    # the compute-cost block (analysis/cost.py module docstring
    # documents the schema); None when the cost pass did not run
    cost: Optional[dict] = None

    def extend(self, diags: List[Diagnostic]) -> None:
        self.diagnostics.extend(diags)

    @property
    def n_errors(self) -> int:
        return sum(1 for d in self.diagnostics
                   if d.severity == SEVERITY_ERROR)

    def to_json(self) -> str:
        return json.dumps({
            "tool": "fcheck",
            "version": 1,
            "n_files": self.n_files,
            "n_diagnostics": len(self.diagnostics),
            "n_errors": self.n_errors,
            "n_suppressed": self.n_suppressed,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "jaxpr_entry_points": self.jaxpr_summary,
            "footprint": self.footprint,
            "cost": self.cost,
        }, indent=2, sort_keys=True)

    def format_human(self) -> str:
        lines = [d.format() for d in sorted(
            self.diagnostics, key=lambda d: (d.file, d.line, d.col))]
        lines.append(
            f"fcheck: {len(self.diagnostics)} finding(s) "
            f"({self.n_errors} error) in {self.n_files} file(s), "
            f"{self.n_suppressed} suppressed by pragma, "
            f"{len(self.jaxpr_summary)} jaxpr entry point(s) audited")
        if self.footprint is not None:
            fp = self.footprint
            ceil = fp.get("chip_ceiling_edges")
            lines.append(
                f"fcheck-footprint: {fp.get('surface_count')} surface "
                f"executable(s) (budget {fp.get('surface_budget')}), "
                f"max pad {fp.get('max_pad_frac'):.0%}, "
                f"chip ceiling "
                f"{ceil if ceil is not None else 'n/a'} edges")
        if self.cost is not None:
            dc = self.cost.get("dead_compute") or {}
            cal = self.cost.get("calibration") or {}
            lines.append(
                f"fcheck-cost: dead-compute "
                f"{dc.get('run_dead_frac', 0.0):.0%} of run FLOPs at "
                f"{dc.get('bucket', 'n/a')} "
                f"(budget {dc.get('waste_budget', 0.0):.0%}), "
                f"{len(self.cost.get('gate') or [])} gate row(s), "
                f"calibration "
                f"{cal.get('est_device_ms', 'n/a')} ms device est")
        return "\n".join(lines)
