"""fcheck-contract: whole-program name-contract & wire-schema pass.

Thirteen PRs in, a growing share of the system's correctness lives in
*string contracts* nobody checks: 50+ fcobs counter/gauge/series write
sites across the serve modules, typed jax-free client dataclasses
(serve/client.py) parsing hand-rolled ``/metricsz``/``/healthz``/
``/status`` JSON, and CI gates (obs/history.py check_* rules,
scripts/ci_check.sh greps, ``bench_report --check``) that read metric
names as literals.  A misspelled counter, a gate reading a key nobody
writes, or a client field the server stopped emitting all fail
*silently* — the gate goes vacuously green, the field quietly reads
``None``.  This pass makes those contracts static, in the fcheck
tradition (PR 1 lint -> PR 7 concurrency -> PR 8 footprint):

**Writer inventory** — AST constant propagation over every
``.inc(``/``.gauge(``/``.observe(``/``.mark(``/``.hist(`` tag, every
``CompileGuard(..., counter=...)`` kwarg, and every
``flight.record(<kind>)`` event name in the package.  f-strings,
``+``-joins, loop variables over literal tuples, module/param string
constants and string ``IfExp``\\ s resolve into bounded *templates*
(``serve.device.{i}.jobs`` -> ``serve.device.*.jobs``); an
unresolvable fragment becomes a wildcard segment.  Dict-literal keys,
``dict(k=...)`` kwargs and ``out["k"] = ...`` subscript stores across
the package (plus the repo-root ``bench.py`` telemetry writer) form
the *wire-key universe* — every JSON field any endpoint can emit.

**Reader inventory** — the names consumed by obs/history.py gates and
tables, scripts/bench_report.py, the grep/jq/heredoc literals in
scripts/ci_check.sh (a small shell lexer; ``<<'TAG'`` heredocs are
re-parsed as Python), the typed-client ``.get(``/``["k"]`` lookups in
serve/client.py, and the README counter and rule tables.

**Rules** (all in the ``--only``/pragma vocabulary; suppress a
deliberate violation with ``# fcheck: ok=<rule> -- reason``, or
``<!-- # fcheck: ok=doc-drift -- reason -->`` in markdown):

- ``phantom-reader`` — a gate/CI read names a metric no writer
  produces, or a payload key nothing emits (the stale-gate bug class:
  the gate can never fire).
- ``schema-drift`` — a typed-client key with no matching server
  emitter, or server keys a matched client parser silently drops.
- ``dead-counter`` — a metric written but never read by any gate,
  client, CI probe or package consumer, nor documented in the README
  counters reference.
- ``event-vocab`` — a ``flight.record(...)`` kind missing from
  obs/flight.py ``EVENT_KINDS``, or a vocabulary entry no site records
  (the postmortem renderer and ``merge_events(kinds=...)`` filters
  trust that vocabulary).
- ``doc-drift`` — README rule table missing a rule id, the
  auto-generated "Counters & series reference" appendix out of sync
  with the writer inventory, or prose referencing a counter that does
  not exist.

**Modes** — the pass is whole-program: it runs in *repo mode* when the
scanned source set contains the package's serving + obs surface (the
sentinel modules below), and in *fixture mode* over any scanned file
declaring a module-level ``CONTRACT_SPEC`` literal (the analysis
fixtures).  Partial scans (a single file under pre-commit) skip it —
a lone module would make every cross-module name look phantom.

**Runtime cross-check** — :func:`assert_covered` takes a live
``/metricsz`` snapshot and the committed inventory artifact
(``runs/contract_r19.json``, written by ``--emit-inventory``) and
asserts every observed name unions cleanly with the static writer
templates; scripts/ci_check.sh runs it inside the loopback serve
smoke, closing the static-model-vs-reality loop the same way the
lockorder recorder audits the static lock graph.

Everything here is stdlib-only: the pass must run with jax absent or
wedged (the pre-commit hook and ``bench_report --check`` both load it
jax-free).
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from fastconsensus_tpu.analysis.diagnostics import Diagnostic, apply_pragmas

CONTRACT_RULES = {
    "phantom-reader": "gate/CI reads a name no writer produces",
    "schema-drift": "typed client vs server wire schema mismatch",
    "dead-counter": "metric written but never read nor documented",
    "event-vocab": "flight event kinds vs EVENT_KINDS vocabulary",
    "doc-drift": "README rule/counter tables vs the inventory",
}

INVENTORY_TOOL = "fcheck-contract"
INVENTORY_VERSION = 1

# the scanned set must contain this serving + obs surface for the
# whole-program rules to be meaningful (repo mode)
_SENTINELS = ("serve/server.py", "serve/client.py", "obs/counters.py",
              "obs/history.py", "obs/flight.py")

# README markers around the auto-generated counters appendix
APPENDIX_BEGIN = "<!-- fcheck-contract: counters begin -->"
APPENDIX_END = "<!-- fcheck-contract: counters end -->"

# wildcard placeholder while resolving; rendered as "*" in templates
_WILD = "\x00"
_MAX_EXPAND = 16

_METHOD_KINDS = {"inc": "counter", "gauge": "gauge", "observe": "series",
                 "hist": "hist", "mark": "rate"}

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_*.]*$")
_PLAIN_KEY_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
_DOTTED_RE = re.compile(r"\b[a-z][a-z0-9_]*(?:\.[a-z0-9_*]+)+\b")
# file-ish suffixes the shell/README scanners must not mistake for
# metric names
_FILE_SUFFIXES = (".py", ".sh", ".json", ".jsonl", ".md", ".txt",
                  ".yaml", ".yml", ".log", ".toml", ".cfg", ".ini",
                  ".npz", ".out", ".pid", ".csv", ".tmp")
# README backtick tokens whose first segment names a module/tool, not
# a metric
_MODULE_PREFIXES = {"fastconsensus_tpu", "np", "jax", "os", "sys",
                    "ast", "json", "scripts", "tests", "analysis",
                    "jnp", "self", "args", "pytest"}


# ---------------------------------------------------------------------------
# constant propagation: resolve a string expression to a bounded set of
# template strings (wildcard placeholder for unresolvable fragments)
# ---------------------------------------------------------------------------

def _module_env(tree: ast.AST) -> Dict[str, Set[str]]:
    """Module-level ``NAME = "str"`` / ``NAME = ("a", "b")`` constants.
    Nested literal collections flatten (``PHASE_STAMPS``-style
    vocabulary tuples): every string inside counts as a candidate."""
    env: Dict[str, Set[str]] = {}
    for node in ast.iter_child_nodes(tree):
        targets: List[ast.expr] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None:
            continue
        vals = _literal_strings(value) or _flatten_strings(value)
        if not vals:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                env.setdefault(t.id, set()).update(vals)
    return env


def _flatten_strings(node: ast.expr) -> Set[str]:
    """Every string constant inside a (possibly nested) tuple/list
    literal — the shape of the package's name-vocabulary declarations
    (``PHASE_STAMPS``: tuples of (phase, stamp) pairs)."""
    out: Set[str] = set()
    if isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and \
                    isinstance(elt.value, str):
                out.add(elt.value)
            else:
                out |= _flatten_strings(elt)
    return out


def _literal_strings(node: ast.expr) -> Optional[Set[str]]:
    """A string constant or tuple/list of string constants, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            out.add(elt.value)
        return out if out else None
    return None


def _function_env(fn: ast.AST, module_env: Dict[str, Set[str]]
                  ) -> Dict[str, Set[str]]:
    """Flow-insensitive string bindings visible inside ``fn``: module
    constants, string parameter defaults, ``for x in ("a", "b")`` loop
    variables (including tuples named by a module constant), and simple
    local string assignments — enough to resolve every metric-name
    f-string the serve stack actually writes."""
    env = dict(module_env)
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        args = fn.args
        pos = args.posonlyargs + args.args
        for arg, default in zip(pos[len(pos) - len(args.defaults):],
                                args.defaults):
            vals = _literal_strings(default)
            if vals:
                env.setdefault(arg.arg, set()).update(vals)
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None:
                vals = _literal_strings(default)
                if vals:
                    env.setdefault(arg.arg, set()).update(vals)
    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.comprehension)):
            target = node.target
            it = node.iter
            vals = _literal_strings(it)
            if vals is None and isinstance(it, ast.Name):
                vals = module_env.get(it.id)
            if vals and isinstance(target, ast.Name):
                env.setdefault(target.id, set()).update(vals)
        elif isinstance(node, ast.Assign):
            vals = _resolve(node.value, env)
            if vals:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        env.setdefault(t.id, set()).update(vals)
    return env


def _resolve(node: ast.expr, env: Dict[str, Set[str]]
             ) -> Optional[Set[str]]:
    """Resolve a string expression to a bounded set of candidate
    strings (``_WILD`` marks unresolvable fragments); None when the
    node is not string-like at all (e.g. a float passed to
    ``LatencyHistogram.record``)."""
    if isinstance(node, ast.Constant):
        return {node.value} if isinstance(node.value, str) else None
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.IfExp):
        body = _resolve(node.body, env) or {_WILD}
        orelse = _resolve(node.orelse, env) or {_WILD}
        return _cap(body | orelse)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve(node.left, env)
        right = _resolve(node.right, env)
        if left is None and right is None:
            return None
        return _cap({a + b for a in (left or {_WILD})
                     for b in (right or {_WILD})})
    if isinstance(node, ast.JoinedStr):
        combos: Set[str] = {""}
        for part in node.values:
            if isinstance(part, ast.Constant):
                vals = {str(part.value)}
            elif isinstance(part, ast.FormattedValue):
                vals = _resolve(part.value, env) or {_WILD}
            else:
                vals = {_WILD}
            combos = _cap({c + v for c in combos for v in vals})
        return combos
    return None


def _cap(vals: Set[str]) -> Set[str]:
    """Bound template expansion: past the cap, collapse to one
    all-wildcard candidate rather than enumerate."""
    return vals if len(vals) <= _MAX_EXPAND else {_WILD}


def _to_templates(vals: Iterable[str]) -> Set[str]:
    """Candidate strings -> dotted templates with ``*`` wildcard
    (sub)segments.  Candidates whose *first* segment is not literal are
    dropped: a leading wildcard would match everything and silently
    satisfy any reader."""
    out: Set[str] = set()
    for v in vals:
        segs = []
        for seg in v.split("."):
            seg = re.sub(r"\x00+", "*", seg)
            segs.append(seg)
        if not segs or "*" in segs[0] or not segs[0]:
            continue
        tpl = ".".join(segs)
        if _NAME_RE.match(tpl.replace("*", "x")):
            out.add(tpl)
    return out


def _seg_match(a: str, b: str) -> bool:
    from fnmatch import fnmatchcase

    if "*" in a and "*" not in b:
        return fnmatchcase(b, a)
    if "*" in b and "*" not in a:
        return fnmatchcase(a, b)
    if "*" in a and "*" in b:
        return True
    return a == b


def template_matches(template: str, name: str) -> bool:
    """Does a writer template cover a (possibly templated) read name?
    Segment-wise; ``*`` matches within its own segment only."""
    ta, tb = template.split("."), name.split(".")
    if len(ta) != len(tb):
        return False
    return all(_seg_match(a, b) for a, b in zip(ta, tb))


def _covered(name: str, templates: Iterable[str]) -> bool:
    return any(template_matches(t, name) for t in templates)


# ---------------------------------------------------------------------------
# extraction: writers (metrics / events / wire keys) and readers
# ---------------------------------------------------------------------------

class ModuleFacts:
    """Everything one Python module contributes to the contract."""

    def __init__(self, path: str):
        self.path = path
        # template -> {"kind": str, "lines": [int]}
        self.metrics: Dict[str, Dict[str, Any]] = {}
        self.events: List[Tuple[str, int]] = []        # (kind, line)
        self.wire_keys: Dict[str, int] = {}            # key -> first line
        # dict-literal emit groups for the reverse schema check
        self.emit_groups: List[Tuple[int, Set[str]]] = []
        self.reads: List[Tuple[str, int]] = []         # resolved names
        # classname -> (line, read keys) for ``from_payload`` parsers
        self.parsers: Dict[str, Tuple[int, Set[str]]] = {}
        self.event_kinds: Optional[Tuple[Sequence[str], int]] = None
        self.spec: Optional[Tuple[dict, int]] = None

    def add_metric(self, tpl: str, kind: str, line: int) -> None:
        slot = self.metrics.setdefault(tpl, {"kind": kind, "lines": []})
        slot["lines"].append(line)


def _scan_module(path: str, src: str) -> Optional[ModuleFacts]:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:
        return None  # astlint owns the syntax-error diagnostic
    facts = ModuleFacts(path)
    module_env = _module_env(tree)

    for node in ast.iter_child_nodes(tree):
        # a module-level vocabulary tuple (PHASE_STAMPS, SLO_CLASSES,
        # _SL_PHASES...) *declares* the plain keys its consumers build
        # dicts from — that declaration is the wire contract
        if isinstance(node, (ast.Assign, ast.AnnAssign)) and \
                getattr(node, "value", None) is not None:
            for s in _flatten_strings(node.value):
                if _PLAIN_KEY_RE.match(s):
                    facts.wire_keys.setdefault(s, node.lineno)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if name == "CONTRACT_SPEC":
                try:
                    spec = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    raise ValueError(
                        f"{path}:{node.lineno}: CONTRACT_SPEC must be a "
                        f"literal dict")
                if not isinstance(spec, dict):
                    raise ValueError(
                        f"{path}:{node.lineno}: CONTRACT_SPEC must be a "
                        f"dict, got {type(spec).__name__}")
                facts.spec = (spec, node.lineno)
            elif name == "EVENT_KINDS":
                vals = _literal_strings(node.value)
                if vals:
                    facts.event_kinds = (sorted(vals), node.lineno)

    # function-scoped envs: map every node to its enclosing function so
    # call-site resolution sees loop vars / param defaults / locals
    envs: Dict[int, Dict[str, Set[str]]] = {}
    owner: Dict[int, int] = {}

    def assign_owner(fn: ast.AST, fid: int) -> None:
        for sub in ast.walk(fn):
            owner.setdefault(id(sub), fid)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            envs[id(node)] = _function_env(node, module_env)
            assign_owner(node, id(node))

    def env_for(node: ast.AST) -> Dict[str, Set[str]]:
        return envs.get(owner.get(id(node), -1), module_env)

    current_class: List[Tuple[ast.ClassDef, bool]] = []

    def walk(node: ast.AST) -> None:
        if isinstance(node, ast.ClassDef):
            has_parser = any(
                isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef))
                and b.name == "from_payload" for b in node.body)
            if has_parser:
                keys: Set[str] = set()
                for b in node.body:
                    if isinstance(b, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) and \
                            b.name == "from_payload":
                        keys |= _parser_keys(b, env_for(b) or module_env)
                facts.parsers[node.name] = (node.lineno, keys)
        if isinstance(node, ast.Dict):
            keys = set()
            for k in node.keys:
                if k is None:
                    continue  # **spread
                if isinstance(k, ast.Constant):
                    kvals = {k.value} if isinstance(k.value, str) \
                        else set()
                else:
                    kvals = _resolve(k, env_for(node)) or set()
                for kv in kvals:
                    if _PLAIN_KEY_RE.match(kv):
                        keys.add(kv)
                        facts.wire_keys.setdefault(kv, node.lineno)
            if len(keys) >= 3:
                facts.emit_groups.append((node.lineno, keys))
        if isinstance(node, ast.DictComp):
            kvals = _resolve(node.key, env_for(node)) or set()
            for kv in kvals:
                if _PLAIN_KEY_RE.match(kv):
                    facts.wire_keys.setdefault(kv, node.lineno)
        if isinstance(node, ast.Call):
            env = env_for(node)
            func = node.func
            if isinstance(func, ast.Name) and func.id == "dict" and \
                    node.keywords:
                keys = {kw.arg for kw in node.keywords if kw.arg}
                for k in keys:
                    facts.wire_keys.setdefault(k, node.lineno)
                if len(keys) >= 3:
                    facts.emit_groups.append((node.lineno, keys))
            if isinstance(func, ast.Attribute):
                attr = func.attr
                if attr in _METHOD_KINDS and node.args:
                    vals = _resolve(node.args[0], env)
                    if vals:
                        for tpl in _to_templates(vals):
                            facts.add_metric(tpl, _METHOD_KINDS[attr],
                                             node.lineno)
                elif attr == "record" and node.args:
                    vals = _resolve(node.args[0], env)
                    if vals and all(
                            re.match(r"^[a-z][a-z0-9_]*$", v)
                            for v in vals):
                        for v in sorted(vals):
                            facts.events.append((v, node.lineno))
                elif attr in ("get", "pop") and node.args:
                    vals = _resolve(node.args[0], env)
                    if vals:
                        for tpl in _to_templates(vals):
                            facts.reads.append((tpl, node.lineno))
                elif attr == "setdefault" and node.args:
                    vals = _resolve(node.args[0], env)
                    if vals:
                        for v in vals:
                            if _PLAIN_KEY_RE.match(v):
                                facts.wire_keys.setdefault(v,
                                                           node.lineno)
            for kw in node.keywords:
                if kw.arg == "counter":
                    vals = _resolve(kw.value, env)
                    if vals:
                        for tpl in _to_templates(vals):
                            facts.add_metric(tpl, "counter", node.lineno)
        if isinstance(node, ast.Subscript):
            if isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                key = node.slice.value
                if isinstance(node.ctx, ast.Load):
                    if _NAME_RE.match(key):
                        facts.reads.append((key, node.lineno))
                elif _PLAIN_KEY_RE.match(key):
                    # Store / Del: a wire field the module emits
                    facts.wire_keys.setdefault(key, node.lineno)
            elif isinstance(node.ctx, ast.Store):
                # out[name] = ... with a resolvable loop/local name
                for kv in _resolve(node.slice, env_for(node)) or ():
                    if _PLAIN_KEY_RE.match(kv):
                        facts.wire_keys.setdefault(kv, node.lineno)
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(tree)
    return facts


def _parser_keys(fn: ast.AST, env: Dict[str, Set[str]]) -> Set[str]:
    """Keys a ``from_payload`` classmethod consumes: subscript loads,
    ``.get(``/``.pop(`` first args, and string args handed to local
    helper closures (the ``_opt("field")`` idiom)."""
    local_helpers = {b.name for b in ast.walk(fn)
                     if isinstance(b, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                     and b is not fn}
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript) and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str) and \
                isinstance(node.ctx, ast.Load):
            keys.add(node.slice.value)
        elif isinstance(node, ast.Call):
            func = node.func
            is_get = isinstance(func, ast.Attribute) and \
                func.attr in ("get", "pop")
            is_helper = isinstance(func, ast.Name) and \
                func.id in local_helpers
            if (is_get or is_helper) and node.args:
                arg0 = node.args[0]
                if isinstance(arg0, ast.Constant) and \
                        isinstance(arg0.value, str):
                    keys.add(arg0.value)
    return {k for k in keys if _NAME_RE.match(k)}


# ---------------------------------------------------------------------------
# external readers: shell (ci_check.sh) and markdown (README.md)
# ---------------------------------------------------------------------------

def _scan_shell(src: str) -> List[Tuple[str, int]]:
    """A small shell lexer for scripts/ci_check.sh: ``<<'TAG'``
    heredoc bodies are re-parsed as Python (so ``counters.get("x.y")``
    resolves exactly like package code); everything else contributes
    the dotted literals inside its quoted strings (grep/jq patterns).
    Returns (name, line) reads."""
    reads: List[Tuple[str, int]] = []
    lines = src.splitlines()
    heredoc = re.compile(r"<<-?\s*'?([A-Za-z_][A-Za-z0-9_]*)'?")
    i = 0
    while i < len(lines):
        line = lines[i]
        m = heredoc.search(line)
        if m:
            tag = m.group(1)
            body: List[str] = []
            j = i + 1
            while j < len(lines) and lines[j].strip() != tag:
                body.append(lines[j])
                j += 1
            text = "\n".join(body)
            parsed = None
            try:
                parsed = ast.parse(text)
            except SyntaxError:
                parsed = None
            if parsed is not None:
                for name, ln in _python_reads(parsed,
                                              _module_env(parsed)):
                    reads.append((name, i + 1 + ln))
            else:
                for k, body_line in enumerate(body):
                    for name in _shell_line_names(body_line):
                        reads.append((name, i + 2 + k))
            i = j + 1
            continue
        for name in _shell_line_names(line):
            reads.append((name, i + 1))
        i += 1
    return reads


def _shell_line_names(line: str) -> List[str]:
    # strip an unquoted trailing comment so pragma reasons and prose
    # never read as probes
    depth = {"'": False, '"': False}
    for pos, ch in enumerate(line):
        if ch in depth and not depth["'" if ch == '"' else '"']:
            depth[ch] = not depth[ch]
        elif ch == "#" and not depth["'"] and not depth['"']:
            line = line[:pos]
            break
    out: List[str] = []
    for quoted in re.findall(r"'([^']*)'|\"([^\"]*)\"", line):
        for frag in quoted:
            if not frag:
                continue
            for tok in _DOTTED_RE.findall(frag.replace("\\", "")):
                if tok.endswith(_FILE_SUFFIXES):
                    continue
                if tok.split(".", 1)[0] in _MODULE_PREFIXES:
                    continue
                out.append(tok)
    return out


def _python_reads(tree: ast.AST, module_env: Dict[str, Set[str]]
                  ) -> List[Tuple[str, int]]:
    """Dotted/plain key reads from parsed Python (heredocs and the
    gate scripts): ``.get(``/``.pop(`` first args and subscript loads,
    resolved through the same constant propagation as package code."""
    envs: Dict[int, Dict[str, Set[str]]] = {}
    owner: Dict[int, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            envs[id(node)] = _function_env(node, module_env)
            for sub in ast.walk(node):
                owner.setdefault(id(sub), id(node))
    reads: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        env = envs.get(owner.get(id(node), -1), module_env)
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("get", "pop") and node.args:
            vals = _resolve(node.args[0], env)
            if vals:
                for tpl in _to_templates(vals):
                    reads.append((tpl, node.lineno))
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str) and \
                isinstance(node.ctx, ast.Load) and \
                _NAME_RE.match(node.slice.value):
            reads.append((node.slice.value, node.lineno))
    return reads


def _scan_readme(src: str) -> Dict[str, Any]:
    """README facts: backticked rule ids, dotted counter references in
    prose (``<i>``/``{name}`` placeholders normalize to wildcards), and
    the auto-generated counters appendix rows between the markers."""
    refs: List[Tuple[str, int]] = []
    appendix: Dict[str, Tuple[str, int]] = {}
    rule_ids: Set[str] = set()
    lines = src.splitlines()
    begin = end = None
    for idx, line in enumerate(lines):
        if APPENDIX_BEGIN in line:
            begin = idx
        elif APPENDIX_END in line:
            end = idx
    row_re = re.compile(r"^\|\s*`([^`]+)`\s*\|\s*([a-z]+)\s*\|")
    for idx, line in enumerate(lines):
        in_appendix = begin is not None and end is not None and \
            begin < idx < end
        if in_appendix:
            m = row_re.match(line.strip())
            if m:
                appendix[m.group(1)] = (m.group(2), idx + 1)
            continue
        for tok in re.findall(r"`([^`]+)`", line):
            if re.match(r"^[a-z][a-z0-9]*(-[a-z0-9]+)+$", tok):
                rule_ids.add(tok)
                continue
            norm = re.sub(r"<[^<>]*>|\{[^{}]*\}", "*", tok)
            if " " in norm or "/" in norm or "(" in norm or \
                    "=" in norm or norm.endswith(_FILE_SUFFIXES):
                continue
            if "." not in norm or not re.match(r"^[a-z]", norm):
                continue
            if not _NAME_RE.match(norm):
                continue
            if norm.split(".", 1)[0] in _MODULE_PREFIXES:
                continue
            refs.append((norm, idx + 1))
    return {"refs": refs, "appendix": appendix, "rule_ids": rule_ids,
            "has_appendix": begin is not None and end is not None,
            "appendix_line": (begin + 1) if begin is not None else 1}


# ---------------------------------------------------------------------------
# the contract universe and the five rules
# ---------------------------------------------------------------------------

class Universe:
    """One resolved contract universe (repo-wide or one fixture)."""

    def __init__(self) -> None:
        self.metrics: Dict[str, Dict[str, Any]] = {}
        self.wire_keys: Dict[str, str] = {}      # key -> "file:line"
        self.events: List[Tuple[str, str, int]] = []
        self.emit_groups: List[Tuple[str, int, Set[str]]] = []
        self.pkg_reads: List[Tuple[str, str, int]] = []
        self.gate_reads: List[Tuple[str, str, int]] = []
        self.client_reads: List[Tuple[str, str, int]] = []
        self.parsers: Dict[str, Tuple[str, int, Set[str]]] = {}
        self.event_kinds: Optional[Tuple[Sequence[str], str, int]] = None
        self.readme: Optional[Dict[str, Any]] = None
        self.readme_path: str = "README.md"
        self.rule_universe: Optional[Set[str]] = None
        # fixture mode: emitter dicts and parsers share one file, so
        # the reverse schema check must not skip same-file groups (in
        # repo mode it must, or client.py's own payload dicts would
        # anchor against its parsers)
        self.same_file_groups_ok = False

    # -- assembly -----------------------------------------------------

    def add_metric_writers(self, facts: ModuleFacts) -> None:
        for tpl, info in facts.metrics.items():
            slot = self.metrics.setdefault(
                tpl, {"kind": info["kind"], "writers": []})
            for ln in info["lines"]:
                slot["writers"].append(f"{facts.path}:{ln}")

    def add_writer_facts(self, facts: ModuleFacts) -> None:
        self.add_metric_writers(facts)
        for key, ln in facts.wire_keys.items():
            self.wire_keys.setdefault(key, f"{facts.path}:{ln}")
        for kind, ln in facts.events:
            self.events.append((kind, facts.path, ln))
        for ln, keys in facts.emit_groups:
            self.emit_groups.append((facts.path, ln, keys))
        if facts.event_kinds and self.event_kinds is None:
            kinds, ln = facts.event_kinds
            self.event_kinds = (kinds, facts.path, ln)

    def add_reads(self, facts: ModuleFacts, role: str) -> None:
        dest = {"pkg": self.pkg_reads, "gate": self.gate_reads,
                "client": self.client_reads}[role]
        for name, ln in facts.reads:
            dest.append((name, facts.path, ln))
        if role == "client":
            for cls, (ln, keys) in facts.parsers.items():
                self.parsers[cls] = (facts.path, ln, keys)
                for k in keys:
                    dest.append((k, facts.path, ln))

    # -- rule helpers -------------------------------------------------

    def metric_templates(self) -> List[str]:
        return sorted(self.metrics)

    def name_known(self, name: str) -> bool:
        """Is a read name satisfied by any writer?  Dotted names match
        the metric templates; plain names match the wire-key universe
        (or a dotless metric, e.g. the rate-tracker tags)."""
        if "." in name:
            return _covered(name, self.metrics)
        return name in self.wire_keys or _covered(name, self.metrics)


def _check_universe(uni: Universe, rules: Set[str]) -> List[Diagnostic]:
    diags: List[Diagnostic] = []

    def add(rule: str, msg: str, file: str, line: int) -> None:
        diags.append(Diagnostic(rule=rule, message=msg, file=file,
                                line=line, col=0, severity="error"))

    # ---- phantom-reader: gate/CI reads with no producer -------------
    if "phantom-reader" in rules:
        for name, path, line in uni.gate_reads:
            if not uni.name_known(name):
                kind = "metric" if "." in name else "key"
                add("phantom-reader",
                    f"reads {kind} '{name}' that no writer produces — "
                    f"this gate/probe can never fire; fix the name or "
                    f"add the writer", path, line)

    # ---- schema-drift: typed client vs server wire schema -----------
    if "schema-drift" in rules:
        for name, path, line in uni.client_reads:
            if not uni.name_known(name):
                add("schema-drift",
                    f"typed client reads '{name}' but no server/emitter "
                    f"writes that key — the field silently parses as "
                    f"missing", path, line)
        for cls, (cpath, cline, reads) in sorted(uni.parsers.items()):
            best: Optional[Tuple[float, int, str, int, Set[str]]] = None
            for gpath, gline, keys in uni.emit_groups:
                if gpath == cpath and not uni.same_file_groups_ok:
                    continue  # the parser's own module
                inter = len(reads & keys)
                if inter < 3 or not reads:
                    continue
                frac = inter / len(reads)
                if frac < 0.6:
                    continue
                if best is None or (frac, inter) > best[:2]:
                    best = (frac, inter, gpath, gline, keys)
            if best is not None:
                dropped = sorted(best[4] - reads)
                if dropped:
                    add("schema-drift",
                        f"emitter dict matches client parser {cls} "
                        f"({cpath.rsplit(os.sep, 1)[-1]}:{cline}) but "
                        f"also emits {', '.join(dropped)} which the "
                        f"client silently drops — consume or remove",
                        best[2], best[3])

    # ---- dead-counter: written, never read nor documented -----------
    if "dead-counter" in rules:
        consumed: List[str] = [n for n, _, _ in uni.pkg_reads]
        consumed += [n for n, _, _ in uni.gate_reads]
        consumed += [n for n, _, _ in uni.client_reads]
        if uni.readme:
            consumed += [n for n, _ in uni.readme["refs"]]
            consumed += list(uni.readme["appendix"])
        dotted_reads = [n for n in consumed if "." in n]
        plain_reads = {n for n in consumed if "." not in n}
        for tpl in sorted(uni.metrics):
            if "." in tpl:
                live = any(template_matches(tpl, r)
                           for r in dotted_reads)
            else:
                live = tpl in plain_reads
            if not live:
                where = uni.metrics[tpl]["writers"][0]
                path, _, line = where.rpartition(":")
                add("dead-counter",
                    f"metric '{tpl}' ({uni.metrics[tpl]['kind']}) is "
                    f"written but never read by any gate, client, CI "
                    f"probe or package consumer, and is not documented "
                    f"— delete it or document it in the counters "
                    f"reference", path, int(line))

    # ---- event-vocab: record() kinds vs EVENT_KINDS -----------------
    if "event-vocab" in rules:
        if uni.event_kinds is None:
            if uni.events:
                kind, path, line = uni.events[0]
                add("event-vocab",
                    "flight events are recorded but no EVENT_KINDS "
                    "vocabulary is declared (obs/flight.py)", path, line)
        else:
            vocab, vpath, vline = uni.event_kinds
            vocab_set = set(vocab)
            recorded = {k for k, _, _ in uni.events}
            for kind, path, line in uni.events:
                if kind not in vocab_set:
                    add("event-vocab",
                        f"flight event '{kind}' is recorded but missing "
                        f"from EVENT_KINDS — postmortem readers and "
                        f"kind filters won't know it", path, line)
            for kind in sorted(vocab_set - recorded):
                add("event-vocab",
                    f"EVENT_KINDS declares '{kind}' but no site records "
                    f"it — stale vocabulary entry", vpath, vline)

    # ---- doc-drift: README tables vs the inventory ------------------
    if "doc-drift" in rules and uni.readme is not None:
        rm = uni.readme
        rpath = uni.readme_path
        if uni.rule_universe:
            for rule in sorted(uni.rule_universe - rm["rule_ids"]):
                add("doc-drift",
                    f"rule id '{rule}' is not documented in the README "
                    f"static-analysis rule table", rpath, 1)
        if not rm["has_appendix"]:
            add("doc-drift",
                "README has no auto-generated counters reference "
                f"(markers '{APPENDIX_BEGIN}' .. '{APPENDIX_END}')",
                rpath, 1)
        else:
            inv_names = set(uni.metrics)
            doc_names = set(rm["appendix"])
            for name in sorted(inv_names - doc_names):
                add("doc-drift",
                    f"counters reference is missing '{name}' — "
                    f"regenerate the appendix (python -m "
                    f"fastconsensus_tpu.analysis --emit-inventory)",
                    rpath, rm["appendix_line"])
            for name in sorted(doc_names - inv_names):
                _, line = rm["appendix"][name]
                add("doc-drift",
                    f"counters reference documents '{name}' but no "
                    f"writer produces it — stale row", rpath, line)
            for name, (kind, line) in sorted(rm["appendix"].items()):
                if name in uni.metrics and \
                        uni.metrics[name]["kind"] != kind:
                    add("doc-drift",
                        f"counters reference lists '{name}' as {kind} "
                        f"but the writer registers a "
                        f"{uni.metrics[name]['kind']}", rpath, line)
        # prose references feed dead-counter liveness only: dotted
        # tokens in running text are as often Python API paths
        # (`obs.latency.render_text`) as counters, so only the
        # *tables* are held to the inventory

    return diags


# ---------------------------------------------------------------------------
# entry points: lint_paths pass, fixture mode, repo mode
# ---------------------------------------------------------------------------

def _find_pkg_root(sources: Dict[str, str]) -> Optional[str]:
    """The fastconsensus_tpu package root, iff the scanned set covers
    the full serving/obs surface (all sentinels present)."""
    norm = {os.path.normpath(os.path.abspath(p)): p for p in sources}
    roots: Set[str] = set()
    for sentinel in _SENTINELS:
        tail = os.path.normpath(os.path.join("fastconsensus_tpu",
                                             sentinel))
        hits = [p for p in norm if p.endswith(os.sep + tail)]
        if not hits:
            return None
        roots.add(hits[0][: -len(os.sep + tail)])
    if len(roots) != 1:
        return None
    return os.path.join(roots.pop(), "fastconsensus_tpu")


def _rule_universe() -> Set[str]:
    from fastconsensus_tpu.analysis.astlint import ASTLINT_RULES
    from fastconsensus_tpu.analysis.concurrency import CONCURRENCY_RULES
    from fastconsensus_tpu.analysis.cost import COST_RULES
    from fastconsensus_tpu.analysis.faults import FAULT_RULES
    from fastconsensus_tpu.analysis.footprint import FOOTPRINT_RULES

    return set(ASTLINT_RULES) | set(CONCURRENCY_RULES) | \
        set(FOOTPRINT_RULES) | set(CONTRACT_RULES) | \
        set(FAULT_RULES) | set(COST_RULES) | {
        "jaxpr-f64", "jaxpr-device-put", "jaxpr-gather-size",
        "trace-error"}


def build_universe(sources: Dict[str, str],
                   pkg_root: str) -> Universe:
    """Assemble the repo-wide contract universe from the scanned
    package sources plus the out-of-package surfaces (bench.py,
    scripts/, README.md) read from disk."""
    repo_root = os.path.dirname(pkg_root)
    uni = Universe()
    uni.rule_universe = _rule_universe()

    client_tail = os.path.normpath(os.path.join("serve", "client.py"))
    history_tail = os.path.normpath(os.path.join("obs", "history.py"))
    pkg_prefix = os.path.normpath(pkg_root) + os.sep
    for path, src in sorted(sources.items()):
        ap = os.path.normpath(os.path.abspath(path))
        if not ap.startswith(pkg_prefix):
            continue  # fixtures or stray files riding the same scan
        facts = _scan_module(path, src)
        if facts is None:
            continue
        if ap.endswith(os.sep + client_tail):
            uni.add_reads(facts, "client")
            # the client also *writes* the request payload the server
            # parses (submit bodies), so its dict keys stay in the
            # wire universe — but its emit groups must not anchor the
            # reverse check against its own parsers
            for key, ln in facts.wire_keys.items():
                uni.wire_keys.setdefault(key, f"{facts.path}:{ln}")
            # ...and its own client-side counters (retry hygiene) are
            # real metrics the appendix must document, without letting
            # client payload dicts into the writer wire universe
            uni.add_metric_writers(facts)
        elif ap.endswith(os.sep + history_tail):
            uni.add_writer_facts(facts)
            uni.add_reads(facts, "gate")
        else:
            uni.add_writer_facts(facts)
            uni.add_reads(facts, "pkg")

    for extra in ("bench.py",):
        path = os.path.join(repo_root, extra)
        if os.path.isfile(path):
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            facts = _scan_module(path, src)
            if facts is not None:
                uni.add_writer_facts(facts)
                uni.add_reads(facts, "pkg")
                sources.setdefault(path, src)

    bench_report = os.path.join(repo_root, "scripts", "bench_report.py")
    if os.path.isfile(bench_report):
        with open(bench_report, encoding="utf-8") as fh:
            src = fh.read()
        facts = _scan_module(bench_report, src)
        if facts is not None:
            uni.add_reads(facts, "gate")
            sources.setdefault(bench_report, src)

    ci_check = os.path.join(repo_root, "scripts", "ci_check.sh")
    if os.path.isfile(ci_check):
        with open(ci_check, encoding="utf-8") as fh:
            src = fh.read()
        seen: Set[Tuple[str, int]] = set()
        for name, line in _scan_shell(src):
            if (name, line) in seen:
                continue
            seen.add((name, line))
            uni.gate_reads.append((name, ci_check, line))
        sources.setdefault(ci_check, src)

    readme = os.path.join(repo_root, "README.md")
    if os.path.isfile(readme):
        with open(readme, encoding="utf-8") as fh:
            src = fh.read()
        uni.readme = _scan_readme(src)
        uni.readme_path = readme
        sources.setdefault(readme, src)

    return uni


def _fixture_universe(path: str, src: str, facts: ModuleFacts
                      ) -> Tuple[Universe, Set[str]]:
    """One fixture file = one self-contained mini-universe.  The
    CONTRACT_SPEC literal supplies what the repo supplies globally:
    which rules to evaluate, README text, the event vocabulary."""
    assert facts.spec is not None
    spec, spec_line = facts.spec
    unknown = set(spec) - {"rules", "readme", "event_kinds"}
    if unknown:
        raise ValueError(
            f"{path}:{spec_line}: unknown CONTRACT_SPEC key(s): "
            f"{', '.join(sorted(unknown))}")
    rules = set(spec.get("rules", CONTRACT_RULES))
    bad = rules - set(CONTRACT_RULES)
    if bad:
        raise ValueError(
            f"{path}:{spec_line}: CONTRACT_SPEC rules {sorted(bad)} "
            f"are not contract rules ({', '.join(sorted(CONTRACT_RULES))})")
    uni = Universe()
    uni.same_file_groups_ok = True
    uni.add_writer_facts(facts)
    uni.add_reads(facts, "gate")
    for cls, (ln, keys) in facts.parsers.items():
        uni.parsers[cls] = (path, ln, keys)
        for k in keys:
            uni.client_reads.append((k, path, ln))
    # a parser's keys are gate reads too in the single-file world;
    # drop the duplicates so each miss fires once, as schema-drift
    parser_keys = {k for _, (_, ks) in facts.parsers.items() for k in ks}
    uni.gate_reads = [(n, p, ln) for n, p, ln in uni.gate_reads
                      if n not in parser_keys]
    if "event_kinds" in spec:
        kinds = spec["event_kinds"]
        if not (isinstance(kinds, (list, tuple))
                and all(isinstance(k, str) for k in kinds)):
            raise ValueError(f"{path}:{spec_line}: CONTRACT_SPEC "
                             f"event_kinds must be a list of strings")
        uni.event_kinds = (list(kinds), path, spec_line)
    if "readme" in spec:
        uni.readme = _scan_readme(str(spec["readme"]))
        uni.readme_path = path
        # fixture doc-drift exercises the counter tables, not the
        # repo's rule-id table
        uni.rule_universe = None
    return uni, rules


def check_contracts(sources: Dict[str, str]
                    ) -> Tuple[List[Diagnostic], int]:
    """The lint_paths pass: fixture mode for every scanned file with a
    ``CONTRACT_SPEC``, repo mode when the scan covers the package's
    serving/obs surface.  Returns (diagnostics, n_suppressed)."""
    diags: List[Diagnostic] = []
    suppressed = 0

    for path, src in sorted(sources.items()):
        if "CONTRACT_SPEC" not in src:
            continue
        facts = _scan_module(path, src)
        if facts is None or facts.spec is None:
            continue
        uni, rules = _fixture_universe(path, src, facts)
        kept, n_sup = apply_pragmas(_check_universe(uni, rules), src)
        diags.extend(kept)
        suppressed += n_sup

    pkg_root = _find_pkg_root(sources)
    if pkg_root is not None:
        # build_universe setdefaults the out-of-package surfaces
        # (bench.py, scripts/, README) into this copy, so pragma
        # application below sees their text too
        all_sources = dict(sources)
        uni = build_universe(all_sources, pkg_root)
        raw = _check_universe(uni, set(CONTRACT_RULES))
        by_file: Dict[str, List[Diagnostic]] = {}
        for d in raw:
            by_file.setdefault(d.file, []).append(d)
        for fpath, fdiags in sorted(by_file.items()):
            src = all_sources.get(fpath)
            if src is None:
                try:
                    with open(fpath, encoding="utf-8") as fh:
                        src = fh.read()
                except OSError:
                    src = ""
            kept, n_sup = apply_pragmas(fdiags, src)
            diags.extend(kept)
            suppressed += n_sup
    return diags, suppressed


# ---------------------------------------------------------------------------
# inventory artifact, runtime cross-check, README appendix
# ---------------------------------------------------------------------------

def build_inventory(sources: Dict[str, str], pkg_root: str) -> dict:
    """The committed artifact (runs/contract_r19.json): writer
    templates, wire keys, event vocabulary and reader sites — the
    static half of the runtime cross-check, and what
    ``bench_report --check`` and the README appendix validate
    against.  Paths are repo-relative so the artifact diffs cleanly."""
    repo_root = os.path.dirname(pkg_root)
    uni = build_universe(dict(sources), pkg_root)

    def rel(path: str) -> str:
        ap = os.path.abspath(path)
        root = os.path.abspath(repo_root) + os.sep
        return ap[len(root):].replace(os.sep, "/") \
            if ap.startswith(root) else path

    metrics = []
    for tpl in sorted(uni.metrics):
        info = uni.metrics[tpl]
        writers = sorted({rel(w.rpartition(":")[0]) + ":" +
                          w.rpartition(":")[2] for w in info["writers"]})
        metrics.append({"name": tpl, "kind": info["kind"],
                        "writers": writers})
    readers = {"gate": sorted({f"{rel(p)}:{ln}:{n}"
                               for n, p, ln in uni.gate_reads}),
               "client": sorted({f"{rel(p)}:{ln}:{n}"
                                 for n, p, ln in uni.client_reads})}
    events = sorted({k for k, _, _ in uni.events})
    vocab = sorted(uni.event_kinds[0]) if uni.event_kinds else []
    return {"tool": INVENTORY_TOOL, "version": INVENTORY_VERSION,
            "rules": sorted(CONTRACT_RULES),
            "metrics": metrics,
            "wire_keys": sorted(uni.wire_keys),
            "events": events,
            "event_vocab": vocab,
            "readers": readers}


def inventory_from_paths(paths: Sequence[str]) -> dict:
    """Walk ``paths`` like lint_paths and build the repo inventory —
    the ``--emit-inventory`` / ``--emit-appendix`` CLI entry."""
    sources: Dict[str, str] = {}
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", "build"))
                for f in sorted(names):
                    if f.endswith(".py"):
                        fp = os.path.join(root, f)
                        with open(fp, encoding="utf-8") as fh:
                            sources[fp] = fh.read()
        elif p.endswith(".py") and os.path.isfile(p):
            with open(p, encoding="utf-8") as fh:
                sources[p] = fh.read()
    pkg_root = _find_pkg_root(sources)
    if pkg_root is None:
        raise ValueError(
            "--emit-inventory needs a scan covering the package's "
            "serving/obs surface (scan fastconsensus_tpu/)")
    return build_inventory(sources, pkg_root)


def load_inventory(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        inv = json.load(fh)
    if inv.get("tool") != INVENTORY_TOOL:
        raise ValueError(f"{path} is not a {INVENTORY_TOOL} inventory "
                         f"(tool={inv.get('tool')!r})")
    return inv


def _observed_names(snapshot: Any) -> List[str]:
    """Metric names out of a live ``/metricsz`` payload (or any fcobs
    registry snapshot), or pass a plain iterable of names through."""
    if isinstance(snapshot, dict):
        fcobs = snapshot.get("fcobs", snapshot)
        names: List[str] = []
        for block in ("counters", "gauges", "series"):
            sub = fcobs.get(block)
            if isinstance(sub, dict):
                names.extend(sub)
        lat = snapshot.get("latency")
        if isinstance(lat, dict):
            for h in lat.get("histograms", ()):
                if isinstance(h, dict) and isinstance(h.get("name"), str):
                    names.append(h["name"])
            # arrivals/dispatches are keyed by *bucket* (n64_e96), a
            # dynamic shape vocabulary, not metric names — skipped
        return names
    return [str(n) for n in snapshot]


def uncovered(snapshot: Any, inventory: Any) -> List[str]:
    """Observed metric names the static writer inventory does not
    cover (inventory = dict or artifact path)."""
    if isinstance(inventory, str):
        inventory = load_inventory(inventory)
    templates = [m["name"] for m in inventory.get("metrics", ())]
    wire = set(inventory.get("wire_keys", ()))
    missing = []
    for name in _observed_names(snapshot):
        if "." in name:
            if not _covered(name, templates):
                missing.append(name)
        elif name not in wire and not _covered(name, templates):
            missing.append(name)
    return sorted(set(missing))


def assert_covered(snapshot: Any, inventory: Any) -> int:
    """Runtime cross-check: every live metric name must union cleanly
    with the static writer inventory.  Returns the number of names
    checked; raises AssertionError naming every stray."""
    names = _observed_names(snapshot)
    missing = uncovered(names, inventory)
    if missing:
        raise AssertionError(
            "live metrics not covered by the static writer inventory "
            f"({len(missing)}): {', '.join(missing)} — a writer the "
            "analyzer cannot see, or a stale runs/contract_r*.json "
            "(regenerate with --emit-inventory)")
    return len(names)


def phantom_reads_for(path: str, inventory: Any
                      ) -> List[Tuple[str, int]]:
    """The ``bench_report --check`` fast-fail: every ``.get(``/``[``
    key the given gate module reads that the inventory knows no writer
    for.  Loads jax-free (pure ast over the file), and honors the same
    ``# fcheck: ok=phantom-reader`` pragmas as the lint pass."""
    if isinstance(inventory, str):
        inventory = load_inventory(inventory)
    templates = [m["name"] for m in inventory.get("metrics", ())]
    wire = set(inventory.get("wire_keys", ()))
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    tree = ast.parse(src, filename=path)
    raw: List[Diagnostic] = []
    for name, line in _python_reads(tree, _module_env(tree)):
        if "." in name:
            ok = _covered(name, templates)
        else:
            ok = name in wire or _covered(name, templates)
        if not ok:
            raw.append(Diagnostic(rule="phantom-reader", message=name,
                                  file=path, line=line, col=0,
                                  severity="error"))
    kept, _ = apply_pragmas(raw, src)
    return sorted({(d.message, d.line) for d in kept})


def render_counters_appendix(inventory: dict) -> str:
    """The README "Counters & series reference" body (between the
    appendix markers), generated from the inventory so doc-drift can
    hold it to the writers."""
    kind_label = {"counter": "counter", "gauge": "gauge",
                  "series": "series", "hist": "histogram",
                  "rate": "rate"}
    lines = ["| name | kind | writers |",
             "|---|---|---|"]
    for m in inventory["metrics"]:
        bases: List[str] = []
        for w in m["writers"]:
            base = w.rsplit(":", 1)[0].rsplit("/", 1)[-1]
            if base not in bases:
                bases.append(base)
        writers = ", ".join(bases[:3])
        if len(bases) > 3:
            writers += f" (+{len(bases) - 3})"
        lines.append(f"| `{m['name']}` | {m['kind']} | {writers} |")
    lines.append("")
    lines.append("Flight-recorder event vocabulary "
                 "(obs/flight.py `EVENT_KINDS`): "
                 + ", ".join(f"`{k}`"
                             for k in inventory.get("event_vocab", ())))
    return "\n".join(lines)
