"""fcheck: the project's static-analysis suite (AST lint + concurrency
pass + jaxpr audit + footprint model + fault flow + name contracts +
runtime guards).

Seven layers, one report (run ``python -m fastconsensus_tpu.analysis``):

1. **AST lint** (analysis/astlint.py) — project-specific source rules:
   PRNG key reuse, Python control flow on traced values, retrace
   hazards, weak static args, float64 drift, host syncs in hot loops,
   Pallas kernels closing over tracers, mesh-axis typos.
2. **Concurrency pass** (analysis/concurrency.py) — whole-program race
   & lock-discipline rules over the multi-threaded serving stack:
   guarded-field, lock-order (cycle = potential deadlock),
   blocking-under-lock, notify-outside-lock, unguarded-root-write.
3. **jaxpr audit** (analysis/jaxpr_audit.py) — traces every registered
   jitted entry point (analysis/entrypoints.py) at canonical shapes and
   walks the staged program for forbidden primitives (f64 casts,
   embedded device_put, ungated huge gathers).
4. **Footprint model** (analysis/footprint.py) — the serving stack's
   compile-time memory and executable-surface model: a donation-aware
   liveness sweep over traced jaxprs prices every executable the bucket
   ladder implies (``jaxpr-peak-bytes`` vs a per-chip budget), the
   enumerated surface is budgeted (``surface-count``), bucket padding
   is budgeted (``padding-waste``), and ``derive_chip_ceiling`` feeds
   the model back into serving (``serve --chip-max-edges auto`` and
   startup ``--warm`` validation).
5. **Fault flow** (analysis/faults.py) — whole-program exception-flow
   & resource-lifecycle rules: per-function raise sets propagated
   through the call table and matched against handler coverage —
   ``escape-thread-root``, ``swallowed-error``,
   ``unmapped-http-error``, ``resource-leak``.  The committed
   injection-site inventory (``--emit-fault-inventory`` ->
   ``runs/faults_r19.json``) feeds the opt-in runtime harness
   (serve/faultinject.py, ``FCTPU_FAULT_INJECT=<site_id>``) that the
   ci_check injection campaign drives against a live pool.
6. **Name contracts** (analysis/contracts.py) — the whole-program
   string-contract pass over the serving/observability surface:
   constant-propagated writer templates for every fcobs
   counter/gauge/series/histogram tag and flight event, the wire-key
   universe every HTTP endpoint emits, and the reader inventories
   (obs/history.py gates, scripts/bench_report.py,
   scripts/ci_check.sh greps, the typed client, the README tables) —
   ``phantom-reader``, ``schema-drift``, ``dead-counter``,
   ``event-vocab``, ``doc-drift``.  Jax-free; the committed
   ``runs/contract_r19.json`` inventory feeds a live ``/metricsz``
   cross-check (``contracts.assert_covered``).
7. **Runtime guards** — :class:`CompileGuard`
   (analysis/recompile_guard.py) bounds XLA compilations over a region
   (the tier-1 compile-budget pins), and the opt-in lock-order recorder
   (analysis/lockorder.py, ``FCTPU_LOCK_ORDER=1``) logs the observed
   lock acquisition digraph so the pool stress test can assert it stays
   acyclic and consistent with layer 2's static graph.

CI gates on a clean run (scripts/ci_check.sh); deliberate violations
carry ``# fcheck: ok=<rule>`` pragmas with reasons
(analysis/diagnostics.py).
"""

from fastconsensus_tpu.analysis.diagnostics import (Diagnostic,  # noqa: F401
                                                    Report)
from fastconsensus_tpu.analysis.recompile_guard import (  # noqa: F401
    CompileGuard, RecompileError, assert_max_compiles)


def _module_name(path):
    """Dotted module name of a scanned file, for the cross-module
    key-reuse summary table: everything from the ``fastconsensus_tpu``
    package root down when the file lives inside it, the bare stem
    otherwise (fixtures and scripts import each other by stem, if at
    all)."""
    import os

    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    name = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    if "fastconsensus_tpu" in parts[:-1]:
        i = parts.index("fastconsensus_tpu")
        mods = parts[i:-1] + ([] if name == "__init__" else [name])
        return ".".join(mods)
    return name


def lint_paths(paths, report=None):
    """Lint every ``.py`` under ``paths`` (files or directories) into a
    Report (created if not given).

    Four passes: the first summarizes every function's PRNG-key
    consumption (astlint.summarize_key_params), the second lints with
    that table in hand — so the ``key-reuse`` rule tracks keys through
    helper calls across module boundaries (e.g. ``seg.pair_jitter``)
    instead of treating every callee as an opaque single draw — the
    third runs the whole-program concurrency analysis
    (analysis/concurrency.py: guarded-field, lock-order,
    blocking-under-lock, notify-outside-lock, unguarded-root-write)
    over the same source set, the fourth the whole-program fault pass
    (analysis/faults.py: escape-thread-root, swallowed-error,
    unmapped-http-error, resource-leak), and the fifth the
    name-contract pass (analysis/contracts.py: repo mode when the scan
    covers the serving/obs surface, fixture mode for CONTRACT_SPEC
    files).
    """
    import os

    from fastconsensus_tpu.analysis.astlint import (lint_source,
                                                    summarize_key_params)
    from fastconsensus_tpu.analysis.concurrency import check_concurrency
    from fastconsensus_tpu.analysis.contracts import check_contracts
    from fastconsensus_tpu.analysis.faults import check_faults

    if report is None:
        report = Report()
    files = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", "build"))
                files.extend(os.path.join(root, f) for f in sorted(names)
                             if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    sources = {}
    for f in files:
        with open(f, encoding="utf-8") as fh:
            sources[f] = fh.read()
    summaries = {}
    for f, src in sources.items():
        mod = _module_name(f)
        table = summarize_key_params(src, filename=f)
        if table:
            # first writer wins on a (pathological) duplicate module
            # name; identical files produce identical tables anyway
            summaries.setdefault(mod, table)
    for f, src in sources.items():
        diags, suppressed = lint_source(src, filename=f,
                                        key_summaries=summaries)
        report.extend(diags)
        report.n_suppressed += suppressed
        report.n_files += 1
    conc_diags, conc_suppressed = check_concurrency(sources)
    report.extend(conc_diags)
    report.n_suppressed += conc_suppressed
    flt_diags, flt_suppressed = check_faults(sources)
    report.extend(flt_diags)
    report.n_suppressed += flt_suppressed
    con_diags, con_suppressed = check_contracts(sources)
    report.extend(con_diags)
    report.n_suppressed += con_suppressed
    return report
