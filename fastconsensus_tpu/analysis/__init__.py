"""fcheck: the project's static-analysis suite (AST lint + jaxpr audit +
recompile guard).

Three layers, one report (run ``python -m fastconsensus_tpu.analysis``):

1. **AST lint** (analysis/astlint.py) — project-specific source rules:
   PRNG key reuse, Python control flow on traced values, retrace
   hazards, weak static args, float64 drift, host syncs in hot loops,
   Pallas kernels closing over tracers.
2. **jaxpr audit** (analysis/jaxpr_audit.py) — traces every registered
   jitted entry point (analysis/entrypoints.py) at canonical shapes and
   walks the staged program for forbidden primitives (f64 casts,
   embedded device_put, ungated huge gathers).
3. **recompile guard** (analysis/recompile_guard.py) — a runtime context
   manager bounding XLA compilations over a region; the tier-1 test
   pins the 2-round consensus compile budget with it.

CI gates on a clean run (scripts/ci_check.sh); deliberate violations
carry ``# fcheck: ok=<rule>`` pragmas with reasons
(analysis/diagnostics.py).
"""

from fastconsensus_tpu.analysis.diagnostics import (Diagnostic,  # noqa: F401
                                                    Report)
from fastconsensus_tpu.analysis.recompile_guard import (  # noqa: F401
    CompileGuard, RecompileError, assert_max_compiles)


def lint_paths(paths, report=None):
    """Lint every ``.py`` under ``paths`` (files or directories) into a
    Report (created if not given)."""
    import os

    from fastconsensus_tpu.analysis.astlint import lint_source

    if report is None:
        report = Report()
    files = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", "build"))
                files.extend(os.path.join(root, f) for f in sorted(names)
                             if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    for f in files:
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        diags, suppressed = lint_source(src, filename=f)
        report.extend(diags)
        report.n_suppressed += suppressed
        report.n_files += 1
    return report
