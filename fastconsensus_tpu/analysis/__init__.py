"""fcheck: the project's static-analysis suite (AST lint + jaxpr audit +
recompile guard).

Three layers, one report (run ``python -m fastconsensus_tpu.analysis``):

1. **AST lint** (analysis/astlint.py) — project-specific source rules:
   PRNG key reuse, Python control flow on traced values, retrace
   hazards, weak static args, float64 drift, host syncs in hot loops,
   Pallas kernels closing over tracers.
2. **jaxpr audit** (analysis/jaxpr_audit.py) — traces every registered
   jitted entry point (analysis/entrypoints.py) at canonical shapes and
   walks the staged program for forbidden primitives (f64 casts,
   embedded device_put, ungated huge gathers).
3. **recompile guard** (analysis/recompile_guard.py) — a runtime context
   manager bounding XLA compilations over a region; the tier-1 test
   pins the 2-round consensus compile budget with it.

CI gates on a clean run (scripts/ci_check.sh); deliberate violations
carry ``# fcheck: ok=<rule>`` pragmas with reasons
(analysis/diagnostics.py).
"""

from fastconsensus_tpu.analysis.diagnostics import (Diagnostic,  # noqa: F401
                                                    Report)
from fastconsensus_tpu.analysis.recompile_guard import (  # noqa: F401
    CompileGuard, RecompileError, assert_max_compiles)


def _module_name(path):
    """Dotted module name of a scanned file, for the cross-module
    key-reuse summary table: everything from the ``fastconsensus_tpu``
    package root down when the file lives inside it, the bare stem
    otherwise (fixtures and scripts import each other by stem, if at
    all)."""
    import os

    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    name = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    if "fastconsensus_tpu" in parts[:-1]:
        i = parts.index("fastconsensus_tpu")
        mods = parts[i:-1] + ([] if name == "__init__" else [name])
        return ".".join(mods)
    return name


def lint_paths(paths, report=None):
    """Lint every ``.py`` under ``paths`` (files or directories) into a
    Report (created if not given).

    Two passes: the first summarizes every function's PRNG-key
    consumption (astlint.summarize_key_params), the second lints with
    that table in hand — so the ``key-reuse`` rule tracks keys through
    helper calls across module boundaries (e.g. ``seg.pair_jitter``)
    instead of treating every callee as an opaque single draw.
    """
    import os

    from fastconsensus_tpu.analysis.astlint import (lint_source,
                                                    summarize_key_params)

    if report is None:
        report = Report()
    files = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", "build"))
                files.extend(os.path.join(root, f) for f in sorted(names)
                             if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    sources = {}
    for f in files:
        with open(f, encoding="utf-8") as fh:
            sources[f] = fh.read()
    summaries = {}
    for f, src in sources.items():
        mod = _module_name(f)
        table = summarize_key_params(src, filename=f)
        if table:
            # first writer wins on a (pathological) duplicate module
            # name; identical files produce identical tables anyway
            summaries.setdefault(mod, table)
    for f, src in sources.items():
        diags, suppressed = lint_source(src, filename=f,
                                        key_summaries=summaries)
        report.extend(diags)
        report.n_suppressed += suppressed
        report.n_files += 1
    return report
