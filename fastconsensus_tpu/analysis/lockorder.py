"""Runtime lock-order recorder: the dynamic half of fcheck-concurrency.

The static ``lock-order`` rule (analysis/concurrency.py) cannot see
through stored callables — ``AdmissionQueue._extra_depth`` is a lambda
installed at runtime that reaches ``_Worker._cond`` from under the
queue's own condition, an edge no AST walk can attribute.  This module
records the acquisition digraph actually *observed* while the code
runs, so the test suite can assert that the union of the static and the
observed graphs stays acyclic — the tripwire that keeps the static
model honest.

Opt-in only (``FCTPU_LOCK_ORDER=1``, wired in tests/conftest.py, or an
explicit :func:`recording` block): :func:`install` replaces
``threading.Lock`` / ``RLock`` / ``Condition`` with recording wrappers
**for locks created from inside the fastconsensus_tpu tree** — stdlib
and third-party lock construction (including the RLock a bare
``Condition()`` builds internally, whose creating frame is
threading.py) passes through untouched.  Each wrapped lock remembers
its *creation site* (``file:line`` — which for the ``self._lock =
threading.Lock()`` idiom is the declaration the static pass keys on,
see ``concurrency.lock_sites``), and every acquisition while other
recorded locks are held appends the edge (held site -> acquired site)
to the active :class:`LockOrderRecorder`.

``Condition`` is wrapped by handing the real ``threading.Condition`` a
recording Lock: ``wait()`` then releases and re-acquires through the
wrapper, so the held-stack is correct across waits (a thread parked in
``wait`` holds nothing; edges re-record on wake-up).

Overhead is a thread-local list append per acquisition — irrelevant for
tests, which is the only place this runs.  Production never imports it.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

from fastconsensus_tpu.analysis.concurrency import find_cycle

_REAL = {
    "Lock": threading.Lock,
    "RLock": threading.RLock,
    "Condition": threading.Condition,
}

_recorder: Optional["LockOrderRecorder"] = None
_installed = False
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class LockOrderRecorder:
    """Accumulates observed acquisition edges between lock creation
    sites ((abspath, lineno) pairs)."""

    def __init__(self) -> None:
        self._lock = _REAL["Lock"]()
        self._edges: Dict[Tuple[Tuple[str, int], Tuple[str, int]],
                          int] = {}
        self._local = threading.local()

    def _held(self) -> List[Tuple[Tuple[str, int], int]]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def note_acquire(self, site: Tuple[str, int], lid: int) -> None:
        stack = self._held()
        if stack:
            with self._lock:
                for held_site, held_lid in stack:
                    if held_lid == lid:
                        # re-entrant RLock acquisition of the SAME
                        # instance: not an ordering edge (a same-SITE
                        # edge between DISTINCT instances is — that is
                        # the two-workers-in-opposite-orders hazard)
                        continue
                    key = (held_site, site)
                    self._edges[key] = self._edges.get(key, 0) + 1
        stack.append((site, lid))

    def note_release(self, site: Tuple[str, int], lid: int) -> None:
        stack = self._held()
        # release order may not be LIFO (rare but legal): drop the
        # most recent matching entry
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == (site, lid):
                del stack[i]
                return

    def edges(self) -> Set[Tuple[Tuple[str, int], Tuple[str, int]]]:
        with self._lock:
            return set(self._edges)

    def edge_counts(self) -> Dict[Tuple[Tuple[str, int],
                                        Tuple[str, int]], int]:
        with self._lock:
            return dict(self._edges)

    def named_edges(self, sites: Dict[Tuple[str, int], str]
                    ) -> Set[Tuple[str, str]]:
        """Observed edges mapped onto the static pass's lock keys
        (``concurrency.lock_sites``); sites the static pass does not
        know keep their ``file:line`` spelling so nothing is silently
        dropped."""
        def name(site: Tuple[str, int]) -> str:
            return sites.get(site, f"{site[0]}:{site[1]}")

        return {(name(a), name(b)) for a, b in self.edges()}

    def assert_acyclic(self, extra_edges: Optional[
            Set[Tuple[str, str]]] = None,
            sites: Optional[Dict[Tuple[str, int], str]] = None) -> None:
        """Raise AssertionError when the observed digraph — unioned
        with ``extra_edges`` (canonically the static graph) — has a
        cycle.  This is THE consistency contract between the two
        halves: every ordering the runtime exhibits must compose with
        every ordering the static pass proved, or a deadlock is one
        unlucky interleaving away."""
        edges = self.named_edges(sites or {})
        if extra_edges:
            edges = edges | set(extra_edges)
        cyc = find_cycle(edges)
        if cyc is not None:
            raise AssertionError(
                "observed lock-order cycle (union with static graph): "
                + " -> ".join(cyc + [cyc[0]]))


class _TracedLock:
    """Records acquisitions of one underlying lock against the active
    recorder.  Duck-types the full Lock protocol; ``threading.
    Condition`` drives it through acquire/release, so waits release the
    held-stack entry and re-add it on wake-up."""

    def __init__(self, inner, site: Tuple[str, int]) -> None:
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        # fcheck: ok=resource-leak (lock-wrapper protocol: the
        # paired release() is the caller's obligation, exactly
        # as with the raw lock this class impersonates)
        ok = self._inner.acquire(blocking, timeout)
        if ok and _recorder is not None:
            _recorder.note_acquire(self._site, id(self))
        return ok

    def release(self) -> None:
        if _recorder is not None:
            _recorder.note_release(self._site, id(self))
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # Condition protocol: threading.Condition binds these when present.
    # Delegating keeps wait() correct for a re-entrant inner lock (the
    # plain-Lock fallbacks Condition would use otherwise misdetect
    # ownership of a held RLock) while the recorder's held-stack still
    # drops the entry across the wait and re-adds it on wake-up.

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # fcheck: ok=resource-leak (ownership probe: a
        # successful non-blocking acquire is released on the
        # very next line)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        if _recorder is not None:
            _recorder.note_release(self._site, id(self))
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            # fcheck: ok=resource-leak (Condition protocol: the
            # paired release happened in _release_save before the
            # wait; this is the wake-up re-acquire)
            self._inner.acquire()
        if _recorder is not None:
            _recorder.note_acquire(self._site, id(self))

    def __enter__(self) -> bool:
        # fcheck: ok=resource-leak (context-manager protocol:
        # __exit__ below is the paired release)
        return self.acquire()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<TracedLock {self._site[0]}:{self._site[1]} " \
               f"{self._inner!r}>"


def _creation_site() -> Optional[Tuple[str, int]]:
    """(abspath, lineno) of the first stack frame outside this module
    and threading.py — None when the construction did not come from the
    fastconsensus_tpu tree (those locks stay unwrapped)."""
    f = sys._getframe(2)
    this = os.path.abspath(__file__)
    while f is not None:
        fname = os.path.abspath(f.f_code.co_filename)
        if fname != this and not fname.endswith(
                os.sep + "threading.py"):
            if fname.startswith(_PKG_DIR + os.sep):
                return (fname, f.f_lineno)
            return None
        f = f.f_back
    return None


def _make_lock() -> object:
    site = _creation_site()
    inner = _REAL["Lock"]()
    if site is None:
        return inner
    return _TracedLock(inner, site)


def _make_rlock() -> object:
    site = _creation_site()
    inner = _REAL["RLock"]()
    if site is None:
        return inner
    return _TracedLock(inner, site)


def _make_condition(lock=None) -> object:
    site = _creation_site()
    if site is None:
        return _REAL["Condition"](lock)
    if lock is None:
        # the condition's internal lock IS the recorded lock: every
        # with-block, notify and wait goes through the wrapper
        lock = _TracedLock(_REAL["RLock"](), site)
    return _REAL["Condition"](lock)


def install(recorder: Optional[LockOrderRecorder] = None
            ) -> LockOrderRecorder:
    """Patch ``threading.Lock/RLock/Condition`` so locks created from
    package code record into ``recorder`` (a fresh one by default).
    Idempotent: calling again swaps the active recorder only."""
    global _recorder, _installed
    if recorder is None:
        recorder = LockOrderRecorder()
    _recorder = recorder
    if not _installed:
        threading.Lock = _make_lock          # type: ignore[misc]
        threading.RLock = _make_rlock        # type: ignore[misc]
        threading.Condition = _make_condition  # type: ignore[misc]
        _installed = True
    return recorder


def uninstall() -> None:
    """Restore the real factories.  Locks already wrapped keep working
    (they hold real locks inside) but stop recording."""
    global _recorder, _installed
    _recorder = None
    if _installed:
        threading.Lock = _REAL["Lock"]        # type: ignore[misc]
        threading.RLock = _REAL["RLock"]      # type: ignore[misc]
        threading.Condition = _REAL["Condition"]  # type: ignore[misc]
        _installed = False


def maybe_install_from_env() -> Optional[LockOrderRecorder]:
    """Install iff ``FCTPU_LOCK_ORDER=1`` (the test-suite hook)."""
    if os.environ.get("FCTPU_LOCK_ORDER") == "1":
        return install()
    return None


class recording:
    """``with lockorder.recording() as rec:`` — scoped install/swap.

    If the factories are already patched (env-var install), only the
    active recorder is swapped and restored; otherwise the factories
    are patched for the block and unpatched after."""

    def __enter__(self) -> LockOrderRecorder:
        global _recorder
        self._was_installed = _installed
        self._prev = _recorder
        self._rec = LockOrderRecorder()
        install(self._rec)
        return self._rec

    def __exit__(self, exc_type, exc, tb) -> None:
        global _recorder
        if self._was_installed:
            _recorder = self._prev
        else:
            uninstall()
