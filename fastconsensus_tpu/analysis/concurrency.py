"""fcheck-concurrency: static race & lock-discipline analysis.

PRs 4-6 turned the reproduction into a multi-threaded serving stack —
HTTP handler threads, a dispatcher, device-pinned worker threads — and
the JAX-side rules (astlint.py) see none of it: a snapshot read racing a
worker's dict mutation changes neither shapes nor distributions, only
whether ``/healthz`` occasionally throws ``RuntimeError: dictionary
changed size during iteration``.  PR 6 shipped exactly one such bug
(``Tracer.drain_since``'s pre-fix snapshot-vs-clear), caught by hand in
review.  This pass makes the discipline machine-checked.

Unlike the per-file rules in astlint.py this analysis is whole-program:
``lint_paths`` hands it the complete scanned source set, and summaries
resolve across modules the way the cross-function ``key-reuse`` table
does — local defs, import aliases, plus one deliberately type-blind
fallback for attribute calls (``self.cache.get`` reaches every scanned
method named ``get`` on a class whose name contains the receiver
identifier).  Over-approximate on purpose: for reachability and lock
ordering, extra edges mean extra findings, never missed ones, and the
pragma convention absorbs the occasional false positive.

Five rules:

``guarded-field``
    Per class, every ``self._x`` touched at least once inside
    ``with self.<lock>:`` is inferred to be *lock-guarded*; an access to
    the same field outside any own-lock ``with`` (outside ``__init__``,
    which runs before the object is shared) is a race candidate.  Fires
    only when the accessing methods are reachable from more than one
    thread root — roots are discovered from ``threading.Thread(
    target=...)`` across the whole file set plus the implicit external
    (caller/main) root, and propagate through the call graph.  Accesses
    in *receiver position* (``self._reg.inc(...)``) are exempt: they
    dereference a stable reference whose own object is responsible for
    its locking — the rule targets reads of mutable *structure* (bare
    loads, subscripts, iteration, argument-position reads like
    ``dict(self.buckets)``) and all writes.  Also fires on cross-object
    reads of another class's underscore-private guarded field
    (``other._events[...]``): private state guarded inside its class
    cannot be safely dereferenced from outside it.

``lock-order``
    The acquisition-order digraph: ``with B:`` while A is held adds the
    edge A -> B, both lexically and through call chains (a function
    called under A contributes an edge to every lock it transitively
    acquires).  Locks are keyed per declaration site (``Module.Class.
    _attr`` / ``module._name``), so all instances of one class are one
    node — a self-edge IS a finding (two instances acquired in opposite
    orders by two threads deadlock).  Any cycle is flagged as a
    potential deadlock.  The runtime half (analysis/lockorder.py,
    ``FCTPU_LOCK_ORDER=1``) records the *observed* digraph during the
    pool stress test and asserts its union with this static graph stays
    acyclic — the dynamic tripwire that keeps the static model honest
    (stored-callable indirection like ``AdmissionQueue._extra_depth``
    is invisible statically but shows up dynamically).

``blocking-under-lock``
    A call that can block indefinitely — device dispatch
    (``run_consensus``/``run_consensus_batch``), ``block_until_ready``,
    ``jax.device_get``, ``Thread.join``, socket/HTTP traffic,
    ``subprocess.run``, ``time.sleep``, or ``Condition.wait()`` with no
    timeout while a lock *other than the condition's own* is held —
    executed while holding any lock, resolved transitively through
    helpers.  Holding a lock across a device dispatch turns every
    thread that needs that lock into a hostage of the XLA queue.

``notify-outside-lock``
    ``Condition.notify()`` / ``notify_all()`` not lexically inside
    ``with <same condition>:``.  CPython raises RuntimeError at
    runtime, but only on the path that reaches it — this catches the
    branch nobody tested.

``unguarded-root-write``
    A write inside a worker-thread root (a ``Thread(target=...)``
    function) to shared state — a ``self`` attribute or ``global``
    name also touched by functions on a different thread root — with
    no lock held and no guarded access anywhere (fields with SOME
    guarded access are ``guarded-field``'s jurisdiction).  Write-once
    handshakes are real findings to *decide* about: guard them or
    pragma them with the reason.

All rules honor ``# fcheck: ok=<rule>: <reason>`` pragmas
(diagnostics.parse_pragmas), counted in the JSON report like every
other suppression.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from fastconsensus_tpu.analysis.diagnostics import (Diagnostic,
                                                    apply_pragmas)

# threading factories whose assignment declares a lock (lock identity is
# keyed on the declaration site).
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
# Intrinsically blocking calls (rule `blocking-under-lock`), by method
# name on any receiver:
_BLOCKING_ATTRS = {"block_until_ready", "recv", "recv_into", "accept",
                   "connect", "sendall", "getresponse"}
# ... and by (module, function):
_BLOCKING_QUALIFIED = {
    ("jax", "device_get"), ("time", "sleep"), ("subprocess", "run"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
}
# Project device-dispatch entry points: a jitted consensus call is an
# unbounded device-queue wait from the host's point of view.
_DEVICE_DISPATCH = {"run_consensus", "run_consensus_batch"}
_THREADISH = ("thread", "worker", "dispatcher", "proc", "child")

EXTERNAL_ROOT = "<external>"

CONCURRENCY_RULES = ("guarded-field", "lock-order",
                     "blocking-under-lock", "notify-outside-lock",
                     "unguarded-root-write")


def _call_name(node: ast.Call) -> Tuple[Optional[str], str]:
    """(dotted qualifier, attr/function name) of a call target."""
    f = node.func
    if isinstance(f, ast.Name):
        return None, f.id
    if isinstance(f, ast.Attribute):
        parts = []
        v = f.value
        while isinstance(v, ast.Attribute):
            parts.append(v.attr)
            v = v.value
        if isinstance(v, ast.Name):
            parts.append(v.id)
            return ".".join(reversed(parts)), f.attr
        return None, f.attr
    return None, ""


def _module_name(path: str) -> str:
    """Dotted module key of a scanned file — the SAME keying the
    key-reuse summary table uses, so the two cross-module passes
    resolve identically."""
    from fastconsensus_tpu.analysis import _module_name as shared

    return shared(path)


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — display-only fallback
        return "<expr>"


class _FnInfo:
    """Per-function concurrency summary (one pass over the body)."""

    def __init__(self, module: str, cls: Optional[str], name: str,
                 node: ast.FunctionDef, filename: str) -> None:
        self.module = module
        self.cls = cls
        self.name = name
        self.node = node
        self.filename = filename
        self.ref = f"{module}.{cls}.{name}" if cls else f"{module}.{name}"
        # (lock key, line, col) acquisitions; lexical nesting edges
        self.acquisitions: List[Tuple[str, int, int]] = []
        self.lexical_edges: Set[Tuple[str, str]] = set()
        # calls with >= 1 lock lexically held: (held, qual, name, node)
        self.calls_under: List[Tuple[FrozenSet[str], Optional[str], str,
                                     ast.Call]] = []
        # every call (call graph / reachability / lock propagation)
        self.calls: List[Tuple[Optional[str], str]] = []
        # structural accesses on self: attr -> [(guard lock key | None,
        # line, col, is_write)]
        self.self_accesses: Dict[str, List[Tuple[Optional[str], int, int,
                                                 bool]]] = {}
        # structural reads on non-self receivers: (attr, line, col, held)
        self.other_accesses: List[Tuple[str, int, int,
                                        FrozenSet[str]]] = []
        # self attributes that appear as a dotted-through receiver
        # (``self._batches.popleft()`` / ``self.buckets.get``): the
        # mutation-signal half of the guarded-field table — containers
        # are mutated through bound methods, which the structural
        # access record cannot see as writes
        self.receiver_uses: Set[str] = set()
        # global-declared name accesses: name -> [(guard, line, col,
        # is_write)]
        self.global_accesses: Dict[str, List[Tuple[Optional[str], int,
                                                   int, bool]]] = {}
        self.global_names: Set[str] = {
            n for g in ast.walk(node) if isinstance(g, ast.Global)
            for n in g.names}
        self.direct_diags: List[Diagnostic] = []
        self.blocks_directly = False
        self.thread_targets: List[str] = []   # Thread(target=...) refs


class _ModuleInfo:
    def __init__(self, module: str, filename: str, source: str) -> None:
        self.module = module
        self.filename = filename
        self.source = source
        self.functions: Dict[str, _FnInfo] = {}
        self.classes: Dict[str, Dict[str, _FnInfo]] = {}
        self.class_locks: Dict[str, Dict[str, int]] = {}
        self.module_locks: Dict[str, int] = {}
        self.alias_modules: Dict[str, str] = {}
        self.from_imports: Dict[str, Tuple[str, str]] = {}


class ConcurrencyAnalyzer:
    """Whole-program pass over a ``{filename: source}`` set."""

    def __init__(self, sources: Dict[str, str]) -> None:
        self.sources = sources
        self.modules: Dict[str, _ModuleInfo] = {}
        self.diags: List[Diagnostic] = []
        # lock declaration sites: (abspath, line) -> lock key
        self.lock_sites: Dict[Tuple[str, int], str] = {}
        # static acquisition-order digraph: edge -> first witness site
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    # ---------------- collection ----------------

    def collect(self) -> None:
        for filename, source in self.sources.items():
            try:
                tree = ast.parse(source, filename=filename)
            # fcheck: ok=swallowed-error (astlint reports the syntax
            # error itself; this pass just skips the unparsable file)
            except SyntaxError:
                continue  # astlint reports the syntax error itself
            mod = _ModuleInfo(_module_name(filename), filename, source)
            self._collect_imports(tree, mod)
            self._collect_locks(tree, mod)
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    fn = _FnInfo(mod.module, None, node.name, node,
                                 filename)
                    self._summarize(fn, mod)
                    mod.functions[node.name] = fn
                elif isinstance(node, ast.ClassDef):
                    methods: Dict[str, _FnInfo] = {}
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            fn = _FnInfo(mod.module, node.name,
                                         sub.name, sub, filename)
                            self._summarize(fn, mod)
                            methods[sub.name] = fn
                    mod.classes[node.name] = methods
            self.modules[mod.module] = mod

    @staticmethod
    def _collect_imports(tree: ast.Module, mod: _ModuleInfo) -> None:
        for stmt in tree.body:
            if isinstance(stmt, ast.Import):
                for a in stmt.names:
                    if a.asname:
                        mod.alias_modules[a.asname] = a.name
            elif isinstance(stmt, ast.ImportFrom) and stmt.level == 0 \
                    and stmt.module:
                for a in stmt.names:
                    alias = a.asname or a.name
                    mod.alias_modules.setdefault(
                        alias, f"{stmt.module}.{a.name}")
                    mod.from_imports[alias] = (stmt.module, a.name)

    def _collect_locks(self, tree: ast.Module, mod: _ModuleInfo) -> None:
        """Lock declaration sites: module-level ``X = threading.Lock()``
        and ``self._x = threading.Lock()`` anywhere inside a class."""
        def is_lock_call(value: ast.AST) -> bool:
            if not isinstance(value, ast.Call):
                return False
            qual, name = _call_name(value)
            return name in _LOCK_FACTORIES and (
                qual is None or qual == "threading" or
                qual.endswith(".threading"))

        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and is_lock_call(stmt.value):
                name = stmt.targets[0].id
                mod.module_locks[name] = stmt.lineno
                self.lock_sites[(os.path.abspath(mod.filename),
                                 stmt.lineno)] = f"{mod.module}.{name}"
            elif isinstance(stmt, ast.ClassDef):
                attrs: Dict[str, int] = {}
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Assign) and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0], ast.Attribute) \
                            and isinstance(node.targets[0].value,
                                           ast.Name) \
                            and node.targets[0].value.id == "self" \
                            and is_lock_call(node.value):
                        attr = node.targets[0].attr
                        attrs[attr] = node.lineno
                        self.lock_sites[
                            (os.path.abspath(mod.filename),
                             node.lineno)] = \
                            f"{mod.module}.{stmt.name}.{attr}"
                if attrs:
                    mod.class_locks[stmt.name] = attrs

    # ---------------- per-function summary ----------------

    def _lock_key_of(self, expr: ast.AST, fn: _FnInfo,
                     mod: _ModuleInfo) -> Optional[str]:
        """The lock key an expression denotes, or None: ``self._x``
        against the class's declared lock attrs, a bare name against
        the module's lock globals."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and fn.cls is not None:
            if expr.attr in mod.class_locks.get(fn.cls, {}):
                return f"{mod.module}.{fn.cls}.{expr.attr}"
        if isinstance(expr, ast.Name) and expr.id in mod.module_locks:
            return f"{mod.module}.{expr.id}"
        return None

    def _summarize(self, fn: _FnInfo, mod: _ModuleInfo) -> None:
        self._with_exprs: Tuple[str, ...] = ()
        self._walk(list(fn.node.body), fn, mod, held=(), with_exprs=())

    def _walk(self, stmts: List[ast.stmt], fn: _FnInfo,
              mod: _ModuleInfo, held: Tuple[str, ...],
              with_exprs: Tuple[str, ...]) -> None:
        for stmt in stmts:
            # the notify rule needs the lexical with-stack at expression
            # scan time; re-established per statement because nested
            # _walk calls overwrite it
            self._with_exprs = with_exprs
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs run on unknown threads; skipped
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new_held = list(held)
                new_with = list(with_exprs)
                for item in stmt.items:
                    self._expr(item.context_expr, fn, mod, held, "plain")
                    key = self._lock_key_of(item.context_expr, fn, mod)
                    if key is not None:
                        for h in new_held:
                            fn.lexical_edges.add((h, key))
                        fn.acquisitions.append(
                            (key, stmt.lineno, stmt.col_offset))
                        new_held.append(key)
                    new_with.append(_unparse(item.context_expr))
                self._walk(stmt.body, fn, mod, tuple(new_held),
                           tuple(new_with))
                continue
            if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                if stmt.value is not None:
                    self._expr(stmt.value, fn, mod, held, "plain")
                for t in targets:
                    self._store(t, fn, mod, held,
                                also_read=isinstance(stmt, ast.AugAssign))
                continue
            if isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    self._store(t, fn, mod, held, also_read=False)
                continue
            for field in ("test", "iter", "value", "exc", "msg"):
                child = getattr(stmt, field, None)
                if isinstance(child, ast.expr):
                    self._expr(child, fn, mod, held, "plain")
            if isinstance(stmt, ast.Expr):
                pass  # covered by the "value" field above
            for field in ("body", "orelse", "finalbody"):
                block = getattr(stmt, field, None)
                if isinstance(block, list) and block and \
                        isinstance(block[0], ast.stmt):
                    self._walk(block, fn, mod, held, with_exprs)
            for h in getattr(stmt, "handlers", ()):
                self._walk(h.body, fn, mod, held, with_exprs)

    def _store(self, target: ast.AST, fn: _FnInfo, mod: _ModuleInfo,
               held: Tuple[str, ...], also_read: bool) -> None:
        guard = held[-1] if held else None
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._store(el, fn, mod, held, also_read)
            return
        if isinstance(target, ast.Starred):
            self._store(target.value, fn, mod, held, also_read)
            return
        if isinstance(target, ast.Subscript):
            # writing THROUGH a container mutates the container: the
            # base is a structural access (del self._jobs[k] included)
            self._expr(target.value, fn, mod, held, "plain",
                       force_write=True)
            self._expr(target.slice, fn, mod, held, "plain")
            return
        if isinstance(target, ast.Attribute):
            if isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                fn.self_accesses.setdefault(target.attr, []).append(
                    (guard, target.lineno, target.col_offset, True))
                if also_read:
                    fn.self_accesses[target.attr].append(
                        (guard, target.lineno, target.col_offset,
                         False))
            else:
                self._expr(target.value, fn, mod, held, "base")
            return
        if isinstance(target, ast.Name):
            if target.id in fn.global_names and \
                    target.id not in mod.module_locks:
                fn.global_accesses.setdefault(target.id, []).append(
                    (guard, target.lineno, target.col_offset, True))

    def _expr(self, node: Optional[ast.AST], fn: _FnInfo,
              mod: _ModuleInfo, held: Tuple[str, ...], role: str,
              force_write: bool = False) -> None:
        """Role-aware expression scan.  ``role``:

        * ``plain`` — a genuine data read (argument, operand, subscript
          base, iteration source): records structural accesses;
        * ``callee`` — the func of a Call (``self._reg.inc``): the
          terminal attribute is a method name, and the chain below it
          is reference plumbing — nothing is recorded;
        * ``base`` — the receiver chain under an attribute/callee:
          plumbing, nothing recorded.
        """
        if node is None:
            return
        guard = held[-1] if held else None
        if isinstance(node, ast.Call):
            self._scan_call(node, fn, mod, held)
            self._expr(node.func, fn, mod, held, "callee")
            for a in node.args:
                self._expr(a.value if isinstance(a, ast.Starred) else a,
                           fn, mod, held, "plain")
            for kw in node.keywords:
                self._expr(kw.value, fn, mod, held, "plain")
            return
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                if role == "plain":
                    fn.self_accesses.setdefault(node.attr, []).append(
                        (guard, node.lineno, node.col_offset,
                         force_write))
                else:
                    fn.receiver_uses.add(node.attr)
                return
            if role == "plain":
                fn.other_accesses.append(
                    (node.attr, node.lineno, node.col_offset,
                     frozenset(held)))
            self._expr(node.value, fn, mod, held, "base")
            return
        if isinstance(node, ast.Subscript):
            self._expr(node.value, fn, mod, held, "plain")
            self._expr(node.slice, fn, mod, held, "plain")
            return
        if isinstance(node, ast.Name):
            if role == "plain" and node.id in fn.global_names and \
                    node.id not in mod.module_locks:
                fn.global_accesses.setdefault(node.id, []).append(
                    (guard, node.lineno, node.col_offset, False))
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, fn, mod, held, "plain")
            elif isinstance(child, (ast.comprehension,)):
                self._expr(child.iter, fn, mod, held, "plain")
                for cond in child.ifs:
                    self._expr(cond, fn, mod, held, "plain")

    # -- calls: graph edges, thread roots, blocking, notify ------------

    def _scan_call(self, node: ast.Call, fn: _FnInfo,
                   mod: _ModuleInfo, held: Tuple[str, ...]) -> None:
        qual, name = _call_name(node)
        fn.calls.append((qual, name))
        if held:
            fn.calls_under.append((frozenset(held), qual, name, node))
        if name == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    ref = self._target_ref(kw.value, fn, mod)
                    if ref is not None:
                        fn.thread_targets.append(ref)
        if name in ("notify", "notify_all") and \
                isinstance(node.func, ast.Attribute):
            recv = _unparse(node.func.value)
            if self._lock_key_of(node.func.value, fn, mod) is not None \
                    and recv not in self._with_exprs:
                fn.direct_diags.append(Diagnostic(
                    rule="notify-outside-lock",
                    message=f"{recv}.{name}() is not lexically inside "
                            f"'with {recv}:': notifying an unheld "
                            "condition raises RuntimeError on exactly "
                            "the path nobody tested",
                    file=fn.filename, line=node.lineno,
                    col=node.col_offset))
        blocking = self._blocking_reason(qual, name, node, fn, mod,
                                         held)
        if blocking is not None:
            fn.blocks_directly = True
            if held:
                fn.direct_diags.append(Diagnostic(
                    rule="blocking-under-lock",
                    message=f"{blocking} while holding "
                            f"{sorted(held)}: every thread that needs "
                            "the lock now waits on this call too",
                    file=fn.filename, line=node.lineno,
                    col=node.col_offset))

    def _blocking_reason(self, qual: Optional[str], name: str,
                         node: ast.Call, fn: _FnInfo, mod: _ModuleInfo,
                         held: Tuple[str, ...]) -> Optional[str]:
        """Why this call is intrinsically blocking, or None.  (Sets the
        transitive may-block bit even with no lock held; the report
        itself only fires under a lock.)"""
        if name in _DEVICE_DISPATCH:
            return f"device dispatch {name}(...)"
        if name in _BLOCKING_ATTRS and isinstance(node.func,
                                                  ast.Attribute):
            return f".{name}() (blocking I/O / device sync)"
        if qual is not None:
            base = mod.alias_modules.get(qual, qual)
            for bq, bn in _BLOCKING_QUALIFIED:
                if name == bn and (base == bq or
                                   base.startswith(bq + ".")):
                    return f"{bq}.{bn}(...)"
            if name == "urlopen" and "urllib" in base:
                return "urllib urlopen(...)"
        if name == "join" and isinstance(node.func, ast.Attribute) \
                and not node.args and not node.keywords:
            recv = _unparse(node.func.value).lower()
            if any(t in recv for t in _THREADISH) or \
                    recv.startswith("self."):
                return f"{_unparse(node.func.value)}.join() " \
                       "(unbounded thread join)"
        if name == "wait" and isinstance(node.func, ast.Attribute) \
                and not node.args and not any(
                    kw.arg == "timeout" for kw in node.keywords):
            # Condition.wait() with no timeout: holding the condition's
            # OWN lock is the protocol; any OTHER held lock sleeps with
            # the waiter forever
            own = self._lock_key_of(node.func.value, fn, mod)
            foreign = [h for h in held if h != own]
            if foreign:
                return f"{_unparse(node.func.value)}.wait() with no " \
                       f"timeout (foreign lock(s) {sorted(foreign)} " \
                       "held through the wait)"
            return None
        return None

    def _target_ref(self, expr: ast.AST, fn: _FnInfo,
                    mod: _ModuleInfo) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and fn.cls is not None:
            return f"{mod.module}.{fn.cls}.{expr.attr}"
        if isinstance(expr, ast.Name):
            if expr.id in mod.functions:
                return f"{mod.module}.{expr.id}"
            tgt = mod.from_imports.get(expr.id)
            if tgt is not None:
                return f"{tgt[0]}.{tgt[1]}"
        return None

    # ---------------- cross-function resolution ----------------

    def _all_fns(self):
        for mod in self.modules.values():
            yield from mod.functions.values()
            for methods in mod.classes.values():
                yield from methods.values()

    def _build_tables(self) -> None:
        self.by_ref: Dict[str, _FnInfo] = {}
        self.by_method: Dict[str, List[_FnInfo]] = {}
        for fn in self._all_fns():
            self.by_ref[fn.ref] = fn
            self.by_method.setdefault(fn.name, []).append(fn)

    def _resolve(self, caller: _FnInfo, qual: Optional[str],
                 name: str) -> List[_FnInfo]:
        """Callees a call may reach (module docstring: name-based with
        a receiver-identifier/class-name containment fallback)."""
        mod = self.modules[caller.module]
        if qual is None:
            local = self.by_ref.get(f"{caller.module}.{name}")
            if local is not None:
                return [local]
            tgt = mod.from_imports.get(name)
            if tgt is not None:
                hit = self.by_ref.get(f"{tgt[0]}.{tgt[1]}")
                return [hit] if hit is not None else []
            return []
        if qual == "self" and caller.cls is not None:
            own = self.by_ref.get(f"{caller.module}.{caller.cls}.{name}")
            if own is not None:
                return [own]
        base = mod.alias_modules.get(qual, qual)
        direct = self.by_ref.get(f"{base}.{name}")
        if direct is not None:
            return [direct]
        ident = qual.rsplit(".", 1)[-1].lstrip("_").lower()
        if not ident:
            return []
        out = []
        for cand in self.by_method.get(name, ()):
            if cand.cls is None:
                continue
            cname = cand.cls.lstrip("_").lower()
            if ident in cname or cname in ident:
                out.append(cand)
        return out

    def _compute_roots(self) -> Dict[str, Set[str]]:
        """Thread-root sets per function ref, to fixpoint: Thread
        targets are worker roots; callers' roots propagate to callees;
        a function nobody scanned calls is an entry point and carries
        the implicit EXTERNAL root."""
        callers: Dict[str, Set[str]] = {}
        worker_roots: Set[str] = set()
        for fn in self._all_fns():
            worker_roots.update(fn.thread_targets)
            for qual, name in fn.calls:
                for callee in self._resolve(fn, qual, name):
                    callers.setdefault(callee.ref, set()).add(fn.ref)
        roots: Dict[str, Set[str]] = {}
        for fn in self._all_fns():
            r: Set[str] = set()
            if fn.ref in worker_roots:
                r.add(fn.ref)
            elif fn.ref not in callers:
                r.add(EXTERNAL_ROOT)
            roots[fn.ref] = r
        changed = True
        while changed:
            changed = False
            for ref, callset in callers.items():
                cur = roots.setdefault(ref, set())
                for caller in callset:
                    extra = roots.get(caller, set()) - cur
                    if extra:
                        cur.update(extra)
                        changed = True
        return roots

    def _transitive_acquisitions(self) -> Dict[str, Set[str]]:
        acq: Dict[str, Set[str]] = {
            fn.ref: {k for k, _, _ in fn.acquisitions}
            for fn in self._all_fns()}
        changed = True
        while changed:
            changed = False
            for fn in self._all_fns():
                cur = acq[fn.ref]
                for qual, name in fn.calls:
                    for callee in self._resolve(fn, qual, name):
                        extra = acq.get(callee.ref, set()) - cur
                        if extra:
                            cur.update(extra)
                            changed = True
        return acq

    def _transitive_blocking(self) -> Set[str]:
        blocks = {fn.ref for fn in self._all_fns() if fn.blocks_directly}
        changed = True
        while changed:
            changed = False
            for fn in self._all_fns():
                if fn.ref in blocks:
                    continue
                for qual, name in fn.calls:
                    if any(c.ref in blocks
                           for c in self._resolve(fn, qual, name)):
                        blocks.add(fn.ref)
                        changed = True
                        break
        return blocks

    # ---------------- rules ----------------

    def run(self) -> List[Diagnostic]:
        self.collect()
        self._build_tables()
        roots = self._compute_roots()
        self._rule_guarded_field(roots)
        self._rule_lock_order()
        self._rule_blocking_transitive()
        self._rule_root_writes(roots)
        for fn in self._all_fns():
            self.diags.extend(fn.direct_diags)
        return self.diags

    # -- rule 1: guarded-field ----------------------------------------

    def _rule_guarded_field(self, roots: Dict[str, Set[str]]) -> None:
        guarded: Dict[Tuple[str, str], Dict[str, str]] = {}
        for mod in self.modules.values():
            for cls, methods in mod.classes.items():
                if cls not in mod.class_locks:
                    continue
                init_names = ("__init__", "__new__", "__post_init__")
                mutated: Set[str] = set()
                for fn in methods.values():
                    if fn.name in init_names:
                        continue
                    mutated.update(fn.receiver_uses)
                    for attr, accs in fn.self_accesses.items():
                        if any(w for _, _, _, w in accs):
                            mutated.add(attr)
                table: Dict[str, str] = {}
                for fn in methods.values():
                    for attr, accs in fn.self_accesses.items():
                        if attr in mod.class_locks[cls] or \
                                attr not in mutated:
                            # no mutation outside __init__ anywhere in
                            # the class: an immutable reference (idx,
                            # config) needs no guard even when some
                            # method happens to read it under one
                            continue
                        for guard, _, _, _ in accs:
                            if guard is not None:
                                table.setdefault(attr, guard)
                if table:
                    guarded[(mod.module, cls)] = table
        # (a) same-class unguarded access, multi-root gated
        for (module, cls), table in guarded.items():
            mod = self.modules[module]
            methods = mod.classes[cls]
            for attr, lock_key in table.items():
                sites = []
                fn_roots: Set[str] = set()
                for fn in methods.values():
                    for guard, line, col, is_write in \
                            fn.self_accesses.get(attr, ()):
                        fn_roots.update(roots.get(fn.ref, ()))
                        if guard is None and fn.name not in (
                                "__init__", "__new__", "__post_init__"):
                            sites.append((fn, line, col, is_write))
                if len(fn_roots) < 2:
                    continue  # one thread root: no interleaving
                lock_attr = lock_key.rsplit(".", 1)[-1]
                for fn, line, col, is_write in sites:
                    what = "written" if is_write else "read"
                    self.diags.append(Diagnostic(
                        rule="guarded-field",
                        message=f"self.{attr} {what} outside 'with "
                                f"self.{lock_attr}:' but lock-guarded "
                                f"elsewhere in {cls}; its methods run "
                                f"on {len(fn_roots)} thread roots — "
                                "guard the access or pragma it with "
                                "the reason it is safe",
                        file=fn.filename, line=line, col=col))
        # (b) cross-object structural read of a private guarded field
        private = {attr: (cls, lock)
                   for (module, cls), table in guarded.items()
                   for attr, lock in table.items()
                   if attr.startswith("_")}
        for fn in self._all_fns():
            for attr, line, col, held in fn.other_accesses:
                hit = private.get(attr)
                if hit is None:
                    continue
                cls, lock_key = hit
                if fn.cls == cls or lock_key in held:
                    continue
                self.diags.append(Diagnostic(
                    rule="guarded-field",
                    message=f".{attr} of {cls} is lock-guarded inside "
                            f"its class ({lock_key}) but dereferenced "
                            "here from outside it without that lock — "
                            "use a locked accessor on the owner",
                    file=fn.filename, line=line, col=col))

    # -- rule 2: lock-order -------------------------------------------

    def _rule_lock_order(self) -> None:
        acq = self._transitive_acquisitions()
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for fn in self._all_fns():
            for a, b in fn.lexical_edges:
                edges.setdefault((a, b), (fn.filename, fn.node.lineno))
            for held, qual, name, node in fn.calls_under:
                for callee in self._resolve(fn, qual, name):
                    for b in acq.get(callee.ref, ()):
                        for a in held:
                            edges.setdefault(
                                (a, b), (fn.filename, node.lineno))
        self.edges = edges
        cyc = find_cycle(set(edges))
        if cyc is not None:
            nxt = cyc[1] if len(cyc) > 1 else cyc[0]
            filename, line = edges.get((cyc[0], nxt), ("<unknown>", 0))
            self.diags.append(Diagnostic(
                rule="lock-order",
                message="lock acquisition-order cycle: "
                        + " -> ".join(cyc + [cyc[0]])
                        + " — threads entering the cycle at different "
                        "locks deadlock; pick one global order (or "
                        "pragma the acquisition with why the orders "
                        "can never interleave)",
                file=filename, line=line))

    # -- rule 3: blocking through call chains -------------------------

    def _rule_blocking_transitive(self) -> None:
        blocks = self._transitive_blocking()
        for fn in self._all_fns():
            mod = self.modules[fn.module]
            for held, qual, name, node in fn.calls_under:
                if self._blocking_reason(qual, name, node, fn, mod,
                                         held) is not None:
                    continue  # direct hit, already reported
                hit = [c for c in self._resolve(fn, qual, name)
                       if c.ref in blocks]
                if hit:
                    self.diags.append(Diagnostic(
                        rule="blocking-under-lock",
                        message=f"{name}(...) can block (via "
                                f"{hit[0].ref}) and is called while "
                                f"holding {sorted(held)}: move the "
                                "call outside the lock or pragma it "
                                "with why the block is bounded",
                        file=fn.filename, line=node.lineno,
                        col=node.col_offset))

    # -- rule 5: unguarded writes from thread roots -------------------

    def _rule_root_writes(self, roots: Dict[str, Set[str]]) -> None:
        worker_roots: Set[str] = set()
        for fn in self._all_fns():
            worker_roots.update(fn.thread_targets)
        for fn in self._all_fns():
            if fn.ref not in worker_roots:
                continue
            mod = self.modules[fn.module]
            if fn.cls is not None:
                methods = mod.classes[fn.cls]
                lock_attrs = mod.class_locks.get(fn.cls, {})
                for attr, accs in fn.self_accesses.items():
                    if attr in lock_attrs:
                        continue
                    if any(g is not None for m in methods.values()
                           for g, _, _, _ in
                           m.self_accesses.get(attr, ())):
                        continue  # guarded-field's jurisdiction
                    others = [m for m in methods.values()
                              if m is not fn
                              and attr in m.self_accesses
                              and roots.get(m.ref, set())
                              - roots.get(fn.ref, set())]
                    if not others:
                        continue  # thread-confined (or same root)
                    for guard, line, col, is_write in accs:
                        if is_write and guard is None:
                            self.diags.append(Diagnostic(
                                rule="unguarded-root-write",
                                message=f"self.{attr} written in "
                                        f"thread root {fn.name}() "
                                        "with no lock, and also "
                                        f"touched by "
                                        f"{others[0].name}() on a "
                                        "different thread root — "
                                        "guard both sides or pragma "
                                        "with why the race is benign",
                                file=fn.filename, line=line, col=col))
            peers = list(mod.functions.values()) + [
                m for ms in mod.classes.values() for m in ms.values()]
            for name, accs in fn.global_accesses.items():
                if any(g is not None for p in peers
                       for g, _, _, _ in p.global_accesses.get(
                           name, ())):
                    continue
                shared = any(
                    p is not fn and name in p.global_accesses
                    and roots.get(p.ref, set())
                    - roots.get(fn.ref, set())
                    for p in peers)
                if not shared:
                    continue
                for guard, line, col, is_write in accs:
                    if is_write and guard is None:
                        self.diags.append(Diagnostic(
                            rule="unguarded-root-write",
                            message=f"module global {name!r} written "
                                    f"in thread root {fn.name}() with "
                                    "no lock while functions on other "
                                    "thread roots touch it — guard "
                                    "both sides or pragma with why "
                                    "the race is benign",
                            file=fn.filename, line=line, col=col))


def find_cycle(edges: Set[Tuple[str, str]]) -> Optional[List[str]]:
    """A cycle in the digraph as a node list (start not repeated), or
    None.  Deterministic: nodes and neighbors visited in sorted order.
    Shared by the static rule and the runtime recorder's assertion
    (analysis/lockorder.py)."""
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    for k in adj:
        adj[k].sort()
    color: Dict[str, int] = {}   # 1 = on stack, 2 = done
    path: List[str] = []

    def dfs(u: str) -> Optional[List[str]]:
        color[u] = 1
        path.append(u)
        for v in adj.get(u, ()):
            c = color.get(v, 0)
            if c == 1:
                return path[path.index(v):]
            if c == 0:
                found = dfs(v)
                if found is not None:
                    return found
        path.pop()
        color[u] = 2
        return None

    for node in sorted(adj):
        if color.get(node, 0) == 0:
            found = dfs(node)
            if found is not None:
                return found
    return None


def check_concurrency(sources: Dict[str, str]
                      ) -> Tuple[List[Diagnostic], int]:
    """Run the whole-program concurrency pass over ``{filename:
    source}``; returns (diagnostics, n_suppressed), pragmas already
    applied per file."""
    analyzer = ConcurrencyAnalyzer(sources)
    raw = analyzer.run()
    by_file: Dict[str, List[Diagnostic]] = {}
    for d in raw:
        by_file.setdefault(d.file, []).append(d)
    kept: List[Diagnostic] = []
    suppressed = 0
    for filename, diags in by_file.items():
        k, s = apply_pragmas(diags, sources.get(filename, ""))
        kept.extend(k)
        suppressed += s
    return kept, suppressed


def static_lock_graph(sources: Dict[str, str]) -> Set[Tuple[str, str]]:
    """The static acquisition-order digraph over a source set (edge =
    (held, acquired) lock keys) — the half the runtime recorder
    (analysis/lockorder.py) is checked against."""
    analyzer = ConcurrencyAnalyzer(sources)
    analyzer.run()
    return set(analyzer.edges)


def lock_sites(sources: Dict[str, str]) -> Dict[Tuple[str, int], str]:
    """(abspath, lineno) of every lock declaration -> its static lock
    key, for mapping the runtime recorder's creation sites onto the
    static graph's vocabulary."""
    analyzer = ConcurrencyAnalyzer(sources)
    analyzer.collect()
    return dict(analyzer.lock_sites)
