"""fcheck-cost: static compute-cost & roofline model of the serving
stack — the FLOP/byte complement of fcheck-footprint's memory model.

fcheck-footprint answered "will this executable *fit*"; nothing yet
answered "what will it *cost*".  The gap has a price the repo has
already measured: fcqual proved on-device that most lfr1k vertices
leave the active frontier after round 1 (``frontier_frac_by_round``
0.807 -> 0.059 in the committed quality artifact), yet the engine
re-runs the base detector over ALL n vertices every round — exactly
the waste vertex-parallel Louvain and pruning formulations eliminate.
Before the frontier-masking and batched-first tentpoles land, this
module prices the surface so those PRs have a quantified bill to
shrink, and so the serving layer stops guessing ``1.0 s`` for buckets
it has never timed.

1. **Eqn-level cost visitor** (:func:`eqn_cost`): walks a traced
   jaxpr and accumulates FLOPs (``dot_general`` = 2*M*N*K, scatter
   family = one update-add per update element, elementwise = one op
   per output element) and HBM byte traffic (operand + result bytes
   per equation — deliberately fusion-blind, so the model is a
   conservative ceiling exactly like ``peak_live_bytes``), recursing
   through pjit/cond/scan sub-jaxprs and bounding ``while`` trip
   counts by the sweep budget mirrored from models/louvain.py
   (:data:`MAX_SWEEPS`).  ``cond`` branches price at the max branch.
2. **Jax-free ladder mirror** (:func:`mirror_cost`): a closed-form
   fit of the visitor over the bucket ladder, split at the
   matmul/hash detection-path flip (``MATMUL_MAX_N``), linear in
   ensemble width and batch rung.  The mirror is what the pre-commit
   hook, the fixture postures and the *runtime* consume — priors must
   never import jax.  Fit coefficients are pinned against the traced
   visitor by tests/test_cost.py (ratio band).
3. **Roofline** (:class:`MachineModel`): ``est_device_s =
   max(flops/peak_flops, hbm_bytes/bandwidth) + dispatch overhead``.
   The default machine is the CPU CI host's effective envelope,
   calibrated so the modeled ``rounds`` executable at the committed
   serve_load bucket lands inside the measured ``serve.phase.device``
   band — and *kept* calibrated by the bench_report gate below.
4. **Runtime feedback**: :func:`static_service_prior` (the cold
   ``TrafficShaper`` / ``LatencyRegistry.service_estimate`` fallback
   that replaces the hardcoded 1.0 s guess) and :func:`spill_weight`
   (``StickyScheduler`` backlog weighting — a queued 100 s bucket is
   not the same backlog as a queued 50 ms bucket).

Three fcheck rules ride on the model (all jax-free via the mirror, so
``--only`` with cost rules keeps ``--no-jaxpr`` semantics trivially):

* ``cost-dead-compute``    — the fraction of a full consensus run's
  rounds-executable FLOPs attributable to vertices a frontier mask
  would freeze (computed from the committed fcqual frontier series,
  assuming vertex-proportional round cost) exceeds the pinned waste
  budget (``--waste-budget``).  The committed ``runs/cost_r16.json``
  artifact carries the bill per round — the target number the
  frontier-masking PR must shrink.
* ``cost-duality``         — prices the solo-vs-batch executable
  duality per representative bucket: the per-job batched cost must
  save at least ``duality_min_saving`` of the solo cost (default 0.0:
  batching must never be worse per job).  This is the measured cost
  of the two-path engine the batched-first refactor removes.
* ``cost-roofline-regress``— fixture mode: the mirror's
  ``est_device_s`` for a ``"kind@bucket"`` baseline entry grew beyond
  ``regress_frac``.  The history-facing twin lives in
  obs/history.check_costs: the newest committed ``runs/cost_rNN.json``
  vs its predecessor, per gate row.

**Fixture mode**: a scanned source file may define a module-level
``COST_SPEC = {...}`` literal (see :meth:`CostSpec.from_mapping`);
the analyzer evaluates the rules against that posture — how the
bad_/ok_ fixtures in tests/analysis_fixtures/ drive each rule.

**Report / artifact schema** (the ``cost`` block of the ``--json``
report, and the committed ``runs/cost_rNN.json`` artifact rendered
and gated by ``scripts/bench_report.py``)::

    {
      "tool": "fcheck-cost", "version": 1,
      "config":  {max_nodes, max_edges, max_batch, n_p, algorithm,
                  waste_budget, duality_min_saving, regress_frac,
                  peak_flops, hbm_bytes_per_s, dispatch_overhead_s},
      "frontier_series": [...],        # fcqual frontier_frac_by_round
      "dead_compute": {bucket, n_p, rounds, round_flops,
                       per_round: [{round, frontier_frac, dead_frac,
                                    dead_flops}...],
                       run_dead_frac, late_round_dead_frac,
                       waste_budget},
      "duality": [ {bucket, batch, solo_est_s, batch_est_s,
                    per_job_est_s, per_job_saving_frac} ... ],
      "gate": [ {kind, bucket, batch, flops, hbm_bytes,
                 arith_intensity, est_device_s} ... ],   # traced
      "buckets": [ {bucket, n_class, e_class, flops, hbm_bytes,
                    arith_intensity, est_device_s} ... ], # mirror
      "calibration": {bucket, n_p, kind, est_device_ms}   # traced
    }

``gate`` / ``buckets`` / ``calibration`` are filled on full package
scans only (they trace); the rules themselves never do.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Tuple

from fastconsensus_tpu.analysis.diagnostics import Diagnostic
from fastconsensus_tpu.analysis.footprint import (
    BATCH_RUNGS, MATMUL_MAX_N, MIN_NODE_CLASS, SurfaceSpec, _aval_bytes,
    batch_rungs, edge_classes, grid_up, reachable)

COST_RULES = ("cost-dead-compute", "cost-duality", "cost-roofline-regress")

# --------------------------------------------------------------------
# CI-pinned budgets and the committed frontier series.
# --------------------------------------------------------------------

# Run-level dead-compute budget.  The committed fcqual frontier series
# bills 61% of the lfr1k run's rounds-executable FLOPs to frozen
# vertices (late rounds ~89%); 0.75 passes that measured bill with
# headroom while a frontier that collapses even faster (more waste per
# run) trips the rule and forces the masking work.
WASTE_BUDGET_DEFAULT = 0.75

# Per-job batched saving floor: batching must never cost MORE per job
# than solo dispatch (the whole point of the rung ladder); any
# positive floor is a posture choice (fixtures pin the rule with 0.9).
DUALITY_MIN_SAVING_DEFAULT = 0.0

# est_device_s growth vs a committed baseline that counts as a
# roofline regression (fixture mode here; obs/history.check_costs
# applies the same default across committed cost artifacts).
REGRESS_FRAC_DEFAULT = 0.5

# The committed fcqual frontier trajectory
# (runs/bench_lfr1k_quality_r12.json telemetry.quality
# .frontier_frac_by_round) — the measured fraction of vertices still
# active entering each round.  Pinned against the artifact by
# tests/test_cost.py so the dead-compute bill always reflects what the
# device actually measured, not a stale copy.
FRONTIER_SERIES_DEFAULT = (0.807, 0.533, 0.161, 0.059)

# The lfr1k posture the dead-compute bill prices: synth.lfr_graph(1000,
# 0.3) -> 5638 edges -> bucket n1024_e6144 at the fcqual config's
# ensemble width (n_p=20).
DEAD_BUCKET_DEFAULT = (1024, 6144)
DEAD_N_P_DEFAULT = 20

# models/louvain.py local_move sweep budget (``max_sweeps`` default) —
# the trip bound the visitor applies to every ``lax.while_loop`` and
# the iteration count baked into the mirror fits.  Mirrored here so
# the jax-free half never imports the model; pinned by tests.
MAX_SWEEPS = 32

# --------------------------------------------------------------------
# The machine model (roofline).
# --------------------------------------------------------------------

# Effective envelope of the CPU CI host, calibrated against the
# committed serve_load history: the modeled rounds executable at
# bucket n64_e96 / n_p=4 must land within CALIBRATION_BAND of the
# measured serve.phase.device p95 at the reference RPS
# (runs/bench_serve_load_r10.json: 13.03 ms; the model says ~10.9 ms).
# A TPU deployment passes its chip's real numbers via CostSpec.
PEAK_FLOPS_DEFAULT = 4.0e11          # sustained FLOP/s
HBM_BW_DEFAULT = 4.0e11              # sustained bytes/s
DISPATCH_OVERHEAD_S_DEFAULT = 5.0e-4  # per-executable dispatch cost

# Predicted-vs-measured ratio the bench_report calibration gate
# tolerates (either direction).  The static model is a fusion-blind
# ceiling driven by worst-case trip counts, so it will not be exact —
# but drifting past 4x in either direction means the priors feeding
# the shaper/scheduler have come unmoored from the hardware.
CALIBRATION_BAND = 4.0


@dataclasses.dataclass(frozen=True)
class MachineModel:
    """Roofline envelope: time = max(compute, traffic) + dispatch."""

    peak_flops: float = PEAK_FLOPS_DEFAULT
    hbm_bytes_per_s: float = HBM_BW_DEFAULT
    dispatch_overhead_s: float = DISPATCH_OVERHEAD_S_DEFAULT

    def est_device_s(self, flops: float, hbm_bytes: float) -> float:
        return max(flops / self.peak_flops,
                   hbm_bytes / self.hbm_bytes_per_s) \
            + self.dispatch_overhead_s


# --------------------------------------------------------------------
# The posture (COST_SPEC fixture mode).
# --------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CostSpec:
    """One serving posture priced by the cost pass.  Surface bounds
    mirror ``serve.server.ServeConfig`` admission defaults (same as
    footprint.SurfaceSpec, pinned by tests)."""

    max_nodes: int = 1 << 20
    max_edges: int = 1 << 22
    max_batch: int = 8
    n_p: int = 20                      # ConsensusConfig default
    algorithm: str = "louvain"
    waste_budget: float = WASTE_BUDGET_DEFAULT
    duality_min_saving: float = DUALITY_MIN_SAVING_DEFAULT
    regress_frac: float = REGRESS_FRAC_DEFAULT
    frontier_series: Tuple[float, ...] = FRONTIER_SERIES_DEFAULT
    # Fixture-mode roofline baseline: {"kind@bucket" or
    # "kind@bucket:b": est_device_s} — cost-roofline-regress compares
    # the mirror against these (the history twin compares committed
    # artifacts instead).
    baseline: Optional[Dict[str, float]] = None
    peak_flops: float = PEAK_FLOPS_DEFAULT
    hbm_bytes_per_s: float = HBM_BW_DEFAULT
    dispatch_overhead_s: float = DISPATCH_OVERHEAD_S_DEFAULT
    # Restrict evaluation to these rules (fixture mode; None = all).
    rules: Optional[Tuple[str, ...]] = None
    origin: str = "<defaults>"
    origin_line: int = 0

    _KEYS = ("max_nodes", "max_edges", "max_batch", "n_p", "algorithm",
             "waste_budget", "duality_min_saving", "regress_frac",
             "frontier_series", "baseline", "peak_flops",
             "hbm_bytes_per_s", "dispatch_overhead_s", "rules")

    @classmethod
    def from_mapping(cls, d: Dict, origin: str = "<spec>",
                     origin_line: int = 0) -> "CostSpec":
        unknown = set(d) - set(cls._KEYS)
        if unknown:
            raise ValueError(
                f"{origin}: unknown COST_SPEC key(s) "
                f"{sorted(unknown)}; known: {list(cls._KEYS)}")
        kw = dict(d)
        for k in ("frontier_series", "rules"):
            if kw.get(k) is not None:
                kw[k] = tuple(kw[k])
        if kw.get("baseline") is not None and \
                not isinstance(kw["baseline"], dict):
            raise ValueError(
                f"{origin}: COST_SPEC baseline must be a dict of "
                f"'kind@bucket' -> est_device_s")
        if kw.get("rules"):
            bad = set(kw["rules"]) - set(COST_RULES)
            if bad:
                raise ValueError(
                    f"{origin}: COST_SPEC rules {sorted(bad)} are "
                    f"not cost rules {list(COST_RULES)}")
        return cls(origin=origin, origin_line=origin_line, **kw)

    def wants(self, rule: str) -> bool:
        return self.rules is None or rule in self.rules

    def machine(self) -> MachineModel:
        return MachineModel(self.peak_flops, self.hbm_bytes_per_s,
                            self.dispatch_overhead_s)

    def surface(self) -> SurfaceSpec:
        """The footprint-side view of this posture (grid enumeration
        helpers are shared, not re-mirrored)."""
        return SurfaceSpec(max_nodes=self.max_nodes,
                           max_edges=self.max_edges,
                           max_batch=self.max_batch, n_p=self.n_p,
                           algorithm=self.algorithm)


def find_specs(paths: Iterable[str]) -> List[CostSpec]:
    """Module-level ``COST_SPEC = {...}`` literals in the scanned
    sources (fixture mode).  Non-literal or unknown-key specs raise
    ValueError — a typo'd fixture must not silently evaluate defaults.
    """
    import ast
    import os

    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", "build"))
                files.extend(os.path.join(root, f) for f in sorted(names)
                             if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    specs: List[CostSpec] = []
    for f in files:
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=f)
        # fcheck: ok=swallowed-error (unreadable/unparsable
        # files are astlint's finding; the spec scan skips them)
        except (OSError, SyntaxError):
            continue
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "COST_SPEC"
                    for t in node.targets):
                d = ast.literal_eval(node.value)   # ValueError on junk
                if not isinstance(d, dict):
                    raise ValueError(
                        f"{f}:{node.lineno}: COST_SPEC must be a "
                        f"dict literal")
                specs.append(CostSpec.from_mapping(
                    d, origin=f, origin_line=node.lineno))
    return specs


# --------------------------------------------------------------------
# The eqn-level visitor (needs a traced jaxpr; never imports jax
# itself — footprint._aval_bytes handles the dtype arithmetic).
# --------------------------------------------------------------------

# Pure data movement: priced in bytes only (a copy is traffic, not
# arithmetic).  gather rides here — its cost is the indexed traffic.
_MOVEMENT_PRIMS = frozenset({
    "reshape", "broadcast_in_dim", "transpose", "convert_element_type",
    "squeeze", "expand_dims", "slice", "dynamic_slice",
    "dynamic_update_slice", "concatenate", "pad", "rev", "gather",
    "iota", "copy", "stop_gradient", "select_n", "bitcast_convert_type",
    "device_put", "real", "imag",
})

_CALL_JAXPR_PRIMS = ("pjit", "closed_call", "core_call", "custom_jvp_call",
                     "custom_vjp_call", "remat", "checkpoint", "xla_call")


def _nelems(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


def _io_bytes(eqn) -> int:
    total = 0
    for v in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None:
            total += _aval_bytes(aval)
    return total


def _sub_jaxpr_params(eqn) -> List:
    subs = []
    for v in eqn.params.values():
        if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
            subs.append(v)
        elif isinstance(v, (tuple, list)):
            subs.extend(el for el in v
                        if hasattr(el, "eqns") or hasattr(el, "jaxpr"))
    return subs


def eqn_cost(jaxpr, while_bound: int = MAX_SWEEPS) -> Dict[str, float]:
    """FLOPs + HBM byte traffic of a traced (closed) jaxpr.

    Counting rules (a conservative ceiling, like peak_live_bytes):

    * ``dot_general``: 2 * output elements * contracted extent (MACs
      count as two ops, the roofline convention).
    * scatter family: one combine op per update element.
    * movement primitives: bytes only.
    * everything else: one op per output element (elementwise model).
    * bytes: operand + result bytes of every equation — fusion-blind
      by design (XLA fusion only ever lowers true traffic).
    * ``while``: cond + body x ``while_bound`` (the sweep budget —
      data-dependent trips cannot be known statically, so the model
      prices the budget the kernel itself enforces); ``scan``: body x
      ``length``; ``cond``: the max-cost branch; call primitives: the
      sum of their sub-jaxprs.
    """
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    flops = 0.0
    hbm = 0.0
    for eqn in inner.eqns:
        name = eqn.primitive.name
        if name == "while":
            cf = eqn_cost(eqn.params["cond_jaxpr"], while_bound)
            bf = eqn_cost(eqn.params["body_jaxpr"], while_bound)
            flops += while_bound * (cf["flops"] + bf["flops"])
            hbm += while_bound * (cf["hbm_bytes"] + bf["hbm_bytes"])
        elif name == "cond":
            branches = [eqn_cost(br, while_bound)
                        for br in eqn.params["branches"]]
            flops += max(b["flops"] for b in branches)
            hbm += max(b["hbm_bytes"] for b in branches)
        elif name == "scan":
            body = eqn_cost(eqn.params["jaxpr"], while_bound)
            length = int(eqn.params.get("length", 1))
            flops += length * body["flops"]
            hbm += length * body["hbm_bytes"]
        elif name == "dot_general":
            (lhs_c, _), _ = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval
            k = 1
            for d in lhs_c:
                k *= int(lhs.shape[d])
            flops += 2.0 * _nelems(eqn.outvars[0].aval) * k
            hbm += _io_bytes(eqn)
        elif name.startswith("scatter"):
            flops += float(_nelems(eqn.invars[-1].aval))
            hbm += _io_bytes(eqn)
        elif name in _MOVEMENT_PRIMS:
            hbm += _io_bytes(eqn)
        else:
            subs = _sub_jaxpr_params(eqn)
            if subs:
                for sub in subs:
                    c = eqn_cost(sub, while_bound)
                    flops += c["flops"]
                    hbm += c["hbm_bytes"]
            else:
                flops += float(sum(_nelems(v.aval)
                                   for v in eqn.outvars))
                hbm += _io_bytes(eqn)
    return {"flops": flops, "hbm_bytes": hbm}


def _trace_cost(kind: str, n_class: int, e_class: int, b: int, mode: str,
                spec: CostSpec) -> Dict[str, float]:
    """Trace one surface executable and run the visitor.  Memoized per
    process alongside the footprint trace cache (same entry points)."""
    key = (kind, n_class, e_class, b, mode, spec.n_p, spec.algorithm)
    try:
        return _COST_CACHE[key]
    # fcheck: ok=swallowed-error (cache miss, not an error:
    # the trace below fills the entry)
    except KeyError:
        pass
    import logging

    from fastconsensus_tpu.analysis import entrypoints as eps

    logger = logging.getLogger("fastconsensus_tpu")
    level = logger.level
    logger.setLevel(logging.ERROR)   # hash-cap warnings are expected at
    try:                             # frontier shapes; keep CI logs clean
        closed = eps.trace_serving_executable(
            kind, n_class, e_class, b=b, mode=mode, n_p=spec.n_p,
            algorithm=spec.algorithm)
    finally:
        logger.setLevel(level)
    res = eqn_cost(closed)
    _COST_CACHE[key] = res
    return res


_COST_CACHE: Dict[Tuple, Dict[str, float]] = {}


# --------------------------------------------------------------------
# The jax-free ladder mirror.
# --------------------------------------------------------------------
#
# Closed-form fits of the visitor over the bucket ladder, per kind and
# detection-path regime (matmul: n <= MATMUL_MAX_N; hash above), each
# linear in ensemble width n_p and batch rung b.  The MAX_SWEEPS^2
# nested sweep bound is baked into the coefficients (the rounds block
# nests the local-move sweep loop inside the convergence loop).
# Coefficients are least-squares fits of the traced visitor at ladder
# buckets; tests/test_cost.py pins traced/mirror inside a ratio band.

# rounds block, matmul regime: per-n_p flops ~ c3*n^3 + c2*n^2 + ce*e
_ROUNDS_MM_F = (2046.0, 19500.0, 1600.0)
# ...bytes ~ d2*n^2 + dn*n + de*e
_ROUNDS_MM_B = (2.37e5, 6.0e5, 2.55e5)
# rounds block, hash regime: per-n_p flops ~ fn*n + fe*e
_ROUNDS_HASH_F = (9.1e4, 3.48e5)
_ROUNDS_HASH_B = (1.32e6, 6.54e6)
# final detect, matmul regime: per-n_p flops ~ c3*n^3 + c2*n^2
_DETECT_MM_F = (64.0, 610.0)
_DETECT_MM_B = (7400.0, 2.5e4)
_DETECT_HASH_F = (2.4e3, 1.1e4)
_DETECT_HASH_B = (4.5e4, 2.0e5)
# tail merge: ~ (n + e) with a weak ensemble-width term
_TAIL_F = 300.0
_TAIL_B = 6000.0


def _mirror_rounds(n: int, e: int) -> Tuple[float, float]:
    if n <= MATMUL_MAX_N:
        c3, c2, ce = _ROUNDS_MM_F
        d2, dn, de = _ROUNDS_MM_B
        return (c3 * n ** 3 + c2 * n ** 2 + ce * e,
                d2 * n ** 2 + dn * n + de * e)
    fn, fe = _ROUNDS_HASH_F
    bn, be = _ROUNDS_HASH_B
    return (fn * n + fe * e, bn * n + be * e)


def _mirror_detect(n: int, e: int) -> Tuple[float, float]:
    if n <= MATMUL_MAX_N:
        c3, c2 = _DETECT_MM_F
        d2, dn = _DETECT_MM_B
        return (c3 * n ** 3 + c2 * n ** 2, d2 * n ** 2 + dn * n)
    fn, fe = _DETECT_HASH_F
    bn, be = _DETECT_HASH_B
    return (fn * n + fe * e, bn * n + be * e)


def mirror_cost(kind: str, n_class: int, e_class: int, b: int = 1,
                n_p: int = 20) -> Dict[str, float]:
    """Jax-free {flops, hbm_bytes} for one surface executable.  ``kind``
    accepts the surface vocabulary with or without the ``[mode]``
    suffix — warm/cold/scratch share one traced program, so the mode
    never changes the modeled cost (compile time is not priced here).
    """
    base = kind.split("[", 1)[0]
    n, e = int(n_class), int(e_class)
    npp, bb = max(int(n_p), 1), max(int(b), 1)
    if base in ("rounds", "batch"):
        f, by = _mirror_rounds(n, e)
        return {"flops": f * npp * bb, "hbm_bytes": by * npp * bb}
    if base in ("detect", "detect-batch"):
        f, by = _mirror_detect(n, e)
        return {"flops": f * npp * bb, "hbm_bytes": by * npp * bb}
    if base == "tail":
        scale = (n + e) * (1.0 + npp / 16.0)
        return {"flops": _TAIL_F * scale, "hbm_bytes": _TAIL_B * scale}
    raise ValueError(f"unknown surface kind {kind!r}")


def mirror_est_s(kind: str, n_class: int, e_class: int, b: int = 1,
                 n_p: int = 20,
                 machine: Optional[MachineModel] = None) -> float:
    """Jax-free roofline seconds for one surface executable."""
    m = machine or MachineModel()
    c = mirror_cost(kind, n_class, e_class, b=b, n_p=n_p)
    return m.est_device_s(c["flops"], c["hbm_bytes"])


# --------------------------------------------------------------------
# Runtime feedback: static priors for the shaper / scheduler.
# --------------------------------------------------------------------

_BUCKET_KEY_RE = re.compile(r"^n(\d+)_e(\d+)$")

# One backlog unit for the spill weighting = a job this long.  The
# scheduler's spill_backlog counts jobs; weighting by
# est_device_s/unit makes a queued 100 s bucket weigh its true drain
# time while sub-second buckets keep weight 1.0 (identical routing to
# the unweighted era — pinned by the fcpool CI smoke).
SPILL_COST_UNIT_S = 1.0
SPILL_WEIGHT_MAX = 16.0


def parse_bucket_key(bucket_key: str) -> Optional[Tuple[int, int]]:
    """``"n64_e96" -> (64, 96)``; None for anything unparseable (batch
    group keys, mesh-tier tags — callers fall back to history-only)."""
    m = _BUCKET_KEY_RE.match(str(bucket_key or ""))
    if not m:
        return None
    return int(m.group(1)), int(m.group(2))


def static_service_prior(bucket_key: str, n_p: int = 20,
                         algorithm: str = "louvain",
                         machine: Optional[MachineModel] = None
                         ) -> Optional[float]:
    """Cold-start device-seconds prior for one bucket: the mirrored
    roofline estimate of the solo rounds executable (the executable a
    cold bucket's first job runs).  Jax-free and pure arithmetic —
    safe on every admission path.  None when the key is not a ladder
    bucket.  ``algorithm`` is accepted for signature parity with the
    estimator it seeds; the mirror prices the louvain-family surface
    either way (lpm executables are strictly cheaper — the prior stays
    a ceiling).
    """
    parsed = parse_bucket_key(bucket_key)
    if parsed is None:
        return None
    n, e = parsed
    return mirror_est_s("rounds", n, e, b=1, n_p=n_p, machine=machine)


def spill_weight(bucket_key: str, n_p: int = 20) -> float:
    """StickyScheduler backlog weight: queued jobs of this bucket count
    as ``est_device_s / SPILL_COST_UNIT_S`` backlog units each, clamped
    to [1, SPILL_WEIGHT_MAX] — sub-unit buckets route exactly as the
    unweighted era did; a bucket whose jobs run for minutes spills off
    a busy home after a single queued job instead of serializing."""
    prior = static_service_prior(bucket_key, n_p=n_p)
    if prior is None:
        return 1.0
    return min(max(prior / SPILL_COST_UNIT_S, 1.0), SPILL_WEIGHT_MAX)


# --------------------------------------------------------------------
# The rules (all jax-free via the mirror).
# --------------------------------------------------------------------


def dead_compute_bill(spec: CostSpec) -> Dict:
    """The frontier dead-compute bill: per round, the fraction of the
    rounds executable's FLOPs spent on vertices the committed fcqual
    frontier series says have already left the active set (assuming
    vertex-proportional round cost — the vertex-parallel formulation's
    premise).  Priced at the lfr1k posture the series was measured on.
    """
    n, e = DEAD_BUCKET_DEFAULT
    n = grid_up(min(n, spec.max_nodes), MIN_NODE_CLASS)
    e = grid_up(min(e, spec.max_edges), MIN_NODE_CLASS)
    n_p = DEAD_N_P_DEFAULT
    round_cost = mirror_cost("rounds", n, e, b=1, n_p=n_p)
    round_flops = round_cost["flops"]
    series = [float(f) for f in spec.frontier_series]
    per_round = []
    for i, frac in enumerate(series):
        dead = max(0.0, min(1.0, 1.0 - frac))
        per_round.append({
            "round": i + 1,
            "frontier_frac": round(frac, 6),
            "dead_frac": round(dead, 6),
            "dead_flops": int(round_flops * dead),
        })
    dead_fracs = [r["dead_frac"] for r in per_round]
    run_dead = sum(dead_fracs) / len(dead_fracs) if dead_fracs else 0.0
    late = dead_fracs[len(dead_fracs) // 2:] or [0.0]
    return {
        "bucket": f"n{n}_e{e}",
        "n_p": n_p,
        "rounds": len(series),
        "round_flops": int(round_flops),
        "per_round": per_round,
        "run_dead_frac": round(run_dead, 6),
        "late_round_dead_frac": round(sum(late) / len(late), 6),
        "waste_budget": spec.waste_budget,
    }


def check_dead_compute(spec: CostSpec) -> Tuple[List[Diagnostic], Dict]:
    bill = dead_compute_bill(spec)
    diags: List[Diagnostic] = []
    if bill["run_dead_frac"] > spec.waste_budget:
        diags.append(Diagnostic(
            rule="cost-dead-compute",
            message=(
                f"frontier dead-compute bill: {bill['run_dead_frac']:.2f}"
                f" of the run's rounds-executable FLOPs at "
                f"{bill['bucket']} go to vertices the measured frontier "
                f"series has already frozen (late rounds "
                f"{bill['late_round_dead_frac']:.2f}), over the "
                f"{spec.waste_budget:.2f} waste budget "
                f"(--waste-budget): land the frontier mask or re-pin "
                f"the budget with the quantified bill"),
            file=spec.origin, line=spec.origin_line))
    return diags, bill


def _rep_buckets(spec: CostSpec) -> List[Tuple[int, int]]:
    """Representative buckets the duality table and the traced gate
    price: the ladder floor, the matmul-regime top (the detection-path
    flip), and a hash-regime bucket — clamped to the posture."""
    surface = spec.surface()
    cands = [(MIN_NODE_CLASS, grid_up(96, MIN_NODE_CLASS)),
             (MATMUL_MAX_N, grid_up(3 * MATMUL_MAX_N // 2,
                                    MIN_NODE_CLASS)),
             (4 * MATMUL_MAX_N, grid_up(8 * MATMUL_MAX_N,
                                        MIN_NODE_CLASS))]
    out = []
    for n, e in cands:
        n = grid_up(min(n, spec.max_nodes), MIN_NODE_CLASS)
        e = grid_up(min(e, spec.max_edges), MIN_NODE_CLASS)
        if reachable(n, e, surface) and (n, e) not in out:
            out.append((n, e))
    return out


def duality_table(spec: CostSpec) -> List[Dict]:
    """Per representative bucket at the top batch rung: the solo
    executable, the batched executable, and the per-job saving the
    rung buys (dispatch amortization under the roofline).  This is the
    price sheet of the solo/batch engine duality — what the
    batched-first refactor collapses to one path."""
    machine = spec.machine()
    top = batch_rungs(spec.max_batch)[-1]
    rows: List[Dict] = []
    for n, e in _rep_buckets(spec):
        solo = mirror_est_s("rounds", n, e, b=1, n_p=spec.n_p,
                            machine=machine)
        if top > 1:
            batch = mirror_est_s("batch", n, e, b=top, n_p=spec.n_p,
                                 machine=machine)
        else:
            batch = solo
        per_job = batch / max(top, 1)
        saving = 1.0 - per_job / solo if solo > 0 else 0.0
        rows.append({
            "bucket": f"n{n}_e{e}",
            "batch": top,
            "solo_est_s": round(solo, 9),
            "batch_est_s": round(batch, 9),
            "per_job_est_s": round(per_job, 9),
            "per_job_saving_frac": round(saving, 6),
        })
    return rows


def check_duality(spec: CostSpec) -> Tuple[List[Diagnostic], List[Dict]]:
    rows = duality_table(spec)
    diags: List[Diagnostic] = []
    for row in rows:
        if row["per_job_saving_frac"] < spec.duality_min_saving:
            diags.append(Diagnostic(
                rule="cost-duality",
                message=(
                    f"solo/batch duality at {row['bucket']}: the "
                    f"B={row['batch']} rung saves "
                    f"{row['per_job_saving_frac']:.3f} per job over "
                    f"solo dispatch ({row['per_job_est_s']:.6f}s vs "
                    f"{row['solo_est_s']:.6f}s), under the "
                    f"{spec.duality_min_saving:.3f} floor — the "
                    f"two-path surface costs more than it returns "
                    f"here"),
                file=spec.origin, line=spec.origin_line))
            break   # one finding prices the posture; rows carry the rest
    return diags, rows


_BASELINE_KEY_RE = re.compile(
    r"^(?P<kind>[a-z-]+(?:\[[a-z]+\])?)@n(?P<n>\d+)_e(?P<e>\d+)"
    r"(?::(?P<b>\d+))?$")


def check_regress(spec: CostSpec) -> List[Diagnostic]:
    """Fixture-mode roofline regression: mirror estimates vs the
    spec's committed baseline map.  (The committed-artifact twin is
    obs/history.check_costs.)"""
    if not spec.baseline:
        return []
    machine = spec.machine()
    diags: List[Diagnostic] = []
    for key in sorted(spec.baseline):
        m = _BASELINE_KEY_RE.match(key)
        if not m:
            raise ValueError(
                f"{spec.origin}: COST_SPEC baseline key {key!r} is not "
                f"'kind@n<N>_e<E>[:b]'")
        base_s = float(spec.baseline[key])
        b = int(m.group("b") or 1)
        est = mirror_est_s(m.group("kind"), int(m.group("n")),
                           int(m.group("e")), b=b, n_p=spec.n_p,
                           machine=machine)
        if base_s > 0 and est > base_s * (1.0 + spec.regress_frac):
            diags.append(Diagnostic(
                rule="cost-roofline-regress",
                message=(
                    f"roofline regression at {key}: modeled "
                    f"est_device_s {est:.6f}s is "
                    f"{est / base_s:.2f}x the committed baseline "
                    f"{base_s:.6f}s (tolerance "
                    f"+{spec.regress_frac:.0%}); re-baseline only "
                    f"with the perf change that justifies it"),
                file=spec.origin, line=spec.origin_line))
    return diags


# --------------------------------------------------------------------
# Traced tables (full package scans only).
# --------------------------------------------------------------------


def _gate_row(kind_label: str, kind: str, n: int, e: int, b: int,
              mode: str, spec: CostSpec,
              machine: MachineModel) -> Dict:
    c = _trace_cost(kind, n, e, b, mode, spec)
    flops, hbm = c["flops"], c["hbm_bytes"]
    return {
        "kind": kind_label,
        "bucket": f"n{n}_e{e}",
        "batch": b,
        "flops": int(flops),
        "hbm_bytes": int(hbm),
        "arith_intensity": round(flops / hbm, 6) if hbm else None,
        "est_device_s": round(machine.est_device_s(flops, hbm), 9),
    }


def gate_table(spec: CostSpec) -> List[Dict]:
    """Traced cost rows for all 16 executable kinds per representative
    bucket (4 solo + 4 per batch rung > 1 — the footprint surface
    vocabulary).  Warm/cold/scratch share one traced program, so each
    mode row re-prices the same trace: the duplication is deliberate —
    the artifact enumerates the surface the engine actually compiles.
    """
    machine = spec.machine()
    rows: List[Dict] = []
    for n, e in _rep_buckets(spec):
        solo = _trace_cost("rounds", n, e, 1, "warm", spec)
        for mode in ("warm", "scratch"):
            rows.append(_gate_row(f"rounds[{mode}]", "rounds", n, e, 1,
                                  "warm", spec, machine))
        del solo
        rows.append(_gate_row("tail", "tail", n, e, 1, "-", spec,
                              machine))
        rows.append(_gate_row("detect", "detect", n, e, 1, "-", spec,
                              machine))
        for rung in batch_rungs(spec.max_batch):
            if rung <= 1:
                continue
            for mode in ("warm", "cold", "scratch"):
                rows.append(_gate_row(f"batch[{mode}]", "batch", n, e,
                                      rung, "warm", spec, machine))
            rows.append(_gate_row("detect-batch", "detect-batch", n, e,
                                  rung, "-", spec, machine))
    return rows


def cost_table(spec: CostSpec, max_rows: int = 12) -> List[Dict]:
    """The mirror's per-bucket cost table (the artifact ``buckets``
    block): the e-spine sampled at power-of-two classes plus floor and
    top, each at its densest-connected node class, solo rounds."""
    machine = spec.machine()
    surface = spec.surface()
    es = edge_classes(surface)
    spine = [e for e in es if e & (e - 1) == 0]
    for must in (es[0], es[-1]):
        if must not in spine:
            spine.append(must)
    spine = sorted(set(spine))
    if len(spine) > max_rows:
        idx = {0, len(spine) - 1}
        step = (len(spine) - 1) / (max_rows - 1)
        idx |= {round(i * step) for i in range(max_rows)}
        spine = [spine[i] for i in sorted(idx)]
    rows: List[Dict] = []
    for e_class in spine:
        n_class = grid_up(min(2 * e_class, spec.max_nodes),
                          MIN_NODE_CLASS)
        if not reachable(n_class, e_class, surface):
            continue
        c = mirror_cost("rounds", n_class, e_class, b=1, n_p=spec.n_p)
        flops, hbm = c["flops"], c["hbm_bytes"]
        rows.append({
            "bucket": f"n{n_class}_e{e_class}",
            "n_class": n_class, "e_class": e_class,
            "flops": int(flops),
            "hbm_bytes": int(hbm),
            "arith_intensity": round(flops / hbm, 6) if hbm else None,
            "est_device_s": round(
                machine.est_device_s(flops, hbm), 9),
        })
    return rows


# The serve_load reference posture the calibration block prices: the
# committed runs/bench_serve_load_rNN.json history drives karate-sized
# jobs (bucket n64_e96, louvain, n_p=4) and records the measured
# serve.phase.device tail per point — obs/history.check_cost_calibration
# compares this block against it within CALIBRATION_BAND.
CALIBRATION_BUCKET = (64, 96)
CALIBRATION_N_P = 4


def calibration_block(spec: CostSpec) -> Dict:
    """Traced predicted-device-time block for the serve_load reference
    posture (see CALIBRATION_BUCKET)."""
    n, e = CALIBRATION_BUCKET
    cal_spec = dataclasses.replace(spec, n_p=CALIBRATION_N_P)
    c = _trace_cost("rounds", n, e, 1, "warm", cal_spec)
    est = spec.machine().est_device_s(c["flops"], c["hbm_bytes"])
    return {
        "bucket": f"n{n}_e{e}",
        "n_p": CALIBRATION_N_P,
        "kind": "rounds[warm]",
        "est_device_ms": round(est * 1000.0, 3),
        "band": CALIBRATION_BAND,
    }


# --------------------------------------------------------------------
# Orchestration (what __main__ calls).
# --------------------------------------------------------------------


def evaluate(spec: CostSpec, rules: Optional[Iterable[str]] = None,
             with_table: bool = False
             ) -> Tuple[List[Diagnostic], Dict]:
    """Run the selected cost rules against one posture; returns
    (diagnostics, cost report block — see the module docstring
    schema).  The rules are mirror-only (never import jax);
    ``with_table`` adds the traced gate/calibration blocks (full
    package scans — the CLI pays the traces exactly where footprint
    pays its table)."""
    selected = set(rules) if rules is not None else set(COST_RULES)
    selected &= {r for r in COST_RULES if spec.wants(r)}
    diags: List[Diagnostic] = []
    block: Dict = {
        "tool": "fcheck-cost",
        "version": 1,
        "config": {
            "max_nodes": spec.max_nodes, "max_edges": spec.max_edges,
            "max_batch": spec.max_batch, "n_p": spec.n_p,
            "algorithm": spec.algorithm,
            "waste_budget": spec.waste_budget,
            "duality_min_saving": spec.duality_min_saving,
            "regress_frac": spec.regress_frac,
            "peak_flops": spec.peak_flops,
            "hbm_bytes_per_s": spec.hbm_bytes_per_s,
            "dispatch_overhead_s": spec.dispatch_overhead_s,
        },
        "frontier_series": [round(float(f), 6)
                            for f in spec.frontier_series],
        "dead_compute": None,
        "duality": [],
        "gate": [],
        "buckets": [],
        "calibration": None,
    }
    if "cost-dead-compute" in selected:
        dead_diags, bill = check_dead_compute(spec)
        diags.extend(dead_diags)
        block["dead_compute"] = bill
    if "cost-duality" in selected:
        dual_diags, rows = check_duality(spec)
        diags.extend(dual_diags)
        block["duality"] = rows
    if "cost-roofline-regress" in selected:
        diags.extend(check_regress(spec))
    if with_table:
        block["gate"] = gate_table(spec)
        block["buckets"] = cost_table(spec)
        block["calibration"] = calibration_block(spec)
    return diags, block
