"""Layer 2 of fcheck: trace registered jitted entry points and audit the
jaxprs.

The AST lint (layer 1) sees the source; this layer sees what JAX will
actually *stage*.  Every registered entry point
(analysis/entrypoints.py) is traced with canonical small shapes via
``jax.make_jaxpr`` — which alone catches tracer leaks, shape bugs and
signature drift before any device is touched — and the resulting jaxpr
(recursively, through pjit/scan/while/cond sub-jaxprs) is walked for
primitives that must never appear in this codebase's device programs:

* ``convert_element_type``/avals producing **float64/complex128** — TPUs
  have no f64; with jax's x64 mode off the cast silently downcasts, with
  it on it doubles memory and leaves the fast path (graph.py's slabs are
  strictly f32/i32/bool);
* ``device_put`` **inside a traced computation** — a host transfer
  staged into the device program (the host touches the graph exactly
  twice per run, graph.py module docstring);
* **oversized gathers** — a single gather materializing more elements
  than ``gather_threshold`` (default 2^26 ~ 256 MB of f32): the
  symptom of an accidentally dense N^2 indexing pattern escaping a
  size-gated path (louvain.MATMUL_MAX_N exists precisely to gate those).

It also records a primitive histogram per entry point (scatters, sorts,
whiles, ...) in the JSON report — drift in those counts is an early
smell of a lowering change even when nothing is outright forbidden.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from fastconsensus_tpu.analysis.diagnostics import Diagnostic

# Primitive families worth summarizing per entry point (observability;
# not errors by themselves).
_SUMMARY_PRIMS = (
    "gather", "scatter", "scatter-add", "scatter-max", "scatter-min",
    "sort", "while", "cond", "scan", "dot_general", "custom_vjp_call",
    "pjit", "psum", "all_gather", "convert_element_type",
)

_BAD_DTYPES = ("float64", "complex128")


def _iter_eqns(jaxpr) -> Iterable:
    """All equations of a (Closed)Jaxpr, recursing into sub-jaxprs."""
    import jax.core as core  # noqa: F401  (jaxpr types live here)

    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _iter_eqns(sub)


def _sub_jaxprs(eqn) -> Iterable:
    for v in eqn.params.values():
        if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
            yield v
        elif isinstance(v, (tuple, list)):
            for el in v:
                if hasattr(el, "eqns") or hasattr(el, "jaxpr"):
                    yield el


def audit_jaxpr(closed_jaxpr, name: str,
                gather_threshold: int = 1 << 26
                ) -> Tuple[List[Diagnostic], Dict[str, int]]:
    """Walk a traced jaxpr; returns (diagnostics, primitive histogram)."""
    diags: List[Diagnostic] = []
    hist: Dict[str, int] = {}
    for eqn in _iter_eqns(closed_jaxpr):
        prim = eqn.primitive.name
        if prim in _SUMMARY_PRIMS:
            hist[prim] = hist.get(prim, 0) + 1
        if prim == "convert_element_type":
            new = str(eqn.params.get("new_dtype", ""))
            if new in _BAD_DTYPES:
                diags.append(Diagnostic(
                    rule="jaxpr-f64", file=name,
                    message=f"convert_element_type to {new} staged into "
                            f"{name}: TPU paths are f32/i32 only "
                            f"(silently downcast with x64 off, 2x memory "
                            f"with it on)"))
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt in _BAD_DTYPES:
                diags.append(Diagnostic(
                    rule="jaxpr-f64", file=name,
                    message=f"{prim} produces {dt} inside {name}"))
                break
        if prim == "device_put":
            diags.append(Diagnostic(
                rule="jaxpr-device-put", file=name,
                message=f"device_put staged inside {name}: a host "
                        f"transfer in the device program (the slab "
                        f"crosses the boundary once per run — graph.py)"))
        if prim == "gather":
            out = eqn.outvars[0].aval if eqn.outvars else None
            size = 1
            for d in getattr(out, "shape", ()):
                size *= int(d)
            if size > gather_threshold:
                diags.append(Diagnostic(
                    rule="jaxpr-gather-size", file=name,
                    message=f"gather in {name} materializes {size} "
                            f"elements (> {gather_threshold}): an "
                            f"ungated dense indexing pattern "
                            f"(louvain.MATMUL_MAX_N gates the N^2 "
                            f"paths for a reason)"))
    return diags, hist


def audit_entry_points(names: Optional[List[str]] = None,
                       gather_threshold: int = 1 << 26,
                       hbm_bytes: Optional[int] = None
                       ) -> Tuple[List[Diagnostic], Dict[str, Dict[str, int]]]:
    """Trace + audit every registered entry point (or the named subset).

    A failure to trace at all is itself a diagnostic (``trace-error``):
    the canonical shapes are the contract the jitted surface must keep.

    Each entry point's summary also carries its liveness-sweep
    ``peak_bytes`` (analysis/footprint.py) — at the registry's canonical
    small shapes this is observability (drift in the peak is the memory
    analog of primitive-count drift), and with ``hbm_bytes`` set any
    entry point modeling past the budget is a ``jaxpr-peak-bytes``
    finding (the serving-surface gate at real bucket shapes lives in
    the footprint pass) — plus its cost-visitor ``flops`` and
    ``arith_intensity`` (flops per HBM byte; analysis/cost.py), so one
    table answers both "will it fit" and "what will it cost".
    """
    from fastconsensus_tpu.analysis import entrypoints as eps
    from fastconsensus_tpu.analysis.cost import eqn_cost
    from fastconsensus_tpu.analysis.footprint import peak_live_bytes

    diags: List[Diagnostic] = []
    summary: Dict[str, Dict[str, int]] = {}
    for ep in eps.entry_points():
        if names and ep.name not in names:
            continue
        try:
            closed = ep.trace()
        # fcheck: ok=swallowed-error (nothing is swallowed: the
        # handler converts the failure into a trace-error
        # diagnostic, which is this tool's error channel)
        except Exception as e:  # noqa: BLE001 — any trace failure is news
            diags.append(Diagnostic(
                rule="trace-error", file=ep.name,
                message=f"entry point failed to trace with canonical "
                        f"shapes: {type(e).__name__}: {e}"))
            continue
        d, hist = audit_jaxpr(closed, ep.name,
                              gather_threshold=gather_threshold)
        diags.extend(d)
        peak = peak_live_bytes(closed)["peak"]
        hist["peak_bytes"] = peak
        cost = eqn_cost(closed)
        hist["flops"] = int(cost["flops"])
        hist["arith_intensity"] = round(
            cost["flops"] / cost["hbm_bytes"], 6) \
            if cost["hbm_bytes"] else 0.0
        if hbm_bytes is not None and peak > hbm_bytes:
            diags.append(Diagnostic(
                rule="jaxpr-peak-bytes", file=ep.name,
                message=f"{ep.name} models {peak:,} peak live device "
                        f"bytes at its CANONICAL (small) shapes > the "
                        f"per-chip budget {hbm_bytes:,} (--hbm-bytes)"))
        summary[ep.name] = hist
    return diags, summary
