"""Layer 3 of fcheck: a runtime guard against silent retracing.

The engine's whole performance story rests on compiling each round shape
ONCE and re-running it (engine.py:_jitted_round — jit caches key on the
function object; a fresh wrapper per round cost a measured ~18 s/run
through the TPU tunnel).  Nothing in the type system enforces that: an
innocent refactor that rebuilds a partial per call, or hashes an
unstable static arg, recompiles every round and no output changes — only
the wall clock.

:class:`CompileGuard` counts XLA backend compilations via jax's
monitoring events (``/jax/core/compile/backend_compile_duration`` — one
firing per executable actually built; cache hits, including persistent
compile-cache hits, do not fire).  Use it as a context manager around a
region that must not compile more than N times:

    with CompileGuard(max_compiles=12) as g:
        run_consensus(...)
    # or g.count for reporting

The tier-1 regression test (tests/test_analysis.py) runs a 2-round
small-graph consensus under the guard and additionally asserts a second
identical run compiles ZERO times — executable reuse across runs is the
lru-cache contract the engine documents.
"""

from __future__ import annotations

import threading
from typing import List, Optional


class RecompileError(AssertionError):
    """Raised when a guarded region exceeds its compile budget."""


class CompileGuard:
    """Count backend compiles in a region; optionally bound them.

    Thread-safe counting (XLA may compile from worker threads); guards
    may nest — each counts independently.  ``events`` records the raw
    monitoring event names seen, for debugging a budget breach.

    ``registry``/``counter`` fold each observed compile into an
    fcobs-style counter registry AS IT HAPPENS (duck-typed: anything with
    ``.inc(name)`` — canonically ``fastconsensus_tpu.obs.counters
    .ObsRegistry``), so a traced run's compile count lands in the same
    artifact as its spans and host-sync counts (``bench.py`` telemetry).
    :meth:`attach` sets the same hook after construction.

    ``thread_ident`` restricts counting to compiles observed on ONE
    thread (``threading.get_ident()``): jax's monitoring events fire on
    the thread driving the compile, so a multi-worker process (the
    fcpool device workers, serve/pool.py) can attribute compiles
    per-worker with concurrent guards — an unfiltered guard in that
    process would charge worker A for executables worker B built.
    """

    _COMPILE_EVENTS = (
        "/jax/core/compile/backend_compile_duration",
    )

    def __init__(self, max_compiles: Optional[int] = None,
                 registry=None, counter: str = "xla.compiles",
                 thread_ident: Optional[int] = None) -> None:
        self.max_compiles = max_compiles
        self.count = 0
        self.events: List[str] = []
        self._lock = threading.Lock()
        self._registered = False
        self._active = False
        self._registry = registry
        self._counter = counter
        self._thread_ident = thread_ident

    def attach(self, registry, counter: str = "xla.compiles"
               ) -> "CompileGuard":
        """Mirror every observed compile into ``registry.inc(counter)``;
        returns self so it chains with the constructor/with-statement."""
        self._registry = registry
        self._counter = counter
        return self

    # -- listener ---------------------------------------------------

    def _on_event(self, name: str, duration: float, **kwargs) -> None:
        # _active gates counting even if the listener itself could not be
        # unregistered (see _unregister): jax holds the bound method, so
        # only a flag on the instance can make it inert
        if not self._active or name not in self._COMPILE_EVENTS:
            return
        if self._thread_ident is not None and \
                threading.get_ident() != self._thread_ident:
            return
        with self._lock:
            self.count += 1
            self.events.append(name)
        if self._registry is not None:
            self._registry.inc(self._counter)

    def __enter__(self) -> "CompileGuard":
        import jax.monitoring

        self._active = True
        jax.monitoring.register_event_duration_secs_listener(
            self._on_event)
        self._registered = True
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._unregister()
        if exc_type is None and self.max_compiles is not None and \
                self.count > self.max_compiles:
            raise RecompileError(
                f"guarded region compiled {self.count} executables "
                f"(budget {self.max_compiles}): something is retracing "
                f"per call — check for fresh jit wrappers or unstable "
                f"static args (engine.py:_jitted_round notes)")
        return False

    def _unregister(self) -> None:
        if not self._registered:
            return
        self._registered = False
        self._active = False  # inert even if the unregister below fails
        try:
            from jax._src import monitoring as _mon

            _mon._unregister_event_duration_listener_by_callback(
                self._on_event)
        # fcheck: ok=swallowed-error (best-effort unregister
        # against a private jax API: the comment below is the
        # whole story, and _active already neutralizes the hook)
        except Exception:
            # private API moved: the listener stays in jax's list (a
            # one-entry leak per guard) but _active keeps it a no-op
            pass


def assert_max_compiles(n: int) -> CompileGuard:
    """``with assert_max_compiles(12): ...`` — sugar over CompileGuard."""
    return CompileGuard(max_compiles=n)
