"""Layer 1 of fcheck: project-specific AST lint rules for JAX/TPU code.

The rules encode the device-side discipline this codebase's correctness
hinges on (module docstrings of ops/pallas_kernels.py, utils/prng.py,
engine.py) — invariants no runtime test can see because violating them
changes *performance* or *distributions*, not output shapes:

``key-reuse``
    The same PRNG key consumed by two draws on one execution path (or by
    a draw inside a Python loop with the key derived outside it).  JAX
    keys are not stateful; reuse silently correlates draws
    (utils/prng.py's single-tree contract).

    The rule is **cross-function**: passing a key to a helper counts by
    what the helper actually does with it.  A per-function summary
    (:func:`summarize_key_params`) classifies every key-ish parameter as
    a pure *deriver* (weight 0 — only split/fold_in-style derivations:
    safe to call repeatedly, e.g. a local ``fan_out(key, n)`` wrapper),
    a single *draw* (weight 1 — e.g. ``seg.pair_jitter``, which salts
    one ``random.bits`` from its key), or an internal *re-user* (weight
    2).  Summaries resolve through module-local defs and import aliases
    (``from ..ops import segment as seg`` -> ``seg.pair_jitter``);
    ``lint_paths`` builds the table over the whole scanned file set
    first, so the weights cross module boundaries.  Unknown callees keep
    the conservative weight of 1.

``traced-branch``
    Python ``if``/``while`` tests (or ``bool()`` casts) built from
    ``jnp.*`` calls.  Inside jit this is a tracer leak
    (ConcretizationTypeError at best); outside it is a hidden
    device->host sync.  Device-side control flow belongs to ``lax.cond``
    / ``lax.while_loop``; host-side predicates belong to numpy.

``retrace-risk``
    ``jax.jit`` called in a local scope without an ``lru_cache``-style
    decorator on the builder: jit keys its executable cache on the
    function object, so a fresh wrapper per call recompiles every time
    (engine.py:_jitted_round, measured ~18 s/run on the TPU tunnel).

``weak-static-arg``
    Static jit parameters that are positional (``static_argnums`` —
    silently wrong under keyword calls / partials) or carry unhashable
    (mutable) defaults, both of which force or break retraces.

``f64-dtype``
    ``float64`` reaching a ``jnp`` array: TPUs have no f64; with x64
    enabled this doubles memory and falls off the fast path, with it
    disabled it silently downcasts.  Host-side ``np.float64`` is fine
    and not flagged.

``sync-in-loop``
    ``.item()`` / ``.block_until_ready()`` / ``jax.device_get`` /
    ``np.asarray`` inside a Python loop — per-iteration host-device
    round-trips, the classic hot-loop killer (engine.py's bulk-readback
    notes).  Deliberate once-per-round readbacks carry a
    ``# fcheck: ok=sync-in-loop`` pragma with the reason.

``kernel-tracer-closure``
    A Pallas kernel body (a function passed to ``pl.pallas_call``)
    defined in a local scope with free variables: closing over traced
    arrays breaks Mosaic lowering (ops/pallas_kernels.py:31-33).
    Kernels must be module-level functions taking everything through
    refs or static ``functools.partial`` binds.

``module-jnp-const``
    Module-level ``jnp.*`` constant: materializes a device array at
    import time (before backend/mesh configuration) and, captured in a
    kernel, violates the closure rule above.

``mesh-axis``
    A sharding-annotation axis name — a ``PartitionSpec`` entry
    (``shard_map`` in/out specs, ``with_sharding_constraint``) or a
    collective's axis argument (``psum("p")``, ``pmax``,
    ``all_gather``, ...) — that no mesh in the module declares.  Axis
    names are stringly-typed: a typo'd axis passes every shape check
    and fails only at runtime on a real multi-chip mesh (the
    parallel/sharding.py / ops/sharded_tail.py hazard the ROADMAP
    names).  The declared set is collected from module-level
    ``*_AXIS = "name"`` constants and ``Mesh(...)`` axis-name tuples;
    modules declaring neither are exempt (the rule cannot know their
    mesh).

All rules support ``# fcheck: ok=<rule>`` suppression pragmas
(diagnostics.parse_pragmas).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from fastconsensus_tpu.analysis.diagnostics import (Diagnostic,
                                                    apply_pragmas)

# jax.random functions that *derive* keys (safe to call repeatedly on one
# key with different data) vs those that *consume* a key for a draw.
_KEY_DERIVERS = {
    "split", "fold_in", "key", "PRNGKey", "wrap_key_data", "key_data",
    "clone", "stream", "partition_keys",
}
_KEY_DRAWS = {
    "uniform", "normal", "bernoulli", "randint", "bits", "choice",
    "permutation", "categorical", "gumbel", "exponential", "laplace",
    "logistic", "truncated_normal", "beta", "dirichlet", "gamma",
    "poisson", "rademacher", "maxwell", "ball", "orthogonal", "t",
}
# jnp calls whose result in a Python bool context is a traced-value leak
# (reductions / predicates); elementwise math is excluded to keep the
# rule precise.
_TRACED_PREDICATES = {
    "any", "all", "sum", "max", "min", "mean", "prod", "count_nonzero",
    "isfinite", "isnan", "isinf", "array_equal", "allclose", "isclose",
    "logical_and", "logical_or", "logical_not", "equal", "not_equal",
    "greater", "less", "greater_equal", "less_equal", "where", "argmax",
    "argmin",
}
# The stable rule-id universe this linter can emit (CLI --only
# validation; concurrency.CONCURRENCY_RULES and the jaxpr audit's
# jaxpr-*/trace-error ids are the other families).
ASTLINT_RULES = (
    "key-reuse", "traced-branch", "retrace-risk", "weak-static-arg",
    "f64-dtype", "sync-in-loop", "kernel-tracer-closure",
    "module-jnp-const", "mesh-axis", "syntax-error",
)
_SYNC_CALLS_ATTR = {"item", "block_until_ready"}
_F64_NAMES = {"float64", "double", "complex128"}
# lax collectives whose axis argument is a mesh axis NAME; mapped to the
# positional index that argument takes (axis_name= kwarg also accepted).
_COLLECTIVE_AXIS_ARG = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1, "all_gather": 1,
    "psum_scatter": 1, "all_to_all": 1, "ppermute": 1, "pshuffle": 1,
    "axis_index": 0, "axis_size": 0,
}


def _scope_nodes(fn: ast.AST):
    """Yield nodes in ``fn``'s own scope, skipping nested function bodies
    (each nested def is linted as its own function)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _call_name(node: ast.Call) -> Tuple[Optional[str], str]:
    """(module-ish qualifier, attr/function name) of a call target."""
    f = node.func
    if isinstance(f, ast.Name):
        return None, f.id
    if isinstance(f, ast.Attribute):
        qual = None
        v = f.value
        parts = []
        while isinstance(v, ast.Attribute):
            parts.append(v.attr)
            v = v.value
        if isinstance(v, ast.Name):
            parts.append(v.id)
            qual = ".".join(reversed(parts))
        return qual, f.attr
    return None, ""


def _is_jaxish(qual: Optional[str]) -> bool:
    return qual is not None and (
        qual in ("jnp", "jax", "lax", "np_like") or
        qual.startswith("jax.") or qual.startswith("jnp."))


def _is_random_qual(qual: Optional[str]) -> bool:
    return qual is not None and (
        qual.endswith("random") or qual in ("prng",))


def _is_key_deriver(qual: Optional[str], name: str) -> bool:
    """A call that re-derives keys rather than consuming one.

    The qualifier must look PRNG-ish: ``line.split()`` (str.split) and
    other name collisions must not count as key derivations.
    """
    return name in _KEY_DERIVERS and _is_random_qual(qual)


def _contains_jnp_predicate(expr: ast.AST) -> Optional[ast.Call]:
    """A jnp reduction/predicate call anywhere inside ``expr``, if any."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            qual, name = _call_name(node)
            if name in _TRACED_PREDICATES and qual is not None and (
                    qual == "jnp" or qual.startswith("jnp.") or
                    qual in ("jax.numpy",)):
                return node
    return None


class _KeyState:
    """Per-path PRNG-key consumption counts, alias-aware.

    ``depth`` records the loop depth a key was derived at: consuming a
    key inside a loop it was derived OUTSIDE of counts double (the
    consumption repeats per iteration with the same key), while a key
    derived fresh each iteration is fine.
    """

    def __init__(self) -> None:
        self.alias: Dict[str, str] = {}   # name -> canonical key name
        self.count: Dict[str, int] = {}   # canonical -> consumptions
        self.depth: Dict[str, int] = {}   # canonical -> derivation depth
        self.site: Dict[str, Tuple[int, int]] = {}  # first consumption

    def canon(self, name: str) -> Optional[str]:
        return self.alias.get(name)

    def fresh(self, name: str, depth: int = 0) -> None:
        self.alias[name] = name
        self.count[name] = 0
        self.depth[name] = depth

    def drop(self, name: str) -> None:
        self.alias.pop(name, None)

    def copy(self) -> "_KeyState":
        s = _KeyState()
        s.alias = dict(self.alias)
        s.count = dict(self.count)
        s.depth = dict(self.depth)
        s.site = dict(self.site)
        return s

    def merge_max(self, *others: "_KeyState") -> None:
        for o in others:
            for k, v in o.count.items():
                if v > self.count.get(k, 0):
                    self.count[k] = v
                    if k in o.site:
                        self.site[k] = o.site[k]
            self.alias.update(o.alias)
            self.depth.update(o.depth)


def _key_param_names(fn: ast.FunctionDef) -> List[str]:
    """The parameters of ``fn`` the key-reuse rule tracks as PRNG keys
    (name-based, same heuristic as the intra-function seeding)."""
    out = []
    for a in (fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs):
        n = a.arg
        if n == "key" or n == "rng" or n.endswith("_key") or \
                n == "keys" or n.endswith("_keys"):
            out.append(n)
    return out


class Linter:
    def __init__(self, source: str, filename: str = "<memory>",
                 key_summaries: Optional[Dict[str, Dict[str, dict]]] = None
                 ) -> None:
        self.source = source
        self.filename = filename
        self.diags: List[Diagnostic] = []
        self.n_suppressed = 0
        # cross-function key flow (module docstring, `key-reuse`):
        # {module: {function: summary}} built by lint_paths over the
        # whole scanned set; local defs and import aliases resolve into
        # it at call sites.
        self._key_summaries = key_summaries or {}
        self._local_summaries: Dict[str, dict] = {}
        self._alias_modules: Dict[str, str] = {}
        self._from_imports: Dict[str, Tuple[str, str]] = {}
        self._summary_peaks: Optional[Dict[str, int]] = None

    def run(self) -> List[Diagnostic]:
        try:
            tree = ast.parse(self.source, filename=self.filename)
        except SyntaxError as e:
            self.diags.append(Diagnostic(
                rule="syntax-error", message=str(e.msg),
                file=self.filename, line=e.lineno or 0, col=e.offset or 0))
            return self.diags
        self._collect_imports(tree)
        self._summarize_tree(tree)
        self._module_level(tree)
        self._check_mesh_axes(tree)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(node)
            if isinstance(node, ast.Call):
                self._check_call(node)
        self.diags, self.n_suppressed = apply_pragmas(self.diags,
                                                      self.source)
        return self.diags

    # ---------------- cross-function key summaries ----------------

    def _package_parts(self) -> List[str]:
        """Dotted-path components of the package containing this file's
        module (the relative-import anchor): everything from the
        ``fastconsensus_tpu`` root down to the directory, which is the
        level-1 base for regular modules and ``__init__`` alike.  Empty
        outside the tree — bare-stem modules (fixtures, scripts) cannot
        anchor relative imports."""
        parts = os.path.normpath(
            os.path.abspath(self.filename)).split(os.sep)
        if "fastconsensus_tpu" not in parts[:-1]:
            return []
        return parts[parts.index("fastconsensus_tpu"):-1]

    def _collect_imports(self, tree: ast.Module) -> None:
        """Alias -> module map for resolving helper calls into the
        cross-module summary table (``import a.b.c as x`` and
        ``from a.b import c [as x]`` both bind x to a module; ``from
        a.b.c import fn`` binds a function — tracked separately).
        Relative imports (``from ..ops import segment as seg``) resolve
        against this file's own package path; outside the package tree
        they stay unresolved (conservative weight 1)."""
        for stmt in tree.body:
            if isinstance(stmt, ast.Import):
                for a in stmt.names:
                    if a.asname:
                        self._alias_modules[a.asname] = a.name
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.level == 0:
                    module = stmt.module
                else:
                    pkg = self._package_parts()
                    if not pkg or stmt.level - 1 >= len(pkg):
                        continue
                    base = pkg[: len(pkg) - (stmt.level - 1)]
                    module = ".".join(
                        base + ([stmt.module] if stmt.module else []))
                if not module:
                    continue
                for a in stmt.names:
                    alias = a.asname or a.name
                    # could name a submodule OR a function; record both
                    # interpretations and let lookup pick whichever the
                    # summary table actually contains
                    self._alias_modules.setdefault(
                        alias, f"{module}.{a.name}")
                    self._from_imports[alias] = (module, a.name)

    def _summarize_tree(self, tree: ast.Module) -> Dict[str, dict]:
        """Key-consumption summaries of this module's top-level
        functions: for each key-ish parameter, the max number of
        consumptions one call incurs (0 = pure deriver, 1 = one draw,
        2 = internal reuse), computed with the same path-sensitive walk
        the lint itself uses.  Methods are skipped (call-site positional
        mapping would be off by the bound ``self``).  Summaries land in
        ``self._local_summaries`` AS they are built, so a later function
        calling an earlier helper resolves it (definition order covers
        the helper-before-caller layout this codebase uses)."""
        out = self._local_summaries
        for node in tree.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            args = node.args
            pos = [a.arg for a in (args.posonlyargs + args.args)]
            if pos and pos[0] in ("self", "cls"):
                continue
            key_params = _key_param_names(node)
            if not key_params:
                continue
            state = _KeyState()
            for n in key_params:
                state.fresh(n)
            self._summary_peaks = {}
            self._walk_keys(list(node.body), state, loop_depth=0,
                            skip_defs=True)
            peaks = self._summary_peaks
            self._summary_peaks = None
            out[node.name] = {
                "name": node.name,
                "params": pos,
                "weights": {p: min(peaks.get(p, 0), 2)
                            for p in key_params},
            }
        return out

    def _lookup_summary(self, qual: Optional[str],
                        name: str) -> Optional[dict]:
        """The callee's key summary, resolved through local defs, import
        aliases, or a fully-dotted qualifier; None = unknown callee."""
        if qual is None:
            local = self._local_summaries.get(name)
            if local is not None:
                return local
            tgt = self._from_imports.get(name)
            if tgt is not None:
                return self._key_summaries.get(tgt[0], {}).get(tgt[1])
            return None
        mod = self._alias_modules.get(qual, qual)
        return self._key_summaries.get(mod, {}).get(name)

    def _diag(self, rule: str, node: ast.AST, message: str) -> None:
        self.diags.append(Diagnostic(
            rule=rule, message=message, file=self.filename,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0)))

    # ---------------- module level ----------------

    def _module_level(self, tree: ast.Module) -> None:
        for stmt in tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                value = stmt.value
                if isinstance(value, ast.Call):
                    qual, _ = _call_name(value)
                    if qual == "jnp" or qual == "jax.numpy":
                        self._diag(
                            "module-jnp-const", stmt,
                            "module-level jnp constant materializes a "
                            "device array at import time (and would break "
                            "kernel closures); use a Python scalar or "
                            "build it inside the jitted function")

    # ---------------- mesh-axis ----------------

    def _declared_axes(self, tree: ast.Module
                       ) -> Tuple[Dict[str, str], Set[str]]:
        """(axis-constant name -> value, declared axis values).

        Declarations: module-level ``FOO_AXIS = "name"`` string
        constants (the parallel/sharding.py convention — the literals
        are part of the mesh contract) and axis-name tuples passed to
        ``Mesh(...)`` (second positional arg or ``axis_names=``).
        """
        consts: Dict[str, str] = {}
        axes: Set[str] = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id.endswith("_AXIS") \
                    and isinstance(stmt.value, ast.Constant) \
                    and isinstance(stmt.value.value, str):
                consts[stmt.targets[0].id] = stmt.value.value
                axes.add(stmt.value.value)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            _, name = _call_name(node)
            if name != "Mesh":
                continue
            names_arg = node.args[1] if len(node.args) >= 2 else None
            for kw in node.keywords:
                if kw.arg == "axis_names":
                    names_arg = kw.value
            if isinstance(names_arg, (ast.Tuple, ast.List)):
                for el in names_arg.elts:
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, str):
                        axes.add(el.value)
                    elif isinstance(el, ast.Name) and el.id in consts:
                        axes.add(consts[el.id])
        return consts, axes

    def _axis_expr(self, expr: Optional[ast.AST], axes: Set[str],
                   consts: Dict[str, str], where: str) -> None:
        """Flag a string axis name (or tuple of them) not in ``axes``.
        Non-literal expressions that cannot be resolved through the
        module's axis constants are skipped (conservative)."""
        if expr is None:
            return
        if isinstance(expr, (ast.Tuple, ast.List)):
            for el in expr.elts:
                self._axis_expr(el, axes, consts, where)
            return
        value = None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            value = expr.value
        elif isinstance(expr, ast.Name) and expr.id in consts:
            value = consts[expr.id]
        if value is not None and value not in axes:
            self._diag(
                "mesh-axis", expr,
                f"axis {value!r} in {where} is not declared by any mesh "
                f"in this module (known axes: {sorted(axes)}); a typo'd "
                "axis name passes tracing and fails only at runtime on "
                "a real mesh")

    def _check_mesh_axes(self, tree: ast.Module) -> None:
        consts, axes = self._declared_axes(tree)
        if not axes:
            return  # no mesh contract declared here — nothing to check
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            qual, name = _call_name(node)
            if name in _COLLECTIVE_AXIS_ARG and qual is not None and \
                    (qual == "lax" or qual.endswith(".lax")):
                idx = _COLLECTIVE_AXIS_ARG[name]
                target = node.args[idx] if len(node.args) > idx else None
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        target = kw.value
                self._axis_expr(target, axes, consts, f"lax.{name}")
            elif name in ("P", "PartitionSpec"):
                for arg in node.args:
                    if isinstance(arg, ast.Starred):
                        continue
                    self._axis_expr(arg, axes, consts, "PartitionSpec")

    # ---------------- per-call rules ----------------

    def _check_call(self, node: ast.Call) -> None:
        qual, name = _call_name(node)
        self._check_f64(node, qual, name)
        if (qual == "pl" or (qual or "").endswith("pallas")) and \
                name == "pallas_call":
            # handled per-function for closure analysis; nothing here
            pass

    def _check_f64(self, node: ast.Call, qual: Optional[str],
                   name: str) -> None:
        """float64 flowing into jnp/jax calls (dtype= kwarg, astype,
        jnp.float64 references)."""
        jaxish = qual is not None and (
            qual == "jnp" or qual == "jax.numpy" or qual.startswith("jax"))
        for kw in node.keywords:
            if kw.arg == "dtype" and self._is_f64_expr(kw.value) and jaxish:
                self._diag("f64-dtype", node,
                           f"float64 dtype passed to {qual}.{name} — TPUs "
                           "have no f64 path (silently downcast or 2x "
                           "memory); use float32/int32")
        if name == "astype":
            for arg in node.args:
                if self._is_f64_expr(arg):
                    self._diag("f64-dtype", node,
                               "astype to float64 in array code; use "
                               "float32 (host-side np arrays are exempt "
                               "— move the cast to numpy if intended)")

    @staticmethod
    def _is_f64_expr(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Attribute) and expr.attr in _F64_NAMES:
            qual = None
            if isinstance(expr.value, ast.Name):
                qual = expr.value.id
            return qual in ("jnp", "np", "numpy", "jax")
        if isinstance(expr, ast.Constant) and expr.value in (
                "float64", "double", "complex128"):
            return True
        if isinstance(expr, ast.Name) and expr.id == "float":
            # dtype=float means float64 under x64 — ambiguous, flag it
            return True
        return False

    # ---------------- per-function rules ----------------

    def _check_function(self, fn: ast.FunctionDef) -> None:
        self._check_key_reuse(fn)
        self._check_traced_branch(fn)
        self._check_retrace(fn)
        self._check_static_args(fn)
        self._check_sync_in_loop(fn)
        self._check_kernel_closures(fn)

    # -- key-reuse ---------------------------------------------------

    def _check_key_reuse(self, fn: ast.FunctionDef) -> None:
        state = _KeyState()
        for n in _key_param_names(fn):
            state.fresh(n)
        self._walk_keys(list(fn.body), state, loop_depth=0,
                        skip_defs=True)

    def _consume(self, state: _KeyState, name: str, node: ast.AST,
                 weight: int, via: Optional[str] = None) -> None:
        canon = state.canon(name)
        if canon is None or weight <= 0:
            return
        state.count[canon] = state.count.get(canon, 0) + weight
        if canon not in state.site:
            state.site[canon] = (getattr(node, "lineno", 0),
                                 getattr(node, "col_offset", 0))
        if self._summary_peaks is not None:
            # summary mode: record the peak, emit nothing (the callers
            # of this function get the weight; its own body gets its
            # own normal lint pass)
            self._summary_peaks[canon] = max(
                self._summary_peaks.get(canon, 0), state.count[canon])
            return
        if state.count[canon] >= 2:
            hint = f" (helper {via!r} draws from its key argument)" \
                if via else ""
            self._diag(
                "key-reuse", node,
                f"PRNG key {name!r} consumed more than once on one "
                "execution path; split/fold_in a fresh subkey per "
                f"consumer (utils/prng.py){hint}")
            # report once per key
            state.drop(name)
            state.count.pop(canon, None)

    def _key_expr_handling(self, state: _KeyState, value: ast.AST,
                           targets: List[ast.expr], node: ast.AST,
                           loop_depth: int) -> bool:
        """Handle an assignment whose RHS may derive or alias keys.
        Returns True if the assignment was key-related."""
        # alias: k2 = k1
        if isinstance(value, ast.Name) and state.canon(value.id):
            for t in targets:
                if isinstance(t, ast.Name):
                    state.alias[t.id] = state.canon(value.id)
            return True
        if isinstance(value, ast.Call):
            qual, name = _call_name(value)
            if _is_key_deriver(qual, name):
                # deriving consumes nothing; targets become fresh keys
                for t in targets:
                    if isinstance(t, ast.Name):
                        state.fresh(t.id, loop_depth)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        for el in t.elts:
                            if isinstance(el, ast.Name):
                                state.fresh(el.id, loop_depth)
                return True
            if self._is_deriver_helper(qual, name, value, state):
                # a derive-only HELPER consumes nothing either, but its
                # return value is whatever the helper returns — not
                # necessarily keys — so targets merely stop being
                # tracked (unlike the jax derivers above)
                for t in targets:
                    if isinstance(t, ast.Name):
                        state.drop(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        for el in t.elts:
                            if isinstance(el, ast.Name):
                                state.drop(el.id)
                return True
        return False

    def _is_deriver_helper(self, qual: Optional[str], name: str,
                           call: ast.Call, state: _KeyState) -> bool:
        """A helper whose summary says every tracked key argument maps
        to a weight-0 (derive-only) parameter — e.g. a local
        ``fan_out(key, n)`` wrapper around ``random.split``.  Such
        helpers may be called repeatedly on one key, exactly like the
        jax derivers themselves."""
        summary = self._lookup_summary(qual, name)
        if summary is None:
            return False
        saw_key = False
        for pos, arg in enumerate(call.args):
            if isinstance(arg, ast.Name) and state.canon(arg.id):
                saw_key = True
                if self._arg_weight(summary, pos=pos, kw=None) != 0:
                    return False
        for kw in call.keywords:
            if isinstance(kw.value, ast.Name) and \
                    state.canon(kw.value.id):
                saw_key = True
                if self._arg_weight(summary, pos=None,
                                    kw=kw.arg) != 0:
                    return False
        return saw_key

    @staticmethod
    def _arg_weight(summary: Optional[dict], pos: Optional[int],
                    kw: Optional[str]) -> int:
        """How many consumptions passing a key as this argument costs,
        per the callee's summary; 1 (the conservative default) when the
        callee or the receiving parameter is unknown."""
        if summary is None:
            return 1
        pname = kw
        if pname is None and pos is not None and \
                pos < len(summary["params"]):
            pname = summary["params"][pos]
        if pname is None:
            return 1
        w = summary["weights"].get(pname)
        return 1 if w is None else w

    def _walk_keys(self, stmts: List[ast.stmt], state: _KeyState,
                   loop_depth: int, skip_defs: bool = False) -> bool:
        """Walk statements tracking key consumption; returns True if this
        block terminates (return/raise) so callers skip merging it."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs are linted as their own functions
            if isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    self._scan_expr_keys(stmt.value, state, loop_depth)
                return True
            if isinstance(stmt, ast.Raise):
                return True
            if isinstance(stmt, (ast.Break, ast.Continue)):
                return True
            if isinstance(stmt, ast.Assign):
                if not self._key_expr_handling(state, stmt.value,
                                               stmt.targets, stmt,
                                               loop_depth):
                    self._scan_expr_keys(stmt.value, state, loop_depth)
                    # reassignment from a non-key expr kills key tracking
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            state.drop(t.id)
                continue
            if isinstance(stmt, ast.AugAssign):
                self._scan_expr_keys(stmt.value, state, loop_depth)
                continue
            if isinstance(stmt, ast.If):
                s_body = state.copy()
                s_else = state.copy()
                self._scan_expr_keys(stmt.test, state, loop_depth)
                t_body = self._walk_keys(stmt.body, s_body, loop_depth)
                t_else = self._walk_keys(stmt.orelse, s_else, loop_depth)
                live = [s for s, t in ((s_body, t_body), (s_else, t_else))
                        if not t]
                if live:
                    state.alias.clear()
                    state.count.clear()
                    state.merge_max(*live)
                elif t_body and t_else:
                    return True
                continue
            if isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    self._scan_expr_keys(stmt.iter, state, loop_depth)
                else:
                    self._scan_expr_keys(stmt.test, state, loop_depth)
                s_loop = state.copy()
                self._walk_keys(stmt.body, s_loop, loop_depth + 1)
                state.merge_max(s_loop)
                self._walk_keys(stmt.orelse, state, loop_depth)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._scan_expr_keys(item.context_expr, state,
                                         loop_depth)
                if self._walk_keys(stmt.body, state, loop_depth):
                    return True
                continue
            if isinstance(stmt, ast.Try):
                if self._walk_keys(stmt.body, state, loop_depth):
                    return True
                for h in stmt.handlers:
                    self._walk_keys(h.body, state.copy(), loop_depth)
                self._walk_keys(stmt.orelse, state, loop_depth)
                self._walk_keys(stmt.finalbody, state, loop_depth)
                continue
            if isinstance(stmt, ast.Expr):
                self._scan_expr_keys(stmt.value, state, loop_depth)
                continue
            # anything else: scan expressions conservatively
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._scan_expr_keys(child, state, loop_depth)
        return False

    def _scan_expr_keys(self, expr: ast.AST, state: _KeyState,
                        loop_depth: int) -> None:
        """Count key consumptions inside an expression.

        A bare key name passed as an argument to a call counts by what
        the callee does with it: nothing for pure derivers
        (split/fold_in/... and weight-0 summarized helpers), the
        callee's summarized consumption count for known helpers
        (cross-function pass — module docstring), and the conservative
        1 for unknown callees.  Inside a Python loop a consumption of a
        key derived *outside* the loop counts double (it repeats every
        iteration).
        """
        if expr is None:
            return
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            qual, name = _call_name(node)
            derives = _is_key_deriver(qual, name)
            summary = None if derives else self._lookup_summary(qual,
                                                                name)
            via = summary["name"] if summary else None
            args = [(pos, None, a) for pos, a in enumerate(node.args)] \
                + [(None, kw.arg, kw.value) for kw in node.keywords]
            for pos, kwname, arg in args:
                if isinstance(arg, ast.Name) and state.canon(arg.id):
                    if derives:
                        continue
                    weight = self._arg_weight(summary, pos=pos,
                                              kw=kwname)
                    if weight <= 0:
                        continue
                    canon = state.canon(arg.id)
                    if loop_depth > state.depth.get(canon, 0):
                        weight = max(weight, 2)
                    self._consume(state, arg.id, node, weight, via=via)

    # -- traced-branch ----------------------------------------------

    def _check_traced_branch(self, fn: ast.FunctionDef) -> None:
        for node in _scope_nodes(fn):
            test = None
            what = None
            if isinstance(node, (ast.If, ast.IfExp)):
                test, what = node.test, "if"
            elif isinstance(node, ast.While):
                test, what = node.test, "while"
            elif isinstance(node, ast.Assert):
                test, what = node.test, "assert"
            elif isinstance(node, ast.Call):
                q, n = _call_name(node)
                if q is None and n == "bool" and node.args:
                    test, what = node.args[0], "bool()"
            if test is None:
                continue
            hit = _contains_jnp_predicate(test)
            if hit is not None:
                _, pname = _call_name(hit)
                self._diag(
                    "traced-branch", node,
                    f"Python {what} on jnp.{pname}(...): a traced value "
                    "in host control flow (ConcretizationTypeError under "
                    "jit, hidden device sync outside); use lax.cond/"
                    "lax.while_loop or numpy for host predicates")

    # -- retrace-risk ------------------------------------------------

    @staticmethod
    def _decorator_names(fn: ast.FunctionDef) -> List[str]:
        out = []
        for dec in fn.decorator_list:
            node = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(node, ast.Attribute):
                out.append(node.attr)
            elif isinstance(node, ast.Name):
                out.append(node.id)
        return out

    def _check_retrace(self, fn: ast.FunctionDef) -> None:
        decs = self._decorator_names(fn)
        cached = any(d in ("lru_cache", "cache") for d in decs)
        if cached:
            return
        for node in _scope_nodes(fn):
            if isinstance(node, ast.Call):
                qual, name = _call_name(node)
                if name == "jit" and qual in ("jax", "jax.experimental"):
                    # direct call producing a jitted fn inside a plain
                    # function body: a fresh wrapper (and executable
                    # cache) per invocation
                    self._diag(
                        "retrace-risk", node,
                        "jax.jit called inside a function without "
                        "lru_cache: every call builds a fresh wrapper "
                        "and recompiles (cache keys on the function "
                        "object — engine.py:_jitted_round)")

    # -- weak-static-arg --------------------------------------------

    def _check_static_args(self, fn: ast.FunctionDef) -> None:
        static_names: Set[str] = set()
        for dec in fn.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            qual, name = _call_name(dec)
            inner_jit = name == "jit"
            if name == "partial" and dec.args:
                q2, n2 = _call_name(ast.Call(func=dec.args[0], args=[],
                                             keywords=[])) \
                    if isinstance(dec.args[0],
                                  (ast.Attribute, ast.Name)) else (None, "")
                inner_jit = n2 == "jit"
            if not inner_jit:
                continue
            for kw in dec.keywords:
                if kw.arg == "static_argnums":
                    self._diag(
                        "weak-static-arg", dec,
                        "static_argnums is positional: silently wrong "
                        "under keyword calls and partials; use "
                        "static_argnames")
                if kw.arg == "static_argnames":
                    for el in ast.walk(kw.value):
                        if isinstance(el, ast.Constant) and \
                                isinstance(el.value, str):
                            static_names.add(el.value)
        if not static_names:
            return
        args = fn.args
        named = args.posonlyargs + args.args + args.kwonlyargs
        defaults = list(args.defaults)
        # align defaults with trailing positional args
        pos = args.posonlyargs + args.args
        pairs = list(zip(pos[len(pos) - len(defaults):], defaults)) + \
            [(a, d) for a, d in zip(args.kwonlyargs, args.kw_defaults)
             if d is not None]
        for a, d in pairs:
            if a.arg in static_names and isinstance(
                    d, (ast.List, ast.Dict, ast.Set)):
                self._diag(
                    "weak-static-arg", a,
                    f"static arg {a.arg!r} has an unhashable (mutable) "
                    "default: jit static args must hash; use a tuple or "
                    "None sentinel")
        for a in named:
            if a.arg in static_names:
                ann = a.annotation
                if isinstance(ann, ast.Name) and ann.id in ("list",
                                                            "dict",
                                                            "set"):
                    self._diag(
                        "weak-static-arg", a,
                        f"static arg {a.arg!r} annotated as unhashable "
                        f"{ann.id}; jit static args must hash")

    # -- sync-in-loop ------------------------------------------------

    def _sync_call_name(self, node: ast.Call) -> Optional[str]:
        qual, name = _call_name(node)
        if name in _SYNC_CALLS_ATTR and isinstance(node.func,
                                                   ast.Attribute):
            return f".{name}()"
        if qual == "jax" and name == "device_get":
            return "jax.device_get"
        if qual in ("np", "numpy") and name in ("asarray", "array"):
            return f"np.{name}"
        return None

    def _check_sync_in_loop(self, fn: ast.FunctionDef) -> None:
        def check_stmt_exprs(stmt: ast.stmt) -> None:
            """Flag sync calls in one simple statement, skipping nested
            function/lambda bodies."""
            stack = [stmt]
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue
                if isinstance(node, ast.Call):
                    sync = self._sync_call_name(node)
                    if sync:
                        self._diag(
                            "sync-in-loop", node,
                            f"{sync} inside a Python loop: a host-device "
                            "sync per iteration; batch the readback "
                            "outside the loop (or pragma with the reason "
                            "if this loop IS the host driver)")
                stack.extend(ast.iter_child_nodes(node))

        def scan(stmts: List[ast.stmt], in_loop: bool) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
                    scan(stmt.body, True)
                    scan(stmt.orelse, in_loop)
                    continue
                if isinstance(stmt, ast.If):
                    if in_loop:
                        check_stmt_exprs(stmt.test)
                    scan(stmt.body, in_loop)
                    scan(stmt.orelse, in_loop)
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    if in_loop:
                        for item in stmt.items:
                            check_stmt_exprs(item.context_expr)
                    scan(stmt.body, in_loop)
                    continue
                if isinstance(stmt, ast.Try):
                    scan(stmt.body, in_loop)
                    for h in stmt.handlers:
                        scan(h.body, in_loop)
                    scan(stmt.orelse, in_loop)
                    scan(stmt.finalbody, in_loop)
                    continue
                if in_loop:
                    check_stmt_exprs(stmt)

        scan(fn.body, False)

    # -- kernel-tracer-closure --------------------------------------

    def _check_kernel_closures(self, fn: ast.FunctionDef) -> None:
        """Kernel functions passed to pallas_call must not be local defs
        with free variables (they would close over traced arrays)."""
        local_defs: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.FunctionDef) and node is not fn:
                local_defs[node.name] = node
        for node in _scope_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            qual, name = _call_name(node)
            if name != "pallas_call" or not node.args:
                continue
            kernel = node.args[0]
            # unwrap functools.partial(kernel, ...)
            if isinstance(kernel, ast.Call):
                kq, kn = _call_name(kernel)
                if kn == "partial" and kernel.args:
                    kernel = kernel.args[0]
            if isinstance(kernel, ast.Lambda):
                self._diag(
                    "kernel-tracer-closure", kernel,
                    "lambda passed to pallas_call: kernel bodies must be "
                    "module-level functions (a local lambda closes over "
                    "the tracing scope)")
                continue
            if isinstance(kernel, ast.Name) and kernel.id in local_defs:
                kdef = local_defs[kernel.id]
                free = _free_names(kdef)
                if free:
                    self._diag(
                        "kernel-tracer-closure", kdef,
                        f"pallas kernel {kdef.name!r} is a local def "
                        f"with free variables {sorted(free)!r}: it may "
                        "close over traced arrays (Mosaic lowering "
                        "breaks — ops/pallas_kernels.py:31-33); make "
                        "it module-level and bind statics via "
                        "functools.partial")


def _free_names(fn: ast.FunctionDef) -> Set[str]:
    """Names read in ``fn`` that are neither params, locals, globals the
    module defines, builtins, nor common module aliases."""
    import builtins

    bound: Set[str] = {a.arg for a in (
        fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs)}
    if fn.args.vararg:
        bound.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        bound.add(fn.args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for el in ast.walk(t):
                    if isinstance(el, ast.Name):
                        bound.add(el.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(node.target, ast.Name):
                bound.add(node.target.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for el in ast.walk(node.target):
                if isinstance(el, ast.Name):
                    bound.add(el.id)
        elif isinstance(node, ast.comprehension):
            for el in ast.walk(node.target):
                if isinstance(el, ast.Name):
                    bound.add(el.id)
    free: Set[str] = set()
    module_aliases = {"jnp", "jax", "np", "pl", "lax", "functools",
                      "pltpu", "math", "partial"}
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            n = node.id
            if n in bound or n in module_aliases or \
                    hasattr(builtins, n) or n.isupper():
                continue  # uppercase = module constant convention
            free.add(n)
    return free


def summarize_key_params(source: str, filename: str = "<memory>"
                         ) -> Dict[str, dict]:
    """Per-function key-consumption summaries of one module (the
    cross-function ``key-reuse`` table; see Linter._summarize_tree).
    Unparseable sources summarize to nothing — the lint pass will
    report the syntax error itself."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError:
        return {}
    linter = Linter(source, filename)
    linter._collect_imports(tree)
    return linter._summarize_tree(tree)


def lint_source(source: str, filename: str = "<memory>",
                key_summaries: Optional[Dict[str, Dict[str, dict]]] = None
                ) -> Tuple[List[Diagnostic], int]:
    """Lint one source string; returns (diagnostics, n_suppressed).
    ``key_summaries`` ({module: {function: summary}}) enables the
    cross-module half of the key-reuse rule (lint_paths builds it over
    the whole scanned set; module-local helpers resolve either way)."""
    linter = Linter(source, filename, key_summaries=key_summaries)
    diags = linter.run()
    return diags, linter.n_suppressed
