"""CLI: ``python -m fastconsensus_tpu.analysis [paths...]``.

Exit codes: 0 = clean, 1 = diagnostics found, 2 = analyzer internal
error.  With no paths, lints the ``fastconsensus_tpu`` package itself.

The jaxpr audit (which imports jax and traces the engine) runs by
default whenever a scanned path lies inside the package — so the CI
invocation audits everything, while pointing the tool at fixture
snippets stays import-free and fast.  ``--jaxpr`` / ``--no-jaxpr``
override.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def _inside_package(paths: List[str]) -> bool:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for p in paths:
        ap = os.path.abspath(p)
        if ap == pkg or ap.startswith(pkg + os.sep) or \
                pkg.startswith(ap + os.sep):
            return True
    return False


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fastconsensus_tpu.analysis",
        description="fcheck: AST lint + jaxpr audit for the "
                    "fastconsensus_tpu codebase")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint (default: the "
                             "fastconsensus_tpu package)")
    parser.add_argument("--json", metavar="PATH", default=None,
                        help="write the machine-readable report here")
    parser.add_argument("--jaxpr", dest="jaxpr", action="store_true",
                        default=None, help="force the jaxpr audit on")
    parser.add_argument("--no-jaxpr", dest="jaxpr", action="store_false",
                        help="skip the jaxpr audit (pure source lint)")
    parser.add_argument("--entry-point", action="append", default=None,
                        metavar="NAME",
                        help="audit only these entry points (repeatable)")
    parser.add_argument("--gather-threshold", type=int, default=1 << 26,
                        help="jaxpr audit: max elements one gather may "
                             "materialize (default 2^26)")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress per-diagnostic output")
    args = parser.parse_args(argv)

    from fastconsensus_tpu.analysis import Report, lint_paths

    paths = args.paths or [os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))]
    report = Report()
    try:
        lint_paths(paths, report)
    except OSError as e:
        print(f"fcheck: cannot read {e.filename or e}: {e.strerror or e}",
              file=sys.stderr)
        return 2

    run_jaxpr = args.jaxpr
    if run_jaxpr is None:
        run_jaxpr = _inside_package(paths)
    if run_jaxpr:
        try:
            from fastconsensus_tpu.analysis.jaxpr_audit import \
                audit_entry_points

            diags, summary = audit_entry_points(
                names=args.entry_point,
                gather_threshold=args.gather_threshold)
            report.extend(diags)
            report.jaxpr_summary = summary
        except Exception as e:  # noqa: BLE001 — analyzer must not crash CI
            print(f"fcheck: jaxpr audit failed to run: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    if args.json:
        os.makedirs(os.path.dirname(os.path.abspath(args.json)),
                    exist_ok=True)
        with open(args.json, "w", encoding="utf-8") as fh:
            fh.write(report.to_json())
    if not args.quiet:
        print(report.format_human())
    return 1 if report.diagnostics else 0


if __name__ == "__main__":
    sys.exit(main())
