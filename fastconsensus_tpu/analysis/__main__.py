"""CLI: ``python -m fastconsensus_tpu.analysis [paths...]``.

Exit codes: 0 = clean, 1 = diagnostics found, 2 = analyzer internal
error.  With no paths, lints the ``fastconsensus_tpu`` package itself.

The jaxpr audit (which imports jax and traces the engine) runs by
default whenever a scanned path lies inside the package — so the CI
invocation audits everything, while pointing the tool at fixture
snippets stays import-free and fast.  ``--jaxpr`` / ``--no-jaxpr``
override.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def _inside_package(paths: List[str]) -> bool:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for p in paths:
        ap = os.path.abspath(p)
        if ap == pkg or ap.startswith(pkg + os.sep) or \
                pkg.startswith(ap + os.sep):
            return True
    return False


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fastconsensus_tpu.analysis",
        description="fcheck: AST lint + jaxpr audit for the "
                    "fastconsensus_tpu codebase")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint (default: the "
                             "fastconsensus_tpu package)")
    parser.add_argument("--json", metavar="PATH", default=None,
                        help="write the machine-readable report here")
    parser.add_argument("--jaxpr", dest="jaxpr", action="store_true",
                        default=None, help="force the jaxpr audit on")
    parser.add_argument("--no-jaxpr", dest="jaxpr", action="store_false",
                        help="skip the jaxpr audit (pure source lint)")
    parser.add_argument("--entry-point", action="append", default=None,
                        metavar="NAME",
                        help="audit only these entry points (repeatable)")
    parser.add_argument("--only", default=None, metavar="RULE[,RULE...]",
                        help="keep only these rule ids in the report "
                             "(comma-separated, e.g. "
                             "'lock-order,guarded-field'); the jaxpr "
                             "audit is skipped unless a jaxpr-* rule "
                             "is selected — lets a developer iterate "
                             "on one rule and CI archive per-rule "
                             "reports")
    parser.add_argument("--gather-threshold", type=int, default=1 << 26,
                        help="jaxpr audit: max elements one gather may "
                             "materialize (default 2^26)")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress per-diagnostic output")
    args = parser.parse_args(argv)

    from fastconsensus_tpu.analysis import Report, lint_paths

    paths = args.paths or [os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))]
    report = Report()
    try:
        lint_paths(paths, report)
    except OSError as e:
        print(f"fcheck: cannot read {e.filename or e}: {e.strerror or e}",
              file=sys.stderr)
        return 2

    only = None
    if args.only:
        from fastconsensus_tpu.analysis.astlint import ASTLINT_RULES
        from fastconsensus_tpu.analysis.concurrency import \
            CONCURRENCY_RULES

        known = set(ASTLINT_RULES) | set(CONCURRENCY_RULES) | {
            "jaxpr-f64", "jaxpr-device-put", "jaxpr-gather-size",
            "trace-error"}
        only = {r.strip() for r in args.only.split(",") if r.strip()}
        unknown = only - known
        if unknown:
            # a typo'd --only would make the gate vacuously green
            print(f"fcheck: unknown rule id(s) in --only: "
                  f"{', '.join(sorted(unknown))}; known rules: "
                  f"{', '.join(sorted(known))}", file=sys.stderr)
            return 2

    run_jaxpr = args.jaxpr
    if run_jaxpr is None:
        run_jaxpr = _inside_package(paths)
    if run_jaxpr and only is not None and \
            not any(r.startswith("jaxpr") for r in only):
        run_jaxpr = False  # no jaxpr rule selected: skip the jax import
    if run_jaxpr:
        try:
            from fastconsensus_tpu.analysis.jaxpr_audit import \
                audit_entry_points

            diags, summary = audit_entry_points(
                names=args.entry_point,
                gather_threshold=args.gather_threshold)
            report.extend(diags)
            report.jaxpr_summary = summary
        except Exception as e:  # noqa: BLE001 — analyzer must not crash CI
            print(f"fcheck: jaxpr audit failed to run: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    if only is not None:
        report.diagnostics = [d for d in report.diagnostics
                              if d.rule in only]

    if args.json:
        os.makedirs(os.path.dirname(os.path.abspath(args.json)),
                    exist_ok=True)
        with open(args.json, "w", encoding="utf-8") as fh:
            fh.write(report.to_json())
    if not args.quiet:
        print(report.format_human())
    return 1 if report.diagnostics else 0


if __name__ == "__main__":
    sys.exit(main())
