"""CLI: ``python -m fastconsensus_tpu.analysis [paths...]``.

Exit codes: 0 = clean, 1 = diagnostics found, 2 = analyzer internal
error.  With no paths, lints the ``fastconsensus_tpu`` package itself.

The jaxpr audit (which imports jax and traces the engine) runs by
default whenever a scanned path lies inside the package — so the CI
invocation audits everything, while pointing the tool at fixture
snippets stays import-free and fast.  ``--jaxpr`` / ``--no-jaxpr``
override.
"""

from __future__ import annotations

import argparse
import os
import sys
from dataclasses import replace as dataclasses_replace
from typing import List, Optional


def _inside_package(paths: List[str]) -> bool:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for p in paths:
        ap = os.path.abspath(p)
        if ap == pkg or ap.startswith(pkg + os.sep) or \
                pkg.startswith(ap + os.sep):
            return True
    return False


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fastconsensus_tpu.analysis",
        description="fcheck: AST lint + jaxpr audit for the "
                    "fastconsensus_tpu codebase")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint (default: the "
                             "fastconsensus_tpu package)")
    parser.add_argument("--json", metavar="PATH", default=None,
                        help="write the machine-readable report here")
    parser.add_argument("--jaxpr", dest="jaxpr", action="store_true",
                        default=None, help="force the jaxpr audit on")
    parser.add_argument("--no-jaxpr", dest="jaxpr", action="store_false",
                        help="skip the jaxpr audit and every traced "
                             "footprint probe (pure source lint + grid "
                             "math, no jax import); --only "
                             "jaxpr-peak-bytes still re-enables that "
                             "one traced rule explicitly")
    parser.add_argument("--entry-point", action="append", default=None,
                        metavar="NAME",
                        help="audit only these entry points (repeatable)")
    parser.add_argument("--only", default=None, metavar="RULE[,RULE...]",
                        help="keep only these rule ids in the report "
                             "(comma-separated, e.g. "
                             "'lock-order,guarded-field'); the jaxpr "
                             "audit is skipped unless a jaxpr-* rule "
                             "is selected — lets a developer iterate "
                             "on one rule and CI archive per-rule "
                             "reports")
    parser.add_argument("--gather-threshold", type=int, default=1 << 26,
                        help="jaxpr audit: max elements one gather may "
                             "materialize (default 2^26)")
    parser.add_argument("--footprint", dest="footprint",
                        action="store_true", default=None,
                        help="force the footprint pass on (memory & "
                             "surface model; analysis/footprint.py)")
    parser.add_argument("--no-footprint", dest="footprint",
                        action="store_false",
                        help="skip the footprint pass")
    parser.add_argument("--hbm-bytes", type=int, default=None,
                        metavar="BYTES",
                        help="per-chip device-memory budget for the "
                             "jaxpr-peak-bytes rule (default: the "
                             "CI-pinned synthetic budget, "
                             "footprint.CHIP_HBM_BYTES_DEFAULT)")
    parser.add_argument("--surface-budget", type=int, default=None,
                        metavar="N",
                        help="executable-count budget for the "
                             "surface-count rule (default pinned in "
                             "footprint.SURFACE_BUDGET_DEFAULT)")
    parser.add_argument("--pad-waste-frac", type=float, default=None,
                        metavar="FRAC",
                        help="padding-waste threshold: worst-case pad "
                             "bytes / payload bytes per bucket "
                             "(default pinned in footprint."
                             "PAD_WASTE_FRAC_DEFAULT)")
    parser.add_argument("--footprint-out", metavar="PATH", default=None,
                        help="also write the footprint block alone as a "
                             "bench-history artifact (runs/"
                             "footprint_rNN.json; scripts/bench_report."
                             "py renders and gates it)")
    parser.add_argument("--waste-budget", type=float, default=None,
                        metavar="FRAC",
                        help="cost-dead-compute threshold: run-level "
                             "fraction of rounds-executable FLOPs the "
                             "committed frontier series bills to frozen "
                             "vertices (default pinned in cost."
                             "WASTE_BUDGET_DEFAULT)")
    parser.add_argument("--cost-out", metavar="PATH", default=None,
                        help="also write the compute-cost block alone as "
                             "a bench-history artifact (runs/"
                             "cost_rNN.json; scripts/bench_report.py "
                             "renders and gates it)")
    parser.add_argument("--emit-inventory", metavar="PATH", default=None,
                        help="write the fcheck-contract writer/reader "
                             "inventory artifact (runs/contract_rNN."
                             "json) — the static half of the runtime "
                             "/metricsz cross-check and the source of "
                             "the README counters appendix; needs a "
                             "package scan")
    parser.add_argument("--emit-fault-inventory", metavar="PATH",
                        default=None,
                        help="write the fcheck-fault injection-site "
                             "inventory artifact (runs/faults_rNN."
                             "json) — every serve/ raise site + its "
                             "statically claimed absorbing boundary; "
                             "serve/faultinject.py patches these "
                             "sites and the ci_check injection "
                             "campaign asserts the claims hold live")
    parser.add_argument("--emit-appendix", action="store_true",
                        help="with --emit-inventory (or on a package "
                             "scan): print the README 'Counters & "
                             "series reference' body to stdout and "
                             "exit (scripts/ci_check.sh diffs it "
                             "against the committed README)")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress per-diagnostic output")
    args = parser.parse_args(argv)

    from fastconsensus_tpu.analysis import Report, lint_paths

    paths = args.paths or [os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))]
    report = Report()
    try:
        lint_paths(paths, report)
    except OSError as e:
        print(f"fcheck: cannot read {e.filename or e}: {e.strerror or e}",
              file=sys.stderr)
        return 2
    except ValueError as e:
        # a malformed fixture posture (CONTRACT_SPEC / FOOTPRINT_SPEC)
        # must fail loudly, not lint as an empty universe
        print(f"fcheck: {e}", file=sys.stderr)
        return 2

    only = None
    if args.only:
        from fastconsensus_tpu.analysis.astlint import ASTLINT_RULES
        from fastconsensus_tpu.analysis.concurrency import \
            CONCURRENCY_RULES
        from fastconsensus_tpu.analysis.contracts import CONTRACT_RULES
        from fastconsensus_tpu.analysis.cost import COST_RULES
        from fastconsensus_tpu.analysis.faults import FAULT_RULES
        from fastconsensus_tpu.analysis.footprint import FOOTPRINT_RULES

        known = set(ASTLINT_RULES) | set(CONCURRENCY_RULES) | \
            set(FOOTPRINT_RULES) | set(CONTRACT_RULES) | \
            set(FAULT_RULES) | set(COST_RULES) | {
            "jaxpr-f64", "jaxpr-device-put", "jaxpr-gather-size",
            "trace-error"}
        only = {r.strip() for r in args.only.split(",") if r.strip()}
        unknown = only - known
        if unknown:
            # a typo'd --only would make the gate vacuously green
            print(f"fcheck: unknown rule id(s) in --only: "
                  f"{', '.join(sorted(unknown))}; known rules: "
                  f"{', '.join(sorted(known))}", file=sys.stderr)
            return 2

    run_jaxpr = args.jaxpr
    if run_jaxpr is None:
        run_jaxpr = _inside_package(paths)
    if run_jaxpr and only is not None and \
            not any(r.startswith("jaxpr") for r in only):
        run_jaxpr = False  # no jaxpr rule selected: skip the jax import
    if run_jaxpr:
        try:
            from fastconsensus_tpu.analysis.jaxpr_audit import \
                audit_entry_points

            diags, summary = audit_entry_points(
                names=args.entry_point,
                gather_threshold=args.gather_threshold,
                hbm_bytes=args.hbm_bytes)
            report.extend(diags)
            report.jaxpr_summary = summary
        except Exception as e:  # noqa: BLE001 — analyzer must not crash CI
            print(f"fcheck: jaxpr audit failed to run: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    # -- footprint pass (analysis/footprint.py): device-memory & surface
    # model.  Runs on package scans (like the jaxpr audit) or whenever a
    # scanned fixture declares a FOOTPRINT_SPEC posture; --only without
    # any footprint rule skips it, and only the jaxpr-peak-bytes rule
    # ever imports jax (surface-count / padding-waste are grid math, so
    # the pre-commit hook stays jax-free).
    from fastconsensus_tpu.analysis import footprint as fpmod

    run_footprint = args.footprint
    fixture_specs = []
    if run_footprint is not False and (
            only is None or only & set(fpmod.FOOTPRINT_RULES)):
        try:
            fixture_specs = fpmod.find_specs(paths)
        except ValueError as e:
            print(f"fcheck: bad FOOTPRINT_SPEC: {e}", file=sys.stderr)
            return 2
        if run_footprint is None:
            run_footprint = _inside_package(paths) or bool(fixture_specs)
    elif run_footprint is None:
        run_footprint = False
    if run_footprint and only is not None and \
            not (only & set(fpmod.FOOTPRINT_RULES)):
        run_footprint = False
    if run_footprint:
        overrides = {k: v for k, v in (
            ("hbm_bytes", args.hbm_bytes),
            ("surface_budget", args.surface_budget),
            ("pad_waste_frac", args.pad_waste_frac)) if v is not None}
        specs = fixture_specs or [fpmod.SurfaceSpec()]
        if overrides:
            specs = [dataclasses_replace(s, **overrides) for s in specs]
        sel = set(only & set(fpmod.FOOTPRINT_RULES)) if only is not None \
            else set(fpmod.FOOTPRINT_RULES)
        if args.jaxpr is False and (only is None
                                    or "jaxpr-peak-bytes" not in only):
            # --no-jaxpr promises "no jax import": keep the footprint
            # pass to its grid-math rules (the per-file pre-commit hook
            # lands here) unless the traced rule was NAMED via --only —
            # an explicit selection wins over the default scope
            sel -= {"jaxpr-peak-bytes"}
        try:
            for spec in specs:
                # the repo-default posture carries the full table +
                # derived ceiling into the report; fixture postures,
                # --only rule-iteration runs and --no-jaxpr (both
                # traced) contribute diagnostics only — the table is
                # ~25 traces, which the full-report runs pay and the
                # per-rule/per-commit loops must not
                full = not fixture_specs and only is None \
                    and args.jaxpr is not False
                diags, block = fpmod.evaluate(spec, rules=sel,
                                              with_table=full,
                                              with_ceiling=full)
                report.extend(diags)
                if full:
                    report.footprint = block
        except Exception as e:  # noqa: BLE001 — analyzer must not crash CI
            print(f"fcheck: footprint pass failed to run: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    # -- compute-cost pass (analysis/cost.py): FLOP/byte/roofline model
    # of the same surface.  Mirrors the footprint gating, with one
    # simplification: all three cost rules run on the jax-free ladder
    # mirror, so --no-jaxpr never narrows the selection — it only skips
    # the traced gate/calibration tables (full package scans pay them).
    from fastconsensus_tpu.analysis import cost as costmod

    run_cost = args.footprint     # --footprint/--no-footprint govern
    cost_specs = []               # the whole static surface family
    if run_cost is not False and (
            only is None or only & set(costmod.COST_RULES)):
        try:
            cost_specs = costmod.find_specs(paths)
        except ValueError as e:
            print(f"fcheck: bad COST_SPEC: {e}", file=sys.stderr)
            return 2
        if run_cost is None:
            run_cost = _inside_package(paths) or bool(cost_specs)
    elif run_cost is None:
        run_cost = False
    if run_cost and only is not None and \
            not (only & set(costmod.COST_RULES)):
        run_cost = False
    if run_cost:
        overrides = {k: v for k, v in (
            ("waste_budget", args.waste_budget),) if v is not None}
        specs = cost_specs or [costmod.CostSpec()]
        if overrides:
            specs = [dataclasses_replace(s, **overrides) for s in specs]
        sel = set(only & set(costmod.COST_RULES)) if only is not None \
            else set(costmod.COST_RULES)
        try:
            for spec in specs:
                # the repo-default posture carries the traced gate +
                # calibration tables into the report; fixture postures,
                # --only iteration and --no-jaxpr runs stay mirror-only
                full = not cost_specs and only is None \
                    and args.jaxpr is not False
                diags, block = costmod.evaluate(spec, rules=sel,
                                                with_table=full)
                report.extend(diags)
                if full:
                    report.cost = block
        except Exception as e:  # noqa: BLE001 — analyzer must not crash CI
            print(f"fcheck: cost pass failed to run: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    if only is not None:
        report.diagnostics = [d for d in report.diagnostics
                              if d.rule in only]

    if args.footprint_out:
        if report.footprint is None:
            print("fcheck: --footprint-out needs the footprint pass on "
                  "the repo posture (no fixture specs, a footprint rule "
                  "selected)", file=sys.stderr)
            return 2
        import json as _json

        out_dir = os.path.dirname(os.path.abspath(args.footprint_out))
        os.makedirs(out_dir, exist_ok=True)
        with open(args.footprint_out, "w", encoding="utf-8") as fh:
            _json.dump(report.footprint, fh, indent=2, sort_keys=True)
            fh.write("\n")

    if args.cost_out:
        if report.cost is None:
            print("fcheck: --cost-out needs the cost pass on the repo "
                  "posture (no fixture specs, no --only, jaxpr on)",
                  file=sys.stderr)
            return 2
        import json as _json

        out_dir = os.path.dirname(os.path.abspath(args.cost_out))
        os.makedirs(out_dir, exist_ok=True)
        with open(args.cost_out, "w", encoding="utf-8") as fh:
            _json.dump(report.cost, fh, indent=2, sort_keys=True)
            fh.write("\n")

    if args.emit_fault_inventory:
        import json as _json

        from fastconsensus_tpu.analysis import faults as fltmod

        try:
            finv = fltmod.fault_inventory_from_paths(paths)
        except (ValueError, OSError) as e:
            print(f"fcheck: {e}", file=sys.stderr)
            return 2
        out_dir = os.path.dirname(
            os.path.abspath(args.emit_fault_inventory))
        os.makedirs(out_dir, exist_ok=True)
        with open(args.emit_fault_inventory, "w",
                  encoding="utf-8") as fh:
            _json.dump(finv, fh, indent=2, sort_keys=True)
            fh.write("\n")

    if args.emit_inventory or args.emit_appendix:
        import json as _json

        from fastconsensus_tpu.analysis import contracts as conmod

        try:
            inventory = conmod.inventory_from_paths(paths)
        except (ValueError, OSError) as e:
            print(f"fcheck: {e}", file=sys.stderr)
            return 2
        if args.emit_inventory:
            out_dir = os.path.dirname(
                os.path.abspath(args.emit_inventory))
            os.makedirs(out_dir, exist_ok=True)
            with open(args.emit_inventory, "w", encoding="utf-8") as fh:
                _json.dump(inventory, fh, indent=2, sort_keys=True)
                fh.write("\n")
        if args.emit_appendix:
            # generator mode, not a gate: the drift check diffs this
            # output against the committed README section
            print(conmod.render_counters_appendix(inventory))
            return 0

    if args.json:
        os.makedirs(os.path.dirname(os.path.abspath(args.json)),
                    exist_ok=True)
        with open(args.json, "w", encoding="utf-8") as fh:
            fh.write(report.to_json())
    if not args.quiet:
        print(report.format_human())
    return 1 if report.diagnostics else 0


if __name__ == "__main__":
    sys.exit(main())
