"""fcflight: the always-on flight recorder — bounded per-thread event rings.

When a serving replica wedges or a request lands at the p99, the
existing observability answers "how is the fleet doing on average"
(fcobs counters, fclat histograms, fcqual series) but not "what was
*this* process doing just now".  The tracer (obs/tracer.py) could
answer it, but it is off by default and unbounded per span — the wrong
shape for an incident recorder that must be running BEFORE the incident.

The flight recorder is the always-on complement:

* **Per-thread ring buffers.**  Each recording thread owns one ring
  (minted lazily on first ``record()`` and cached in a
  ``threading.local``), so the hot-path append takes only that ring's
  own — uncontended — lock: O(1), no cross-thread contention, no
  allocation beyond the event dict itself.  Threads past ``max_rings``
  share one overflow ring (lock-protected; correctness unchanged,
  contention only in a pathological thread storm).
* **Hard memory cap.**  A ring holds at most ``capacity`` events and
  overwrites its oldest (the overwritten count is reported as
  ``dropped``); the recorder's whole footprint is bounded by
  ``max_rings × capacity`` small dicts regardless of uptime or load.
* **Atomic snapshot.**  ``snapshot()`` copies the ring list under the
  recorder lock, then each ring's contents under that ring's lock —
  each ring is copied atomically, appends racing the snapshot land in
  the next one.  Ring and recorder locks are leaves (nothing is
  acquired while holding them), so fcheck-concurrency passes over this
  module with zero pragmas.

Event vocabulary (the serving stack's instrumentation points; the
``kind`` field is an open set, these are the core ones):

=================  ====================================================
``admit``          AdmissionQueue accepted a job
``reject_429``     queue full — backpressure returned to the client
``shed``           deadline shed at admission (fcshape)
``hold``           hold-for-coalesce episode closed over a pop
``pop``            job left the admission queue
``route``          StickyScheduler picked a worker
``dequeue``        worker thread picked a batch off its deque
``device``         device call dispatched (bucket/rung/cold tagged)
``device_done``    device call returned
``finish``         job reached DONE (e2e attached)
``fail``           job reached FAILED
``cache_hit``      admission answered from the result cache
``cordon``         a worker was cordoned (death or watchdog)
``requeue``        jobs re-admitted after a worker death/cordon
``watchdog_trip``  the hang watchdog declared a worker suspect
``bundle``         a post-mortem bundle was written
``span_open``/``span_close``  tracer spans, mirrored when tracing is on
``proxy``          router proxied a status/result poll to a replica
``rehome_replay``  router replayed an in-flight job onto a new replica
``fleet_bundle``   FleetManager collected a replica's bundles (fctrace)
``delta``          fcdelta admission: parent resolved, mode decided
=================  ====================================================

The router tier (serve/router.py) records into the same vocabulary:
``route`` doubles as the router's placement decision (ring lookup →
replica), and ``proxy``/``rehome_replay``/``fleet_bundle`` are the
router/fleet-side kinds — every one carries the ``trace`` id minted at
submit, which is how ``fleettrace render`` stitches router and replica
rings into one timeline.

Everything here is stdlib-only and jax-free: the post-mortem reader
(``python -m fastconsensus_tpu.obs.postmortem``) renders snapshots on a
box where jax cannot even import.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

# Defaults bound the recorder to max_rings * capacity events; at ~200
# bytes per small event dict that is ~6 MiB worst case for a serving
# process with every ring full — the "hard memory cap" contract.
DEFAULT_CAPACITY = 2048
DEFAULT_MAX_RINGS = 16

# The machine-readable twin of the docstring table above: every kind
# the serving stack records.  fcheck-contract's ``event-vocab`` rule
# holds the two sides together — a ``record("newkind", ...)`` without a
# row here fails the gate, and a row nothing records is flagged stale —
# so postmortem renderers and ``merge_events(kinds=...)`` filters can
# trust this tuple as the full vocabulary.
EVENT_KINDS = (
    "admit", "reject_429", "shed", "hold", "pop", "route", "dequeue",
    "device", "device_done", "finish", "fail", "cache_hit", "cordon",
    "requeue", "watchdog_trip", "bundle", "span_open", "span_close",
    "proxy", "rehome_replay", "fleet_bundle", "delta",
)


class _Ring:
    """One thread's bounded event ring (oldest-overwrite)."""

    def __init__(self, thread_name: str, capacity: int) -> None:
        self.thread_name = thread_name
        self.capacity = capacity
        self._ring_lock = threading.Lock()
        self._slots: List[Optional[Dict[str, Any]]] = [None] * capacity
        self._appended = 0

    def append(self, event: Dict[str, Any]) -> None:
        with self._ring_lock:
            self._slots[self._appended % self.capacity] = event
            self._appended += 1

    def snapshot(self) -> Dict[str, Any]:
        """This ring's retained events, oldest first, plus the count
        overwritten before the snapshot."""
        with self._ring_lock:
            n = self._appended
            slots = list(self._slots)
        if n <= self.capacity:
            events = [e for e in slots[:n]]
        else:
            head = n % self.capacity
            events = slots[head:] + slots[:head]
        return {
            "thread": self.thread_name,
            "dropped": max(n - self.capacity, 0),
            "events": [e for e in events if e is not None],
        }

    def clear(self) -> None:
        with self._ring_lock:
            self._slots = [None] * self.capacity
            self._appended = 0


class FlightRecorder:
    """The process flight recorder; see the module docstring."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 max_rings: int = DEFAULT_MAX_RINGS) -> None:
        self.capacity = max(int(capacity), 1)
        self.max_rings = max(int(max_rings), 1)
        self._lock = threading.Lock()
        self._rings: List[_Ring] = []
        self._overflow: Optional[_Ring] = None
        self._tl = threading.local()

    # -- hot path -----------------------------------------------------

    def record(self, kind: str, job: Optional[str] = None,
               **aux: Any) -> None:
        """Append one event to the calling thread's ring.  ``aux``
        values should be small scalars (str/int/float/bool) — they are
        serialized verbatim into post-mortem bundles."""
        ring = getattr(self._tl, "ring", None)
        if ring is None:
            ring = self._ring_for_thread()
        event: Dict[str, Any] = {"ts": time.monotonic(), "kind": kind}
        if job is not None:
            event["job"] = job
        if aux:
            event.update(aux)
        ring.append(event)

    def _ring_for_thread(self) -> _Ring:
        name = threading.current_thread().name
        with self._lock:
            if len(self._rings) < self.max_rings:
                ring = _Ring(name, self.capacity)
                self._rings.append(ring)
            else:
                # thread storm: correctness over contention — latecomers
                # share one ring so the memory cap holds
                if self._overflow is None:
                    self._overflow = _Ring("<overflow>", self.capacity)
                    self._rings.append(self._overflow)
                ring = self._overflow
        self._tl.ring = ring
        return ring

    # -- cold path ----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """All rings (each copied atomically under its own lock): the
        bundle's ``flight.json`` payload.

        The ``time_unix``/``time_mono`` pair is the monotonic↔wall
        clock anchor (same convention as the bundle MANIFEST): event
        ``ts`` values are ``time.monotonic()`` stamps, so a reader maps
        them onto the wall clock via ``ts + (time_unix - time_mono)``.
        That is what lets ``fleettrace render`` align snapshots taken
        on DIFFERENT processes (each with its own monotonic epoch) onto
        one shared fleet timeline."""
        with self._lock:
            rings = list(self._rings)
        ring_snaps = [r.snapshot() for r in rings]
        return {
            "capacity": self.capacity,
            "max_rings": self.max_rings,
            "n_events": sum(len(r["events"]) for r in ring_snaps),
            "dropped": sum(r["dropped"] for r in ring_snaps),
            "time_unix": round(time.time(), 3),
            "time_mono": round(time.monotonic(), 6),
            "rings": ring_snaps,
        }

    def events(self, job: Optional[str] = None,
               kinds: Optional[Any] = None,
               limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Merged timeline across rings, sorted by ``ts`` (each event
        tagged with its ring's thread name).  ``job``/``kinds`` filter;
        ``limit`` keeps the most recent N after filtering — the
        ``/debugz/slowest`` per-job timeline helper."""
        snap = self.snapshot()
        return merge_events(snap, job=job, kinds=kinds, limit=limit)

    def reset(self) -> None:
        """Clear every ring's contents (tests).  Rings stay registered:
        threads cache their ring in a ``threading.local``, so dropping
        rings here would orphan those cached references and lose their
        future events from snapshots."""
        with self._lock:
            rings = list(self._rings)
        for ring in rings:
            ring.clear()


def merge_events(snapshot: Dict[str, Any], job: Optional[str] = None,
                 kinds: Optional[Any] = None,
                 limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """Flatten a :meth:`FlightRecorder.snapshot` into one thread-tagged
    timeline, sorted by ``ts`` — shared by the live ``/debugz``
    endpoints and the jax-free bundle reader (obs/postmortem.py)."""
    kind_set = set(kinds) if kinds is not None else None
    out: List[Dict[str, Any]] = []
    for ring in snapshot.get("rings", ()):
        thread = ring.get("thread")
        for event in ring.get("events", ()):
            if job is not None and event.get("job") != job:
                continue
            if kind_set is not None and event.get("kind") not in kind_set:
                continue
            out.append({**event, "thread": thread})
    out.sort(key=lambda e: e.get("ts", 0.0))
    if limit is not None and len(out) > limit:
        out = out[-limit:]
    return out


_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    """The process-global recorder (the serving stack records into it;
    post-mortem bundles snapshot it)."""
    return _RECORDER


def record(kind: str, job: Optional[str] = None, **aux: Any) -> None:
    """Module-level convenience: record into the global recorder."""
    _RECORDER.record(kind, job, **aux)
