"""fcobs counters: a process-wide counter / gauge / series registry.

Where spans (obs/tracer.py) answer *where the wall clock went*, the
registry answers *how many times did X happen* — and stays always-on:
every operation is a dict update under a lock, cheap enough for the host
driver loop's handful-of-events-per-round rate, so counts exist even when
span tracing is disabled (``bench.py`` builds its ``telemetry`` block
from exactly this).

Three kinds of signal:

* **counters** — monotonically increasing ints (``inc``): consensus
  rounds, deliberate host-sync crossings (:func:`host_sync` — called at
  every ``# fcheck: ok=sync-in-loop``-pragma'd readback in engine.py /
  consensus.py), XLA compiles (``analysis.CompileGuard`` attaches via its
  ``registry=`` hook), closure/repair edge totals, regrow events;
* **gauges** — last-write-wins floats (``gauge``): slab capacity, device
  memory (:func:`record_device_memory`);
* **series** — observed samples (``observe``) summarized on demand
  (:meth:`ObsRegistry.summary`: count / total / mean / p50 / p95 / max):
  per-round wall seconds, per-member detect-call latency.

Scoping: the registry is process-global (one consensus run per process is
the operating mode — CLI, bench, supervised long runs).  Callers that
need per-run deltas ``reset()`` before the run or diff ``snapshot()``s.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional


def percentile(sorted_values: List[float], q: float) -> float:
    """Nearest-rank percentile of an ascending-sorted non-empty list."""
    if not sorted_values:
        raise ValueError("percentile of an empty series")
    n = len(sorted_values)
    rank = max(1, min(n, math.ceil(q * n)))
    return sorted_values[rank - 1]


class ObsRegistry:
    """Thread-safe counter/gauge/series store; see module docstring."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._series: Dict[str, List[float]] = {}
        self._series_limit: Optional[int] = None
        # samples dropped per series by the window (set_series_limit):
        # summaries over a truncated series describe the RECENT WINDOW,
        # not the run — snapshot() must say so (a windowed p95 presented
        # as a run p95 is how a latency regression hides in /metricsz)
        self._series_dropped: Dict[str, int] = {}

    # -- writes ------------------------------------------------------

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            series = self._series.setdefault(name, [])
            series.append(float(value))
            if self._series_limit and len(series) > self._series_limit:
                n_drop = len(series) - self._series_limit
                del series[:n_drop]
                self._series_dropped[name] = \
                    self._series_dropped.get(name, 0) + n_drop

    def set_series_limit(self, limit: Optional[int]) -> None:
        """Bound every series to its most recent ``limit`` samples.

        One-shot consumers (CLI, bench) keep the default ``None`` — full
        history, whole-run percentiles.  A RESIDENT process must bound
        this: the serving layer observes per-job/per-round latencies
        forever, and unbounded sample lists are a slow memory leak
        (serve/server.py sets a window at start; summaries then describe
        the recent window, which is what a serving dashboard wants
        anyway).  Applies retroactively to existing series.
        """
        with self._lock:
            self._series_limit = None if limit is None \
                else max(1, int(limit))
            if self._series_limit:
                for name, series in self._series.items():
                    n_drop = len(series) - self._series_limit
                    if n_drop > 0:
                        del series[:n_drop]
                        self._series_dropped[name] = \
                            self._series_dropped.get(name, 0) + n_drop

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._series.clear()
            self._series_dropped.clear()

    def restore_counters(self, saved: Dict[str, int]) -> Dict[str, int]:
        """Restore checkpointed counter totals by *delta*: each counter is
        raised to at least its saved value (``inc`` by ``saved - current``
        when positive, nothing otherwise).

        This is the telemetry-continuity primitive (utils/checkpoint.py
        persists ``snapshot()["counters"]`` in the checkpoint metadata): a
        fresh process resuming a run starts at zero, so the delta restore
        replays the dead process's totals and every later ``inc`` lands on
        top — ``--trace`` summaries of a resumed run report cumulative
        counts.  In the same process that already holds the run's counts
        (e.g. an immediate in-process resume after convergence) the delta
        is zero and nothing double-counts.  Returns the applied deltas.
        """
        applied: Dict[str, int] = {}
        with self._lock:
            for name, value in saved.items():
                delta = int(value) - self._counters.get(name, 0)
                if delta > 0:
                    self._counters[name] = \
                        self._counters.get(name, 0) + delta
                    applied[name] = delta
        return applied

    # -- reads -------------------------------------------------------

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def counters_since(self, base: Dict[str, int]) -> Dict[str, int]:
        """Positive counter increments since a ``counters()`` snapshot —
        the run-scoping primitive: a checkpoint must persist THIS run's
        counts (plus its own restored base), not whatever an earlier run
        in the same process left in the global registry."""
        return {k: v - base.get(k, 0) for k, v in self.counters().items()
                if v - base.get(k, 0) > 0}

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def series(self, name: str) -> List[float]:
        with self._lock:
            return list(self._series.get(name, ()))

    def summary(self, name: str) -> Optional[dict]:
        """Summary stats of one series, or None if nothing was observed.

        When the series window (:meth:`set_series_limit`) has dropped
        samples, the summary describes the RECENT WINDOW only and says
        so: ``window_truncated: True`` plus the dropped count — without
        the stamp, a windowed p95 reads as a run total's p95 (the
        serving layer's whole-run latency now lives on the fclat
        histograms in obs/latency.py, which never truncate).
        """
        with self._lock:
            values = list(self._series.get(name, ()))
            dropped = self._series_dropped.get(name, 0)
        if not values:
            return None
        values.sort()
        total = sum(values)
        out = {
            "count": len(values),
            "total": round(total, 6),
            "mean": round(total / len(values), 6),
            "p50": round(percentile(values, 0.50), 6),
            "p95": round(percentile(values, 0.95), 6),
            "max": round(values[-1], 6),
        }
        if dropped:
            out["window_truncated"] = True
            out["dropped"] = dropped
        return out

    def snapshot(self) -> dict:
        """One JSON-ready dict of everything (series as summaries)."""
        with self._lock:
            names = list(self._series)
        return {
            "counters": self.counters(),
            "gauges": self.gauges(),
            "series": {n: self.summary(n) for n in names},
        }


_REGISTRY = ObsRegistry()


def get_registry() -> ObsRegistry:
    """The process-global registry."""
    return _REGISTRY


def host_sync(tag: str, n: int = 1) -> None:
    """Count a deliberate host-device sync crossing.

    Called next to every pragma'd ``jax.device_get`` /
    ``block_until_ready`` in the driver (engine.py / consensus.py), so a
    bench artifact can separate "the engine started syncing per item" from
    "the device got slower" — the distinction the round-3 transport
    incident took a day to make by hand.
    """
    _REGISTRY.inc("host_sync.total", n)
    _REGISTRY.inc(f"host_sync.{tag}", n)


def fold_round(entry: dict) -> None:
    """Fold one round's history entry (consensus.run_consensus.record)
    into the registry: round counts, closure/repair/drop totals, the
    converged-edge fraction series, the slab-capacity gauge, and the
    fcqual ``consensus.quality.*`` series (obs/quality.py).  The quality
    keys are optional — pre-fcqual entries (resumed legacy checkpoints)
    fold without them."""
    _REGISTRY.inc("rounds.total")
    if entry.get("cold"):
        _REGISTRY.inc("rounds.cold")
    _REGISTRY.inc("closure.edges_added", entry.get("n_closure_added", 0))
    _REGISTRY.inc("repair.edges_added", entry.get("n_repaired", 0))
    _REGISTRY.inc("capacity.edges_dropped", entry.get("n_dropped", 0))
    n_alive = entry.get("n_alive", 0)
    if n_alive:
        frac = 1.0 - entry.get("n_unconverged", 0) / n_alive
        _REGISTRY.observe("round.converged_frac", frac)
    if entry.get("capacity"):
        _REGISTRY.gauge("slab.capacity", entry["capacity"])
    # fcqual: per-round quality series + cumulative counters (the
    # counters persist in checkpoints and delta-restore on resume, like
    # every other counter in the registry)
    for key in ("agreement", "frontier_frac", "churn_frac",
                "modularity_mean"):
        if entry.get(key) is not None:
            _REGISTRY.observe(f"consensus.quality.{key}", float(entry[key]))
    _REGISTRY.inc("quality.labels_changed_total",
                  entry.get("labels_changed", 0))
    _REGISTRY.inc("quality.agg_overflow_total",
                  entry.get("n_agg_overflow", 0))


def device_memory() -> Optional[dict]:
    """Allocator stats of the first local device, where the backend
    exposes them (TPU/GPU ``memory_stats()``; None on CPU and on any
    plugin that does not implement the call)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — observability must never raise
        return None
    if not stats:
        return None
    return {k: int(v) for k, v in stats.items()
            if isinstance(v, (int, float))}


def record_device_memory(prefix: str = "device_mem") -> Optional[dict]:
    """Gauge the headline allocator numbers (bytes in use / peak / limit)
    into the registry; returns the raw stats dict (or None)."""
    stats = device_memory()
    if stats:
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                _REGISTRY.gauge(f"{prefix}.{key}", stats[key])
    return stats
