"""fcobs bench history: normalize BENCH artifacts, trend them, gate CI.

The repo accumulates one bench artifact per growth round (``BENCH_r0*.
json`` — the driver's wrapper object with a ``parsed`` record) plus
ad-hoc run artifacts (``runs/bench_*.json`` — raw ``bench.py`` JSON
lines), and until now nothing read them back: a perf regression between
rounds was whatever a human happened to notice.  This module is the
reader:

* :func:`load_records` — one normalized record per recognizable bench
  object in a file, tolerant of the three committed shapes (driver
  wrapper, raw JSON object, JSON lines) and silently skipping files that
  are not bench records (e.g. ``BENCH_BASELINE.json``, a cache).
* :func:`build_history` — records grouped per *config* (parsed from the
  bench unit string: graph / algorithm / n_p / mesh) and ordered by
  sequence number (the driver's ``n``, or an ``_rN`` filename suffix).
* :func:`trend_table` — text/markdown trend report per config:
  throughput, vs-baseline, NMI, rounds, and the fcobs telemetry columns
  (warm compiles, host syncs, p95 round / detect-call latency) where the
  artifact carries them (PR-2+ artifacts do; earlier ones show ``-``).
* :func:`check_history` — the regression gate: the newest *sequenced*
  record per config is compared against the median of its predecessors;
  a throughput drop beyond ``max_drop_frac``, an NMI drop beyond
  ``nmi_drop``, a converged-run history going non-converged, or a
  warm-run compile count > 0 is a finding.  Unsequenced ad-hoc records
  inform the trend table but are never "the latest" — a one-off degraded
  rerun (e.g. ``runs/bench_emailEu_rerun.json``, a transport-degraded
  probe) must not fail CI forever.

The fclat serve_load artifacts (``runs/bench_serve_load_rNN.json``,
written by ``bench.py serve_load`` — open-loop Poisson latency-vs-RPS
curves) ride the same reader: records keep their per-RPS curve verbatim
(``serve_load`` in the normalized record), :func:`serve_load_table`
renders the latency-vs-RPS view (percentiles, 429 rate, SLO attainment,
per-phase p95 breakdown) and :func:`check_serve_load` gates tail
latency at the curve's reference RPS — these artifacts are
lower-is-better, so :func:`check_history` deliberately skips its
throughput/NMI rules for them (the warm-compile rule still applies).

The fcfleet serve_fleet artifacts (``runs/bench_serve_fleet_rNN.json``,
written by ``bench.py serve_fleet`` — weak-scaling RPS points over a
replica fleet plus a chaos-drill block) ride the same reader: records
keep the block verbatim (``serve_fleet`` in the normalized record),
:func:`serve_fleet_table` renders the scaling + drill view and
:func:`check_serve_fleet` gates it — absolute drill-health rules from
the first artifact, scaling-efficiency trajectory once a same-size
predecessor exists.  Their headline value is a higher-is-better
scaling ratio, so :func:`check_history` skips its value rules for
them too.  Since PR 18 those artifacts also carry a
``telemetry.fleet_latency`` block (the router's ``/fleetz`` scrape —
router-phase p95s, per-replica proxy overhead, the fleet-merged e2e
p95, the exact-merge verdict); :func:`check_fleet_latency` gates it.

The fcdelta serve_delta artifacts (``runs/bench_serve_delta_rNN.json``,
written by ``bench.py serve_delta`` — drift-vs-quality scenarios that
perturb a base graph by k% of its edges and answer each perturbation
both incrementally (warm-start from the parent's cached ensemble,
moves frontier-restricted to the changed neighborhood) and from
scratch) ride the same reader: records keep the block verbatim
(``serve_delta`` in the normalized record) and :func:`check_delta`
gates it — absolute rules from the first artifact, because the
incremental path's whole contract is *relative to the from-scratch run
in the same artifact*: an incremental answer whose NMI trails its own
from-scratch twin by more than the band, or that costs as much device
time as just recomputing, is wrong regardless of history.  Their
headline value is a speedup ratio, so :func:`check_history` skips its
value rules for them too.

The fcqual quality block (``telemetry.quality`` — obs/quality.py's
:func:`~fastconsensus_tpu.obs.quality.summarize_history` output, stamped
by ``bench.py`` on every run artifact) rides the same reader: records
keep the block verbatim (``quality`` in the normalized record),
:func:`quality_table` renders the convergence-quality trend (rounds to
converge, final ensemble agreement / modularity, the late-round
active-frontier fraction) and :func:`check_quality` gates it — a
rounds-to-converge blow-up, a final-agreement drop, or a late-frontier
fraction that stops shrinking is a *partition-quality* regression the
throughput gate cannot see (a kernel bug that scrambles labels can
leave partitions/s untouched).

The fcheck-footprint artifacts (``runs/footprint_rNN.json``, written by
``python -m fastconsensus_tpu.analysis --footprint-out``) ride the same
reader: :func:`load_footprints` / :func:`footprint_table` render the
serving memory model's trend (executable surface, chip ceiling, worst
peak, padding) and :func:`check_footprints` gates on silent surface
growth between committed rounds.

``scripts/bench_report.py`` is the CLI; ``scripts/ci_check.sh`` runs it
with ``--check`` as a gate.
"""

from __future__ import annotations

import json
import os
import re
from statistics import median as _median
from typing import Dict, List, Optional, Tuple

# Thresholds the CI gate uses unless overridden.  max_drop_frac is
# deliberately loose (a 50% drop): the committed history itself shows
# benign 10-20% run-to-run noise on the tracked config, and the round-3
# artifact (6.9 p/s vs 67.7 prior — a 10x transport collapse) is exactly
# the magnitude the gate exists to catch.
DEFAULT_MAX_DROP_FRAC = 0.5
DEFAULT_NMI_DROP = 0.05

# serve_load (fclat latency-curve) gate thresholds: these artifacts are
# LOWER-IS-BETTER latency curves, so the throughput-drop rule above
# never applies to them (check_history skips them; check_serve_load
# owns them).  Growth bounds are loose for the same reason the drop
# bound is: CPU-CI tail latency is noisy run to run, and the gate
# exists to catch the 2-10x regressions a queueing bug or a lost
# coalescing path produces, not scheduler jitter.
DEFAULT_P95_GROWTH_FRAC = 1.0     # p95 at the reference RPS may double
DEFAULT_SLO_DROP = 0.15           # absolute attainment drop at ref RPS
DEFAULT_R429_GROWTH = 0.20        # absolute 429-rate growth at ref RPS

# fcfleet (serve_fleet) gate thresholds.  These artifacts are
# HIGHER-IS-BETTER scaling ratios (achieved-rps at N replicas vs 1
# under weak scaling), but ratios taken at different fleet sizes are
# not one trajectory — check_history skips its value rules for them
# (the warm-compile rule still applies) and check_serve_fleet owns
# them, anchored on matching largest fleet size.  The absolute rules
# (drill health, bundles, inheritance) arm from the FIRST committed
# artifact: a chaos drill that loses jobs is wrong regardless of
# history.
DEFAULT_FLEET_SCALING_DROP = 0.15   # fractional efficiency drop vs median
DEFAULT_FLEET_ATTAIN_MIN = 0.99     # absolute SLO attainment floor/point

# fcdelta (serve_delta) gate thresholds.  Absolute, armed from the
# first committed artifact: every scenario carries its OWN from-scratch
# twin, so the comparison never needs history.  The NMI band matches
# the ISSUE acceptance (incremental quality within 0.02 of scratch);
# the device bound is the existential one — an "incremental" run that
# costs at least a from-scratch recompute has no reason to exist.
DEFAULT_DELTA_NMI_GAP = 0.02        # incremental NMI may trail scratch by
DEFAULT_DELTA_ATTAIN_MIN = 1.0      # delta-class SLO attainment floor

# fctrace (telemetry.fleet_latency) gate thresholds.  The absolute
# rules arm from the first committed artifact: an unscrapable replica
# during the /fleetz scrape, an inexact histogram merge (fleet counts
# != sum of per-replica counts), or a merged fleet p95 that EXCEEDS
# the worst single replica's p95 (a mixture quantile on a shared
# bucket grid is bounded by its components — violating that means the
# merge is wrong, not the fleet slow) each block.  The trajectory
# bounds are loose, like the serve_load ones: CPU-CI proxy hops are
# scheduler-noisy, and the gate hunts the 2-10x regressions a
# busy-poll or serialization bug produces.
DEFAULT_FLEET_E2E_GROWTH = 1.0      # fleet-merged e2e p95 may double
DEFAULT_PROXY_OVERHEAD_GROWTH = 1.5 # worst proxy-overhead p95 growth

# fcqual (quality-block) gate thresholds.  Same calibration philosophy:
# loose enough that detector stochasticity (seeded, but the LFR graphs
# themselves differ per generator build) never trips them, tight enough
# that the failure modes they exist for — a weight-update bug doubling
# rounds-to-converge, a churn bug collapsing ensemble agreement, a
# frontier that stops contracting because thresholding went dead — all
# land well outside the band.
DEFAULT_ROUNDS_GROWTH_FRAC = 1.0  # rounds-to-converge may double
DEFAULT_AGREEMENT_DROP = 0.10     # absolute final-agreement drop
DEFAULT_FRONTIER_GROWTH = 0.25    # absolute late-frontier-frac growth


def _seq_from_name(path: str) -> Optional[int]:
    """``BENCH_r03.json`` / ``bench_lfr1k_r5.json`` -> 3 / 5; None when
    the filename carries no round suffix."""
    m = re.search(r"_r0*(\d+)(?:\.json)?$", os.path.basename(path))
    return int(m.group(1)) if m else None


def _config_key(rec: dict) -> str:
    """Stable per-config grouping key from the bench unit string, e.g.
    ``partitions/s/chip (lfr=lfr1k, alg=louvain, n_p=50)`` ->
    ``lfr1k/louvain/np50`` (plus the mesh shape when sharded)."""
    unit = str(rec.get("unit", ""))
    m = re.search(r"\(lfr=([^,)]+), *alg=([^,)]+), *n_p=(\d+)\)", unit)
    if m:
        # primary: the unit parse — it is the only key the ENTIRE
        # committed history carries, so old and new artifacts of one
        # config land in one trajectory
        key = f"{m.group(1)}/{m.group(2)}/np{m.group(3)}"
    elif rec.get("config"):
        # PR-3+ bench.py artifacts carry an explicit config name;
        # fallback for a future unit-string format change
        key = str(rec["config"])
    else:
        key = str(rec.get("metric", "unknown"))
    mesh = rec.get("mesh")
    if mesh and mesh != "1x1":
        key += f"/mesh{mesh}"
    return key


def _normalize(rec: dict, source: str, seq: Optional[int]) -> dict:
    tel = rec.get("telemetry") or {}

    def p95(name):
        s = tel.get(name)
        return s.get("p95") if isinstance(s, dict) else None

    return {
        "source": source,
        "seq": seq,
        "config": _config_key(rec),
        "metric": rec.get("metric"),
        # the raw bench unit string, kept for matchers that key on the
        # posture it names (check_cost_calibration greps the serve_load
        # bucket out of it)
        "unit": rec.get("unit"),
        "value": float(rec["value"]),
        "vs_baseline": rec.get("vs_baseline"),
        "nmi": rec.get("nmi"),
        "baseline_nmi": rec.get("baseline_nmi"),
        "seconds": rec.get("seconds"),
        "rounds": rec.get("rounds"),
        "converged": rec.get("converged"),
        "backend": rec.get("backend"),
        "mesh": rec.get("mesh"),
        "rtt_post_ms": rec.get("dispatch_rtt_ms_post"),
        "compiles_cold": tel.get("compiles_cold"),
        "compiles_warm": tel.get("compiles_warm"),
        "host_syncs_total": (sum(tel["host_syncs"].values())
                             if isinstance(tel.get("host_syncs"), dict)
                             else None),
        "round_p95_s": p95("round_s"),
        "detect_p95_s": p95("detect_call_s"),
        # multi-device serving artifacts (bench.py serve_multichip /
        # serve/pool.py): per-device jobs/compiles/busy breakdown, kept
        # verbatim for device_table()
        "devices": tel.get("devices") or None,
        # fclat serve_load artifacts (bench.py serve_load): the whole
        # per-RPS latency curve, kept verbatim for serve_load_table()
        # and check_serve_load()
        "serve_load": tel.get("serve_load") or None,
        # fcfleet serve_fleet artifacts (bench.py serve_fleet): the
        # weak-scaling points + chaos-drill block, kept verbatim for
        # serve_fleet_table() and check_serve_fleet()
        "serve_fleet": tel.get("serve_fleet") or None,
        # fcdelta serve_delta artifacts (bench.py serve_delta): the
        # drift-vs-quality scenario block (per-k incremental vs
        # from-scratch device time / NMI / compiles), kept verbatim
        # for check_delta()
        "serve_delta": tel.get("serve_delta") or None,
        # fcflight incident-health block (bench.py serve_load): watchdog
        # trips / bundles written / exemplar count, kept verbatim for
        # check_flight() — a clean sequenced load run that TRIPS the
        # hang watchdog is a serving regression even when the latency
        # curve still passes
        "flight": tel.get("flight") or None,
        # fctrace fleet-latency block (bench.py serve_fleet, scraped
        # from the router's /fleetz): router-phase p95s, per-replica
        # proxy overhead, fleet-merged e2e p95 vs the worst single
        # replica, and the exact-merge verdict, kept verbatim for
        # check_fleet_latency()
        "fleet_latency": tel.get("fleet_latency") or None,
        # fcqual quality block (obs/quality.py summarize_history), kept
        # verbatim for quality_table() and check_quality(); None on
        # pre-fcqual artifacts
        "quality": tel.get("quality") or None,
    }


def _candidate_records(doc) -> List[dict]:
    """Bench records inside one parsed JSON document: the document
    itself, its ``parsed`` field (driver wrapper), or its ``record``
    field (the VMESH artifact shape) — whichever carry metric+value."""
    out = []
    if isinstance(doc, dict):
        # fcheck: ok=phantom-reader (the parsed/record fields are
        # wrapper shapes produced by *external* bench drivers — the
        # VMESH artifact layout — deliberately accepted though nothing
        # in this repo writes them)
        for cand in (doc.get("parsed"), doc.get("record"), doc):
            if isinstance(cand, dict) and "metric" in cand \
                    and "value" in cand:
                out.append(cand)
                break
    return out


def load_records(path: str) -> List[dict]:
    """Normalized bench records from one artifact file (possibly JSON
    lines); [] when the file holds nothing bench-shaped."""
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        return []
    docs = []
    try:
        docs = [json.loads(text)]
    except json.JSONDecodeError:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                docs.append(json.loads(line))
            # fcheck: ok=swallowed-error (a torn/corrupt history
            # line is expected under concurrent appends; the
            # loader keeps every parsable record)
            except json.JSONDecodeError:
                continue
    records = []
    for doc in docs:
        seq = (doc["n"] if isinstance(doc, dict)
               and isinstance(doc.get("n"), int)
               else _seq_from_name(path))
        for rec in _candidate_records(doc):
            records.append(_normalize(rec, os.path.basename(path), seq))
    return records


def build_history(paths: List[str]) -> Dict[str, List[dict]]:
    """Records from every path, grouped by config key and ordered by
    (sequence, source) — unsequenced records sort first (they are
    never "the latest"; see module docstring)."""
    groups: Dict[str, List[dict]] = {}
    for path in paths:
        for rec in load_records(path):
            groups.setdefault(rec["config"], []).append(rec)
    for recs in groups.values():
        recs.sort(key=lambda r: (r["seq"] is not None, r["seq"] or 0,
                                 r["source"]))
    return dict(sorted(groups.items()))


def _fmt(v, nd=3) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        return f"{v:.{nd}f}".rstrip("0").rstrip(".") or "0"
    return str(v)


_COLUMNS: List[Tuple[str, str]] = [
    ("seq", "seq"), ("source", "source"), ("value", "p/s/chip"),
    ("vs_baseline", "vs_cpu"), ("nmi", "nmi"), ("rounds", "rounds"),
    ("converged", "conv"), ("compiles_warm", "warm_compiles"),
    ("host_syncs_total", "host_syncs"), ("round_p95_s", "round_p95_s"),
    ("detect_p95_s", "detect_p95_s"), ("rtt_post_ms", "rtt_post_ms"),
]


def trend_table(groups: Dict[str, List[dict]],
                markdown: bool = False) -> str:
    """Per-config trend report over the normalized history."""
    lines: List[str] = []
    for config, recs in groups.items():
        lines += _render_rows(config, [h for _, h in _COLUMNS],
                              [[_fmt(r[k]) for k, _ in _COLUMNS]
                               for r in recs], markdown)
    return "\n".join(lines).rstrip() or "(no bench records found)"


def device_table(groups: Dict[str, List[dict]],
                 markdown: bool = False) -> str:
    """Per-device breakdown tables for configs whose newest record
    carries one (the ``serve_multichip`` artifacts): device, tier kind,
    jobs, batches, XLA compiles, busy seconds and busy fraction.  Empty
    string when no record in the history has device telemetry."""
    header = ["device", "kind", "jobs", "batches", "compiles",
              "busy_s", "busy_frac", "cordoned"]
    lines: List[str] = []
    for config, recs in groups.items():
        newest = next((r for r in reversed(recs) if r.get("devices")),
                      None)
        if newest is None:
            continue
        rows = []
        for dev in sorted(newest["devices"], key=lambda d: int(d)):
            d = newest["devices"][dev]
            rows.append([dev, str(d.get("kind", "-")),
                         _fmt(d.get("jobs"), 0),
                         _fmt(d.get("batches"), 0),
                         _fmt(d.get("xla_compiles"), 0),
                         _fmt(d.get("busy_s")),
                         _fmt(d.get("busy_frac")),
                         "yes" if d.get("cordoned") else "no"])
        lines += _render_rows(f"{config} devices [{newest['source']}]",
                              header, rows, markdown)
    return "\n".join(lines).rstrip()


_SL_PHASES = ("queue_wait", "hold", "dispatch", "deque_wait", "pack",
              "device", "fanout", "respond")
_SL_CLASSES = ("interactive", "normal", "batch")


def _sl_rows(points) -> List[List[str]]:
    rows = []
    for pt in points:
        slo = pt.get("slo") or {}
        phases = pt.get("phase_p95_ms") or {}
        batch = pt.get("batch") or {}
        rows.append(
            [_fmt(pt.get("rps")), _fmt(pt.get("achieved_rps")),
             _fmt(pt.get("completed"), 0),
             _fmt(pt.get("rejected_429"), 0),
             _fmt(pt.get("p50_ms")), _fmt(pt.get("p95_ms")),
             _fmt(pt.get("p99_ms")), _fmt(slo.get("attainment")),
             _fmt(batch.get("mean_occupancy"))]
            + [_fmt(phases.get(p)) for p in _SL_PHASES])
    return rows


def serve_load_table(groups: Dict[str, List[dict]],
                     markdown: bool = False) -> str:
    """Latency-vs-RPS tables for configs whose newest record carries a
    ``serve_load`` curve (the ``bench.py serve_load`` artifacts): per
    swept RPS point, the achieved throughput, client-observed and
    server-side percentiles, the 429/backpressure rate, SLO attainment,
    mean batch-rung occupancy, and the per-phase p95 breakdown — so a
    coalescing or admission change shows up as queue-wait/hold
    movement, not just a throughput scalar.  A record carrying the
    fcshape ``mixed`` block (the mixed-SLO-class sweep) renders a
    second table with per-class attainment columns.  Empty string when
    no record has a curve."""
    header = (["rps", "achieved", "jobs", "429s", "p50_ms", "p95_ms",
               "p99_ms", "slo_attain", "occup"]
              + [f"{p}_p95" for p in _SL_PHASES])
    lines: List[str] = []
    for config, recs in groups.items():
        newest = next((r for r in reversed(recs)
                       if r.get("serve_load")), None)
        if newest is None:
            continue
        ref = newest["serve_load"].get("reference_rps")
        lines += _render_rows(
            f"{config} latency vs RPS [{newest['source']}; "
            f"reference rps {_fmt(ref)}]", header,
            _sl_rows(newest["serve_load"].get("points", ())), markdown)
        mixed = newest["serve_load"].get("mixed")
        if mixed:
            mix_header = (["rps", "p95_ms", "429s", "sheds", "occup"]
                          + [f"{c}_attain" for c in _SL_CLASSES])
            rows = []
            for pt in mixed.get("points", ()):
                by_cls = pt.get("slo_by_class") or {}
                batch = pt.get("batch") or {}
                rows.append(
                    [_fmt(pt.get("rps")), _fmt(pt.get("p95_ms")),
                     _fmt(pt.get("rejected_429"), 0),
                     _fmt(pt.get("rejected_shed"), 0),
                     _fmt(batch.get("mean_occupancy"))]
                    + [_fmt((by_cls.get(c) or {}).get("attainment"))
                       for c in _SL_CLASSES])
            lines.append("")
            lines += _render_rows(
                f"{config} mixed-SLO sweep [{newest['source']}; "
                f"mix {mixed.get('mix')}]", mix_header, rows, markdown)
    return "\n".join(lines).rstrip()


def _sl_ref_point(rec: dict) -> Optional[dict]:
    """The record's curve point at its own reference RPS (the gate's
    anchor — the least-saturated point, where p95 measures the serving
    path rather than queueing noise)."""
    sl = rec.get("serve_load") or {}
    ref = sl.get("reference_rps")
    for pt in sl.get("points", ()):
        if pt.get("rps") == ref:
            return pt
    return None


def _r429_rate(pt: dict) -> Optional[float]:
    rejected = pt.get("rejected_429")
    submitted = pt.get("submitted")
    if rejected is None or not submitted:
        return None
    return rejected / submitted


def check_serve_load(groups: Dict[str, List[dict]],
                     p95_growth_frac: float = DEFAULT_P95_GROWTH_FRAC,
                     slo_drop: float = DEFAULT_SLO_DROP,
                     r429_growth: float = DEFAULT_R429_GROWTH
                     ) -> List[str]:
    """Tail-latency regression findings over serve_load curves; [] means
    the gate passes.  Per config, the newest sequenced curve is judged
    at the reference RPS against the median of its sequenced
    predecessors: p95 growth beyond ``p95_growth_frac``, an SLO
    attainment drop beyond ``slo_drop`` (absolute), or a 429-rate
    growth beyond ``r429_growth`` (absolute) is a finding.  One
    committed curve has no trajectory and passes — the gate arms itself
    the round after an artifact lands, like check_history."""
    problems: List[str] = []
    for config, recs in groups.items():
        seqd = [r for r in recs if r["seq"] is not None
                and r.get("serve_load")]
        if len(seqd) < 2:
            continue
        latest_seq = max(r["seq"] for r in seqd)
        latest = [r for r in seqd if r["seq"] == latest_seq]
        latest_refs = {((r.get("serve_load") or {}).get("reference_rps"),
                        (r.get("serve_load") or {}).get("mix"))
                       for r in latest}
        # compare at the SAME (reference RPS, workload mix) only: a
        # sweep whose grid (and therefore reference point) changed has
        # no prior anchor — judging its 8-rps p95 against a 2-rps
        # median would manufacture a "regression" out of ordinary
        # queueing — and neither has one whose SLO-class mix changed
        # (fcshape: a mixed workload queues differently by design; the
        # mixed sweep itself rides the separate `mixed` block, which
        # never gates).  bench.py stamps the main sweep's mix
        # explicitly (None = single-class, the only value it emits
        # today); pre-fcshape artifacts carry no key and read as None
        # too, so existing histories keep gating, while any future
        # mixed-main record separates from single-class priors here.
        prior = [r for r in seqd if r["seq"] < latest_seq
                 and ((r.get("serve_load") or {}).get("reference_rps"),
                      (r.get("serve_load") or {}).get("mix"))
                 in latest_refs]
        prior_pts = [(_sl_ref_point(r), r) for r in prior]
        prior_p95 = [p["p95_ms"] for p, _ in prior_pts
                     if p and p.get("p95_ms") is not None]
        prior_attain = [p["slo"]["attainment"] for p, _ in prior_pts
                        if p and (p.get("slo") or {}).get("attainment")
                        is not None]
        prior_429 = [r for r in (_r429_rate(p) for p, _ in prior_pts
                                 if p) if r is not None]
        for r in latest:
            pt = _sl_ref_point(r)
            if pt is None:
                continue
            tag = f"{config} [{r['source']} seq {r['seq']}]"
            ref = (r.get("serve_load") or {}).get("reference_rps")
            if prior_p95 and pt.get("p95_ms") is not None:
                base = _median(prior_p95)
                ceil = (1.0 + p95_growth_frac) * base
                if pt["p95_ms"] > ceil:
                    problems.append(
                        f"{tag}: p95 {pt['p95_ms']:.1f} ms at the "
                        f"reference RPS ({ref}) grew past "
                        f"{ceil:.1f} ms ({p95_growth_frac:.0%} over "
                        f"the prior median {base:.1f} ms) — a tail-"
                        f"latency regression")
            att = (pt.get("slo") or {}).get("attainment")
            if prior_attain and att is not None:
                base = _median(prior_attain)
                if att < base - slo_drop:
                    problems.append(
                        f"{tag}: SLO attainment {att:.3f} at the "
                        f"reference RPS ({ref}) dropped more than "
                        f"{slo_drop} below the prior median "
                        f"{base:.3f}")
            rate = _r429_rate(pt)
            if prior_429 and rate is not None:
                base = _median(prior_429)
                if rate > base + r429_growth:
                    problems.append(
                        f"{tag}: 429 rate {rate:.3f} at the reference "
                        f"RPS ({ref}) grew more than {r429_growth} "
                        f"over the prior median {base:.3f} — the "
                        f"server sheds load it used to serve")
    return problems


def serve_fleet_table(groups: Dict[str, List[dict]],
                      markdown: bool = False) -> str:
    """Weak-scaling + chaos-drill tables for configs whose newest
    record carries a ``serve_fleet`` block (the ``bench.py
    serve_fleet`` artifacts): per fleet size, the offered/achieved
    RPS, failure/shed counts, percentiles, SLO attainment, and warm
    compiles; then a one-row drill summary (victim, drain exit,
    successor, re-homed groups, bundles, the inherited-cache
    resubmit); then, when the record carries the r18
    ``fleet_latency`` block, the fctrace summary — router phase p95s,
    the exact-merged fleet e2e p95 against the worst single replica,
    and the per-replica proxy-overhead attribution.  Empty string
    when no record has the block."""
    header = ["replicas", "offered", "achieved", "jobs", "failed",
              "429s", "p50_ms", "p95_ms", "attain", "compiles"]
    lines: List[str] = []
    for config, recs in groups.items():
        newest = next((r for r in reversed(recs)
                       if r.get("serve_fleet")), None)
        if newest is None:
            continue
        sf = newest["serve_fleet"]
        rows = [[_fmt(pt.get("replicas"), 0),
                 _fmt(pt.get("offered_rps")),
                 _fmt(pt.get("achieved_rps")),
                 _fmt(pt.get("completed"), 0),
                 _fmt(pt.get("failed"), 0),
                 _fmt(pt.get("rejected_429"), 0),
                 _fmt(pt.get("p50_ms"), 1), _fmt(pt.get("p95_ms"), 1),
                 _fmt(pt.get("attainment")),
                 _fmt(pt.get("compiles"), 0)]
                for pt in sf.get("points", ())]
        scaling = ", ".join(f"x{s}={_fmt(v)}" for s, v in
                            sorted((sf.get("scaling") or {}).items()))
        lines += _render_rows(
            f"{config} weak scaling [{newest['source']}; "
            f"{_fmt(sf.get('rps_per_replica'))} rps/replica; "
            f"scaling {scaling or '-'}]", header, rows, markdown)
        drill = sf.get("drill") or {}
        if drill:
            burst = drill.get("burst") or {}
            resub = drill.get("resubmit_after_death") or {}
            lines += _render_rows(
                f"{config} chaos drill [{newest['source']}]",
                ["victim", "drain_exit", "successor", "jobs", "failed",
                 "replays", "rehomed", "bundles", "resubmit_cached"],
                [[_fmt(drill.get("victim")),
                  _fmt(drill.get("victim_drain_exit"), 0),
                  _fmt(drill.get("successor")),
                  _fmt(burst.get("completed"), 0),
                  _fmt(burst.get("failed"), 0),
                  _fmt((drill.get("fleet_counters") or {}).get(
                      "serve.fleet.replays"), 0),
                  _fmt((drill.get("fleet_counters") or {}).get(
                      "serve.fleet.rehomed_buckets"), 0),
                  _fmt(len(drill.get("bundles") or ()), 0),
                  _fmt(resub.get("cached"))]], markdown)
        fl = newest.get("fleet_latency") or {}
        if fl:
            ph = fl.get("router_phase_p95_ms") or {}
            down = ",".join(fl.get("replicas_down") or ()) or "-"
            lines += _render_rows(
                f"{config} fctrace fleet latency [{newest['source']}; "
                f"merge_exact={_fmt(fl.get('merge_exact'))}; "
                f"down={down}]",
                ["admit_p95", "ring_p95", "proxy_p95", "replay_p95",
                 "fleet_e2e_p95", "worst_e2e_p95"],
                [[_fmt(ph.get("admit")), _fmt(ph.get("ring_lookup")),
                  _fmt(ph.get("proxy")), _fmt(ph.get("replay")),
                  _fmt(fl.get("fleet_e2e_p95_ms"), 1),
                  _fmt(fl.get("worst_replica_e2e_p95_ms"), 1)]],
                markdown)
            overhead = fl.get("proxy_overhead_p95_ms") or {}
            if overhead:
                lines += _render_rows(
                    f"{config} router proxy overhead per replica "
                    f"[{newest['source']}]",
                    ["replica", "proxy_p95_ms"],
                    [[name, _fmt(overhead[name])]
                     for name in sorted(overhead)], markdown)
    return "\n".join(lines).rstrip()


def _fleet_efficiency(rec: dict) -> Optional[Tuple[int, float]]:
    """(largest fleet size, scaling efficiency at it) for one
    serve_fleet record — efficiency = achieved-rps ratio / size, so
    records swept to different fleet sizes compare on one axis."""
    sf = rec.get("serve_fleet") or {}
    scaling = sf.get("scaling") or {}
    sizes = [int(s) for s in scaling if scaling[s] is not None]
    if not sizes:
        return None
    largest = max(sizes)
    return largest, float(scaling[str(largest)]) / largest


def check_serve_fleet(groups: Dict[str, List[dict]],
                      scaling_drop: float = DEFAULT_FLEET_SCALING_DROP,
                      attain_min: float = DEFAULT_FLEET_ATTAIN_MIN
                      ) -> List[str]:
    """fcfleet findings over serve_fleet records; [] means the gate
    passes.  Two kinds of rule:

    * **Absolute**, armed from the first committed artifact, judged on
      the newest sequence only: a scaling point that failed/stranded/
      shed jobs or missed its SLO floor; a chaos drill that lost jobs,
      whose victim's rolling drain exited non-zero, that re-homed
      nothing, collected no flight bundle, or whose inherited-cache
      resubmit came back uncached.  A drill that loses work is wrong
      no matter what earlier rounds did.
    * **Trajectory**: the newest sequenced record's scaling efficiency
      (ratio / fleet size, at its largest size) against the median of
      sequenced predecessors AT THE SAME largest size — a drop beyond
      ``scaling_drop`` (fractional) is a finding.  Ratios at different
      fleet sizes are not one trajectory, same reasoning as
      check_serve_load's reference-RPS anchor.
    """
    problems: List[str] = []
    for config, recs in groups.items():
        seqd = [r for r in recs if r["seq"] is not None
                and r.get("serve_fleet")]
        if not seqd:
            continue
        latest_seq = max(r["seq"] for r in seqd)
        for r in seqd:
            if r["seq"] != latest_seq:
                continue
            tag = f"{config} [{r['source']} seq {r['seq']}]"
            sf = r["serve_fleet"]
            for pt in sf.get("points", ()):
                n = pt.get("replicas")
                lost = ((pt.get("failed") or 0)
                        + (pt.get("stranded") or 0))
                if lost:
                    problems.append(
                        f"{tag}: {lost} job(s) failed/stranded at "
                        f"fleet size {n} — a healthy fleet under its "
                        f"offered load must lose nothing")
                if pt.get("rejected_429"):
                    problems.append(
                        f"{tag}: {pt['rejected_429']} submission(s) "
                        f"shed (429) at fleet size {n} — the router "
                        f"stopped absorbing the per-replica load it "
                        f"used to")
                att = pt.get("attainment")
                if att is not None and att < attain_min:
                    problems.append(
                        f"{tag}: SLO attainment {att:.3f} at fleet "
                        f"size {n} below the {attain_min} floor")
            drill = sf.get("drill") or {}
            if drill:
                burst = drill.get("burst") or {}
                lost = ((burst.get("failed") or 0)
                        + (burst.get("stranded") or 0))
                if lost:
                    problems.append(
                        f"{tag}: the chaos drill lost {lost} job(s) — "
                        f"re-home + replay must hide a replica death "
                        f"from clients")
                drain_exit = drill.get("victim_drain_exit")
                if drain_exit not in (None, 0):
                    problems.append(
                        f"{tag}: the drill victim's rolling drain "
                        f"exited {drain_exit} — drain must absorb its "
                        f"armed spill fault and still exit clean")
                fc = drill.get("fleet_counters") or {}
                if not fc.get("serve.fleet.rehomed_buckets"):
                    problems.append(
                        f"{tag}: the drill re-homed no groups — the "
                        f"kill either missed live traffic or the "
                        f"cordon path went dead")
                if not drill.get("bundles"):
                    problems.append(
                        f"{tag}: the drill collected no flight "
                        f"bundle — the SIGQUIT post-mortem path went "
                        f"dead")
                resub = drill.get("resubmit_after_death") or {}
                if not resub.get("found_victim_job"):
                    problems.append(
                        f"{tag}: the drill found no victim-served job "
                        f"to resubmit — the inheritance demo proved "
                        f"nothing")
                elif resub.get("cached") is not True:
                    problems.append(
                        f"{tag}: resubmitting a dead replica's job "
                        f"came back uncached — cache inheritance "
                        f"(on_death spill load) went dead")
        # trajectory: efficiency at the newest record's largest size vs
        # the median of sequenced predecessors at the same size
        latest = [r for r in seqd if r["seq"] == latest_seq]
        for r in latest:
            eff = _fleet_efficiency(r)
            if eff is None:
                continue
            size, latest_eff = eff
            prior = [e for e in (_fleet_efficiency(p) for p in seqd
                                 if p["seq"] < latest_seq)
                     if e is not None and e[0] == size]
            if not prior:
                continue
            base = _median([e for _, e in prior])
            floor = (1.0 - scaling_drop) * base
            if latest_eff < floor:
                tag = f"{config} [{r['source']} seq {r['seq']}]"
                problems.append(
                    f"{tag}: scaling efficiency {latest_eff:.3f} at "
                    f"fleet size {size} fell below {floor:.3f} "
                    f"({scaling_drop:.0%} drop from the prior median "
                    f"{base:.3f}) — the fleet stopped scaling")
    return problems


def check_delta(groups: Dict[str, List[dict]],
                nmi_gap: float = DEFAULT_DELTA_NMI_GAP,
                attain_min: float = DEFAULT_DELTA_ATTAIN_MIN
                ) -> List[str]:
    """fcdelta findings over serve_delta records (``bench.py
    serve_delta`` drift-vs-quality artifacts); [] means the gate
    passes.  Every rule is **absolute** and judged on the newest
    sequence only: each scenario carries its own from-scratch twin of
    the same perturbed graph, so the incremental path's contract —
    cheaper than recomputing, and nearly as good — is checkable inside
    one artifact with no history anchor.

    * a scenario whose policy ``mode`` differs from the scenario's
      ``expected_mode`` (a small drift that fell back, or an oversized
      one the policy failed to refuse) is a policy regression;
    * an incremental scenario whose NMI trails its from-scratch twin
      by more than ``nmi_gap`` broke the quality contract;
    * an incremental scenario whose device time is >= its from-scratch
      twin's broke the speed contract — an "incremental" run that
      costs a full recompute has no reason to exist;
    * an incremental scenario that compiled anything warm broke the
      shared-executable contract (the frontier mask and warm labels
      are data, not shape — bucketed executables must be reused);
    * delta-class SLO attainment below ``attain_min`` means the new
      SLO class regressed the moment it shipped.
    """
    problems: List[str] = []
    for config, recs in groups.items():
        seqd = [r for r in recs if r["seq"] is not None
                and r.get("serve_delta")]
        if not seqd:
            continue
        latest_seq = max(r["seq"] for r in seqd)
        for r in seqd:
            if r["seq"] != latest_seq:
                continue
            tag = f"{config} [{r['source']} seq {r['seq']}]"
            sd = r["serve_delta"]
            for sc in sd.get("scenarios", ()):
                k = sc.get("k_pct")
                mode = sc.get("mode")
                expected = sc.get("expected_mode")
                if expected is not None and mode != expected:
                    problems.append(
                        f"{tag}: k={k}% perturbation ran "
                        f"mode={mode!r} (reason "
                        f"{sc.get('reason')!r}), expected "
                        f"{expected!r} — the delta policy regressed")
                    continue
                if mode != "incremental":
                    continue  # fallback scenarios ARE the scratch run
                inc = sc.get("incremental") or {}
                scr = sc.get("scratch") or {}
                i_nmi, s_nmi = inc.get("nmi"), scr.get("nmi")
                if i_nmi is not None and s_nmi is not None and \
                        i_nmi < s_nmi - nmi_gap:
                    problems.append(
                        f"{tag}: k={k}% incremental NMI {i_nmi:.4f} "
                        f"trails its from-scratch twin {s_nmi:.4f} by "
                        f"more than {nmi_gap} — warm-start quality "
                        f"broke")
                i_dev, s_dev = inc.get("device_s"), scr.get("device_s")
                if i_dev is not None and s_dev is not None and \
                        float(i_dev) >= float(s_dev):
                    problems.append(
                        f"{tag}: k={k}% incremental device time "
                        f"{float(i_dev):.4f}s >= from-scratch "
                        f"{float(s_dev):.4f}s — the warm-start run "
                        f"costs a full recompute")
                if sc.get("warm_compiles"):
                    problems.append(
                        f"{tag}: k={k}% incremental run compiled "
                        f"{sc['warm_compiles']} executable(s) warm — "
                        f"delta runs must reuse the bucketed "
                        f"executables")
            att = sd.get("slo_delta_attainment")
            if att is not None and float(att) < attain_min:
                problems.append(
                    f"{tag}: delta-class SLO attainment "
                    f"{float(att):.3f} below the {attain_min} floor")
    return problems


def check_flight(groups: Dict[str, List[dict]]) -> List[str]:
    """fcflight findings over sequenced records; [] means the gate
    passes.  Unlike the trend gates this one is absolute, not
    trajectory-based: a CLEAN sequenced load run (the CI serve_load
    sweep drives moderate traffic at healthy RPS) must never trip the
    hang watchdog — a trip means either a real stall in the serving
    path or a watchdog threshold so tight it fires on healthy traffic,
    and both block.  Only the newest sequence is judged (historic
    records keep their trips as archaeology), and records without a
    ``flight`` block (pre-fcflight artifacts) pass vacuously."""
    problems: List[str] = []
    for config, recs in groups.items():
        seqd = [r for r in recs if r["seq"] is not None
                and r.get("flight")]
        if not seqd:
            continue
        latest_seq = max(r["seq"] for r in seqd)
        for r in seqd:
            if r["seq"] != latest_seq:
                continue
            trips = int((r.get("flight") or {}).get(
                "watchdog_trips", 0) or 0)
            if trips > 0:
                problems.append(
                    f"{config} [{r['source']} seq {r['seq']}]: the "
                    f"hang watchdog tripped {trips} time(s) during a "
                    f"clean sequenced load run — a serving stall or a "
                    f"threshold regression (telemetry.flight)")
    return problems


def _worst_proxy_p95(rec: dict) -> Optional[float]:
    """The slowest replica's proxy-overhead p95 (ms) in one record's
    fleet_latency block — the per-replica attribution folded to the
    single worst number a trajectory can run on."""
    fl = rec.get("fleet_latency") or {}
    vals = [float(v) for v in (fl.get("proxy_overhead_p95_ms")
                               or {}).values() if v is not None]
    return max(vals) if vals else None


def check_fleet_latency(groups: Dict[str, List[dict]],
                        e2e_growth: float = DEFAULT_FLEET_E2E_GROWTH,
                        proxy_growth: float =
                        DEFAULT_PROXY_OVERHEAD_GROWTH) -> List[str]:
    """fctrace findings over records carrying a ``fleet_latency``
    block (bench.py serve_fleet's /fleetz scrape); [] means the gate
    passes.  Judged on the newest sequence only, two kinds of rule:

    * **Absolute**, armed from the first committed artifact: a replica
      the /fleetz scrape could not reach, an inexact merge (fleet
      histogram counts != sum of per-replica counts — the bit-exact
      merge contract broke), or a fleet-merged e2e p95 above the worst
      single replica's p95 (impossible for a correct mixture quantile
      on the shared bucket grid; small-count bucket rounding gets a
      5% tolerance).
    * **Trajectory**: fleet-merged e2e p95 and worst-replica proxy
      overhead p95 vs the median of sequenced predecessors — growth
      beyond ``e2e_growth`` / ``proxy_growth`` (fractional) is a
      finding.  Pre-fctrace artifacts pass vacuously."""
    problems: List[str] = []
    for config, recs in groups.items():
        seqd = [r for r in recs if r["seq"] is not None
                and r.get("fleet_latency")]
        if not seqd:
            continue
        latest_seq = max(r["seq"] for r in seqd)
        for r in seqd:
            if r["seq"] != latest_seq:
                continue
            tag = f"{config} [{r['source']} seq {r['seq']}]"
            fl = r["fleet_latency"]
            down = fl.get("replicas_down") or ()
            if down:
                problems.append(
                    f"{tag}: /fleetz could not scrape "
                    f"{', '.join(str(d) for d in down)} — a fleet "
                    f"aggregate that omits a replica reads healthy "
                    f"exactly when it is not")
            if fl.get("merge_exact") is False:
                problems.append(
                    f"{tag}: the /fleetz histogram merge is inexact — "
                    f"fleet counts != sum of per-replica counts, the "
                    f"bit-exact merge contract broke")
            fleet_p95 = fl.get("fleet_e2e_p95_ms")
            worst_p95 = fl.get("worst_replica_e2e_p95_ms")
            if fleet_p95 is not None and worst_p95 is not None \
                    and float(fleet_p95) > 1.05 * float(worst_p95):
                problems.append(
                    f"{tag}: fleet-merged e2e p95 {fleet_p95:.1f}ms "
                    f"exceeds the worst replica's {worst_p95:.1f}ms — "
                    f"a mixture quantile cannot, so the merge (or the "
                    f"scrape) is wrong")
            # trajectory vs the median of sequenced predecessors
            prior = [p for p in seqd if p["seq"] < latest_seq]
            if fleet_p95 is not None:
                base = [float(p["fleet_latency"]["fleet_e2e_p95_ms"])
                        for p in prior
                        if p["fleet_latency"].get("fleet_e2e_p95_ms")
                        is not None]
                if base:
                    ceil = (1.0 + e2e_growth) * _median(base)
                    if float(fleet_p95) > ceil:
                        problems.append(
                            f"{tag}: fleet-merged e2e p95 "
                            f"{float(fleet_p95):.1f}ms grew past "
                            f"{ceil:.1f}ms ({e2e_growth:.0%} over the "
                            f"prior median) — the fleet's tail "
                            f"regressed")
            worst_proxy = _worst_proxy_p95(r)
            if worst_proxy is not None:
                base = [w for w in (_worst_proxy_p95(p) for p in prior)
                        if w is not None]
                if base:
                    ceil = (1.0 + proxy_growth) * _median(base)
                    if worst_proxy > ceil:
                        problems.append(
                            f"{tag}: worst-replica proxy overhead p95 "
                            f"{worst_proxy:.2f}ms grew past "
                            f"{ceil:.2f}ms ({proxy_growth:.0%} over "
                            f"the prior median) — the router hop got "
                            f"expensive")
    return problems


_Q_COLUMNS: List[Tuple[str, str]] = [
    ("rounds", "rounds"), ("rounds_to_converge", "rtc"),
    ("final_agreement", "agreement"),
    ("final_modularity_mean", "modularity"),
    ("final_frontier_frac", "frontier"),
    ("late_frontier_frac", "late_frontier"),
    ("final_churn_frac", "churn"),
    ("labels_changed_total", "labels_moved"),
    ("agg_overflow_total", "agg_ovfl"),
]


def quality_table(groups: Dict[str, List[dict]],
                  markdown: bool = False) -> str:
    """Convergence-quality trend tables for configs whose records carry
    the fcqual ``quality`` block: per artifact, rounds run / rounds to
    converge (``-`` = hit max_rounds unconverged), final ensemble
    agreement and mean modularity, the final and late-half active-
    frontier fractions (how much of the graph still has undecided
    consensus edges — the number a frontier-masked detect pass would
    exploit), total label churn, and aggregate-overflow total.  Empty
    string when no record in the history has a quality block."""
    lines: List[str] = []
    for config, recs in groups.items():
        rows = [[_fmt(r["seq"]), r["source"]]
                + [_fmt((r["quality"] or {}).get(k)) for k, _ in
                   _Q_COLUMNS]
                for r in recs if r.get("quality")]
        if not rows:
            continue
        lines += _render_rows(f"{config} quality",
                              ["seq", "source"]
                              + [h for _, h in _Q_COLUMNS],
                              rows, markdown)
    return "\n".join(lines).rstrip()


def check_quality(groups: Dict[str, List[dict]],
                  rounds_growth_frac: float = DEFAULT_ROUNDS_GROWTH_FRAC,
                  agreement_drop: float = DEFAULT_AGREEMENT_DROP,
                  frontier_growth: float = DEFAULT_FRONTIER_GROWTH
                  ) -> List[str]:
    """Partition-quality regression findings over the fcqual blocks; []
    means the gate passes.  Per config, the newest sequenced record
    carrying a quality block is judged against the median of its
    sequenced predecessors (same arming rule as every other gate here:
    fewer than two sequenced quality-carrying records = no trajectory =
    pass):

    * **rounds-to-converge growth** — the run converges, but in more
      than ``(1 + rounds_growth_frac) x`` the prior median rounds: the
      consensus loop is spinning (a weight-update or churn bug that
      throughput alone hides, because later rounds are cheaper);
    * **final-agreement drop** — final ensemble agreement fell more
      than ``agreement_drop`` (absolute) below the prior median: the
      ensemble stopped agreeing on the partition it ships;
    * **late-frontier growth** — the late-half mean active-frontier
      fraction grew more than ``frontier_growth`` (absolute) over the
      prior median: the frontier stopped contracting, i.e. weight
      thresholding/freezing went dead and "converged" is no longer
      doing work.
    """
    problems: List[str] = []
    for config, recs in groups.items():
        seqd = [r for r in recs if r["seq"] is not None
                and r.get("quality")]
        if len(seqd) < 2:
            continue
        latest_seq = max(r["seq"] for r in seqd)
        latest = [r for r in seqd if r["seq"] == latest_seq]
        prior = [r["quality"] for r in seqd if r["seq"] < latest_seq]

        def _prior(key):
            vals = [q.get(key) for q in prior]
            return [v for v in vals if v is not None]

        prior_rtc = _prior("rounds_to_converge")
        prior_agree = _prior("final_agreement")
        prior_frontier = _prior("late_frontier_frac")
        for r in latest:
            q = r["quality"]
            tag = f"{config} [{r['source']} seq {r['seq']}]"
            rtc = q.get("rounds_to_converge")
            if prior_rtc and rtc is not None:
                base = _median(prior_rtc)
                ceil = (1.0 + rounds_growth_frac) * base
                if rtc > ceil:
                    problems.append(
                        f"{tag}: rounds-to-converge {rtc} grew past "
                        f"{ceil:.1f} ({rounds_growth_frac:.0%} over the "
                        f"prior median {base:.1f}) — the consensus loop "
                        f"is spinning (quality.rounds_to_converge)")
            agree = q.get("final_agreement")
            if prior_agree and agree is not None:
                base = _median(prior_agree)
                if agree < base - agreement_drop:
                    problems.append(
                        f"{tag}: final ensemble agreement {agree:.3f} "
                        f"dropped more than {agreement_drop} below the "
                        f"prior median {base:.3f} "
                        f"(quality.final_agreement)")
            frontier = q.get("late_frontier_frac")
            if prior_frontier and frontier is not None:
                base = _median(prior_frontier)
                if frontier > base + frontier_growth:
                    problems.append(
                        f"{tag}: late-round active-frontier fraction "
                        f"{frontier:.3f} grew more than "
                        f"{frontier_growth} over the prior median "
                        f"{base:.3f} — the frontier stopped "
                        f"contracting (quality.late_frontier_frac)")
    return problems


def load_footprints(paths: List[str]) -> List[dict]:
    """fcheck-footprint artifacts (``runs/footprint_rNN.json`` — the
    schema analysis/footprint.py documents), normalized and ordered by
    round sequence; files that are not footprint artifacts are skipped
    silently, mirroring :func:`load_records`."""
    out = []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        # fcheck: ok=swallowed-error (an unreadable footprint
        # artifact simply drops out of the trend gate's window)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(doc, dict) or \
                doc.get("tool") != "fcheck-footprint":
            continue
        gate = doc.get("gate") or []
        worst = max(gate, key=lambda r: r.get("peak_bytes", 0),
                    default=None)
        out.append({
            "source": os.path.basename(path),
            "seq": _seq_from_name(path),
            "surface_count": doc.get("surface_count"),
            "surface_budget": doc.get("surface_budget"),
            "chip_ceiling_edges": doc.get("chip_ceiling_edges"),
            "max_pad_frac": doc.get("max_pad_frac"),
            "hbm_bytes": (doc.get("config") or {}).get("hbm_bytes"),
            "worst_peak_bytes": (worst or {}).get("peak_bytes"),
            "worst_bucket": (worst or {}).get("bucket"),
            "buckets": doc.get("buckets") or [],
        })
    out.sort(key=lambda r: (r["seq"] is not None, r["seq"] or 0,
                            r["source"]))
    return out


def _render_rows(title: str, header: List[str], rows: List[List[str]],
                 markdown: bool) -> List[str]:
    lines: List[str] = []
    if markdown:
        lines.append(f"### {title}")
        lines.append("| " + " | ".join(header) + " |")
        lines.append("|" + "|".join("---" for _ in header) + "|")
        lines.extend("| " + " | ".join(row) + " |" for row in rows)
    else:
        lines.append(f"== {title} ==")
        widths = [max(len(header[i]), *(len(r[i]) for r in rows))
                  for i in range(len(header))]
        lines.append("  ".join(h.ljust(w)
                               for h, w in zip(header, widths)))
        for row in rows:
            lines.append("  ".join(c.ljust(w)
                                   for c, w in zip(row, widths)))
    lines.append("")
    return lines


def _gib(v) -> str:
    return "-" if v is None else f"{v / (1 << 30):.2f}"


def footprint_table(fps: List[dict], markdown: bool = False) -> str:
    """Trend + per-bucket footprint tables: the executable-surface and
    padding columns of the serving memory model.  Empty string when no
    footprint artifact is committed."""
    if not fps:
        return ""
    lines = _render_rows(
        "fcheck-footprint trend",
        ["seq", "source", "surface", "budget", "ceiling_edges",
         "worst_peak_gib", "worst_bucket", "max_pad"],
        [[_fmt(f["seq"]), f["source"], _fmt(f["surface_count"]),
          _fmt(f["surface_budget"]), _fmt(f["chip_ceiling_edges"]),
          _gib(f["worst_peak_bytes"]), _fmt(f["worst_bucket"]),
          _fmt(f["max_pad_frac"])] for f in fps],
        markdown)
    newest = fps[-1]
    if newest["buckets"]:
        lines += _render_rows(
            f"footprint buckets [{newest['source']}]",
            ["bucket", "batch", "peak_gib", "solo_gib", "arg_mib",
             "pad_frac"],
            [[b["bucket"], _fmt(b.get("batch")),
              _gib(b.get("peak_bytes")), _gib(b.get("solo_peak_bytes")),
              "-" if b.get("arg_bytes") is None
              else f"{b['arg_bytes'] / (1 << 20):.1f}",
              _fmt(b.get("pad_frac"))] for b in newest["buckets"]],
            markdown)
    return "\n".join(lines).rstrip()


def check_footprints(fps: List[dict]) -> List[str]:
    """Footprint regression findings: the newest sequenced artifact's
    executable surface grew versus the prior committed one (a silent
    static-axis or ladder expansion — deliberate growth should raise
    footprint.SURFACE_BUDGET_DEFAULT with a rationale in the same
    change), or its surface breached its own pinned budget."""
    problems: List[str] = []
    seqd = [f for f in fps if f["seq"] is not None
            and f["surface_count"] is not None]
    if not seqd:
        return problems
    newest = seqd[-1]
    tag = f"footprint [{newest['source']} seq {newest['seq']}]"
    prior = [f for f in seqd if f["seq"] < newest["seq"]]
    if prior:
        base = prior[-1]
        if newest["surface_count"] > base["surface_count"]:
            problems.append(
                f"{tag}: executable surface grew "
                f"{base['surface_count']} -> {newest['surface_count']} "
                f"vs {base['source']} — every extra executable is a "
                f"compile the fleet pays per bucket; if deliberate, "
                f"raise the pinned surface budget in the same change")
    if newest["surface_budget"] is not None and \
            newest["surface_count"] > newest["surface_budget"]:
        problems.append(
            f"{tag}: surface {newest['surface_count']} exceeds its own "
            f"pinned budget {newest['surface_budget']}")
    return problems


def load_costs(paths: List[str]) -> List[dict]:
    """fcheck-cost artifacts (``runs/cost_rNN.json`` — the schema
    analysis/cost.py documents), normalized and ordered by round
    sequence; files that are not cost artifacts are skipped silently,
    mirroring :func:`load_footprints`."""
    out = []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        # fcheck: ok=swallowed-error (an unreadable cost artifact
        # simply drops out of the trend gate's window)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(doc, dict) or doc.get("tool") != "fcheck-cost":
            continue
        dead = doc.get("dead_compute") or {}
        out.append({
            "source": os.path.basename(path),
            "seq": _seq_from_name(path),
            "run_dead_frac": dead.get("run_dead_frac"),
            "late_round_dead_frac": dead.get("late_round_dead_frac"),
            "waste_budget": dead.get("waste_budget"),
            "dead_bucket": dead.get("bucket"),
            "round_flops": dead.get("round_flops"),
            "duality": doc.get("duality") or [],
            "gate": doc.get("gate") or [],
            "calibration": doc.get("calibration"),
        })
    out.sort(key=lambda r: (r["seq"] is not None, r["seq"] or 0,
                            r["source"]))
    return out


def _tflop(v) -> str:
    return "-" if v is None else f"{v / 1e12:.3f}"


def cost_table(costs: List[dict], markdown: bool = False) -> str:
    """Trend + duality + roofline tables of the static compute-cost
    model.  Empty string when no cost artifact is committed."""
    if not costs:
        return ""
    lines = _render_rows(
        "fcheck-cost trend",
        ["seq", "source", "dead_bucket", "round_tflops", "run_dead",
         "late_dead", "budget", "cal_est_ms"],
        [[_fmt(c["seq"]), c["source"], _fmt(c["dead_bucket"]),
          _tflop(c["round_flops"]), _fmt(c["run_dead_frac"]),
          _fmt(c["late_round_dead_frac"]), _fmt(c["waste_budget"]),
          _fmt((c["calibration"] or {}).get("est_device_ms"))]
         for c in costs],
        markdown)
    newest = costs[-1]
    if newest["duality"]:
        lines += _render_rows(
            f"cost duality [{newest['source']}]",
            ["bucket", "batch", "solo_s", "per_job_s", "saving"],
            [[d["bucket"], _fmt(d.get("batch")),
              _fmt(d.get("solo_est_s")), _fmt(d.get("per_job_est_s")),
              _fmt(d.get("per_job_saving_frac"))]
             for d in newest["duality"]],
            markdown)
    if newest["gate"]:
        worst = sorted(newest["gate"],
                       key=lambda r: -(r.get("est_device_s") or 0.0))[:8]
        lines += _render_rows(
            f"cost roofline, costliest executables [{newest['source']}]",
            ["kind", "bucket", "batch", "tflops", "intensity", "est_s"],
            [[g["kind"], g["bucket"], _fmt(g.get("batch")),
              _tflop(g.get("flops")), _fmt(g.get("arith_intensity")),
              _fmt(g.get("est_device_s"))] for g in worst],
            markdown)
    return "\n".join(lines).rstrip()


# Modeled est_device_s growth per surface kind between consecutive
# committed cost artifacts that counts as a roofline regression.  Loose
# enough that mirror-coefficient refits pass, tight enough that an
# accidental algorithmic blowup (a lost frontier gate reintroducing an
# n^2 path doubles the estimate and more) always lands outside.
DEFAULT_COST_GROWTH_FRAC = 0.5


def check_costs(costs: List[dict],
                growth_frac: float = DEFAULT_COST_GROWTH_FRAC
                ) -> List[str]:
    """Cost regression findings: per matching (kind, bucket, batch)
    gate row, the newest sequenced artifact's modeled ``est_device_s``
    grew beyond ``growth_frac`` over the prior committed one
    (``cost-roofline-regress``), or the newest dead-compute bill
    breaches its own pinned waste budget (``cost-dead-compute``)."""
    problems: List[str] = []
    seqd = [c for c in costs if c["seq"] is not None]
    if not seqd:
        return problems
    newest = seqd[-1]
    tag = f"cost [{newest['source']} seq {newest['seq']}]"
    prior = [c for c in seqd if c["seq"] < newest["seq"]]
    if prior:
        base_rows = {(g["kind"], g["bucket"], g.get("batch")):
                     g.get("est_device_s") for g in prior[-1]["gate"]}
        for g in newest["gate"]:
            base = base_rows.get((g["kind"], g["bucket"], g.get("batch")))
            est = g.get("est_device_s")
            if not base or est is None:
                continue
            if est > base * (1.0 + growth_frac):
                problems.append(
                    f"{tag}: cost-roofline-regress: modeled "
                    f"est_device_s for {g['kind']} at {g['bucket']} "
                    f"grew {base:.6f}s -> {est:.6f}s "
                    f"(> +{growth_frac:.0%} vs {prior[-1]['source']}) "
                    f"— the static surface got costlier; if "
                    f"deliberate, land the re-baseline with the "
                    f"change that justifies it")
    if newest["run_dead_frac"] is not None and \
            newest["waste_budget"] is not None and \
            newest["run_dead_frac"] > newest["waste_budget"]:
        problems.append(
            f"{tag}: cost-dead-compute: run dead-compute fraction "
            f"{newest['run_dead_frac']:.2f} breaches the pinned waste "
            f"budget {newest['waste_budget']:.2f}")
    return problems


def check_cost_calibration(costs: List[dict],
                           groups: Dict[str, List[dict]]) -> List[str]:
    """Predicted-vs-measured honesty gate for the static cost model:
    the newest cost artifact's ``calibration.est_device_ms`` (the
    modeled device time of the serve_load reference executable) must
    land within ``calibration.band`` (either direction) of the measured
    ``serve.phase.device`` tail at the reference RPS of the newest
    committed serve_load curve on the same bucket.  A model outside the
    band is feeding the shaper and scheduler priors that no longer
    describe the hardware."""
    problems: List[str] = []
    cal = None
    for c in costs:
        if c.get("calibration"):
            cal = (c, c["calibration"])
    if cal is None:
        return problems
    crec, cblock = cal
    bucket = str(cblock.get("bucket") or "")
    est_ms = cblock.get("est_device_ms")
    band = float(cblock.get("band") or 4.0)
    if not est_ms or not bucket:
        return problems
    measured: List[Tuple[int, str, float]] = []
    for recs in groups.values():
        for r in recs:
            if not r.get("serve_load") or r["seq"] is None:
                continue
            if f"bucket {bucket}" not in str(r.get("unit", "")):
                continue
            pt = _sl_ref_point(r)
            dev = ((pt or {}).get("phase_p95_ms") or {}).get("device")
            if dev:
                measured.append((r["seq"], r["source"], float(dev)))
    if not measured:
        return problems
    seq, source, dev_ms = max(measured)
    ratio = max(est_ms / dev_ms, dev_ms / est_ms)
    if ratio > band:
        problems.append(
            f"cost [{crec['source']} seq {crec['seq']}]: calibration "
            f"drift at {bucket}: modeled device time {est_ms:.1f} ms "
            f"vs measured serve.phase.device p95 {dev_ms:.1f} ms "
            f"[{source} seq {seq}] is {ratio:.1f}x apart "
            f"(band {band:.0f}x) — refit the machine-model constants "
            f"or find what the model stopped seeing")
    return problems


def check_history(groups: Dict[str, List[dict]],
                  max_drop_frac: float = DEFAULT_MAX_DROP_FRAC,
                  nmi_drop: float = DEFAULT_NMI_DROP) -> List[str]:
    """Regression findings over the history; [] means the gate passes.

    Per config, the newest sequenced record(s) are judged against the
    median of the earlier sequenced ones (median, not min/max: the
    committed history contains one known transport-collapsed round whose
    value must neither fail the gate retroactively nor drag the baseline
    down).  Configs with fewer than two sequenced records have no
    trajectory to judge and pass.
    """
    problems: List[str] = []
    for config, recs in groups.items():
        seqd = [r for r in recs if r["seq"] is not None]
        if len(seqd) < 2:
            continue
        latest_seq = max(r["seq"] for r in seqd)
        latest = [r for r in seqd if r["seq"] == latest_seq]
        prior = [r for r in seqd if r["seq"] < latest_seq]
        if not prior:
            continue
        base_value = _median([r["value"] for r in prior])
        prior_nmi = [r["nmi"] for r in prior if r["nmi"] is not None]
        for r in latest:
            tag = f"{config} [{r['source']} seq {r['seq']}]"
            if r.get("serve_load") or r.get("serve_fleet") \
                    or r.get("serve_delta"):
                # latency-curve artifacts are lower-is-better: the
                # throughput-drop/NMI rules would gate the WRONG
                # direction (an improvement would "fail").  The tail-
                # latency gate (check_serve_load) owns them; the
                # warm-compile retrace rule still applies below.
                # serve_fleet artifacts are higher-is-better scaling
                # RATIOS, but ratios taken at different largest fleet
                # sizes are not one trajectory — check_serve_fleet
                # owns them, anchored on matching size.  serve_delta
                # artifacts are speedup ratios vs an in-artifact
                # from-scratch twin — check_delta owns them with
                # absolute rules.
                if (r["compiles_warm"] or 0) > 0:
                    problems.append(
                        f"{tag}: {r['compiles_warm']} warm-run "
                        f"compile(s) — a retrace regression "
                        f"(telemetry.compiles_warm)")
                continue
            floor = (1.0 - max_drop_frac) * base_value
            if r["value"] < floor:
                problems.append(
                    f"{tag}: throughput {r['value']:.3f} fell below "
                    f"{floor:.3f} ({max_drop_frac:.0%} drop from the "
                    f"prior median {base_value:.3f})")
            if prior_nmi and r["nmi"] is not None and \
                    r["nmi"] < _median(prior_nmi) - nmi_drop:
                problems.append(
                    f"{tag}: NMI {r['nmi']:.4f} dropped more than "
                    f"{nmi_drop} below the prior median "
                    f"{_median(prior_nmi):.4f}")
            prior_conv = [p["converged"] for p in prior
                          if p["converged"] is not None]
            # prior_conv must be non-empty: with no prior convergence
            # evidence at all, all([]) would vacuously "prove" every
            # prior run converged and fail CI on a false premise
            if r["converged"] is False and prior_conv and \
                    all(prior_conv):
                problems.append(
                    f"{tag}: run no longer converges (every prior "
                    f"sequenced run did)")
            if (r["compiles_warm"] or 0) > 0:
                problems.append(
                    f"{tag}: {r['compiles_warm']} warm-run compile(s) — "
                    f"a retrace regression (telemetry.compiles_warm)")
    return problems
