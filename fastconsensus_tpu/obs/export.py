"""fcobs exporters: JSONL event log, Chrome/Perfetto trace JSON, text table.

Three views of one run's spans (obs/tracer.py) + counters
(obs/counters.py):

* :func:`write_jsonl` — append-friendly event log, one JSON object per
  line (``{"kind": "span", ...}`` per span, a final ``{"kind":
  "counters", ...}`` snapshot record).  The machine-diffable artifact for
  regression archaeology.
* :func:`write_perfetto` — Chrome ``trace_event`` JSON (the
  ``{"traceEvents": [...]}`` object form) loadable directly in
  ``ui.perfetto.dev`` or ``chrome://tracing``: complete ("X") events with
  microsecond ``ts``/``dur``, thread tracks named after the host threads
  that ran the spans, the counter snapshot under ``otherData``.  Events
  are sorted by ``ts`` so the artifact is reproducible byte-for-byte for
  a deterministic run.
* :func:`summary_table` — the plain-text per-span-name aggregate (count /
  total / p50 / p95 wall ms) plus counters, for terminals and bench logs.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from fastconsensus_tpu.obs.counters import percentile

PROCESS_NAME = "fastconsensus-tpu"
_PID = 1


def span_stats(events: List[dict]) -> Dict[str, dict]:
    """Per-span-name aggregates over complete ("X") events: count and
    total/p50/p95/max wall milliseconds.  Keyed by span name, ordered by
    descending total time."""
    buckets: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        buckets.setdefault(ev["name"], []).append(ev["dur"] / 1000.0)
    out = {}
    for name, durs in sorted(buckets.items(),
                             key=lambda kv: -sum(kv[1])):
        durs.sort()
        out[name] = {
            "count": len(durs),
            "total_ms": round(sum(durs), 3),
            "p50_ms": round(percentile(durs, 0.50), 3),
            "p95_ms": round(percentile(durs, 0.95), 3),
            "max_ms": round(durs[-1], 3),
        }
    return out


def write_jsonl(path: str, events: List[dict],
                snapshot: Optional[dict] = None) -> None:
    """One JSON object per line: every span event, then the counter
    snapshot (when given) as a trailing ``{"kind": "counters"}`` record."""
    _ensure_parent(path)
    with open(path, "w", encoding="utf-8") as fh:
        for ev in sorted(events, key=lambda e: e["ts"]):
            fh.write(json.dumps({"kind": "span", **ev}) + "\n")
        if snapshot is not None:
            fh.write(json.dumps({"kind": "counters", **snapshot}) + "\n")


def to_perfetto(events: List[dict],
                snapshot: Optional[dict] = None,
                process_name: str = PROCESS_NAME,
                thread_names: Optional[Dict[int, str]] = None) -> dict:
    """Chrome ``trace_event`` object form of a span list (see module
    docstring).  Host thread idents map to small stable tids (in order of
    first appearance) with ``thread_name`` metadata, so multi-threaded
    traces render as named tracks.  ``thread_names`` overrides the
    generic names per raw thread ident — the multi-device server passes
    its worker map so each device renders as its own named track
    ("device-0", "mesh-6", ...; serve/pool.py thread_names)."""
    tids: Dict[int, int] = {}
    for ev in events:
        tids.setdefault(ev.get("tid", 0), len(tids) + 1)
    trace_events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": _PID, "tid": 0, "ts": 0,
        "args": {"name": process_name},
    }]
    for ident, tid in tids.items():
        name = (thread_names or {}).get(ident) or (
            "driver" if tid == 1 else f"thread-{tid}")
        trace_events.append({
            "name": "thread_name", "ph": "M", "pid": _PID, "tid": tid,
            "ts": 0, "args": {"name": name},
        })
    for ev in sorted(events, key=lambda e: e["ts"]):
        args = dict(ev.get("args") or {})
        if ev.get("cpu_us"):
            args["cpu_us"] = ev["cpu_us"]
        out = {
            "name": ev["name"],
            "cat": "fcobs",
            "ph": ev.get("ph", "X"),
            "ts": ev["ts"],
            "pid": _PID,
            "tid": tids.get(ev.get("tid", 0), 1),
        }
        if out["ph"] == "X":
            out["dur"] = ev["dur"]
        else:
            out["s"] = "t"  # instant scope: thread
        if args:
            out["args"] = args
        trace_events.append(out)
    blob = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
    }
    other: dict = {"span_stats": span_stats(events)}
    if snapshot is not None:
        other["counters"] = snapshot
    blob["otherData"] = other
    return blob


def _ensure_parent(path: str) -> None:
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        os.makedirs(parent, exist_ok=True)


def write_perfetto_blob(path: str, blob: dict) -> None:
    """Write an already-built (possibly device-merged) trace blob."""
    _ensure_parent(path)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(blob, fh)
        fh.write("\n")


def write_perfetto(path: str, events: List[dict],
                   snapshot: Optional[dict] = None,
                   process_name: str = PROCESS_NAME,
                   thread_names: Optional[Dict[int, str]] = None) -> None:
    write_perfetto_blob(path, to_perfetto(events, snapshot, process_name,
                                          thread_names=thread_names))


def _rotated_entries(path: str) -> List[tuple]:
    """Existing rotated segments of ``path`` as sorted ``(n, path)``
    pairs — the single owner of the ``{path}.{n}`` chain naming scheme
    (supervise's rotation derives its next suffix from here too)."""
    import re

    rotated = []
    parent = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    pat = re.compile(re.escape(base) + r"\.(\d+)$")
    if os.path.isdir(parent):
        for name in os.listdir(parent):
            m = pat.match(name)
            if m:
                rotated.append((int(m.group(1)),
                                os.path.join(parent, name)))
    return sorted(rotated)


def next_chain_suffix(path: str) -> int:
    """The suffix the NEXT rotation of ``path`` should use (one past the
    highest existing ``{path}.{n}``; 1 for an unrotated path)."""
    entries = _rotated_entries(path)
    return (entries[-1][0] + 1) if entries else 1


class JsonlStreamer:
    """Incremental JSONL event log: spans flush to disk as they close.

    The batch exporter (:func:`write_jsonl`) writes everything at run
    end — which is exactly when a stall-killed (SIGKILL) process never
    gets to run, losing the whole attempt's spans and defeating the
    supervise rotation chain for the supervisor's PRIMARY failure mode.
    The streamer appends every span recorded since the last ``flush()``
    (cli.py flushes once per consensus round), so a killed attempt
    leaves everything but its in-flight round on disk.  Lines append in
    span-close order, not ``ts`` order; readers
    (:func:`read_jsonl_chain`, the summary tooling) sort or rebase by
    ``ts`` and do not rely on file order.  ``close(snapshot)`` flushes
    the tail and appends the final counters record.
    """

    def __init__(self, path: str, tracer) -> None:
        self.path = path
        self._tracer = tracer
        self._n = 0
        _ensure_parent(path)
        # truncate: each attempt owns one fresh segment (rotation, not
        # appending, is how attempts chain — utils/supervise.py)
        open(path, "w", encoding="utf-8").close()

    def flush(self) -> None:
        new = self._tracer.events_since(self._n)
        if not new:
            return
        self._n += len(new)
        with open(self.path, "a", encoding="utf-8") as fh:
            for ev in new:
                fh.write(json.dumps({"kind": "span", **ev}) + "\n")

    def close(self, snapshot: Optional[dict] = None) -> None:
        self.flush()
        if snapshot is not None:
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(json.dumps({"kind": "counters", **snapshot})
                         + "\n")


def chain_segments(path: str) -> List[str]:
    """The rotated-segment chain for an fcobs JSONL log, oldest first.

    ``utils/supervise.py`` rotates a restarting run's event log to
    ``{path}.1``, ``{path}.2``, ... before each relaunch, so a supervised
    run that died N times leaves N rotated segments plus the final live
    file at ``path``.  Returns every existing member in chain order
    (numeric suffixes ascending, then ``path`` itself).
    """
    out = [p for _, p in _rotated_entries(path)]
    if os.path.exists(path):
        out.append(path)
    return out


def profiler_sidecar_path(path: str, segment: str) -> Optional[str]:
    """The Perfetto companion of one JSONL chain segment, if derivable.

    ``cli.py --trace PATH`` writes the Perfetto blob at ``PATH`` and the
    streaming event log at ``PATH.jsonl``; ``supervise --rotate`` moves
    both with lockstep numeric suffixes (both start unrotated, both
    rotate in the same :func:`~fastconsensus_tpu.utils.supervise
    .rotate_for_retry` call), so segment ``PATH.jsonl.k`` pairs with
    ``PATH.k`` and the live ``PATH.jsonl`` with ``PATH``.  Returns None
    when ``path`` does not end in ``.jsonl`` (no naming convention to
    lean on).
    """
    if not path.endswith(".jsonl"):
        return None
    base = path[: -len(".jsonl")]
    return base + segment[len(path):]


def read_jsonl_chain(path: str, with_profiler: bool = False) -> List[dict]:
    """One coherent event stream from a rotated JSONL chain.

    Concatenates every segment of :func:`chain_segments` in order; each
    record gains an ``attempt`` field (1-based segment index), and span
    records' ``ts`` are rebased onto one cumulative timeline — segment
    k's spans start where segment k-1's ended (each process's tracer
    clock restarts at zero, so raw timestamps overlap).  Counter
    records pass through untouched: with checkpointed counter restore
    (obs/counters.restore_counters) the LAST counters record is already
    the run's cumulative truth.

    ``with_profiler``: also pick up each attempt's rotated *Perfetto*
    sidecar (:func:`profiler_sidecar_path` — the ``--trace`` blob the
    same rotation chained next to the JSONL) and splice its
    profiler-originated events in as ``{"kind": "profiler", "attempt":
    k, ...}`` records: a supervised ``--trace --profile-dir`` run's
    per-attempt device timelines read back as one attempt-tagged
    stream.  Only complete/instant events ride along — metadata rows
    ("M") and ``cat == "fcobs"`` spans are skipped (the latter are
    already in the JSONL); timestamps are rebased by the same
    per-attempt offset as the spans (the merge already aligned profiler
    events to that attempt's fcobs clock — obs/device.py).  A missing
    or unparsable sidecar contributes nothing rather than failing the
    read.
    """
    records: List[dict] = []
    offset = 0
    for attempt, seg in enumerate(chain_segments(path), start=1):
        seg_end = 0
        with open(seg, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                rec["attempt"] = attempt
                if rec.get("kind") == "span" and "ts" in rec:
                    seg_end = max(seg_end,
                                  rec["ts"] + rec.get("dur", 0))
                    rec["ts"] = rec["ts"] + offset
                records.append(rec)
        if with_profiler:
            records.extend(
                _profiler_records(path, seg, attempt, offset))
        offset += seg_end
    return records


def _profiler_records(path: str, segment: str, attempt: int,
                      offset: int) -> List[dict]:
    """Profiler events of one segment's Perfetto sidecar (see
    read_jsonl_chain); empty on any miss — chain reading must never
    fail on a half-written attempt."""
    side = profiler_sidecar_path(path, segment)
    if side is None or not os.path.exists(side):
        return []
    try:
        with open(side, encoding="utf-8") as fh:
            blob = json.load(fh)
        events = blob.get("traceEvents") or []
    except (OSError, ValueError):
        return []
    out: List[dict] = []
    for ev in events:
        if ev.get("ph") not in ("X", "i") or ev.get("cat") == "fcobs":
            continue
        rec = {"kind": "profiler", "attempt": attempt, **ev}
        if "ts" in rec:
            rec["ts"] = rec["ts"] + offset
        out.append(rec)
    return out


def flight_bundles(records: List[dict]) -> List[dict]:
    """The fcflight post-mortem bundles a supervised run's telemetry
    chain recorded, attempt-tagged: ``utils/supervise.py`` appends a
    ``{"kind": "flight_bundle", "bundle": <dir>}`` line to a dead
    attempt's JSONL segment before rotating it, and
    :func:`read_jsonl_chain` carries those records through with the
    segment's ``attempt`` — so "which attempts died, and where is each
    one's crash evidence" is one list comprehension, not a directory
    hunt.  Returns ``[{"attempt": k, "bundle": path}, ...]`` in chain
    order."""
    return [{"attempt": r.get("attempt"), "bundle": r.get("bundle")}
            for r in records if r.get("kind") == "flight_bundle"]


def summary_table(events: List[dict],
                  snapshot: Optional[dict] = None) -> str:
    """Aligned plain-text summary: span aggregates, then counters."""
    stats = span_stats(events)
    lines = []
    if stats:
        name_w = max(len("span"), *(len(n) for n in stats))
        header = (f"{'span':<{name_w}}  {'count':>6}  {'total_ms':>10}  "
                  f"{'p50_ms':>9}  {'p95_ms':>9}  {'max_ms':>9}")
        lines.append(header)
        lines.append("-" * len(header))
        for name, s in stats.items():
            lines.append(
                f"{name:<{name_w}}  {s['count']:>6}  {s['total_ms']:>10.3f}"
                f"  {s['p50_ms']:>9.3f}  {s['p95_ms']:>9.3f}"
                f"  {s['max_ms']:>9.3f}")
    else:
        lines.append("(no spans recorded)")
    if snapshot:
        counters = snapshot.get("counters") or {}
        gauges = snapshot.get("gauges") or {}
        if counters or gauges:
            lines.append("")
            lines.append("counters:")
            for k in sorted(counters):
                lines.append(f"  {k} = {counters[k]}")
            for k in sorted(gauges):
                lines.append(f"  {k} = {gauges[k]:g}")
        series = snapshot.get("series") or {}
        live = {k: v for k, v in series.items() if v}
        if live:
            lines.append("series (count / p50 / p95):")
            for k in sorted(live):
                s = live[k]
                lines.append(f"  {k} = {s['count']} / {s['p50']:g} / "
                             f"{s['p95']:g}")
    return "\n".join(lines)
