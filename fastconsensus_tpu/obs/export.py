"""fcobs exporters: JSONL event log, Chrome/Perfetto trace JSON, text table.

Three views of one run's spans (obs/tracer.py) + counters
(obs/counters.py):

* :func:`write_jsonl` — append-friendly event log, one JSON object per
  line (``{"kind": "span", ...}`` per span, a final ``{"kind":
  "counters", ...}`` snapshot record).  The machine-diffable artifact for
  regression archaeology.
* :func:`write_perfetto` — Chrome ``trace_event`` JSON (the
  ``{"traceEvents": [...]}`` object form) loadable directly in
  ``ui.perfetto.dev`` or ``chrome://tracing``: complete ("X") events with
  microsecond ``ts``/``dur``, thread tracks named after the host threads
  that ran the spans, the counter snapshot under ``otherData``.  Events
  are sorted by ``ts`` so the artifact is reproducible byte-for-byte for
  a deterministic run.
* :func:`summary_table` — the plain-text per-span-name aggregate (count /
  total / p50 / p95 wall ms) plus counters, for terminals and bench logs.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from fastconsensus_tpu.obs.counters import percentile

PROCESS_NAME = "fastconsensus-tpu"
_PID = 1


def span_stats(events: List[dict]) -> Dict[str, dict]:
    """Per-span-name aggregates over complete ("X") events: count and
    total/p50/p95/max wall milliseconds.  Keyed by span name, ordered by
    descending total time."""
    buckets: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        buckets.setdefault(ev["name"], []).append(ev["dur"] / 1000.0)
    out = {}
    for name, durs in sorted(buckets.items(),
                             key=lambda kv: -sum(kv[1])):
        durs.sort()
        out[name] = {
            "count": len(durs),
            "total_ms": round(sum(durs), 3),
            "p50_ms": round(percentile(durs, 0.50), 3),
            "p95_ms": round(percentile(durs, 0.95), 3),
            "max_ms": round(durs[-1], 3),
        }
    return out


def write_jsonl(path: str, events: List[dict],
                snapshot: Optional[dict] = None) -> None:
    """One JSON object per line: every span event, then the counter
    snapshot (when given) as a trailing ``{"kind": "counters"}`` record."""
    _ensure_parent(path)
    with open(path, "w", encoding="utf-8") as fh:
        for ev in sorted(events, key=lambda e: e["ts"]):
            fh.write(json.dumps({"kind": "span", **ev}) + "\n")
        if snapshot is not None:
            fh.write(json.dumps({"kind": "counters", **snapshot}) + "\n")


def to_perfetto(events: List[dict],
                snapshot: Optional[dict] = None,
                process_name: str = PROCESS_NAME) -> dict:
    """Chrome ``trace_event`` object form of a span list (see module
    docstring).  Host thread idents map to small stable tids (in order of
    first appearance) with ``thread_name`` metadata, so multi-threaded
    traces render as named tracks."""
    tids: Dict[int, int] = {}
    for ev in events:
        tids.setdefault(ev.get("tid", 0), len(tids) + 1)
    trace_events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": _PID, "tid": 0, "ts": 0,
        "args": {"name": process_name},
    }]
    for ident, tid in tids.items():
        name = "driver" if tid == 1 else f"thread-{tid}"
        trace_events.append({
            "name": "thread_name", "ph": "M", "pid": _PID, "tid": tid,
            "ts": 0, "args": {"name": name},
        })
    for ev in sorted(events, key=lambda e: e["ts"]):
        args = dict(ev.get("args") or {})
        if ev.get("cpu_us"):
            args["cpu_us"] = ev["cpu_us"]
        out = {
            "name": ev["name"],
            "cat": "fcobs",
            "ph": ev.get("ph", "X"),
            "ts": ev["ts"],
            "pid": _PID,
            "tid": tids.get(ev.get("tid", 0), 1),
        }
        if out["ph"] == "X":
            out["dur"] = ev["dur"]
        else:
            out["s"] = "t"  # instant scope: thread
        if args:
            out["args"] = args
        trace_events.append(out)
    blob = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
    }
    other: dict = {"span_stats": span_stats(events)}
    if snapshot is not None:
        other["counters"] = snapshot
    blob["otherData"] = other
    return blob


def _ensure_parent(path: str) -> None:
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        os.makedirs(parent, exist_ok=True)


def write_perfetto(path: str, events: List[dict],
                   snapshot: Optional[dict] = None,
                   process_name: str = PROCESS_NAME) -> None:
    _ensure_parent(path)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_perfetto(events, snapshot, process_name), fh)
        fh.write("\n")


def summary_table(events: List[dict],
                  snapshot: Optional[dict] = None) -> str:
    """Aligned plain-text summary: span aggregates, then counters."""
    stats = span_stats(events)
    lines = []
    if stats:
        name_w = max(len("span"), *(len(n) for n in stats))
        header = (f"{'span':<{name_w}}  {'count':>6}  {'total_ms':>10}  "
                  f"{'p50_ms':>9}  {'p95_ms':>9}  {'max_ms':>9}")
        lines.append(header)
        lines.append("-" * len(header))
        for name, s in stats.items():
            lines.append(
                f"{name:<{name_w}}  {s['count']:>6}  {s['total_ms']:>10.3f}"
                f"  {s['p50_ms']:>9.3f}  {s['p95_ms']:>9.3f}"
                f"  {s['max_ms']:>9.3f}")
    else:
        lines.append("(no spans recorded)")
    if snapshot:
        counters = snapshot.get("counters") or {}
        gauges = snapshot.get("gauges") or {}
        if counters or gauges:
            lines.append("")
            lines.append("counters:")
            for k in sorted(counters):
                lines.append(f"  {k} = {counters[k]}")
            for k in sorted(gauges):
                lines.append(f"  {k} = {gauges[k]:g}")
        series = snapshot.get("series") or {}
        live = {k: v for k, v in series.items() if v}
        if live:
            lines.append("series (count / p50 / p95):")
            for k in sorted(live):
                s = live[k]
                lines.append(f"  {k} = {s['count']} / {s['p50']:g} / "
                             f"{s['p95']:g}")
    return "\n".join(lines)
