"""fcobs: the runtime observability subsystem.

The TPU port's hot loop was a black box: when a bench number moved there
was no artifact separating a retrace regression from a slow detect call
from a host-sync stall.  fcobs is the ground-truth layer — three
stdlib-only modules the engine is permanently instrumented with:

* **obs/tracer.py** — nested host-side spans (wall + CPU time,
  thread-safe, ~free when disabled).  ``run_consensus`` opens spans per
  round / detect chunk / executable setup / growth replay.
* **obs/counters.py** — always-on counter/gauge/series registry:
  consensus round stats, deliberate host-sync crossings (every pragma'd
  readback in the driver), XLA compiles (``analysis.CompileGuard``
  attaches via ``registry=``), detect-call latency series, device memory.
* **obs/export.py** — JSONL event log, Chrome/Perfetto ``trace_event``
  JSON (open in ``ui.perfetto.dev``), plain-text summary table.

Grown in PR 3 from a host tracer into the full stack:

* **obs/device.py** — device-time attribution: annotating tracers mirror
  every span into ``jax.profiler`` (``TraceAnnotation`` +
  per-consensus-round ``StepTraceAnnotation``), ``ProfilerSession``
  wraps a run, and ``merge_profiler_trace`` grafts the profiler's own
  Chrome trace into the fcobs Perfetto blob — one merged host+device
  timeline from ``cli.py --trace --profile-dir``.
* **obs/roundlog.py** — the folded-in ``utils/trace.py`` surface
  (``RoundLog`` round logger, ``phase_span``).
* **obs/history.py** — normalized ``BENCH_*.json`` history, trend
  report, and the CI regression gate (``scripts/bench_report.py``).
* **obs/latency.py** — fclat: fixed log2-bucket streaming latency
  histograms (bounded memory, exact cross-worker merge, p50/p95/p99)
  plus per-bucket arrival/dispatch rate tracking — the request-
  lifecycle layer behind ``/metricsz``'s ``latency`` block and the
  ``bench.py serve_load`` latency-vs-RPS regression gate.
* **obs/quality.py** — fcqual: consensus-convergence & partition-
  quality metrics.  The device half (weight-band counts, active
  frontier, per-member label churn, ensemble agreement, per-member
  modularity) is jitted INTO the round executables and rides the
  existing once-per-round stats readback — the one deliberate
  exception to the obs-is-host-only rule, so it imports jax and is
  NOT imported here (import it directly:
  ``from fastconsensus_tpu.obs import quality``).  The host half
  (``summarize_history``) folds the per-round series into the
  run-level ``telemetry.quality`` block that ``obs/history.py``'s
  ``check_quality`` gates in CI.

* **obs/flight.py** — fcflight: the always-on flight recorder.
  Bounded per-thread ring buffers of structured serving events
  (admit/pop/hold, route, dequeue/device/device_done, shed/429,
  cordon/requeue, watchdog trips, span mirror) with a hard memory cap
  and an O(1) lock-leaf append, so the LAST few thousand events per
  thread are always available to a post-mortem — black-box style, not
  logging.
* **obs/postmortem.py** — fcflight bundle writer + jax-free reader:
  on SIGQUIT / watchdog trip / worker death / drain timeout, dump one
  self-contained directory (flight rings, faulthandler thread stacks,
  counter + latency snapshots, caller sections like the serve in-flight
  jobs table) and read it back with
  ``python -m fastconsensus_tpu.obs.postmortem render|diff``.

Continuity: counter snapshots persist in checkpoint metadata
(utils/checkpoint.py) and delta-restore on resume
(``ObsRegistry.restore_counters``), and ``utils/supervise.py`` rotates
the JSONL event log across restarts (``export.read_jsonl_chain`` reads
the chain back as one stream).

Consumers: ``cli.py --trace[=PATH]`` records a run and writes the
Perfetto + JSONL artifacts; ``bench.py`` emits a ``telemetry`` block
(compile / host-sync counts, round + detect latency percentiles) in its
JSON line.  See README "Observability".
"""

from fastconsensus_tpu.obs.counters import (ObsRegistry,  # noqa: F401
                                            device_memory, fold_round,
                                            get_registry, host_sync,
                                            record_device_memory)
from fastconsensus_tpu.obs.flight import (FlightRecorder,  # noqa: F401
                                          get_flight_recorder)
from fastconsensus_tpu.obs.latency import (LatencyHistogram,  # noqa: F401
                                           LatencyRegistry,
                                           get_latency_registry)
from fastconsensus_tpu.obs.roundlog import RoundLog, phase_span  # noqa: F401
from fastconsensus_tpu.obs.tracer import (Tracer, get_tracer,  # noqa: F401
                                          set_tracer, traced, use_tracer)

__all__ = [
    "Tracer", "get_tracer", "set_tracer", "use_tracer", "traced",
    "ObsRegistry", "get_registry", "host_sync", "fold_round",
    "device_memory", "record_device_memory",
    "LatencyHistogram", "LatencyRegistry", "get_latency_registry",
    "FlightRecorder", "get_flight_recorder",
    "RoundLog", "phase_span",
]
