"""fcobs: the runtime observability subsystem.

The TPU port's hot loop was a black box: when a bench number moved there
was no artifact separating a retrace regression from a slow detect call
from a host-sync stall.  fcobs is the ground-truth layer — three
stdlib-only modules the engine is permanently instrumented with:

* **obs/tracer.py** — nested host-side spans (wall + CPU time,
  thread-safe, ~free when disabled).  ``run_consensus`` opens spans per
  round / detect chunk / executable setup / growth replay.
* **obs/counters.py** — always-on counter/gauge/series registry:
  consensus round stats, deliberate host-sync crossings (every pragma'd
  readback in the driver), XLA compiles (``analysis.CompileGuard``
  attaches via ``registry=``), detect-call latency series, device memory.
* **obs/export.py** — JSONL event log, Chrome/Perfetto ``trace_event``
  JSON (open in ``ui.perfetto.dev``), plain-text summary table.

Consumers: ``cli.py --trace[=PATH]`` records a run and writes the
Perfetto + JSONL artifacts; ``bench.py`` emits a ``telemetry`` block
(compile / host-sync counts, round + detect latency percentiles) in its
JSON line.  See README "Observability".
"""

from fastconsensus_tpu.obs.counters import (ObsRegistry,  # noqa: F401
                                            device_memory, fold_round,
                                            get_registry, host_sync,
                                            record_device_memory)
from fastconsensus_tpu.obs.tracer import (Tracer, get_tracer,  # noqa: F401
                                          set_tracer, traced, use_tracer)

__all__ = [
    "Tracer", "get_tracer", "set_tracer", "use_tracer", "traced",
    "ObsRegistry", "get_registry", "host_sync", "fold_round",
    "device_memory", "record_device_memory",
]
