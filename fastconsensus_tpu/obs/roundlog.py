"""fcobs round log + phase spans: the folded-in utils/trace.py surface.

Pre-fcobs, ``utils/trace.py`` carried two host-timing duplicates of what
the observability subsystem now owns: ``RoundTracer`` (an ``on_round``
hook keeping per-round records, logging, and an optional JSONL sidecar)
and ``phase_timer`` (a wall-clock phase context).  Their fcobs
equivalents live here — same behavior, but wired into the subsystem:
:class:`RoundLog` marks each round as an instant on the ambient span
tracer (visible in ``--trace`` Perfetto output), and :func:`phase_span`
times through a real fcobs span, so phase timings land in the same
artifact as everything else.  ``utils/trace.py`` keeps thin deprecation
shims so existing callers and ``runs/`` scripts don't break.
"""

from __future__ import annotations

import contextlib
import json
import logging
# fcheck: ok=sync-in-loop (host wall-clock reads for round/phase timing;
# no device values involved)
import time
from typing import Dict, List, Optional

from fastconsensus_tpu.obs.tracer import get_tracer

logger = logging.getLogger("fastconsensus_tpu")


class RoundLog:
    """Per-round stats collector; pass ``log.on_round`` to run_consensus.

    Keeps machine-readable ``records`` (the round entry + round/elapsed
    seconds), logs one line per round, optionally appends each record to
    ``jsonl_path`` (the progress file long-run supervision watches), and
    drops an instant marker on the ambient fcobs tracer so a ``--trace``
    timeline shows the host-observed round boundaries.
    """

    def __init__(self, log_level: int = logging.INFO,
                 jsonl_path: Optional[str] = None):
        self.records: List[dict] = []
        self._level = log_level
        self._jsonl_path = jsonl_path
        self._t0 = time.perf_counter()
        self._last = self._t0

    def on_round(self, entry: Dict) -> None:
        now = time.perf_counter()
        rec = dict(entry)
        rec["round_seconds"] = round(now - self._last, 4)
        rec["elapsed_seconds"] = round(now - self._t0, 4)
        self._last = now
        self.records.append(rec)
        frac = (rec["n_unconverged"] / rec["n_alive"]
                if rec["n_alive"] else 0.0)
        logger.log(self._level,
                   "round %d: %d edges alive, %d unconverged (%.1f%%), "
                   "+%d closure, +%d repaired, %d dropped [%.2fs]",
                   rec["round"], rec["n_alive"], rec["n_unconverged"],
                   100.0 * frac, rec["n_closure_added"], rec["n_repaired"],
                   rec["n_dropped"], rec["round_seconds"])
        get_tracer().instant("round_stats", round=rec["round"],
                             n_alive=rec["n_alive"],
                             n_unconverged=rec["n_unconverged"])
        if self._jsonl_path:
            with open(self._jsonl_path, "a") as fh:
                fh.write(json.dumps(rec) + "\n")


@contextlib.contextmanager
def phase_span(name: str, sink: Optional[Dict[str, float]] = None,
               level: int = logging.DEBUG):
    """Time a host-side phase (pack, rounds, write-out) as an fcobs span
    on the ambient tracer, log it, and accumulate into ``sink``."""
    t0 = time.perf_counter()
    with get_tracer().span(f"phase.{name}"):
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            logger.log(level, "phase %s: %.3fs", name, dt)
            if sink is not None:
                sink[name] = sink.get(name, 0.0) + dt
