"""fcobs device attribution: pair host spans with ``jax.profiler``.

Host spans (obs/tracer.py) answer *where the driver's wall clock went*;
this module makes the same span names show up inside the XLA profiler's
timeline, so a Perfetto view finally distinguishes "the `detect` span is
slow because the TPU kernel is slow" from "the span is slow because the
host sat in dispatch".  Three pieces:

* **Annotations** — an annotating
  :class:`~fastconsensus_tpu.obs.tracer.Tracer` (``Tracer(annotate=
  True)``) checks :func:`available` once, binds
  ``jax.profiler.TraceAnnotation`` / ``StepTraceAnnotation`` (the
  latter is the per-consensus-round step marker XLA's trace viewer
  groups device ops under), and wraps every span in one — so the host
  and device tracks carry the same vocabulary.
* **Session** — :class:`ProfilerSession` wraps a region in
  ``jax.profiler.start_trace``/``stop_trace`` (the successor of the old
  ``utils.trace.profiler_trace``) and remembers *when* the profiler
  clock started, which is what timeline merging needs.
* **Merge** — :func:`merge_profiler_trace` grafts the profiler's own
  Chrome-trace output (``plugins/profile/<run>/*.trace.json.gz`` — the
  XLA profiler already emits ``trace_event`` JSON) into an fcobs
  Perfetto blob, shifting its timestamps onto the fcobs clock, so
  ``cli.py --trace --profile-dir`` yields ONE ``ui.perfetto.dev``-
  loadable file with aligned host-span and device tracks.

Every entry point degrades to a no-op on CPU-only jax, a missing
profiler, or an empty profile dir: observability must never take down
the run it observes.
"""

from __future__ import annotations

import glob
import gzip
import json
import logging
import os
# fcheck: ok=sync-in-loop (host clock anchor for timeline alignment;
# never touches device values)
import time
from typing import List, Optional, Tuple

_logger = logging.getLogger("fastconsensus_tpu")


def available() -> bool:
    """True when ``jax.profiler`` exposes the annotation API (it does on
    every backend since jax 0.4.x; False only on import failure)."""
    try:
        import jax.profiler as prof
    except Exception:  # noqa: BLE001 — observability must never raise
        return False
    return hasattr(prof, "TraceAnnotation") and \
        hasattr(prof, "StepTraceAnnotation")


class ProfilerSession:
    """``jax.profiler`` trace over a region, with a merge-ready clock
    anchor.

    ``with ProfilerSession(log_dir) as sess:`` starts a device trace into
    ``log_dir`` (no-op when ``log_dir`` is falsy or the profiler refuses
    to start — e.g. a second concurrent session) and records
    ``time.perf_counter()`` at the moment the profiler clock began.
    :meth:`offset_us` then places profiler timestamps on another
    perf_counter-based clock (the fcobs tracer's), which is all
    :func:`merge_profiler_trace` needs to align the two tracks.
    """

    def __init__(self, log_dir: Optional[str]) -> None:
        self.log_dir = log_dir
        self.active = False
        self.start_pc: Optional[float] = None
        self.start_wall: Optional[float] = None

    def __enter__(self) -> "ProfilerSession":
        if not self.log_dir:
            return self
        try:
            import jax

            # anchors captured BEFORE start_trace: the profiler's trace
            # timestamps are epoch'd at the moment start_trace is
            # CALLED, and first-use profiler init inside the call takes
            # seconds — anchoring after the return shifted every merged
            # device event late by that latency (measured: 3.4 s skew,
            # device activity rendered past the end of the run)
            self.start_wall = time.time()
            self.start_pc = time.perf_counter()
            jax.profiler.start_trace(self.log_dir)
            self.active = True
        except Exception as e:  # noqa: BLE001
            self.start_pc = None
            self.start_wall = None
            _logger.warning("jax.profiler trace unavailable (%s); "
                            "continuing host-only", e)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self.active:
            try:
                import jax

                jax.profiler.stop_trace()
            # fcheck: ok=swallowed-error (the warning IS the
            # outlet: profiler teardown runs outside the serving
            # path and has no registry to stamp by design — obs
            # must not depend on obs)
            except Exception as e:  # noqa: BLE001
                _logger.warning("jax.profiler stop_trace failed: %s", e)
            self.active = False
        return False

    def offset_us(self, tracer_t0: float) -> int:
        """Shift (µs) that maps this session's profiler timestamps onto a
        tracer clock whose zero is perf_counter ``tracer_t0``."""
        if self.start_pc is None:
            return 0
        return int((self.start_pc - tracer_t0) * 1e6)


def _attach_info(blob: dict, info: dict) -> dict:
    """Return a copy of ``blob`` with ``info`` recorded under
    ``otherData.device_attribution``."""
    other = dict(blob.get("otherData") or {})
    other["device_attribution"] = info
    blob = dict(blob)
    blob["otherData"] = other
    return blob


def stamp_attribution(blob: dict, reason: str) -> Tuple[dict, dict]:
    """Record a merge-didn't-happen outcome on the blob.

    The degradation contract is that a ``--profile-dir`` trace ALWAYS
    carries ``otherData.device_attribution`` — including when the
    profiler never even started (unwritable dir, concurrent session), a
    path where there is no profiler output to merge and calling
    :func:`merge_profiler_trace` could pick up a STALE trace from an
    earlier session in the same dir.
    """
    info = {"merged": False, "device_track": False, "reason": reason}
    return _attach_info(blob, info), info


def find_trace_file(log_dir: str,
                    newer_than: Optional[float] = None) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under ``log_dir`` (the profiler writes
    ``plugins/profile/<timestamp>/<host>.trace.json.gz``), or None.

    ``newer_than`` (wall time, seconds) filters out files written before
    THIS session started: a reused ``--profile-dir`` holds earlier
    sessions' traces, and merging a stale one shifted by the current
    run's clock offset would produce a confidently-misaligned timeline.
    A small slack absorbs filesystem timestamp granularity.
    """
    pattern = os.path.join(log_dir, "plugins", "profile", "*",
                           "*.trace.json.gz")
    hits = sorted(glob.glob(pattern), key=os.path.getmtime)
    if newer_than is not None:
        hits = [h for h in hits if os.path.getmtime(h) >= newer_than - 2.0]
    return hits[-1] if hits else None


def load_trace_events(path: str) -> List[dict]:
    """The ``traceEvents`` list of one profiler Chrome-trace file."""
    with gzip.open(path, "rt", encoding="utf-8") as fh:
        blob = json.load(fh)
    return list(blob.get("traceEvents") or [])


def _has_device_track(events: List[dict]) -> bool:
    """Did the profiler record a device (TPU/GPU) process track?"""
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            name = str((ev.get("args") or {}).get("name", ""))
            if "/device:" in name or name.startswith("TPU") or \
                    name.startswith("GPU"):
                return True
    return False


def finalize_merge(blob: dict, session: ProfilerSession,
                   tracer_t0: float) -> Tuple[dict, dict]:
    """The exporters' one merge-or-stamp policy (cli.py and bench.py
    both call this, so CLI and bench traces degrade identically).

    A session that never started is stamped, not merged — merging would
    risk picking up an earlier session's files from the same dir; a
    started session merges only trace files written since it began
    (``find_trace_file(newer_than=...)``), so a run whose ``stop_trace``
    failed to produce output reports "nothing fresh" instead of grafting
    a stale trace at the wrong offset.
    """
    if session.start_pc is None:
        return stamp_attribution(
            blob, "jax.profiler failed to start (see run log); "
                  "nothing to merge")
    return merge_profiler_trace(blob, session.log_dir,
                                offset_us=session.offset_us(tracer_t0),
                                newer_than=session.start_wall)


def merge_profiler_trace(blob: dict, log_dir: str,
                         offset_us: int = 0,
                         drop_python_frames: bool = True,
                         newer_than: Optional[float] = None
                         ) -> Tuple[dict, dict]:
    """Graft the newest profiler trace under ``log_dir`` into an fcobs
    Perfetto blob (obs/export.to_perfetto output).

    Profiler events keep their own pids (the profiler assigns hundreds,
    far from fcobs' pid 1, so the tracks never collide) and are shifted
    by ``offset_us`` onto the fcobs clock (ProfilerSession.offset_us).
    ``drop_python_frames`` (default) filters the profiler's per-python-
    frame events (names prefixed ``$file:line``): they are ~99% of a
    CPU profile by count (measured: 995k of 1M events, a 113 MB
    artifact) and pure noise next to the fcobs spans that already cover
    the host side — what stays is XLA runtime/device activity and the
    annotation mirrors.  Returns ``(merged_blob, info)`` where ``info``
    records what happened (``merged`` bool, ``device_track`` bool,
    source path / dropped count / reason) and is also stored under
    ``otherData.device_attribution`` — so a host-only CPU trace *says*
    it is host-only instead of silently lacking a track.  Any failure
    returns the blob unmerged with the reason in ``info``.
    """
    info = {"merged": False, "device_track": False}
    try:
        path = find_trace_file(log_dir, newer_than=newer_than)
        if path is None:
            fresh = " fresh" if newer_than is not None else ""
            info["reason"] = (f"no{fresh} profiler trace found under "
                              f"{log_dir}")
        else:
            events = load_trace_events(path)
            shifted = []
            dropped = 0
            for ev in events:
                if drop_python_frames and \
                        str(ev.get("name", "")).startswith("$"):
                    dropped += 1
                    continue
                ev = dict(ev)
                if "ts" in ev:
                    ev["ts"] = ev["ts"] + offset_us
                shifted.append(ev)
            blob = dict(blob)
            blob["traceEvents"] = list(blob["traceEvents"]) + shifted
            info.update(merged=True, source=os.path.relpath(path, log_dir),
                        events=len(shifted), python_frames_dropped=dropped,
                        device_track=_has_device_track(events))
            if not info["device_track"]:
                info["reason"] = ("profiler recorded no device track "
                                  "(CPU backend): host-side profiler "
                                  "events only")
    except Exception as e:  # noqa: BLE001 — never break the export
        info["reason"] = f"profiler trace merge failed: {e}"
        _logger.warning("%s", info["reason"])
    return _attach_info(blob, info), info
