"""fclat: request-lifecycle latency attribution — streaming histograms.

The serving stack measures itself with two existing tools, and both are
wrong for *latency at serving scale*: span traces (obs/tracer.py) keep
every event and are therefore windowed on a resident server, and the
``observe()`` series in obs/counters.py hold raw samples whose
``set_series_limit`` window silently turns "run percentiles" into
"recent-window percentiles" (the footgun obs/counters.py now stamps
``window_truncated`` on).  A latency distribution the regression gate
can trust needs **bounded memory, unbounded history**:

* :class:`LatencyHistogram` — a fixed bank of log2 buckets (upper edge
  ``2^k`` seconds for ``k`` in ``MIN_EXP..MAX_EXP``, ~1 µs to ~68 min,
  plus an overflow bucket), exact ``count``/``sum``/``min``/``max``, and
  p50/p95/p99 read off the cumulative counts.  Recording is O(1), the
  whole histogram is ~35 ints, and — because buckets are *fixed*, never
  rebalanced — two histograms **merge exactly**: summing their bucket
  counts gives bit-identical quantiles to having recorded every sample
  into one histogram.  That property is what makes per-worker recording,
  cross-process aggregation, and per-window attribution (via
  :func:`diff_snapshots` — merge's inverse) all safe.
* :class:`RateTracker` — per-key inter-arrival tracking over a bounded
  window of monotonic stamps; ``rates()`` reports arrivals/s per key.
  The serving layer marks one tracker at admission (per-bucket *offered*
  load) and one at scheduler routing (per-bucket *dispatch* rate) — the
  two numbers the ROADMAP's adaptive hold-for-coalesce window needs
  (hold time ∝ expected time-to-fill = rung / arrival rate).
* :class:`LatencyRegistry` — tagged histograms (``hist(name, bucket=...,
  rung=..., priority=..., device=...)``) plus the two rate trackers,
  with a JSON ``snapshot()`` (the ``/metricsz`` ``latency`` block) and a
  text exposition (:func:`render_text`).

Everything here is stdlib-only (jax-free — the history/report tooling
loads by file path with jax poisoned) and thread-safe: every histogram
field is guarded by the instance lock, and the registry lock is never
held across a histogram operation, so the lock graph stays acyclic
(analysis/concurrency.py runs clean over this module without pragmas).

Quantile semantics: a reported pXX is the **upper edge** of the log2
bucket containing that rank (clamped to the exact observed max), i.e. a
conservative bound within 2x of the true quantile — the right trade for
a regression gate, which compares a statistic against *itself* across
rounds: the bucketing error is deterministic and cancels.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

# Bucket upper edges are 2^k seconds, k in [MIN_EXP, MAX_EXP]:
# 2^-20 s ~ 0.95 us (below any measurable phase) up to 2^12 s ~ 68 min
# (beyond any sane request lifetime); one extra overflow bucket above.
MIN_EXP = -20
MAX_EXP = 12
N_BUCKETS = MAX_EXP - MIN_EXP + 2   # [<=2^MIN_EXP, ..., <=2^MAX_EXP, inf]
_OVERFLOW = N_BUCKETS - 1

# fcflight tail exemplars: per bucket, at most this many (id, value)
# pairs ride the histogram — enough to link a bucket's outliers back to
# their flight-recorder timelines, bounded so exemplars can never grow
# the ~35-int histogram into a sample store.  The LARGEST values win a
# slot: for a latency histogram the interesting exemplar is the worst.
EXEMPLAR_SLOTS = 2


def bucket_index(seconds: float) -> int:
    """Index of the log2 bucket holding ``seconds`` (>= 0)."""
    if seconds <= 0.0:
        return 0
    # smallest k with v <= 2^k; exact at powers of two (log2 is exact
    # there), and off-by-one *within* a bucket's float neighborhood is
    # deterministic — the merge-exactness contract only needs every
    # writer to bucket a given value identically
    k = math.ceil(math.log2(seconds))
    if k <= MIN_EXP:
        return 0
    if k > MAX_EXP:
        return _OVERFLOW
    return k - MIN_EXP


def bucket_edge(index: int) -> float:
    """Upper edge (seconds) of bucket ``index``; inf for the overflow."""
    if index >= _OVERFLOW:
        return math.inf
    return 2.0 ** (MIN_EXP + index)


class LatencyHistogram:
    """Fixed log2-bucket streaming histogram; see the module docstring.

    Thread-safe: every field access happens under ``self._lock`` —
    including reads — so concurrent writers and ``/metricsz`` snapshot
    readers never see a torn (count, sum, buckets) triple.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._buckets: List[int] = [0] * N_BUCKETS
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        # bucket index -> [(exemplar id, value), ...] (largest-value
        # wins, at most EXEMPLAR_SLOTS per bucket — fcflight)
        self._exemplars: Dict[int, List[Tuple[str, float]]] = {}

    def record(self, seconds: float,
               exemplar: Optional[str] = None) -> None:
        """Fold one observation (seconds; negatives clamp to 0).

        ``exemplar`` (fcflight) attaches an identifier — the serving
        layer passes the job id on ``serve.e2e`` — to the observation's
        bucket: the largest :data:`EXEMPLAR_SLOTS` values per bucket
        keep their ids, so a tail outlier stays traceable to its
        flight-recorder timeline (``/debugz/slowest``) without the
        histogram ever storing raw samples."""
        v = max(float(seconds), 0.0)
        idx = bucket_index(v)
        with self._lock:
            self._buckets[idx] += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if exemplar is not None:
                slots = self._exemplars.setdefault(idx, [])
                slots.append((str(exemplar), v))
                if len(slots) > EXEMPLAR_SLOTS:
                    slots.sort(key=lambda s: s[1], reverse=True)
                    del slots[EXEMPLAR_SLOTS:]

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready state: exact count/sum/min/max, bucketed
        p50/p95/p99, the sparse non-zero bucket counts (keyed by the
        bucket's upper-edge exponent; ``"inf"`` for the overflow), and
        — when any observation carried one — the per-bucket exemplar
        slots (same keying)."""
        with self._lock:
            buckets = list(self._buckets)
            count, total = self._count, self._sum
            vmin, vmax = self._min, self._max
            exemplars = {i: list(s) for i, s in self._exemplars.items()}
        return _snapshot_from(buckets, count, total, vmin, vmax,
                              exemplars)


def _bucket_key(index: int) -> str:
    return "inf" if index == _OVERFLOW else str(MIN_EXP + index)


def _snapshot_from(buckets: List[int], count: int, total: float,
                   vmin: Optional[float], vmax: Optional[float],
                   exemplars: Optional[Dict[int, List[Tuple[str, float]]]]
                   = None) -> Dict[str, Any]:
    sparse = {}
    for i, c in enumerate(buckets):
        if c:
            sparse[_bucket_key(i)] = c
    out = {
        "count": count,
        "sum_s": round(total, 9),
        "min_s": None if vmin is None else round(vmin, 9),
        "max_s": None if vmax is None else round(vmax, 9),
        "p50_s": _quantile(buckets, count, vmax, 0.50),
        "p95_s": _quantile(buckets, count, vmax, 0.95),
        "p99_s": _quantile(buckets, count, vmax, 0.99),
        "buckets": sparse,
    }
    if exemplars:
        # Emitted only when an observation carried one, keyed like
        # ``buckets``, value [id, seconds] — an optional sidecar so
        # snapshots without exemplars stay byte-identical to before.
        out["exemplars"] = {
            _bucket_key(i): [[e, round(v, 9)] for e, v in slots]
            for i, slots in sorted(exemplars.items()) if slots}
    return out


def _quantile(buckets: List[int], count: int, vmax: Optional[float],
              q: float) -> Optional[float]:
    """Upper-edge-of-bucket quantile, clamped to the exact max."""
    if count < 1:
        return None
    rank = max(1, min(count, math.ceil(q * count)))
    seen = 0
    for i, c in enumerate(buckets):
        seen += c
        if seen >= rank:
            edge = bucket_edge(i)
            if vmax is not None:
                edge = min(edge, vmax)
            return round(edge, 9)
    return None if vmax is None else round(vmax, 9)  # pragma: no cover


def _dense_buckets(snap: Dict[str, Any]) -> List[int]:
    dense = [0] * N_BUCKETS
    for key, c in (snap.get("buckets") or {}).items():
        idx = _OVERFLOW if key == "inf" else int(key) - MIN_EXP
        dense[idx] = int(c)
    return dense


def merge_snapshots(snaps: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Exact merge of histogram snapshots: bucket counts, counts and
    sums add; min/max combine.  Because buckets are fixed, the merged
    quantiles equal those of one histogram that recorded every
    underlying sample — the property tests/test_latency.py pins across
    4 concurrent writers."""
    buckets = [0] * N_BUCKETS
    count, total = 0, 0.0
    vmin: Optional[float] = None
    vmax: Optional[float] = None
    exemplars: Dict[int, List[Tuple[str, float]]] = {}
    for snap in snaps:
        for i, c in enumerate(_dense_buckets(snap)):
            buckets[i] += c
        count += int(snap.get("count", 0))
        total += float(snap.get("sum_s", 0.0))
        v = snap.get("min_s")
        if v is not None:
            vmin = v if vmin is None else min(vmin, v)
        v = snap.get("max_s")
        if v is not None:
            vmax = v if vmax is None else max(vmax, v)
        for key, slots in (snap.get("exemplars") or {}).items():
            idx = _OVERFLOW if key == "inf" else int(key) - MIN_EXP
            merged = exemplars.setdefault(idx, [])
            merged.extend((str(e), float(v)) for e, v in slots)
            if len(merged) > EXEMPLAR_SLOTS:
                merged.sort(key=lambda s: s[1], reverse=True)
                del merged[EXEMPLAR_SLOTS:]
    return _snapshot_from(buckets, count, total, vmin, vmax, exemplars)


def merge_registry_snapshots(snaps: Iterable[Dict[str, Any]]
                             ) -> Dict[str, Any]:
    """Exact merge of whole :meth:`LatencyRegistry.snapshot` payloads —
    the cross-PROCESS use of :func:`merge_snapshots` (fctrace): the
    router's ``/fleetz`` feeds every replica's ``/metricsz`` latency
    block through this, and because the log2 buckets are fixed the
    merged quantiles are bit-identical to one registry having recorded
    every replica's samples.

    Histograms are matched by ``(name, sorted tags)`` — the registry's
    own identity — and each merged entry reports how many source
    registries contributed (``sources``).  The rate-tracker views
    (``arrivals``/``dispatches``) are deliberately NOT merged: their
    windows are monotonic stamps on per-process clocks, which have no
    shared epoch to merge on.
    """
    groups: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                 List[Dict[str, Any]]] = {}
    for snap in snaps:
        for h in (snap or {}).get("histograms") or ():
            key = (str(h.get("name")), _tag_key(h.get("tags") or {}))
            groups.setdefault(key, []).append(h)
    return {"histograms": [
        {"name": name, "tags": dict(tags), "sources": len(hs),
         **merge_snapshots(hs)}
        for (name, tags), hs in sorted(groups.items())]}


def diff_snapshots(new: Dict[str, Any],
                   old: Dict[str, Any]) -> Dict[str, Any]:
    """Merge's inverse: the histogram of samples recorded *between* two
    snapshots of one histogram (``old`` taken first).  Counts and sums
    subtract exactly; min/max are not invertible from counts alone, so
    the diff reports ``new``'s (a conservative bound the window's
    quantile clamp stays correct under)."""
    buckets = [max(n - o, 0) for n, o in zip(_dense_buckets(new),
                                             _dense_buckets(old))]
    count = max(int(new.get("count", 0)) - int(old.get("count", 0)), 0)
    total = max(float(new.get("sum_s", 0.0))
                - float(old.get("sum_s", 0.0)), 0.0)
    # Exemplar slots keep the largest values, so ``new``'s slots are a
    # superset of the window's candidates — carry them through (same
    # not-invertible-from-counts reasoning as min/max above).
    exemplars: Dict[int, List[Tuple[str, float]]] = {}
    for key, slots in (new.get("exemplars") or {}).items():
        idx = _OVERFLOW if key == "inf" else int(key) - MIN_EXP
        exemplars[idx] = [(str(e), float(v)) for e, v in slots]
    return _snapshot_from(buckets, count, total, new.get("min_s"),
                          new.get("max_s"), exemplars)


class RateTracker:
    """Per-key arrival-rate tracking over a bounded stamp window.

    ``max_keys`` bounds the KEY cardinality (the per-key windows are
    already bounded): trackers keyed by open-ended identifiers — the
    shaping group tracker, whose keys are batch-group strings minted
    per distinct config — evict the longest-idle key past the cap, so
    a resident server's memory cannot grow with config diversity.
    """

    WINDOW = 256

    def __init__(self, max_keys: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._max_keys = max_keys
        self._marks: Dict[str, deque] = {}
        self._totals: Dict[str, int] = {}

    def mark(self, name: str, at: Optional[float] = None) -> None:
        t = time.monotonic() if at is None else float(at)
        with self._lock:
            marks = self._marks.get(name)
            if marks is None:
                if self._max_keys is not None and \
                        len(self._marks) >= self._max_keys:
                    idle = min(self._marks,
                               key=lambda k: self._marks[k][-1])
                    del self._marks[idle]
                    self._totals.pop(idle, None)
                marks = self._marks[name] = deque(maxlen=self.WINDOW)
            marks.append(t)
            self._totals[name] = self._totals.get(name, 0) + 1

    def rates(self, now: Optional[float] = None
              ) -> Dict[str, Dict[str, Any]]:
        """``{key: {count, window, window_s, rate_per_s}}`` — the rate
        is arrivals-1 over the span from the first retained mark to
        NOW (not to the last mark: a bucket whose traffic stopped must
        DECAY toward zero, or the adaptive hold-for-coalesce consumer
        would hold jobs for phantom ride-alongs forever).  0.0 until
        two marks exist."""
        t_now = time.monotonic() if now is None else float(now)
        with self._lock:
            items = [(k, list(m), self._totals.get(k, 0))
                     for k, m in self._marks.items()]
        out: Dict[str, Dict[str, Any]] = {}
        for key, marks, total in items:
            span = max(t_now - marks[0], 0.0) if len(marks) >= 2 else 0.0
            rate = (len(marks) - 1) / span if span > 0 else 0.0
            out[key] = {
                "count": total,
                "window": len(marks),
                "window_s": round(span, 6),
                "rate_per_s": round(rate, 6),
            }
        return out

    # The control-signal recency horizon (seconds): rate() judges only
    # marks this recent.  The full-window view (rates()) spans to the
    # oldest retained mark, which is right for exposition but wrong
    # for burst detection — after a quiet spell, a handful of old
    # sparse marks would dilute a fresh burst's rate for the whole
    # window and the hold-for-coalesce consumer would never see it.
    HORIZON_S = 0.5

    def rate(self, name: str, now: Optional[float] = None,
             horizon_s: Optional[float] = None) -> float:
        """One key's CURRENT arrivals/s — the traffic shaper's control
        signal (serve/shaping.py): computed over the marks inside the
        trailing ``horizon_s`` window only (default
        :data:`HORIZON_S`), spanning to NOW, so a fresh burst registers
        within a few arrivals and an idle key reads 0.0 as soon as the
        horizon empties.  Single-key on purpose: the hold decision runs
        under the admission queue's condition, where recomputing every
        key's window would scale the lock hold time with bucket
        cardinality."""
        t_now = time.monotonic() if now is None else float(now)
        h = self.HORIZON_S if horizon_s is None else float(horizon_s)
        cutoff = t_now - h
        with self._lock:
            marks = self._marks.get(name)
            if marks is None or len(marks) < 2:
                return 0.0
            recent = [m for m in marks if m >= cutoff]
        if len(recent) < 2:
            return 0.0
        span = max(t_now - recent[0], 0.0)
        return (len(recent) - 1) / span if span > 0 else 0.0

    def reset(self) -> None:
        with self._lock:
            self._marks.clear()
            self._totals.clear()


# The phases that constitute one job's *service* time — work the device
# path actually performs per job, as opposed to time spent queued/held/
# routed.  The shaping estimator sums these per-phase distributions.
SERVICE_PHASES: Tuple[str, ...] = ("pack", "device", "fanout")


def _tag_key(tags: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in tags.items()))


class LatencyRegistry:
    """Tagged histograms + the arrival/dispatch rate trackers.

    ``hist()`` hands the histogram out from under the registry lock and
    callers record on it afterwards — the registry lock never nests a
    histogram lock, keeping the acquisition graph trivially acyclic.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hists: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                          LatencyHistogram] = {}
        self.arrivals = RateTracker()
        self.dispatches = RateTracker()
        # Per-BATCH-GROUP arrival tracking (fcshape): a rung can only
        # fill with same-group arrivals (bucket + config-minus-seed),
        # so the hold predictor prefers this over the per-bucket rate —
        # mixed-config traffic on one bucket would otherwise trigger
        # holds that can never fill.  Key-capped (group strings are
        # open-ended) and deliberately NOT in snapshot(): it is a
        # control signal, not an exposition surface.
        self.group_arrivals = RateTracker(max_keys=1024)

    def hist(self, name: str, **tags: Any) -> LatencyHistogram:
        key = (str(name), _tag_key(tags))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = LatencyHistogram()
            return h

    def snapshot(self) -> Dict[str, Any]:
        """The ``/metricsz`` ``latency`` block: every histogram (name +
        tags + counts + quantiles) and both rate-tracker views."""
        with self._lock:
            items = sorted(self._hists.items())
        return {
            "histograms": [
                {"name": name, "tags": dict(tags), **h.snapshot()}
                for (name, tags), h in items],
            "arrivals": self.arrivals.rates(),
            "dispatches": self.dispatches.rates(),
        }

    def service_estimate(self, bucket: Optional[str] = None,
                         min_count: int = 1,
                         prior: Optional[float] = None
                         ) -> Optional[Dict[str, Any]]:
        """Measured per-job service time (seconds) derived from the
        existing ``serve.phase.*`` histograms — the traffic shaper's
        (serve/shaping.py) view of how long one job occupies the
        serving path once dispatched.

        Per phase in :data:`SERVICE_PHASES` the tagged histograms are
        exact-merged (``bucket`` filters to one shape bucket; rung-0 —
        cache-hit — histograms are always excluded: a hit performs no
        service, and ``cold``-tagged ones too: a compiling job's device
        phase measures XLA, not serving), then combined across phases: the mean is the sum of
        per-phase means (phases tile a job's lifetime, so means add
        exactly) and ``p95_s`` the sum of per-phase p95s (a
        conservative upper bound — quantiles do not add, but for a
        deadline-slack bound only overestimation is safe).  Batched
        jobs stamp the whole batched call's duration as each member's
        device phase, which also overestimates per-job service — the
        same safe direction.  None until the device phase has
        ``min_count`` samples — unless a ``prior`` (static
        device-seconds estimate, e.g. analysis/cost.py's mirrored
        roofline for the bucket) is supplied: a history-less bucket
        then returns ``{"count": 0, "mean_s": prior, "p95_s": prior,
        "prior": True}`` so cold admission math starts from the model
        instead of a constant guess.  Measured history always wins.
        """
        with self._lock:
            items = list(self._hists.items())
        per_phase: Dict[str, List[Dict[str, Any]]] = {}
        prefix = "serve.phase."
        for (name, tags), h in items:
            if not name.startswith(prefix):
                continue
            phase = name[len(prefix):]
            if phase not in SERVICE_PHASES:
                continue
            td = dict(tags)
            if td.get("rung") == "0" or td.get("cold"):
                continue
            if bucket is not None and td.get("bucket") != str(bucket):
                continue
            per_phase.setdefault(phase, []).append(h.snapshot())
        merged = {p: merge_snapshots(s) for p, s in per_phase.items()}
        dev = merged.get("device")
        if dev is None or dev["count"] < max(int(min_count), 1):
            if prior is not None and prior > 0:
                return {"count": 0, "mean_s": round(float(prior), 9),
                        "p95_s": round(float(prior), 9), "prior": True}
            return None
        mean = sum(m["sum_s"] / m["count"]
                   for m in merged.values() if m["count"])
        p95 = sum(m["p95_s"] or 0.0 for m in merged.values())
        return {"count": dev["count"],
                "mean_s": round(mean, 9),
                "p95_s": round(p95, 9)}

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()
        self.arrivals.reset()
        self.dispatches.reset()
        self.group_arrivals.reset()


_REGISTRY = LatencyRegistry()


def get_latency_registry() -> LatencyRegistry:
    """The process-global registry (serve/server.py records into it;
    ``/metricsz`` snapshots it)."""
    return _REGISTRY


def render_text(snapshot: Dict[str, Any]) -> str:
    """Text exposition of a :meth:`LatencyRegistry.snapshot` — one line
    per histogram (``name{tag=value,...} count=N sum=S p50=... p95=...
    p99=... max=...``) and one per rate-tracker key, stable-ordered so
    diffs between scrapes are meaningful."""
    lines: List[str] = []
    for h in snapshot.get("histograms", ()):
        tags = ",".join(f"{k}={v}" for k, v in sorted(h["tags"].items()))
        label = f"{h['name']}{{{tags}}}" if tags else h["name"]
        lines.append(
            f"{label} count={h['count']} sum={h['sum_s']} "
            f"p50={h['p50_s']} p95={h['p95_s']} p99={h['p99_s']} "
            f"max={h['max_s']}")
    for kind in ("arrivals", "dispatches"):
        for key, r in sorted((snapshot.get(kind) or {}).items()):
            lines.append(
                f"{kind}{{key={key}}} count={r['count']} "
                f"rate_per_s={r['rate_per_s']} window_s={r['window_s']}")
    return "\n".join(lines)
