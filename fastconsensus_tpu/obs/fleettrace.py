"""fctrace: fleet-wide tracing, metrics aggregation, incident merge.

Every observability layer below this one stops at the process
boundary: fclat histograms and fcflight rings describe ONE replica,
post-mortem bundles dump ONE process, and the fcfleet router's own
``/metricsz`` shows only router-local counters.  A request that
crosses router→replica therefore leaves two uncorrelated timelines,
and a fleet kill drill leaves N disjoint bundles with unaligned
clocks.  This module is the stitching layer — three pieces, all
jax-free (stdlib + the jax-free obs siblings only, so the reader runs
on a box where jax cannot even import):

* **Trace context** — the router mints one trace id per submission
  (honoring a client-supplied :data:`TRACE_HEADER`), forwards it on
  the proxied ``/submit`` as the same header, and the replica folds it
  into the JobSpec (outside the content hash — a trace names a
  *submission*, never a result).  Both sides stamp it into their
  flight events, so ``merged_timeline(trace=...)`` reconstructs one
  request end-to-end across processes.
* **Exact-merge aggregation** — :func:`aggregate_fleet` folds every
  replica's ``/metricsz`` into one fleet view: latency histograms
  merge bit-exactly (fixed log2 buckets,
  :func:`~fastconsensus_tpu.obs.latency.merge_registry_snapshots`),
  SLO met/missed counts add per class, counters sum, and the router's
  own ``router.phase.*`` family attributes per-replica proxy
  overhead.  The router's ``GET /fleetz`` is this function over live
  replicas.
* **Incident merge** — flight snapshots and bundle manifests both
  carry a ``time_unix``/``time_mono`` anchor; :func:`merged_timeline`
  maps each process's monotonic event stamps onto the shared wall
  clock (``ts + (time_unix - time_mono)``), tags every event with its
  replica track, and sorts — one clock-aligned fleet timeline out of
  N per-process bundle directories, filterable by trace id.

CLI (mirrors obs/postmortem.py)::

    python -m fastconsensus_tpu.obs.fleettrace render COLLECTED_DIR \
        [--trace ID] [--json] [--tail N]

where ``COLLECTED_DIR`` is what ``FleetManager.collect_bundles()``
produced: one directory holding every replica's bundles, each renamed
``<replica>__<bundle>`` so the merge knows its tracks.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from fastconsensus_tpu.obs import latency as obs_latency
from fastconsensus_tpu.obs.flight import merge_events

SCHEMA = 1

# The trace-context propagation header: client -> router -> replica.
# The router echoes it on every /submit answer too, so a client that
# never set one still learns its request's trace id.
TRACE_HEADER = "X-FCTPU-Trace"

# collect_bundles() joins replica name and bundle basename with this;
# discover_bundles() splits on it to recover the replica track.
REPLICA_SEP = "__"


# ---------------------------------------------------------------------
# fleet metrics aggregation (the /fleetz payload)
# ---------------------------------------------------------------------

def proxy_overhead(router_latency: Optional[Dict[str, Any]]
                   ) -> Dict[str, Dict[str, Any]]:
    """Per-replica proxy-overhead attribution from the ROUTER's own
    registry snapshot: the ``router.phase.proxy`` histograms are tagged
    ``replica=<name>`` per proxied hop, so each replica's entry is the
    router-side cost of talking to it (network + replica handler time
    — the part of fleet latency no replica-side histogram can see)."""
    out: Dict[str, Dict[str, Any]] = {}
    for h in (router_latency or {}).get("histograms") or ():
        if h.get("name") != "router.phase.proxy":
            continue
        name = (h.get("tags") or {}).get("replica", "?")
        out[str(name)] = {
            "count": int(h.get("count", 0)),
            "sum_s": h.get("sum_s"),
            "p50_s": h.get("p50_s"),
            "p95_s": h.get("p95_s"),
        }
    return out


def aggregate_fleet(replica_metrics: Dict[str, Optional[Dict[str, Any]]],
                    router_latency: Optional[Dict[str, Any]] = None,
                    router_fleet: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """Fold per-replica ``/metricsz`` payloads into the fleet view.

    ``replica_metrics`` maps replica name -> its ``/metricsz`` body
    (None for a replica that could not be scraped — it is reported,
    not silently dropped: a fleet aggregate that quietly omits a
    replica reads as "healthy" exactly when it is not).

    The latency histograms merge EXACTLY (fixed buckets — the merged
    counts and quantiles equal one registry having recorded every
    replica's samples); SLO met/missed add per class with attainment
    recomputed from the summed counts (the class's default target is
    carried through, so the fleet slo rows parse with the same typed
    client block as a replica's); numeric fcobs counters sum.
    """
    replicas: Dict[str, Dict[str, Any]] = {}
    lat_snaps: List[Dict[str, Any]] = []
    slo_fleet: Dict[str, Dict[str, Any]] = {}
    counters: Dict[str, float] = {}
    for name in sorted(replica_metrics):
        payload = replica_metrics[name]
        if not payload:
            replicas[name] = {"ok": False}
            continue
        lat = payload.get("latency") or {}
        slo = lat.get("slo") or {}
        replicas[name] = {
            "ok": True,
            "scope": payload.get("scope", "replica"),
            "histograms": len(lat.get("histograms") or ()),
            "slo": slo,
        }
        lat_snaps.append(lat)
        for cls, s in slo.items():
            agg = slo_fleet.setdefault(str(cls), {"met": 0, "missed": 0})
            agg["met"] += int(s.get("met", 0) or 0)
            agg["missed"] += int(s.get("missed", 0) or 0)
            # the default target is replica-invariant config, not a
            # measurement: carry the first one seen through the fold
            if ("target_default_ms" not in agg
                    and s.get("target_default_ms") is not None):
                agg["target_default_ms"] = s["target_default_ms"]
        for cname, val in ((payload.get("fcobs") or {})
                           .get("counters") or {}).items():
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                counters[str(cname)] = counters.get(str(cname), 0) + val
    for agg in slo_fleet.values():
        total = agg["met"] + agg["missed"]
        agg["attainment"] = (round(agg["met"] / total, 6)
                             if total else None)
    out: Dict[str, Any] = {
        "schema": SCHEMA,
        "tool": "fctrace-fleetz",
        "scope": "fleet",
        "replicas": replicas,
        "latency": obs_latency.merge_registry_snapshots(lat_snaps),
        "slo": slo_fleet,
        "counters": {k: counters[k] for k in sorted(counters)},
    }
    if router_latency is not None:
        out["router"] = {
            "latency": router_latency,
            "proxy_overhead": proxy_overhead(router_latency),
        }
    if router_fleet is not None:
        out["fleet"] = router_fleet
    return out


# ---------------------------------------------------------------------
# cross-replica incident merge (collected bundles -> one timeline)
# ---------------------------------------------------------------------

def _load_json(path: str) -> Optional[Any]:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def discover_bundles(root: str) -> List[Tuple[str, str]]:
    """``(replica, bundle_dir)`` pairs under a collected directory.

    Entries named ``<replica>__fcflight_...`` (the collect_bundles
    layout) take their track name from the prefix; a bare
    ``fcflight_...`` entry (root IS one replica's flight dir) falls
    back to ``p<pid>`` from its manifest.  Manifest-less partial dirs
    are skipped — same completeness contract as postmortem.list_bundles.
    """
    out: List[Tuple[str, str]] = []
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return []
    for entry in entries:
        path = os.path.join(root, entry)
        if not os.path.isdir(path):
            continue
        manifest = _load_json(os.path.join(path, "MANIFEST.json"))
        if manifest is None:
            continue
        if REPLICA_SEP in entry and "fcflight_" in entry:
            replica = entry.split(REPLICA_SEP, 1)[0]
        elif entry.startswith("fcflight_"):
            replica = f"p{manifest.get('pid', '?')}"
        else:
            continue
        out.append((replica, path))
    return out


def clock_anchor(bundle_dir: str) -> Optional[float]:
    """The bundle's monotonic→wall offset (``time_unix - time_mono``).
    The flight snapshot's own anchor wins (stamped at the same instant
    as the ring copy); older bundles fall back to the manifest's, which
    is written milliseconds later — within the alignment tolerance any
    cross-host reading needs anyway."""
    for section in ("flight.json", "MANIFEST.json"):
        data = _load_json(os.path.join(bundle_dir, section))
        if (isinstance(data, dict) and data.get("time_unix") is not None
                and data.get("time_mono") is not None):
            return float(data["time_unix"]) - float(data["time_mono"])
    return None


def merged_timeline(root: str, trace: Optional[str] = None
                    ) -> Dict[str, Any]:
    """One clock-aligned fleet timeline out of a collected bundle dir.

    Every flight event becomes ``{"t_wall", "replica", "thread",
    "kind", ...aux}`` with ``t_wall = ts + anchor`` (unix seconds);
    events from bundles with no recoverable anchor are dropped and
    counted in ``skipped_bundles`` rather than mis-ordered.  When one
    replica contributed several bundles (periodic SIGQUIT snapshots of
    one ring), identical events deduplicate on their exact
    (replica, ts, kind, job) identity.  ``trace`` filters to one
    request's events across every track.
    """
    events: List[Dict[str, Any]] = []
    tracks: Dict[str, int] = {}
    skipped: List[str] = []
    seen: set = set()
    for replica, bundle_dir in discover_bundles(root):
        flight = _load_json(os.path.join(bundle_dir, "flight.json"))
        anchor = clock_anchor(bundle_dir)
        if not isinstance(flight, dict) or anchor is None:
            skipped.append(os.path.basename(bundle_dir))
            continue
        for ev in merge_events(flight):
            if trace is not None and ev.get("trace") != trace:
                continue
            ts = float(ev.get("ts", 0.0))
            ident = (replica, ts, ev.get("kind"), ev.get("job"))
            if ident in seen:
                continue
            seen.add(ident)
            events.append({**ev, "replica": replica,
                           "t_wall": round(ts + anchor, 6)})
            tracks[replica] = tracks.get(replica, 0) + 1
    events.sort(key=lambda e: e["t_wall"])
    return {
        "schema": SCHEMA,
        "tool": "fctrace-timeline",
        "trace": trace,
        "replicas": sorted(tracks),
        "events_per_replica": {k: tracks[k] for k in sorted(tracks)},
        "n_events": len(events),
        "skipped_bundles": skipped,
        "events": events,
    }


def render_timeline(payload: Dict[str, Any],
                    tail: Optional[int] = None) -> str:
    """Human-readable view of a :func:`merged_timeline` payload."""
    events = payload.get("events") or []
    lines = [
        "== fctrace merged fleet timeline ==",
        f"replicas : {', '.join(payload.get('replicas') or []) or '-'}",
        f"events   : {payload.get('n_events', 0)}"
        + (f" (trace {payload['trace']})" if payload.get("trace")
           else ""),
    ]
    if payload.get("skipped_bundles"):
        lines.append(f"skipped  : "
                     f"{', '.join(payload['skipped_bundles'])}")
    shown = events[-tail:] if tail is not None else events
    if len(shown) < len(events):
        lines.append(f"-- last {len(shown)} of {len(events)} --")
    for ev in shown:
        extra = {k: v for k, v in ev.items()
                 if k not in ("ts", "t_wall", "kind", "thread",
                              "replica", "job")}
        job = f" job={ev['job']}" if "job" in ev else ""
        extra_s = f" {extra}" if extra else ""
        lines.append(
            f"  [{ev.get('t_wall', 0.0):.6f}] "
            f"{ev.get('replica', '?')}/{ev.get('thread', '?')}: "
            f"{ev.get('kind')}{job}{extra_s}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m fastconsensus_tpu.obs.fleettrace",
        description="fctrace cross-replica incident reader (jax-free)")
    sub = p.add_subparsers(dest="cmd", required=True)
    pr = sub.add_parser(
        "render", help="merge a collected bundle dir into one timeline")
    pr.add_argument("root", help="directory of <replica>__<bundle> "
                                 "dirs (FleetManager.collect_bundles)")
    pr.add_argument("--trace", default=None,
                    help="filter to one trace id's events")
    pr.add_argument("--json", action="store_true",
                    help="emit the merged timeline as JSON")
    pr.add_argument("--tail", type=int, default=None,
                    help="show only the last N events (text mode)")
    args = p.parse_args(argv)
    payload = merged_timeline(args.root, trace=args.trace)
    if not payload["replicas"]:
        print(f"{args.root}: no complete bundles found", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(payload, indent=1))
    else:
        print(render_timeline(payload, tail=args.tail))
    return 0


if __name__ == "__main__":
    sys.exit(main())
