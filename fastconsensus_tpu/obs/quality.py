"""fcqual: consensus-convergence and partition-quality metrics.

Two halves, one file:

* **Device half** (jax): pure jittable functions over the static-shape
  GraphSlab that the consensus tails (``engine.consensus_tail``,
  ``ops.sharded_tail._tail_local``) fold into :class:`RoundStats` each
  round.  Everything here rides the existing once-per-round stats
  readback — the functions return device scalars/vectors that travel in
  the same bulk ``device_get`` as the rest of RoundStats, so
  instrumentation adds **zero new host syncs** (pinned by
  tests/test_quality.py against ``obs.counters.host_sync``).

* **Host half** (stdlib): :func:`summarize_history` compresses a run's
  per-round history entries into the ``quality`` telemetry block that
  bench.py embeds in its BENCH line and fcserve attaches to cached
  results (``/result`` / ``/status``).  The regression *gate* over those
  blocks lives in ``obs/history.py`` (``check_quality``) because the
  gate must run on jax-free boxes; this module imports jax at top level
  and is deliberately NOT re-exported from ``obs/__init__``.

Metric definitions (README "Quality observability: fcqual"):

weight histogram
    End-of-round alive consensus edges split into the three bands the
    convergence criterion is built from (ops.consensus_ops
    .convergence_stats): weight 0 (closure inserts no partition agreed
    on), weight >= n_p (unanimous, frozen by update_weights), and the
    mid band 0 < w < n_p (already reported as ``n_unconverged``).  The
    histogram turns the one-scalar criterion into a distribution.

label churn
    Per ensemble member, the count of vertices whose community id
    differs from the member's previous-round labels.  Raw label
    disagreement — a pure relabeling counts, so this is an upper bound
    on real partition movement; warm-started members keep ids stable in
    practice, which is exactly the regime incremental consensus cares
    about.  Round 0 is measured against the singleton baseline
    (= the warm-start detection init).

ensemble agreement
    Mean pairwise co-membership agreement over the round-start alive
    edges: for an edge with co-membership count c (of n_p members),
    the fraction of member pairs that agree on whether its endpoints
    share a community is (c*(c-1) + (n_p-c)*(n_p-c-1)) / (n_p*(n_p-1)).
    Computed from the per-edge ``counts`` the tail already materializes
    for update_weights — no extra major compute.

member modularity
    Newman modularity of each member's partition on the end-of-round
    *weighted* consensus slab: Q_m = intra_m/W - sum_c (D_c/(2W))^2
    with W the total alive weight, intra_m the alive weight inside m's
    communities, D_c the weighted-degree mass of community c.

active frontier
    Count of vertices incident to >= 1 mid-band edge at round end — the
    exact population a ``where``-masked move phase would process, i.e.
    the measured basis for the ROADMAP's pruned vertex-parallel
    refinement item (FastEnsemble, arXiv:2409.02077; Louvain pruning,
    arXiv:1503.01322).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from fastconsensus_tpu.graph import GraphSlab


class QualityStats(NamedTuple):
    """Device-side per-round quality bundle (one field group of RoundStats).

    Scalars unless noted; the two ``[n_p]`` vectors widen the RoundStats
    block buffers to ``[block, n_p]`` — the ``jax.tree.map`` fold in
    engine.consensus_rounds_block handles that shape generically.
    """

    n_w_zero: jax.Array           # int32[]  alive edges at weight 0
    n_w_full: jax.Array           # int32[]  alive edges at weight >= n_p
    n_frontier: jax.Array         # int32[]  vertices on >= 1 mid-band edge
    labels_changed: jax.Array     # int32[n_p]  per-member label churn
    member_modularity: jax.Array  # float32[n_p]
    agreement: jax.Array          # float32[]  mean pairwise agreement


def singleton_labels(n_p: int, n_nodes: int) -> jax.Array:
    """The round-0 churn baseline: every vertex its own community.

    Identical to the warm-start detection init, so round-0 churn reads
    "vertices the first detection moved off the singleton start".
    """
    return jnp.broadcast_to(
        jnp.arange(n_nodes, dtype=jnp.int32), (n_p, n_nodes))


def weight_band_counts(slab: GraphSlab, n_p: int
                       ) -> Tuple[jax.Array, jax.Array]:
    """(n_w_zero, n_w_full): alive edges at the histogram's two poles.

    The mid band is RoundStats.n_unconverged (same mask as
    convergence_stats); zero/full/mid partition the alive edges.
    """
    alive = slab.alive
    n_zero = jnp.sum((alive & (slab.weight <= 0.0)).astype(jnp.int32))
    n_full = jnp.sum(
        (alive & (slab.weight >= jnp.float32(n_p))).astype(jnp.int32))
    return n_zero, n_full


def frontier_mask(slab: GraphSlab, n_p: int) -> jax.Array:
    """bool[n_nodes]: vertices incident to >= 1 alive mid-band edge.

    This is the population a where-masked move phase would process; dead
    slots scatter to a sacrificial row so the mask is exact under the
    static-capacity slab.
    """
    n = slab.n_nodes
    mid = slab.alive & (slab.weight > 0) & \
        (slab.weight < jnp.float32(n_p))
    one = mid.astype(jnp.int32)
    hits = jnp.zeros((n + 1,), jnp.int32)
    hits = hits.at[jnp.where(mid, slab.src, n)].add(one, mode="drop")
    hits = hits.at[jnp.where(mid, slab.dst, n)].add(one, mode="drop")
    return hits[:n] > 0


def active_frontier(slab: GraphSlab, n_p: int) -> jax.Array:
    """int32[]: size of the active frontier (see frontier_mask)."""
    return jnp.sum(frontier_mask(slab, n_p).astype(jnp.int32))


def label_churn(labels: jax.Array, prev_labels: jax.Array) -> jax.Array:
    """int32[n_p]: per-member count of vertices whose label changed."""
    return jnp.sum((labels != prev_labels).astype(jnp.int32), axis=1)


def edge_agreement(counts: jax.Array, alive: jax.Array, n_p: int
                   ) -> jax.Array:
    """float32[]: mean pairwise co-membership agreement over alive edges.

    ``counts`` is the float32[E] per-edge co-membership count the tail
    computes for update_weights; ``alive`` is the round-start mask the
    counts were taken over.  n_p == 1 has no member pairs: defined as 1.
    """
    if n_p <= 1:
        return jnp.float32(1.0)
    c = counts
    f = jnp.float32(n_p)
    pair_agree = c * (c - 1.0) + (f - c) * (f - c - 1.0)
    tot = jnp.sum(jnp.where(alive, pair_agree, 0.0))
    n_alive = jnp.sum(alive.astype(jnp.int32)).astype(jnp.float32)
    denom = jnp.maximum(n_alive, 1.0) * f * (f - 1.0)
    return tot / denom


def member_modularity(slab: GraphSlab, labels: jax.Array) -> jax.Array:
    """float32[n_p]: Newman modularity of each member on the weighted slab.

    Uses the end-of-round consensus weights (alive edges only):
    Q_m = intra_m / W - sum_c (D_c / (2W))^2.  An empty slab (W == 0)
    reports 0 for every member.
    """
    n = slab.n_nodes
    w = jnp.where(slab.alive, slab.weight, 0.0)
    total_w = jnp.sum(w)
    w_safe = jnp.maximum(total_w, jnp.float32(1e-30))
    deg = slab.strengths()  # float32[n] weighted degree, alive edges

    def one(lab: jax.Array) -> jax.Array:
        intra = jnp.sum(
            jnp.where(lab[slab.src] == lab[slab.dst], w, 0.0))
        # community degree mass: labels are vertex ids in [0, n)
        d_c = jnp.zeros((n,), jnp.float32).at[lab].add(deg)
        return intra / w_safe - jnp.sum((d_c / (2.0 * w_safe)) ** 2)

    q = jax.vmap(one)(labels)
    return jnp.where(total_w > 0.0, q, jnp.zeros_like(q))


def tail_quality(start_alive: jax.Array,
                 counts: jax.Array,
                 slab: GraphSlab,
                 labels: jax.Array,
                 prev_labels: Optional[jax.Array],
                 n_p: int) -> QualityStats:
    """Assemble the per-round quality bundle inside a consensus tail.

    ``start_alive``/``counts`` are the round-start alive mask and the
    co-membership counts taken over it (agreement's population);
    ``slab`` is the end-of-round slab (histogram / frontier /
    modularity population); ``prev_labels`` None means round 0 — churn
    falls back to the singleton baseline.
    """
    if prev_labels is None:
        prev_labels = singleton_labels(n_p, slab.n_nodes)
    n_zero, n_full = weight_band_counts(slab, n_p)
    return QualityStats(
        n_w_zero=n_zero,
        n_w_full=n_full,
        n_frontier=active_frontier(slab, n_p),
        labels_changed=label_churn(labels, prev_labels),
        member_modularity=member_modularity(slab, labels),
        agreement=jnp.float32(edge_agreement(counts, start_alive, n_p)),
    )


# --------------------------------------------------------------------------
# Host half: run-level summary for telemetry blocks (bench.py, fcserve).
# Pure stdlib over already-fetched history dicts — no device access.
# --------------------------------------------------------------------------

#: history-entry keys written by consensus.record()/record_block() that
#: carry the per-round quality series (missing on pre-fcqual histories).
ENTRY_KEYS = ("n_w_zero", "n_w_full", "n_frontier", "frontier_frac",
              "labels_changed", "churn_frac", "agreement",
              "modularity_mean", "n_agg_overflow")


def summarize_history(history: List[Dict[str, Any]],
                      converged: Optional[bool] = None
                      ) -> Optional[Dict[str, Any]]:
    """Compress a run's per-round history into the ``quality`` block.

    Returns None when the history carries no quality series (pre-fcqual
    checkpoints, empty runs) so callers can omit the block instead of
    emitting a husk.  ``converged`` is the run's final convergence flag;
    ``rounds_to_converge`` is reported only when the run converged.
    """
    qrounds = [h for h in history if h.get("agreement") is not None]
    if not qrounds:
        return None
    last = qrounds[-1]
    frontier = [float(h.get("frontier_frac", 0.0)) for h in qrounds]
    # "late" = the second half of the trajectory, where a frontier mask
    # would actually prune work (round 0 is always ~the whole graph)
    late = frontier[len(frontier) // 2:]
    block: Dict[str, Any] = {
        "rounds": len(history),
        "final_agreement": float(last["agreement"]),
        "final_modularity_mean": float(last.get("modularity_mean", 0.0)),
        "final_frontier_frac": float(last.get("frontier_frac", 0.0)),
        "final_churn_frac": float(last.get("churn_frac", 0.0)),
        "late_frontier_frac": sum(late) / max(len(late), 1),
        "frontier_frac_by_round": frontier,
        "agreement_by_round": [float(h["agreement"]) for h in qrounds],
        "labels_changed_total": int(sum(
            int(h.get("labels_changed", 0)) for h in qrounds)),
        "agg_overflow_total": int(sum(
            int(h.get("n_agg_overflow", 0)) for h in qrounds)),
    }
    if converged is not None:
        block["rounds_to_converge"] = \
            len(history) if bool(converged) else None
    return block
