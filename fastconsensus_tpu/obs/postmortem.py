"""fcflight post-mortem bundles: one self-contained incident directory.

When a serving replica wedges (hang-watchdog trip), dies mid-batch
(unhandled worker exception), refuses to drain, or an operator sends
SIGQUIT, the process dumps everything an incident responder needs into
ONE directory — no live process required to read it:

================  =====================================================
``MANIFEST.json`` schema/reason/timestamps/pid/thread names + the
                  section list (always written LAST, so a manifest's
                  presence means the bundle is complete)
``flight.json``   the flight-recorder snapshot (obs/flight.py): every
                  thread's bounded event ring
``stacks.txt``    ``faulthandler`` tracebacks of every thread — where
                  each one actually was, including a thread stuck
                  inside a device call
``counters.json`` the fcobs counter/gauge/series snapshot
``latency.json``  the fclat histogram registry snapshot (per-phase
                  distributions + exemplars)
``<name>.json``   caller sections: the serving layer adds ``jobs``
                  (in-flight table with per-job phase timelines),
                  ``pool``/``scheduler``/``queue`` describes and
                  ``config`` (the resolved ServeConfig); ``cli.py
                  --dump-on-signal`` adds ``run`` (consensus round +
                  policy state)
================  =====================================================

The reader is jax-free by construction (stdlib imports only, and the
package root is PEP-562 lazy, so ``python -m
fastconsensus_tpu.obs.postmortem`` never touches jax — it must work on
the box where jax is exactly what is broken):

    python -m fastconsensus_tpu.obs.postmortem render BUNDLE_DIR
    python -m fastconsensus_tpu.obs.postmortem diff OLD_DIR NEW_DIR

``render`` prints the manifest, thread stacks, counter highlights, the
in-flight jobs table (id / state / bucket / per-phase timeline) and the
tail of the merged flight timeline; ``diff`` prints counter deltas and
per-kind flight-event deltas between two bundles of one process.

Bundle triggers: :func:`install_signal_handler` wires SIGQUIT (and any
other signal) to a collector callback; ``utils/supervise.py`` sends
exactly that SIGQUIT before a stall-SIGKILL and collects the bundle
path into its rotated artifact chain.
"""

from __future__ import annotations

import faulthandler
import json
import os
import signal
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

SCHEMA = 1
BUNDLE_PREFIX = "fcflight"
ENV_DIR = "FCTPU_FLIGHT_DIR"

# process-lifetime bundle counter: makes names unique within one second
# and gives "how many bundles has this process written" for telemetry
_seq_lock = threading.Lock()
_seq = 0


def default_bundle_dir() -> str:
    """Where bundles land when the caller does not say: the
    ``FCTPU_FLIGHT_DIR`` env var, else ``./fcflight``."""
    return os.environ.get(ENV_DIR) or os.path.join(".", "fcflight")


def bundles_written() -> int:
    """How many bundles this process has written (telemetry)."""
    with _seq_lock:
        return _seq


def _json_default(obj: Any) -> str:
    return repr(obj)


def write_bundle(reason: str, sections: Optional[Dict[str, Any]] = None,
                 base_dir: Optional[str] = None) -> str:
    """Write one bundle directory and return its path.

    ``sections`` maps section name -> JSON-serializable payload; the
    flight/counters/latency/stacks sections are collected here so every
    trigger site gets them for free.  Never raises on a serialization
    problem: a section that cannot serialize is written as its repr —
    an incident dump that throws during the incident is worse than a
    lossy one.
    """
    global _seq
    with _seq_lock:
        _seq += 1
        seq = _seq
    base = base_dir or default_bundle_dir()
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    name = f"{BUNDLE_PREFIX}_{stamp}_p{os.getpid()}_n{seq}_{reason}"
    out_dir = os.path.join(base, name)
    os.makedirs(out_dir, exist_ok=True)

    # local imports: stdlib-only siblings, deferred so a half-broken
    # interpreter (the incident case) fails per-section, not wholesale
    auto: Dict[str, Any] = {}
    try:
        from fastconsensus_tpu.obs import flight as _flight
        auto["flight"] = _flight.get_flight_recorder().snapshot()
    except Exception as exc:  # noqa: BLE001 — see docstring
        auto["flight"] = {"error": repr(exc)}
    try:
        from fastconsensus_tpu.obs import counters as _counters
        auto["counters"] = _counters.get_registry().snapshot()
    except Exception as exc:  # noqa: BLE001
        auto["counters"] = {"error": repr(exc)}
    try:
        from fastconsensus_tpu.obs import latency as _latency
        auto["latency"] = _latency.get_latency_registry().snapshot()
    except Exception as exc:  # noqa: BLE001
        auto["latency"] = {"error": repr(exc)}

    written: List[str] = []
    for sec_name, payload in {**auto, **(sections or {})}.items():
        path = os.path.join(out_dir, f"{sec_name}.json")
        try:
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, indent=1, default=_json_default)
                fh.write("\n")
            written.append(f"{sec_name}.json")
        # fcheck: ok=swallowed-error (a post-mortem writer that
        # throws mid-incident destroys the evidence it exists to
        # save: lossy beats throwing, and the manifest records
        # which sections made it)
        except Exception:  # noqa: BLE001 — lossy beats throwing
            continue

    try:
        with open(os.path.join(out_dir, "stacks.txt"), "w",
                  encoding="utf-8") as fh:
            faulthandler.dump_traceback(file=fh, all_threads=True)
        written.append("stacks.txt")
    # fcheck: ok=swallowed-error (same lossy-beats-throwing contract
    # as the sections above; stacks.txt is the most failure-prone
    # section — faulthandler under a dying interpreter)
    except Exception:  # noqa: BLE001
        pass

    manifest = {
        "schema": SCHEMA,
        "tool": "fcflight-bundle",
        "reason": reason,
        "wall_time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "time_unix": round(time.time(), 3),
        "time_mono": round(time.monotonic(), 6),
        "pid": os.getpid(),
        "seq": seq,
        "argv": list(sys.argv),
        "threads": sorted(t.name for t in threading.enumerate()),
        "sections": sorted(written),
    }
    # the manifest lands LAST: its presence marks the bundle complete
    # (a SIGKILL racing the dump leaves a manifest-less partial dir a
    # collector can recognize and skip)
    with open(os.path.join(out_dir, "MANIFEST.json"), "w",
              encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=1)
        fh.write("\n")
    return out_dir


def list_bundles(base_dir: Optional[str] = None) -> List[str]:
    """Complete bundle directories under ``base_dir`` (manifest
    present), sorted oldest first by manifest timestamp."""
    base = base_dir or default_bundle_dir()
    out = []
    try:
        entries = sorted(os.listdir(base))
    except OSError:
        return []
    for entry in entries:
        path = os.path.join(base, entry)
        if entry.startswith(BUNDLE_PREFIX + "_") and \
                os.path.isfile(os.path.join(path, "MANIFEST.json")):
            out.append(path)
    return out


def install_signal_handler(collect: Optional[
        Callable[[], Dict[str, Any]]] = None,
        base_dir: Optional[str] = None,
        signum: int = signal.SIGQUIT,
        reason: str = "sigquit",
        on_written: Optional[Callable[[str], None]] = None) -> Any:
    """Install a signal handler that writes a bundle and returns to the
    interrupted program (the process keeps running — SIGQUIT becomes
    "dump state", not "die").  ``collect`` supplies extra sections at
    dump time; ``on_written`` observes the bundle path (logging,
    ``/healthz``).  Returns the previous handler."""
    def _handler(sig: int, frame: Any) -> None:  # noqa: ARG001
        sections: Dict[str, Any] = {}
        if collect is not None:
            try:
                sections = collect() or {}
            except Exception as exc:  # noqa: BLE001 — dump anyway
                sections = {"collect_error": {"error": repr(exc)}}
        path = write_bundle(reason, sections, base_dir=base_dir)
        if on_written is not None:
            try:
                on_written(path)
            except Exception:  # noqa: BLE001
                pass

    return signal.signal(signum, _handler)


# ---------------------------------------------------------------------
# jax-free reader: render / diff
# ---------------------------------------------------------------------

def _load(bundle_dir: str, section: str) -> Optional[Any]:
    path = os.path.join(bundle_dir, section)
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def _fmt_phases(phases: Optional[Dict[str, Any]]) -> str:
    if not phases:
        return "-"
    return " ".join(f"{k}={1000.0 * float(v):.1f}ms"
                    for k, v in phases.items() if v is not None)


def render(bundle_dir: str, tail: int = 40) -> str:
    """Human-readable bundle summary (the ``render`` subcommand)."""
    from fastconsensus_tpu.obs.flight import merge_events

    manifest = _load(bundle_dir, "MANIFEST.json")
    if manifest is None:
        return f"{bundle_dir}: no MANIFEST.json — not a complete bundle"
    lines = [
        f"== fcflight bundle {os.path.basename(bundle_dir)} ==",
        f"reason   : {manifest.get('reason')}",
        f"written  : {manifest.get('wall_time')} (pid "
        f"{manifest.get('pid')}, bundle #{manifest.get('seq')})",
        f"threads  : {len(manifest.get('threads', []))} "
        f"({', '.join(manifest.get('threads', [])[:8])}"
        f"{', ...' if len(manifest.get('threads', [])) > 8 else ''})",
        f"sections : {', '.join(manifest.get('sections', []))}",
    ]
    config = _load(bundle_dir, "config.json")
    if config:
        lines.append(f"config   : {json.dumps(config, sort_keys=True)}")
    jobs = _load(bundle_dir, "jobs.json")
    if jobs:
        rows = jobs.get("jobs", jobs) if isinstance(jobs, dict) else jobs
        live = [j for j in rows
                if j.get("state") in ("queued", "running")]
        lines.append("")
        lines.append(f"-- jobs: {len(rows)} tracked, {len(live)} "
                     f"in flight --")
        for j in live or rows[-5:]:
            lines.append(
                f"  {j.get('job_id', '?')} state={j.get('state')} "
                f"bucket={j.get('bucket', '-')} "
                f"phases: {_fmt_phases(j.get('phases_s'))}")
    watchdog = _load(bundle_dir, "watchdog.json")
    if watchdog:
        lines.append("")
        lines.append(f"-- watchdog: {json.dumps(watchdog, sort_keys=True)}")
    counters = _load(bundle_dir, "counters.json")
    if counters and isinstance(counters.get("counters"), dict):
        lines.append("")
        lines.append("-- counters (serve.* / quality.*) --")
        for key, val in sorted(counters["counters"].items()):
            if key.startswith(("serve.", "quality.")):
                lines.append(f"  {key} = {val}")
    flight = _load(bundle_dir, "flight.json")
    if flight:
        events = merge_events(flight)
        lines.append("")
        lines.append(f"-- flight timeline: {len(events)} event(s), "
                     f"{flight.get('dropped', 0)} overwritten; "
                     f"last {min(tail, len(events))} --")
        for event in events[-tail:]:
            extra = {k: v for k, v in event.items()
                     if k not in ("ts", "kind", "thread", "job")}
            job = f" job={event['job']}" if "job" in event else ""
            extra_s = f" {extra}" if extra else ""
            lines.append(
                f"  [{event.get('ts', 0.0):.6f}] "
                f"{event.get('thread', '?')}: "
                f"{event.get('kind')}{job}{extra_s}")
    stacks_path = os.path.join(bundle_dir, "stacks.txt")
    if os.path.isfile(stacks_path):
        with open(stacks_path, encoding="utf-8") as fh:
            stacks = fh.read().rstrip()
        lines.append("")
        lines.append("-- thread stacks (faulthandler) --")
        lines.append(stacks)
    return "\n".join(lines)


def _event_kinds(flight: Optional[Dict[str, Any]]) -> Dict[str, int]:
    from fastconsensus_tpu.obs.flight import merge_events

    if not flight:
        return {}
    counts: Dict[str, int] = {}
    for event in merge_events(flight):
        kind = str(event.get("kind"))
        counts[kind] = counts.get(kind, 0) + 1
    return counts


def diff(old_dir: str, new_dir: str) -> str:
    """Counter and flight-event deltas between two bundles (the
    ``diff`` subcommand): what happened between two dumps of one
    process — e.g. a pre-incident SIGQUIT bundle vs the watchdog's."""
    lines = [f"== bundle diff: {os.path.basename(old_dir)} -> "
             f"{os.path.basename(new_dir)} =="]
    old_c = (_load(old_dir, "counters.json") or {}).get("counters") or {}
    new_c = (_load(new_dir, "counters.json") or {}).get("counters") or {}
    deltas = {k: new_c.get(k, 0) - old_c.get(k, 0)
              for k in sorted(set(old_c) | set(new_c))
              if new_c.get(k, 0) != old_c.get(k, 0)}
    lines.append(f"-- counters: {len(deltas)} changed --")
    for key, dv in deltas.items():
        lines.append(f"  {key} {old_c.get(key, 0)} -> {new_c.get(key, 0)}"
                     f" ({'+' if dv >= 0 else ''}{dv})")
    old_k = _event_kinds(_load(old_dir, "flight.json"))
    new_k = _event_kinds(_load(new_dir, "flight.json"))
    lines.append("-- flight events by kind (ring-windowed counts) --")
    for kind in sorted(set(old_k) | set(new_k)):
        lines.append(f"  {kind}: {old_k.get(kind, 0)} -> "
                     f"{new_k.get(kind, 0)}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m fastconsensus_tpu.obs.postmortem",
        description="fcflight post-mortem bundle reader (jax-free)")
    sub = p.add_subparsers(dest="cmd", required=True)
    pr = sub.add_parser("render", help="summarize one bundle")
    pr.add_argument("bundle", help="bundle directory")
    pr.add_argument("--tail", type=int, default=40,
                    help="flight-timeline events to show (default 40)")
    pd = sub.add_parser("diff", help="delta between two bundles")
    pd.add_argument("old", help="earlier bundle directory")
    pd.add_argument("new", help="later bundle directory")
    args = p.parse_args(argv)
    if args.cmd == "render":
        if not os.path.isfile(os.path.join(args.bundle, "MANIFEST.json")):
            print(f"{args.bundle}: no MANIFEST.json — not a complete "
                  f"fcflight bundle", file=sys.stderr)
            return 2
        print(render(args.bundle, tail=args.tail))
        return 0
    print(diff(args.old, args.new))
    return 0


if __name__ == "__main__":
    sys.exit(main())
