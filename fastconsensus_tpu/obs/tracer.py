"""fcobs spans: a low-overhead host-side span tracer for the driver loop.

Spans measure the *host-visible* phases of a consensus run — rounds,
detection chunks, executable (re)builds, growth replays, the final
re-detection — as nested intervals with wall time (``time.perf_counter``)
and CPU time (``time.process_time``).  Device-side kernel timing belongs
to ``jax.profiler`` — and an *annotating* tracer (``Tracer(annotate=
True)``, obs/device.py) mirrors every span into the profiler's timeline
as a ``TraceAnnotation`` so the two views share one vocabulary; fcobs
alone answers the cheaper, always-available question: where did the
driver's wall clock go, and how often did it cross the host-device
boundary (obs/counters.py).

Overhead contract: **disabled is the default and costs ~nothing.**  A
disabled tracer's :meth:`Tracer.span` is one attribute check returning a
shared no-op context manager — no event objects, no clock reads, no lock
traffic — so the instrumentation stays in the hot path permanently and
``cli.py --trace`` / tests merely swap in an enabled tracer
(:func:`set_tracer` / :func:`use_tracer`).

Thread-safety: each thread keeps its own span stack (nesting and
parenting are per-thread properties), and finished spans append to one
shared list under a lock.  XLA may call back from worker threads; spans
opened there interleave correctly.

Finished spans are plain dicts shaped for the exporters (obs/export.py):
``name``, ``ph`` ("X" complete / "i" instant), ``ts``/``dur`` in integer
microseconds relative to the tracer's start, ``cpu_us``, ``tid``,
``depth``, ``parent`` and optional ``args``.  Children close before their
parents, so the event list is ordered by span *end*; exporters re-sort by
``ts``.
"""

from __future__ import annotations

import contextlib
import functools
import threading
# fcheck: ok=sync-in-loop (the tracer's whole job is deliberate host
# clock reads — time.perf_counter/process_time on span entry and exit;
# they touch no device values and never force a device sync)
import time
from typing import Callable, Dict, List, Optional

from fastconsensus_tpu.obs import flight as obs_flight


class _NullSpan:
    """Shared do-nothing context manager handed out by disabled tracers."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span; records itself into the tracer on ``__exit__``."""

    __slots__ = ("_tracer", "name", "args", "_t0", "_cpu0", "_parent",
                 "_depth")

    def __init__(self, tracer: "Tracer", name: str,
                 args: Optional[Dict]) -> None:
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self) -> "_Span":
        stack = self._tracer._stack()
        self._parent = stack[-1].name if stack else None
        self._depth = len(stack)
        stack.append(self)
        # fcflight mirror: enabled-tracer spans also land in the flight
        # recorder's ring, so a post-mortem bundle of a traced run shows
        # the driver's phase structure next to the serving events.  One
        # O(1) ring append; the disabled tracer never constructs a _Span
        # so the overhead contract above is untouched.
        obs_flight.record("span_open", name=self.name)
        self._t0 = time.perf_counter()
        self._cpu0 = time.process_time()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter()
        cpu1 = time.process_time()
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        ev = {
            "name": self.name,
            "ph": "X",
            "ts": int((self._t0 - self._tracer._t0) * 1e6),
            "dur": int((t1 - self._t0) * 1e6),
            "cpu_us": int((cpu1 - self._cpu0) * 1e6),
            "tid": threading.get_ident(),
            "depth": self._depth,
            "parent": self._parent,
        }
        if self.args:
            ev["args"] = self.args
        self._tracer._record(ev)
        obs_flight.record("span_close", name=self.name,
                          dur_us=ev["dur"])
        return False


class _AnnotatedSpan:
    """Host span + ``jax.profiler`` annotation entered/exited together.

    The annotation is entered first and exited last, so the device-side
    region fully encloses the host span it names.  Handed out only by
    annotating tracers (``Tracer(annotate=True)``) — the disabled and
    host-only paths never construct one.
    """

    __slots__ = ("_span", "_ann")

    def __init__(self, span: "_Span", ann) -> None:
        self._span = span
        self._ann = ann

    def __enter__(self) -> "_Span":
        self._ann.__enter__()
        return self._span.__enter__()

    def __exit__(self, exc_type, exc, tb) -> bool:
        try:
            return bool(self._span.__exit__(exc_type, exc, tb))
        finally:
            self._ann.__exit__(exc_type, exc, tb)


class Tracer:
    """Collects nested spans; see the module docstring for the contract.

    ``annotate=True`` additionally wraps every span in a ``jax.profiler``
    ``TraceAnnotation`` (and :meth:`step_span` in a
    ``StepTraceAnnotation``), so a concurrent ``jax.profiler`` trace
    (obs/device.py ProfilerSession) shows the same span names on the
    device timeline.  Requested but unavailable annotation (no usable
    ``jax.profiler``) silently degrades to host-only spans.
    """

    def __init__(self, enabled: bool = True,
                 annotate: bool = False) -> None:
        self.enabled = enabled
        self.annotate = False
        if annotate:
            from fastconsensus_tpu.obs import device as obs_device

            if obs_device.available():
                # bind the profiler classes ONCE: span()/step_span() are
                # on the per-round / per-detect-chunk hot path and must
                # not pay a module import lookup + try/except per call
                import jax.profiler as _prof

                self.annotate = True
                self._annotation = _prof.TraceAnnotation
                self._step_annotation = (
                    lambda name, step: _prof.StepTraceAnnotation(
                        name, step_num=int(step)))
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._local = threading.local()
        self._t0 = time.perf_counter()

    @property
    def t0(self) -> float:
        """perf_counter value of the tracer's ts=0 (timeline merging —
        obs/device.ProfilerSession.offset_us)."""
        return self._t0

    # -- recording ---------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _record(self, ev: dict) -> None:
        with self._lock:
            self._events.append(ev)

    # -- public API --------------------------------------------------

    def span(self, name: str, **args):
        """Context manager timing a named region; ``args`` become the
        span's Perfetto args.  Returns the shared no-op span when the
        tracer is disabled (nothing is allocated or recorded).  An
        annotating tracer pairs the span with a profiler
        ``TraceAnnotation`` of the same name."""
        if not self.enabled:
            return _NULL_SPAN
        span = _Span(self, name, args or None)
        if self.annotate:
            return _AnnotatedSpan(span, self._annotation(name))
        return span

    def step_span(self, name: str, step: int, **args):
        """Like :meth:`span`, but the unit of repetition — one consensus
        round.  ``step`` is recorded in the span args, and an annotating
        tracer emits a ``StepTraceAnnotation(name, step_num=step)`` so
        profiler tooling groups the round's device ops per step."""
        if not self.enabled:
            return _NULL_SPAN
        span = _Span(self, name, {"step": int(step), **args})
        if self.annotate:
            return _AnnotatedSpan(span,
                                  self._step_annotation(name, step))
        return span

    def instant(self, name: str, **args) -> None:
        """Record a zero-duration marker (Perfetto ``ph: "i"``)."""
        if not self.enabled:
            return
        stack = self._stack()
        ev = {
            "name": name,
            "ph": "i",
            "ts": int((time.perf_counter() - self._t0) * 1e6),
            "dur": 0,
            "cpu_us": 0,
            "tid": threading.get_ident(),
            "depth": len(stack),
            "parent": stack[-1].name if stack else None,
        }
        if args:
            ev["args"] = args
        self._record(ev)

    def events(self) -> List[dict]:
        """Snapshot of all finished spans (ordered by span end)."""
        with self._lock:
            return list(self._events)

    def events_since(self, start: int) -> List[dict]:
        """Finished spans from index ``start`` on — the incremental-
        export primitive (export.JsonlStreamer): copies only the new
        tail under the lock, so per-round streaming stays O(new spans)
        instead of re-copying the whole history every flush."""
        with self._lock:
            return list(self._events[start:])

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def drain_since(self, start: int) -> List[dict]:
        """Atomically snapshot finished spans from ``start`` on AND
        clear the buffer — the window-reset primitive for multi-worker
        streamers (serve/server.py): a separate ``events_since`` +
        ``clear`` pair can lose a span another thread closes between
        the two calls (wiped from memory without ever being
        streamed)."""
        with self._lock:
            tail = list(self._events[start:])
            self._events.clear()
            return tail


# The ambient tracer consulted by instrumented code.  Disabled by default:
# run_consensus and the engine call get_tracer() unconditionally, and the
# no-op path is the permanent cost of having the instrumentation at all.
_DISABLED = Tracer(enabled=False)
_active: Tracer = _DISABLED


def get_tracer() -> Tracer:
    """The ambient tracer (a disabled singleton unless one was set)."""
    return _active


def set_tracer(tracer: Optional[Tracer]) -> Tracer:
    """Install ``tracer`` as the ambient tracer (None restores the
    disabled default).  Returns the now-active tracer."""
    global _active
    _active = tracer if tracer is not None else _DISABLED
    return _active


@contextlib.contextmanager
def use_tracer(tracer: Tracer):
    """Scoped :func:`set_tracer`: restores the previous tracer on exit."""
    global _active
    prev = _active
    _active = tracer
    try:
        yield tracer
    finally:
        _active = prev


def traced(name: Optional[str] = None) -> Callable:
    """Decorator form: time every call of ``fn`` as a span on the tracer
    active *at call time*.  With tracing disabled the wrapper adds one
    global read and one attribute check per call."""
    def deco(fn: Callable) -> Callable:
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            tracer = _active
            if not tracer.enabled:
                return fn(*a, **kw)
            with tracer.span(label):
                return fn(*a, **kw)

        return wrapper

    return deco
