"""fcobs spans: a low-overhead host-side span tracer for the driver loop.

Spans measure the *host-visible* phases of a consensus run — rounds,
detection chunks, executable (re)builds, growth replays, the final
re-detection — as nested intervals with wall time (``time.perf_counter``)
and CPU time (``time.process_time``).  Device-side kernel timing belongs
to ``jax.profiler`` (utils/trace.py:profiler_trace); fcobs answers the
cheaper, always-available question: where did the driver's wall clock go,
and how often did it cross the host-device boundary (obs/counters.py).

Overhead contract: **disabled is the default and costs ~nothing.**  A
disabled tracer's :meth:`Tracer.span` is one attribute check returning a
shared no-op context manager — no event objects, no clock reads, no lock
traffic — so the instrumentation stays in the hot path permanently and
``cli.py --trace`` / tests merely swap in an enabled tracer
(:func:`set_tracer` / :func:`use_tracer`).

Thread-safety: each thread keeps its own span stack (nesting and
parenting are per-thread properties), and finished spans append to one
shared list under a lock.  XLA may call back from worker threads; spans
opened there interleave correctly.

Finished spans are plain dicts shaped for the exporters (obs/export.py):
``name``, ``ph`` ("X" complete / "i" instant), ``ts``/``dur`` in integer
microseconds relative to the tracer's start, ``cpu_us``, ``tid``,
``depth``, ``parent`` and optional ``args``.  Children close before their
parents, so the event list is ordered by span *end*; exporters re-sort by
``ts``.
"""

from __future__ import annotations

import contextlib
import functools
import threading
# fcheck: ok=sync-in-loop (the tracer's whole job is deliberate host
# clock reads — time.perf_counter/process_time on span entry and exit;
# they touch no device values and never force a device sync)
import time
from typing import Callable, Dict, List, Optional


class _NullSpan:
    """Shared do-nothing context manager handed out by disabled tracers."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span; records itself into the tracer on ``__exit__``."""

    __slots__ = ("_tracer", "name", "args", "_t0", "_cpu0", "_parent",
                 "_depth")

    def __init__(self, tracer: "Tracer", name: str,
                 args: Optional[Dict]) -> None:
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self) -> "_Span":
        stack = self._tracer._stack()
        self._parent = stack[-1].name if stack else None
        self._depth = len(stack)
        stack.append(self)
        self._t0 = time.perf_counter()
        self._cpu0 = time.process_time()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter()
        cpu1 = time.process_time()
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        ev = {
            "name": self.name,
            "ph": "X",
            "ts": int((self._t0 - self._tracer._t0) * 1e6),
            "dur": int((t1 - self._t0) * 1e6),
            "cpu_us": int((cpu1 - self._cpu0) * 1e6),
            "tid": threading.get_ident(),
            "depth": self._depth,
            "parent": self._parent,
        }
        if self.args:
            ev["args"] = self.args
        self._tracer._record(ev)
        return False


class Tracer:
    """Collects nested spans; see the module docstring for the contract."""

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._local = threading.local()
        self._t0 = time.perf_counter()

    # -- recording ---------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _record(self, ev: dict) -> None:
        with self._lock:
            self._events.append(ev)

    # -- public API --------------------------------------------------

    def span(self, name: str, **args):
        """Context manager timing a named region; ``args`` become the
        span's Perfetto args.  Returns the shared no-op span when the
        tracer is disabled (nothing is allocated or recorded)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        """Record a zero-duration marker (Perfetto ``ph: "i"``)."""
        if not self.enabled:
            return
        stack = self._stack()
        ev = {
            "name": name,
            "ph": "i",
            "ts": int((time.perf_counter() - self._t0) * 1e6),
            "dur": 0,
            "cpu_us": 0,
            "tid": threading.get_ident(),
            "depth": len(stack),
            "parent": stack[-1].name if stack else None,
        }
        if args:
            ev["args"] = args
        self._record(ev)

    def events(self) -> List[dict]:
        """Snapshot of all finished spans (ordered by span end)."""
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


# The ambient tracer consulted by instrumented code.  Disabled by default:
# run_consensus and the engine call get_tracer() unconditionally, and the
# no-op path is the permanent cost of having the instrumentation at all.
_DISABLED = Tracer(enabled=False)
_active: Tracer = _DISABLED


def get_tracer() -> Tracer:
    """The ambient tracer (a disabled singleton unless one was set)."""
    return _active


def set_tracer(tracer: Optional[Tracer]) -> Tracer:
    """Install ``tracer`` as the ambient tracer (None restores the
    disabled default).  Returns the now-active tracer."""
    global _active
    _active = tracer if tracer is not None else _DISABLED
    return _active


@contextlib.contextmanager
def use_tracer(tracer: Tracer):
    """Scoped :func:`set_tracer`: restores the previous tracer on exit."""
    global _active
    prev = _active
    _active = tracer
    try:
        yield tracer
    finally:
        _active = prev


def traced(name: Optional[str] = None) -> Callable:
    """Decorator form: time every call of ``fn`` as a span on the tracer
    active *at call time*.  With tracing disabled the wrapper adds one
    global read and one attribute check per call."""
    def deco(fn: Callable) -> Callable:
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            tracer = _active
            if not tracer.enabled:
                return fn(*a, **kw)
            with tracer.span(label):
                return fn(*a, **kw)

        return wrapper

    return deco
