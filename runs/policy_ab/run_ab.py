#!/usr/bin/env python
"""Policy-constant sensitivity A/B (VERDICT r4 #7).

STALE_ROUNDS in {3,4,6} and FACTOR_WARM in {0.85,0.9,0.95} (one factor
at a time around the shipped point), on karate (full size) and an
lfr10k cell sized for the CPU backend (n_p=16, bounded-6).  Records
rounds to termination, refresh count, and NMI vs truth.  Quality-only:
runs on the CPU backend so the TPU stays free for the 100k flagship
run.  Output: runs/policy_ab/results.jsonl
"""
import json
import os
import sys
import time

sys.path.insert(0, "/root/repo")

from fastconsensus_tpu.utils.hostcpu import force_cpu_backend  # noqa: E402

force_cpu_backend()

import jax  # noqa: E402
import numpy as np  # noqa: E402

BASE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(BASE, "results.jsonl")

CELLS = [("STALE_ROUNDS", 3), ("STALE_ROUNDS", 4), ("STALE_ROUNDS", 6),
         ("FACTOR_WARM", 0.85), ("FACTOR_WARM", 0.95)]


def run_cell(graph, truth, alg, n_p, max_rounds, knob, value, seed=0):
    from fastconsensus_tpu import policy
    from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
    from fastconsensus_tpu.graph import pack_edges
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.metrics import nmi

    # The fused-rounds block reads the policy constants at TRACE time and
    # is lru-cached on shapes only (engine._jitted_rounds_block): without
    # clearing, every cell after the first reuses the first cell's baked
    # constants and the A/B silently measures nothing (round-5 review).
    from fastconsensus_tpu import engine

    engine._jitted_rounds_block.cache_clear()
    engine._jitted_round.cache_clear()
    engine._jitted_tail.cache_clear()
    default = getattr(policy, knob)
    setattr(policy, knob, value)
    try:
        slab = pack_edges(graph, int(truth.shape[0]))
        cfg = ConsensusConfig(algorithm=alg, n_p=n_p, tau=0.2, delta=0.02,
                              seed=seed, max_rounds=max_rounds)
        t0 = time.time()
        res = run_consensus(slab, get_detector(alg), cfg)
        wall = time.time() - t0
        scores = [float(nmi(res.partitions[i], truth))
                  for i in range(min(n_p, 20))]
        refreshes = sum(1 for h in res.history[1:] if h["cold"])
        return {"knob": knob, "value": value, "default": default,
                "rounds": res.rounds, "converged": res.converged,
                "refreshes": refreshes, "nmi_mean": round(
                    float(np.mean(scores)), 4), "wall_s": round(wall, 1),
                "seed": seed}
    finally:
        setattr(policy, knob, default)


def main():
    from fastconsensus_tpu.utils.io import read_edgelist

    edges, _, _ = read_edgelist("/root/repo/examples/karate_club.txt")
    ktruth = np.array([0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 0, 0, 0, 0, 1, 1, 0,
                       0, 1, 0, 1, 0, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1])
    e10k = np.loadtxt("/root/repo/runs/lfr10k_r4/graph.txt", dtype=np.int64)
    t10k = np.load("/root/repo/runs/lfr10k_r4/truth.npy")

    done = set()
    if os.path.exists(OUT):
        with open(OUT) as fh:
            for ln in fh:
                try:
                    r = json.loads(ln)
                except ValueError:
                    continue  # truncated tail from a killed prior run
                done.add((r["config"], r["knob"], r["value"],
                          r.get("seed", 0)))
    with open(OUT, "a") as fh:
        for knob, value in CELLS:
            for seed in (0, 1):
                if ("karate", knob, value, seed) in done:
                    continue
                r = run_cell(edges, ktruth, "louvain", 20, 24, knob, value,
                             seed)
                r["config"] = "karate"
                print(json.dumps(r), flush=True)
                fh.write(json.dumps(r) + "\n")
                fh.flush()
        for knob, value in CELLS:
            if ("lfr10k_np16", knob, value, 0) in done:
                continue
            r = run_cell(e10k, t10k, "leiden", 16, 6, knob, value, 0)
            r["config"] = "lfr10k_np16"
            print(json.dumps(r), flush=True)
            fh.write(json.dumps(r) + "\n")
            fh.flush()


if __name__ == "__main__":
    main()
