#!/usr/bin/env python
"""Policy-constant sensitivity A/B (VERDICT r4 #7).

STALE_ROUNDS in {3,4,6} and FACTOR_WARM in {0.85,0.9,0.95} (one factor
at a time around the shipped point), on karate (full size) and an
lfr10k cell sized for the CPU backend (n_p=16, bounded-6).  Records
rounds to termination, refresh count, and NMI vs truth.  Quality-only:
runs on the CPU backend so the TPU stays free for the 100k flagship
run.  Output: runs/policy_ab/results.jsonl
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
sys.path.insert(0, "/root/repo")

import numpy as np  # noqa: E402

BASE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(BASE, "results.jsonl")

CELLS = [("STALE_ROUNDS", 3), ("STALE_ROUNDS", 4), ("STALE_ROUNDS", 6),
         ("FACTOR_WARM", 0.85), ("FACTOR_WARM", 0.95)]


def run_cell(graph, truth, alg, n_p, max_rounds, knob, value, seed=0):
    from fastconsensus_tpu import policy
    from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
    from fastconsensus_tpu.graph import pack_edges
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.metrics import nmi

    default = getattr(policy, knob)
    setattr(policy, knob, value)
    try:
        slab = pack_edges(graph, int(truth.shape[0]))
        cfg = ConsensusConfig(algorithm=alg, n_p=n_p, tau=0.2, delta=0.02,
                              seed=seed, max_rounds=max_rounds)
        t0 = time.time()
        res = run_consensus(slab, get_detector(alg), cfg)
        wall = time.time() - t0
        scores = [float(nmi(res.partitions[i], truth))
                  for i in range(min(n_p, 20))]
        refreshes = sum(1 for h in res.history[1:] if h["cold"])
        return {"knob": knob, "value": value, "default": default,
                "rounds": res.rounds, "converged": res.converged,
                "refreshes": refreshes, "nmi_mean": round(
                    float(np.mean(scores)), 4), "wall_s": round(wall, 1),
                "seed": seed}
    finally:
        setattr(policy, knob, default)


def main():
    from fastconsensus_tpu.utils.io import read_edgelist

    edges, _, _ = read_edgelist("/root/repo/examples/karate_club.txt")
    ktruth = np.array([0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 0, 0, 0, 0, 1, 1, 0,
                       0, 1, 0, 1, 0, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1])
    e10k = np.loadtxt("/root/repo/runs/lfr10k_r4/graph.txt", dtype=np.int64)
    t10k = np.load("/root/repo/runs/lfr10k_r4/truth.npy")

    with open(OUT, "a") as fh:
        for knob, value in CELLS:
            for seed in (0, 1):
                r = run_cell(edges, ktruth, "louvain", 20, 24, knob, value,
                             seed)
                r["config"] = "karate"
                print(json.dumps(r), flush=True)
                fh.write(json.dumps(r) + "\n")
                fh.flush()
        for knob, value in CELLS:
            r = run_cell(e10k, t10k, "leiden", 16, 6, knob, value, 0)
            r["config"] = "lfr10k_np16"
            print(json.dumps(r), flush=True)
            fh.write(json.dumps(r) + "\n")
            fh.flush()


if __name__ == "__main__":
    main()
