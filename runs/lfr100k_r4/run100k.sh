#!/bin/bash
# lfr100k round-4 A/B vs round-3 (VERDICT r3 #2): same config as the r3
# run (louvain n_p=200 tau 0.2 delta 0.02 max-rounds 8, real-LFR 100k),
# round-4 engine = CSR closure + budget regrowth.  Frozen worktree.
set -u
cd /tmp/fc_ab
export PYTHONPATH=/tmp/fc_ab:/root/.axon_site
d=/root/repo/runs/lfr100k_r4
mkdir -p "$d"
t0=$SECONDS
python -m fastconsensus_tpu.utils.supervise --progress "$d/cache" \
  --stall-seconds 600 -- \
  python -m fastconsensus_tpu.cli -f "$d/graph.txt" --alg louvain -np 200 \
    -t 0.2 -d 0.02 --seed 0 --max-rounds 8 \
    --checkpoint "$d/ck.npz" --resume --detect-cache "$d/cache" \
    --trace-jsonl "$d/rounds.jsonl" --out-dir "$d" \
    >> "$d/run.log" 2>&1
rc=$?
echo "done rc=$rc wall=$((SECONDS-t0))s" >> "$d/run.log"
