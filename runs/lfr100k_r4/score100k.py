#!/usr/bin/env python
"""Score the lfr100k round-4 run: NMI vs planted truth + the VERDICT r3
#2 criterion (final-round hub overflow as a fraction of live hub mass)."""
import glob, json, os, sys
import numpy as np
sys.path.insert(0, "/root/repo")
BASE = os.path.dirname(os.path.abspath(__file__))

def main():
    from fastconsensus_tpu.utils.metrics import nmi
    truth = np.load(os.path.join(BASE, "truth.npy"))
    rows = [json.loads(ln) for ln in open(os.path.join(BASE, "rounds.jsonl"))
            if ln.strip()]
    out = {"rounds": rows[-1]["round"],
           "wall_s": round(sum(r.get("round_seconds", 0) for r in rows), 1),
           "hub_overflow_by_round": [r["n_hub_overflow"] for r in rows],
           "unconverged_frac_by_round": [
               round(r["n_unconverged"] / max(r["n_alive"], 1), 3)
               for r in rows]}
    # hub mass fraction criterion from the final checkpoint
    try:
        from fastconsensus_tpu.utils import checkpoint as ckpt
        slab, *_ = ckpt.load_checkpoint(os.path.join(BASE, "ck.npz"))
        import jax
        deg = np.asarray(jax.device_get(slab.degrees()))
        hub_mass = int(deg[deg > slab.d_hyb].sum())
        out["d_hyb"] = slab.d_hyb
        out["hub_cap"] = slab.hub_cap
        out["hub_mass"] = hub_mass
        out["final_hub_overflow_frac_of_mass"] = round(
            rows[-1]["n_hub_overflow"] / max(hub_mass, 1), 4)
    except Exception as e:  # noqa: BLE001
        out["ck_error"] = str(e)
    mdirs = glob.glob(os.path.join(BASE, "memberships_*"))
    if mdirs:
        scores = []
        for f in sorted(glob.glob(os.path.join(mdirs[0], "*")),
                        key=lambda p: int(os.path.basename(p)))[:20]:
            pairs = np.loadtxt(f, dtype=np.int64)
            lab = np.zeros(truth.shape[0], np.int64)
            lab[pairs[:, 0] - 1] = pairs[:, 1]
            scores.append(float(nmi(lab, truth)))
        out["nmi_mean20"] = round(float(np.mean(scores)), 4)
        out["nmi_first"] = round(scores[0], 4)
    print(json.dumps(out))

if __name__ == "__main__":
    main()
