#!/usr/bin/env python
"""Score lfr10k A/B variants: NMI vs planted truth + trajectory summary."""
import glob
import json
import os
import sys

import numpy as np

sys.path.insert(0, "/root/repo")
BASE = os.path.dirname(os.path.abspath(__file__))


def score(variant: str) -> None:
    from fastconsensus_tpu.utils.metrics import nmi

    d = os.path.join(BASE, variant)
    truth = np.load(os.path.join(BASE, "truth.npy"))
    mdirs = glob.glob(os.path.join(d, "memberships_*"))
    rows = []
    if os.path.exists(os.path.join(d, "rounds.jsonl")):
        with open(os.path.join(d, "rounds.jsonl")) as fh:
            rows = [json.loads(ln) for ln in fh if ln.strip()]
    out = {"variant": variant, "rounds": len({r["round"] for r in rows})}
    if rows:
        last = rows[-1]
        out.update(
            n_alive=last["n_alive"], n_unconverged=last["n_unconverged"],
            unconverged_frac=round(
                last["n_unconverged"] / max(last["n_alive"], 1), 4),
            wall_s=round(sum(r.get("round_seconds", 0) for r in rows
                             if r.get("round_seconds")), 1),
            closure_added_total=sum(r["n_closure_added"] for r in rows),
            hub_overflow_last=last["n_hub_overflow"])
    if mdirs:
        scores = []
        for f in sorted(glob.glob(os.path.join(mdirs[0], "*")),
                        key=lambda p: int(os.path.basename(p)))[:20]:
            pairs = np.loadtxt(f, dtype=np.int64)
            lab = np.zeros(truth.shape[0], np.int64)
            lab[pairs[:, 0] - 1] = pairs[:, 1]
            scores.append(float(nmi(lab, truth)))
        out["nmi_mean"] = round(float(np.mean(scores)), 4)
        out["nmi_first"] = round(scores[0], 4)
        out["n_scored"] = len(scores)
    print(json.dumps(out))


if __name__ == "__main__":
    for v in (sys.argv[1:] or ["b", "c", "a"]):
        try:
            score(v)
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"variant": v, "error": str(e)}))
