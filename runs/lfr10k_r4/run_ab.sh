#!/bin/bash
# lfr10k A/B matrix (round-4 VERDICT #3/#4), run from the frozen worktree
# /tmp/fc_ab so live edits cannot change detect-cache fingerprints mid-run.
set -u
cd /tmp/fc_ab
export PYTHONPATH=/tmp/fc_ab:/root/.axon_site
GRAPH=/root/repo/runs/lfr10k_r4/graph.txt
BASE=/root/repo/runs/lfr10k_r4

run_variant () {
  local name="$1"; shift
  local d="$BASE/$name"
  mkdir -p "$d"
  echo "=== variant $name: start $(date +%T)" >> "$BASE/ab.log"
  local t0=$SECONDS
  python -m fastconsensus_tpu.utils.supervise --progress "$d/rounds.jsonl" \
    --stall-seconds 420 -- \
    python -m fastconsensus_tpu.cli -f "$GRAPH" --alg leiden -np 100 \
      -t 0.2 -d 0.02 --seed 0 --max-rounds 15 \
      --checkpoint "$d/ck.npz" --resume --detect-cache "$d/cache" \
      --trace-jsonl "$d/rounds.jsonl" --out-dir "$d" "$@" \
      >> "$d/run.log" 2>&1
  local rc=$?
  echo "=== variant $name: done $(date +%T) rc=$rc wall=$((SECONDS-t0))s" >> "$BASE/ab.log"
}

run_variant b --closure-tau 0.2
FCTPU_COLD_SWEEPS=8 run_variant c --closure-tau 0.2
run_variant a
echo "=== all done $(date +%T)" >> "$BASE/ab.log"
