import logging, os, sys, time
sys.path.insert(0, "/root/repo")
logging.basicConfig(level=logging.INFO,
                    format="%(asctime)s %(levelname)s %(message)s")
logging.getLogger("fastconsensus_tpu").setLevel(logging.DEBUG)
d = os.path.dirname(os.path.abspath(__file__))
sys.argv = ["cli", "-f", os.path.join(d, "..", "lfr100k_r4", "graph.txt"),
            "--alg", "louvain", "-np", "200", "-t", "0.2", "-d", "0.02",
            "--seed", "0", "--max-rounds", "9", "--closure-tau", "0.2",
            "--checkpoint", os.path.join(d, "ck.npz"), "--resume",
            "--detect-cache", os.path.join(d, "cache"),
            "--trace-jsonl", os.path.join(d, "rounds.jsonl")]
os.chdir(d)
from fastconsensus_tpu import cli
t0 = time.time()
rc = cli.main()
print(f"END_TO_END {time.time()-t0:.1f}s rc={rc}", flush=True)
sys.exit(rc or 0)
