#!/usr/bin/env python
"""Score the round-5 lfr100k run: NMI vs planted truth + trajectory."""
import glob
import json
import os
import sys

import numpy as np

sys.path.insert(0, "/root/repo")
BASE = os.path.dirname(os.path.abspath(__file__))


def main():
    from fastconsensus_tpu.utils.metrics import nmi

    truth = np.load(os.path.join(BASE, "..", "lfr100k_r4", "truth.npy"))
    rows = []
    with open(os.path.join(BASE, "rounds.jsonl")) as fh:
        rows = [json.loads(ln) for ln in fh if ln.strip()]
    by_round = {}
    for r in rows:
        by_round[r["round"]] = r  # last write wins (replays/resumes)
    rounds = sorted(by_round)
    wall = sum(by_round[r].get("round_seconds", 0) for r in rounds)
    last = by_round[rounds[-1]]
    out = {
        "rounds": len(rounds),
        "sum_round_seconds": round(wall, 1),
        "final_unconverged_frac": round(
            last["n_unconverged"] / max(last["n_alive"], 1), 4),
        "n_alive_last": last["n_alive"],
    }
    mdirs = glob.glob(os.path.join(BASE, "memberships_*"))
    if mdirs:
        scores = []
        for f in sorted(glob.glob(os.path.join(mdirs[0], "*")),
                        key=lambda p: int(os.path.basename(p)))[:20]:
            pairs = np.loadtxt(f, dtype=np.int64)
            lab = np.zeros(truth.shape[0], np.int64)
            lab[pairs[:, 0] - 1] = pairs[:, 1]
            scores.append(float(nmi(lab, truth)))
        out["nmi_mean20"] = round(float(np.mean(scores)), 4)
        out["nmi_first"] = round(scores[0], 4)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
