#!/usr/bin/env python
"""emailEu consensus-lift search (VERDICT r4 #8).

The bench's emailEu stand-in (size-skewed SBM, lpm, tau=0.8) measures
consensus NMI 0.290 ~= the single-run LPA baseline 0.294 — no lift
signal.  Sweep tau (the one free consensus knob) and compare three
quantities per point: single-run LPA NMI (our lpm, one member),
consensus NMI (partition 0 of the full run), and the consensus mean.
If no tau lifts consensus above single-run + eps, commit the negative
result.  CPU backend (quality-only; TPU busy with the 100k flagship).
Output: runs/emailEu_sweep/results.jsonl
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
sys.path.insert(0, "/root/repo")

import numpy as np  # noqa: E402

BASE = os.path.dirname(os.path.abspath(__file__))


def main():
    import jax

    from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
    from fastconsensus_tpu.graph import pack_edges
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils import synth
    from fastconsensus_tpu.utils.metrics import nmi

    # the bench emailEu stand-in graph (bench.py CONFIGS["emailEu"])
    n, n_comm, p_in, p_out, alpha = 1005, 42, 0.6, 0.02, 0.85
    w = np.arange(1, n_comm + 1, dtype=float) ** -alpha
    sizes = np.maximum((w / w.sum() * n).astype(np.int64), 2)
    while sizes.sum() > n:
        sizes[np.argmax(sizes)] -= 1
    while sizes.sum() < n:
        sizes[np.argmin(sizes)] += 1
    edges, truth = synth.planted_partition(n, n_comm, p_in, p_out, seed=42,
                                           sizes=sizes)
    det = get_detector("lpm")
    slab = pack_edges(edges, n)

    # single-run reference: one lpm member, 5 seeds
    singles = []
    for s in range(5):
        lab = np.asarray(det(slab, jax.random.split(jax.random.key(s), 1))[0])
        singles.append(float(nmi(lab, truth)))
    single = float(np.mean(singles))
    print(f"single-run lpm NMI: {single:.4f} "
          f"(range {min(singles):.4f}-{max(singles):.4f})", flush=True)

    out_path = os.path.join(BASE, "results.jsonl")
    with open(out_path, "a") as fh:
        fh.write(json.dumps({"single_run_nmi": single,
                             "singles": singles}) + "\n")
        for tau in (0.3, 0.45, 0.6, 0.7, 0.8, 0.9):
            cfg = ConsensusConfig(algorithm="lpm", n_p=50, tau=tau,
                                  delta=0.02, seed=0, max_rounds=24)
            t0 = time.time()
            res = run_consensus(pack_edges(edges, n), det, cfg)
            wall = time.time() - t0
            scores = [float(nmi(res.partitions[i], truth))
                      for i in range(20)]
            rec = {"tau": tau, "nmi_first": round(scores[0], 4),
                   "nmi_mean": round(float(np.mean(scores)), 4),
                   "rounds": res.rounds, "converged": res.converged,
                   "wall_s": round(wall, 1),
                   "lift_vs_single": round(float(np.mean(scores)) - single,
                                           4)}
            print(json.dumps(rec), flush=True)
            fh.write(json.dumps(rec) + "\n")
            fh.flush()


if __name__ == "__main__":
    main()
