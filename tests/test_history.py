"""Bench-history regression tracker (obs/history.py +
scripts/bench_report.py): artifact-shape normalization, the committed
history passing the gate, and synthetic regressions failing it."""

import json
import os
import subprocess
import sys

from fastconsensus_tpu.obs import history

REPO = os.path.join(os.path.dirname(__file__), "..")
REPORT = os.path.join(REPO, "scripts", "bench_report.py")


def _driver_artifact(seq, value, nmi=0.95, telemetry=None, **over):
    parsed = {"metric": "consensus_partitions_per_sec_per_chip",
              "value": value,
              "unit": "partitions/s/chip (lfr=lfr1k, alg=louvain, "
                      "n_p=50)",
              "vs_baseline": value / 3.6, "nmi": nmi,
              "baseline_nmi": 0.9222, "seconds": 1.0, "rounds": 4,
              "converged": True, "n_chips": 1, "mesh": "1x1",
              "backend": "tpu", "dispatch_rtt_ms_post": 0.1}
    if telemetry is not None:
        parsed["telemetry"] = telemetry
    parsed.update(over)
    return {"n": seq, "cmd": "python bench.py", "rc": 0, "parsed": parsed}


def _write_series(tmp_path, values, **last_over):
    paths = []
    for i, v in enumerate(values, start=1):
        over = last_over if i == len(values) else {}
        p = tmp_path / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps(_driver_artifact(i, v, **over)))
        paths.append(str(p))
    return paths


# ----------------------------------------------------------- normalization

def test_load_records_handles_all_committed_shapes():
    # driver wrapper with "n"
    recs = history.load_records(os.path.join(REPO, "BENCH_r03.json"))
    assert len(recs) == 1 and recs[0]["seq"] == 3
    assert recs[0]["config"] == "lfr1k/louvain/np50"
    assert recs[0]["value"] == 6.897
    # raw bench JSON line, seq from the _rN filename suffix
    recs = history.load_records(
        os.path.join(REPO, "runs", "bench_lfr1k_r5.json"))
    assert len(recs) == 1 and recs[0]["seq"] == 5
    # non-bench files contribute nothing (the CPU-baseline cache)
    assert history.load_records(
        os.path.join(REPO, "BENCH_BASELINE.json")) == []
    assert history.load_records("/nonexistent/x.json") == []


def test_telemetry_columns_normalize():
    tel = {"compiles_cold": 24, "compiles_warm": 2,
           "host_syncs": {"total": 9, "round_stats": 4},
           "round_s": {"count": 4, "p50": 0.1, "p95": 0.4},
           "detect_call_s": {"count": 8, "p50": 0.2, "p95": 0.3}}
    from fastconsensus_tpu.obs.history import _normalize

    rec = _normalize(_driver_artifact(1, 50.0, telemetry=tel)["parsed"],
                     "x.json", 1)
    assert rec["compiles_warm"] == 2
    assert rec["host_syncs_total"] == 13
    assert rec["round_p95_s"] == 0.4 and rec["detect_p95_s"] == 0.3


# ------------------------------------------------------------ the gate

def test_committed_history_passes_the_gate():
    """The acceptance contract: the repo's own BENCH_*.json series —
    including the round-3 transport collapse in the MIDDLE of the
    history — must pass, because only the newest record is judged."""
    import glob

    paths = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json"))) + \
        sorted(glob.glob(os.path.join(REPO, "runs", "bench_*.json")))
    groups = history.build_history(paths)
    assert "lfr1k/louvain/np50" in groups
    assert history.check_history(groups) == []


def test_synthetic_throughput_regression_fails(tmp_path):
    paths = _write_series(tmp_path, [60.0, 65.0, 70.0, 9.0])
    problems = history.check_history(history.build_history(paths))
    assert len(problems) == 1 and "throughput" in problems[0]
    # the same collapse in the MIDDLE of the history is not a finding
    paths = _write_series(tmp_path, [60.0, 9.0, 65.0, 70.0])
    assert history.check_history(history.build_history(paths)) == []


def test_nmi_and_convergence_and_warm_compile_regressions(tmp_path):
    paths = _write_series(tmp_path, [60.0, 65.0, 70.0], nmi=0.70)
    probs = history.check_history(history.build_history(paths))
    assert any("NMI" in p for p in probs)

    paths = _write_series(tmp_path, [60.0, 65.0, 70.0], converged=False)
    probs = history.check_history(history.build_history(paths))
    assert any("no longer converges" in p for p in probs)

    paths = _write_series(tmp_path, [60.0, 65.0, 70.0],
                          telemetry={"compiles_warm": 3})
    probs = history.check_history(history.build_history(paths))
    assert any("warm-run compile" in p for p in probs)

    # no prior record carries a converged field at all: a non-converged
    # latest is NOT "a regression vs every prior run converging" —
    # all([]) must not vacuously prove convergence that never existed
    paths = []
    for i, v in enumerate([60.0, 65.0], start=1):
        art = _driver_artifact(i, v)
        del art["parsed"]["converged"]
        p = tmp_path / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps(art))
        paths.append(str(p))
    p = tmp_path / "BENCH_r03.json"
    p.write_text(json.dumps(_driver_artifact(3, 70.0, converged=False)))
    probs = history.check_history(history.build_history(paths + [str(p)]))
    assert not any("converges" in x for x in probs)


def test_unsequenced_records_trend_but_never_gate(tmp_path):
    """An ad-hoc degraded rerun (no sequence number) must not fail CI:
    it shows in the trend table but is never 'the latest'."""
    paths = _write_series(tmp_path, [60.0, 65.0, 70.0])
    adhoc = tmp_path / "bench_lfr1k_rerun.json"
    adhoc.write_text(json.dumps(_driver_artifact(1, 2.0)["parsed"]))
    groups = history.build_history(paths + [str(adhoc)])
    assert len(groups["lfr1k/louvain/np50"]) == 4
    assert history.check_history(groups) == []
    table = history.trend_table(groups)
    assert "bench_lfr1k_rerun.json" in table


# ---------------------------------------------------------------- CLI

def _run_report(*argv):
    return subprocess.run([sys.executable, REPORT, *argv],
                          capture_output=True, text=True, cwd=REPO)


def test_bench_report_cli_check_passes_on_committed_history():
    res = _run_report("--check", "--quiet")
    assert res.returncode == 0, res.stderr
    assert "no regressions" in res.stderr


def test_bench_report_cli_flags_synthetic_regression(tmp_path):
    paths = _write_series(tmp_path, [60.0, 65.0, 9.0])
    res = _run_report("--check", *paths)
    assert res.returncode == 1
    assert "REGRESSION" in res.stderr
    # trend report still prints for the operator
    assert "lfr1k/louvain/np50" in res.stdout
    # markdown mode renders tables
    res = _run_report("--markdown", *paths)
    assert res.returncode == 0 and "| seq |" in res.stdout


def test_bench_report_cli_no_records_is_an_error(tmp_path):
    empty = tmp_path / "BENCH_empty.json"
    empty.write_text("{}")
    res = _run_report(str(empty))
    assert res.returncode == 2


# ------------------------------------------------ serve_load artifacts


def _serve_load_artifact(p95=20.0, attainment=1.0, rejected=0,
                         ref_rps=2.0, mix=None):
    def point(rps, scale):
        met = int(round(30 * attainment))
        return {"rps": rps, "seconds": 8.0, "submitted": 30,
                "completed": 30 - rejected, "failed": 0, "stranded": 0,
                "rejected_429": rejected, "achieved_rps": rps * 0.97,
                "p50_ms": round(p95 * scale * 0.6, 3),
                "p95_ms": round(p95 * scale, 3),
                "p99_ms": round(p95 * scale * 1.4, 3),
                "slo": {"met": met, "missed": 30 - met,
                        "attainment": attainment},
                "phase_p95_ms": {"queue_wait": 0.5, "deque_wait": 1.0,
                                 "pack": 2.0, "device": p95 * scale * 0.8,
                                 "fanout": 0.3, "respond": 0.01},
                "compiles": 0}

    return {"metric": "serve_load_p95_ms", "config": "serve_load",
            "value": p95,
            "unit": f"p95 ms at {ref_rps:g} rps (open-loop poisson, "
                    f"bucket n64_e96, louvain n_p=4)",
            "seconds": 32.0, "converged": True, "n_chips": 1,
            "mesh": "1x1", "backend": "cpu",
            "telemetry": {"compiles_warm": 0,
                          "phase_consistency_frac": 0.0,
                          "serve_load": {"reference_rps": ref_rps,
                                         "slo_class": "interactive",
                                         "mix": mix,
                                         "queue_depth": 32,
                                         "max_batch": 4,
                                         "points": [point(ref_rps, 1.0),
                                                    point(8.0, 2.0)]}}}


def _write_serve_load(tmp_path, seq, **over):
    p = tmp_path / f"bench_serve_load_r{seq:02d}.json"
    p.write_text(json.dumps(_serve_load_artifact(**over)))
    return str(p)


def test_serve_load_normalizes_and_renders():
    recs = history.load_records(
        os.path.join(REPO, "runs", "bench_serve_load_r09.json"))
    assert len(recs) == 1 and recs[0]["seq"] == 9
    assert recs[0]["config"] == "serve_load"
    sl = recs[0]["serve_load"]
    assert len(sl["points"]) >= 4        # the committed curve shape
    assert all(p["p95_ms"] is not None for p in sl["points"])
    groups = history.build_history(
        [os.path.join(REPO, "runs", "bench_serve_load_r09.json")])
    table = history.serve_load_table(groups)
    assert "latency vs RPS" in table
    assert "deque_wait_p95" in table and "slo_attain" in table


def test_check_serve_load_gates_tail_latency(tmp_path):
    # one committed curve: no trajectory, passes
    one = [_write_serve_load(tmp_path, 9)]
    assert history.check_serve_load(history.build_history(one)) == []
    # stable next round passes; 2x+ p95 growth at the reference RPS fails
    ok = one + [_write_serve_load(tmp_path, 10, p95=24.0)]
    assert history.check_serve_load(history.build_history(ok)) == []
    bad = one + [_write_serve_load(tmp_path, 10, p95=200.0)]
    probs = history.check_serve_load(history.build_history(bad))
    assert len(probs) == 1 and "tail-latency" in probs[0]
    # attainment collapse and 429 growth are their own findings
    bad = one + [_write_serve_load(tmp_path, 10, attainment=0.5)]
    probs = history.check_serve_load(history.build_history(bad))
    assert any("attainment" in p for p in probs)
    bad = one + [_write_serve_load(tmp_path, 10, rejected=15)]
    probs = history.check_serve_load(history.build_history(bad))
    assert any("429" in p for p in probs)
    # a sweep whose GRID changed has no prior anchor: its higher-RPS
    # reference point must not be judged against the old low-RPS
    # median (ordinary queueing would read as a regression)
    moved = one + [_write_serve_load(tmp_path, 10, p95=200.0,
                                     ref_rps=8.0)]
    assert history.check_serve_load(history.build_history(moved)) == []
    # fcshape: a sweep whose SLO-class MIX changed has no prior anchor
    # either — a mixed workload queues differently by design, so its
    # p95 must not be judged against single-class (mix None) priors
    mixed = one + [_write_serve_load(
        tmp_path, 10, p95=200.0, mix="interactive:0.5,batch:0.5")]
    assert history.check_serve_load(history.build_history(mixed)) == []
    # while a same-mix regression still gates
    same_mix = [_write_serve_load(tmp_path, 9,
                                  mix="interactive:0.5,batch:0.5"),
                _write_serve_load(tmp_path, 10, p95=200.0,
                                  mix="interactive:0.5,batch:0.5")]
    probs = history.check_serve_load(history.build_history(same_mix))
    assert len(probs) == 1 and "tail-latency" in probs[0]


def test_check_history_never_inverts_on_latency_artifacts(tmp_path):
    """serve_load artifacts are lower-is-better: an IMPROVED (much
    lower) p95 must not trip the throughput-drop rule, and warm
    compiles still gate."""
    paths = [_write_serve_load(tmp_path, 9),
             _write_serve_load(tmp_path, 10, p95=2.0)]   # 10x better
    groups = history.build_history(paths)
    assert history.check_history(groups) == []
    assert history.check_serve_load(groups) == []
    art = _serve_load_artifact(p95=20.0)
    art["telemetry"]["compiles_warm"] = 3
    (tmp_path / "bench_serve_load_r11.json").write_text(json.dumps(art))
    groups = history.build_history(
        paths + [str(tmp_path / "bench_serve_load_r11.json")])
    probs = history.check_history(groups)
    assert any("warm-run compile" in p for p in probs)


def test_bench_report_cli_gates_serve_load_regression(tmp_path):
    """The CLI wires check_serve_load into --check and renders the
    latency-vs-RPS table (the CI negative probe's contract)."""
    paths = [_write_serve_load(tmp_path, 9),
             _write_serve_load(tmp_path, 10, p95=200.0)]
    res = _run_report("--check", *paths)
    assert res.returncode == 1
    assert "tail-latency" in res.stderr
    assert "latency vs RPS" in res.stdout
    res = _run_report(*[paths[0]])
    assert res.returncode == 0 and "deque_wait_p95" in res.stdout


# ------------------------------------------------- footprint artifacts

def _footprint_artifact(surface=13280, budget=16384, ceiling=4194304,
                        peak=1 << 30):
    return {"tool": "fcheck-footprint", "version": 1,
            "config": {"hbm_bytes": 24 << 30},
            "surface_count": surface, "surface_budget": budget,
            "chip_ceiling_edges": ceiling, "max_pad_frac": 0.5,
            "gate": [{"kind": "batch", "bucket": "n256_e128",
                      "batch": 8, "mode": "warm", "peak_bytes": peak,
                      "arg_bytes": 1024, "out_bytes": 512}],
            "buckets": [{"bucket": "n256_e128", "n_class": 256,
                         "e_class": 128, "capacity": 272, "batch": 8,
                         "peak_bytes": peak, "solo_peak_bytes": peak // 8,
                         "arg_bytes": 1024, "out_bytes": 512,
                         "pad_frac": 0.31}]}


def test_load_footprints_normalizes_and_orders(tmp_path):
    a = tmp_path / "footprint_r08.json"
    b = tmp_path / "footprint_r09.json"
    a.write_text(json.dumps(_footprint_artifact()))
    b.write_text(json.dumps(_footprint_artifact(surface=13290)))
    junk = tmp_path / "footprint_rX.json"
    junk.write_text("{\"tool\": \"something-else\"}")
    fps = history.load_footprints([str(b), str(junk), str(a)])
    assert [f["seq"] for f in fps] == [8, 9]
    assert fps[0]["surface_count"] == 13280
    assert fps[1]["worst_peak_bytes"] == 1 << 30
    table = history.footprint_table(fps)
    assert "fcheck-footprint trend" in table and "n256_e128" in table


def test_check_footprints_flags_surface_growth(tmp_path):
    a = tmp_path / "footprint_r08.json"
    b = tmp_path / "footprint_r09.json"
    a.write_text(json.dumps(_footprint_artifact(surface=13280)))
    b.write_text(json.dumps(_footprint_artifact(surface=14000)))
    fps = history.load_footprints([str(a), str(b)])
    problems = history.check_footprints(fps)
    assert len(problems) == 1 and "13280 -> 14000" in problems[0]
    # equal or shrinking surface passes
    b.write_text(json.dumps(_footprint_artifact(surface=13280)))
    fps = history.load_footprints([str(a), str(b)])
    assert history.check_footprints(fps) == []
    # a single committed artifact has no trajectory, but still fails
    # when it breaches its own pinned budget
    only = history.load_footprints([str(a)])
    assert history.check_footprints(only) == []
    a.write_text(json.dumps(_footprint_artifact(surface=20000)))
    assert "pinned budget" in history.check_footprints(
        history.load_footprints([str(a)]))[0]


def test_bench_report_cli_gates_footprint_growth(tmp_path):
    """The CLI wires footprint artifacts into --check when they ride in
    the explicit paths (and into the trend report)."""
    bench = _write_series(tmp_path, [60.0, 65.0, 70.0])
    (tmp_path / "footprint_r08.json").write_text(
        json.dumps(_footprint_artifact()))
    (tmp_path / "footprint_r09.json").write_text(
        json.dumps(_footprint_artifact(surface=14000)))
    paths = bench + [str(tmp_path / "footprint_r08.json"),
                     str(tmp_path / "footprint_r09.json")]
    res = _run_report("--check", *paths)
    assert res.returncode == 1
    assert "executable surface grew" in res.stderr
    assert "fcheck-footprint trend" in res.stdout
