"""fcshape traffic-shaping tests (serve/shaping.py + the EDF queue).

Covers the ISSUE-10 satellite contracts: EDF ordering pinned under 4
submitting threads (no deadline inversion within a priority), the
hold-window bound (a hold never exceeds the deadline slack; a lone
tight-deadline job dispatches immediately), a deterministic fake-clock
unit for the time-to-fill predictor, honest Retry-After derivation and
its typed parse in the jax-free client, and deadline-aware shedding.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest


def _spec(prio=1, slo_ms=None, seed=0, slo=None):
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.jobs import JobSpec

    return JobSpec(edges=np.array([[0, 1], [1, 2], [2, 3]],
                                  dtype=np.int64),
                   n_nodes=4, config=ConsensusConfig(seed=seed),
                   priority=prio, slo=slo, slo_target_ms=slo_ms)


def _job(**kw):
    from fastconsensus_tpu.serve.jobs import Job

    return Job(_spec(**kw))


def _fresh_lat():
    from fastconsensus_tpu.obs.latency import LatencyRegistry

    return LatencyRegistry()


def test_batch_ladder_mirror_matches_bucketer():
    """The shaper's jax-free ladder mirror must equal the real one —
    same contract as the footprint analyzer's grid mirror."""
    from fastconsensus_tpu.serve import bucketer, shaping

    assert shaping.BATCH_LADDER == bucketer.BATCH_LADDER


# -- EDF ordering ------------------------------------------------------


def test_edf_orders_by_deadline_within_priority():
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.queue import AdmissionQueue
    from fastconsensus_tpu.serve.shaping import find_deadline_inversions

    reg = obs_counters.get_registry()
    base = reg.counters()
    q = AdmissionQueue(8)
    loose = _job(slo_ms=60_000.0, seed=1)
    tight = _job(slo_ms=20.0, seed=2)     # admitted later, pops first
    q.submit(loose)
    q.submit(tight)
    log = [q.pop(), q.pop()]
    assert log == [tight, loose]
    assert find_deadline_inversions(log) == []
    since = reg.counters_since(base)
    assert since.get("serve.shape.edf_promotions", 0) >= 1


def test_priority_still_dominates_deadline():
    """EDF orders WITHIN a priority only: a batch-priority job with a
    tight deadline never jumps an interactive job with a loose one."""
    from fastconsensus_tpu.serve.jobs import (PRIORITY_BATCH,
                                              PRIORITY_INTERACTIVE)
    from fastconsensus_tpu.serve.queue import AdmissionQueue

    q = AdmissionQueue(8)
    batch_tight = _job(prio=PRIORITY_BATCH, slo_ms=5.0, seed=1)
    inter_loose = _job(prio=PRIORITY_INTERACTIVE, slo_ms=60_000.0,
                       seed=2)
    q.submit(batch_tight)
    q.submit(inter_loose)
    assert q.pop() is inter_loose


def test_no_edf_posture_shows_the_inversion():
    """The CI negative probe's substance: with edf=False the queue is
    FIFO and the checker must FAIL, naming deadline-inversion — a gate
    that cannot fail is no gate."""
    from fastconsensus_tpu.serve.queue import AdmissionQueue
    from fastconsensus_tpu.serve.shaping import find_deadline_inversions

    q = AdmissionQueue(8, edf=False)
    loose = _job(slo_ms=60_000.0, seed=1)
    tight = _job(slo_ms=20.0, seed=2)
    q.submit(loose)
    q.submit(tight)
    log = [q.pop(), q.pop()]
    problems = find_deadline_inversions(log)
    assert problems and "deadline-inversion" in problems[0]


def test_edf_order_under_contention():
    """The satellite pin: 4 submitting threads race jobs with random
    SLO targets and priorities into the queue; the drained pop order
    must show no deadline inversion within any priority."""
    from fastconsensus_tpu.serve.queue import AdmissionQueue
    from fastconsensus_tpu.serve.shaping import find_deadline_inversions

    q = AdmissionQueue(256)
    rng = np.random.default_rng(7)
    targets = [[float(t) for t in rng.uniform(5.0, 5_000.0, size=40)]
               for _ in range(4)]
    prios = [[int(p) for p in rng.integers(0, 3, size=40)]
             for _ in range(4)]
    barrier = threading.Barrier(4)

    def submitter(i):
        barrier.wait()
        for j, (ms, prio) in enumerate(zip(targets[i], prios[i])):
            q.submit(_job(prio=prio, slo_ms=ms, seed=i * 1000 + j))

    threads = [threading.Thread(target=submitter, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    log = []
    while True:
        job = q.pop(timeout=0.1)
        if job is None:
            break
        log.append(job)
    assert len(log) == 160
    assert find_deadline_inversions(log) == []
    # and the full order is exactly the heap contract
    keys = [(j.spec.priority, j.deadline_mono) for j in log]
    assert keys == sorted(keys)


# -- the time-to-fill predictor (deterministic fake clock) -------------


def test_expected_fill_predictor():
    from fastconsensus_tpu.serve.shaping import expected_fill_s

    assert expected_fill_s(1, 4, 10.0) == pytest.approx(0.3)
    assert expected_fill_s(3, 4, 2.0) == pytest.approx(0.5)
    assert expected_fill_s(4, 4, 2.0) == 0.0          # already full
    assert expected_fill_s(1, 2, 0.0) == float("inf")  # idle bucket


def test_predictor_over_fake_clock_rates():
    """End-to-end predictor unit on explicit stamps: a RateTracker fed
    marks at fake times yields an exact rate, and the fill prediction
    follows — no wall clock anywhere."""
    from fastconsensus_tpu.obs.latency import RateTracker
    from fastconsensus_tpu.serve.shaping import expected_fill_s

    rt = RateTracker()
    for k in range(5):
        rt.mark("b", at=100.0 + 0.1 * k)   # 10 arrivals/s burst
    rate = rt.rate("b", now=100.5)
    assert rate == pytest.approx(8.0)      # 4 intervals over 0.5 s
    assert expected_fill_s(1, 3, rate) == pytest.approx(0.25)
    # the recency-horizon contract: once the horizon empties, the rate
    # reads 0.0 — an idle bucket must never promise ride-alongs
    assert rt.rate("b", now=102.0) == 0.0
    # a stale spell followed by a fresh burst: only the burst counts
    rt.mark("b", at=110.0)
    rt.mark("b", at=110.01)
    assert rt.rate("b", now=110.02) == pytest.approx(50.0, rel=0.1)
    # fewer than two marks in the horizon -> no rate, infinite fill
    rt2 = RateTracker()
    rt2.mark("c", at=100.0)
    assert rt2.rate("c", now=100.1) == 0.0


def test_next_rung():
    from fastconsensus_tpu.serve.shaping import next_rung

    assert next_rung(1, 8) == 2
    assert next_rung(2, 8) == 4
    assert next_rung(3, 4) == 4
    assert next_rung(4, 4) is None
    assert next_rung(1, 1) is None


# -- hold decisions ----------------------------------------------------


def _shaper(lat=None, **cfg_over):
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.shaping import (ShapingConfig,
                                                 TrafficShaper)

    return TrafficShaper(ShapingConfig(**cfg_over),
                         lat=lat if lat is not None else _fresh_lat(),
                         reg=obs_counters.get_registry())


def _prime_service(lat, bucket="n64_e96", secs=0.010, n=16):
    for _ in range(n):
        for phase in ("pack", "device", "fanout"):
            lat.hist(f"serve.phase.{phase}", bucket=bucket,
                     rung=1).record(secs)


def test_hold_never_exceeds_deadline_slack():
    """The satellite bound: whatever the arrival rate promises, the
    hold window is capped by (tightest deadline - now - service
    estimate) — and a negative slack is an instant bypass."""
    lat = _fresh_lat()
    _prime_service(lat, secs=0.010)       # p95 estimate ~= 30 ms
    now = time.monotonic()
    for k in range(8):
        lat.arrivals.mark("n64_e96", at=now - 0.01 * (8 - k))
    sh = _shaper(lat=lat, max_hold_s=10.0)  # cap deliberately huge
    slack = 0.050
    d = sh.hold_decision("n64_e96", have=1, max_b=8, slack_s=slack,
                         now=now)
    assert d.hold_s <= slack              # never past the slack
    assert d.hold_s <= slack - 0.029      # service estimate subtracted
    # lone tight-deadline job: slack below the service estimate ->
    # bypass, zero added latency
    d = sh.hold_decision("n64_e96", have=1, max_b=8, slack_s=0.005,
                         now=now)
    assert d.hold_s == 0.0 and d.reason == "deadline"


def test_hold_proportional_to_fill_and_bypass_when_unfillable():
    lat = _fresh_lat()
    now = time.monotonic()
    for k in range(16):
        lat.arrivals.mark("b", at=now - 0.005 * (16 - k))  # 200/s
    sh = _shaper(lat=lat, max_hold_s=0.050, hold_margin=1.5)
    d = sh.hold_decision("b", have=1, max_b=8, slack_s=10.0, now=now)
    assert d.reason == "hold" and d.target == 2
    assert d.hold_s == pytest.approx(1.5 / 200.0, rel=0.1)
    # a bucket with no arrival history can never fill a rung: bypass
    d = sh.hold_decision("cold", have=1, max_b=8, slack_s=10.0, now=now)
    assert d.hold_s == 0.0 and d.reason == "fill_exceeds_slack"
    # a full rung never holds
    d = sh.hold_decision("b", have=8, max_b=8, slack_s=10.0, now=now)
    assert d.hold_s == 0.0 and d.reason == "rung_full"


def test_solo_tier_and_cordoned_pool_never_hold():
    """A mesh/huge-tier bucket executes solo whatever the pop size —
    holding it coalesces nothing; and a pool with NO eligible chip
    (all cordoned) must not report holding as free (all([]) trap)."""
    lat = _fresh_lat()
    now = time.monotonic()
    for k in range(16):
        lat.arrivals.mark("huge", at=now - 0.005 * (16 - k))
    sh = _shaper(lat=lat, max_hold_s=0.5)
    sh.set_solo_probe(lambda b: b == "huge")
    d = sh.hold_decision("huge", have=1, max_b=8, slack_s=100.0,
                         now=now)
    assert d.hold_s == 0.0 and d.reason == "solo_tier"
    # same traffic on a chip-tier bucket still holds
    for k in range(16):
        lat.arrivals.mark("chip", at=now - 0.005 * (16 - k))
    d = sh.hold_decision("chip", have=1, max_b=8, slack_s=100.0,
                         now=now)
    assert d.reason == "hold"
    # an empty eligible-chip set is NOT "everyone is busy"
    from fastconsensus_tpu.serve.pool import WorkerPool

    class _Cordoned:
        def eligible(self, exclude=frozenset()):
            return False

    pool = WorkerPool.__new__(WorkerPool)
    pool.chip_workers = [_Cordoned()]
    assert pool.chips_all_busy() is False


def test_fill_prediction_prefers_group_rate():
    """Only same-group arrivals can join a rung: with mixed-config
    traffic on one bucket, the bucket rate predicts fills that can
    never happen — the group tracker must win when it has history."""
    lat = _fresh_lat()
    now = time.monotonic()
    for k in range(32):                   # hot bucket: 200 jobs/s...
        lat.arrivals.mark("b", at=now - 0.005 * (32 - k))
    for k in range(8):                    # ...but THIS group: 4/s
        lat.group_arrivals.mark("g1", at=now - 0.25 * (8 - k))
    sh = _shaper(lat=lat, max_hold_s=0.050)
    d = sh.hold_decision("b", have=1, max_b=8, slack_s=100.0, now=now,
                         group="g1")
    # group fill = 1/4 s >> 50 ms cap: bypass, despite the hot bucket
    assert d.hold_s == 0.0 and d.reason == "fill_exceeds_slack"
    # a group with no history falls back to the bucket rate and holds
    d = sh.hold_decision("b", have=1, max_b=8, slack_s=100.0, now=now,
                         group="g-unseen")
    assert d.reason == "hold"


def test_group_switch_mid_hold_does_not_pollute_hold_stamp():
    """A tighter-deadline job of another group that takes the head
    mid-hold pops immediately — and must NOT inherit the aborted
    episode's start stamp (its group never held)."""
    from fastconsensus_tpu.serve.queue import AdmissionQueue

    lat = _fresh_lat()
    now = time.monotonic()
    a = _job(slo_ms=60_000.0, seed=1)
    bucket_key = a.spec.bucket().key()
    group_a = a.spec.batch_group()
    for k in range(16):
        lat.group_arrivals.mark(group_a, at=now - 0.02 * (16 - k))
        lat.arrivals.mark(bucket_key, at=now - 0.02 * (16 - k))
    q = AdmissionQueue(8)
    q.set_shaper(_shaper(lat=lat, max_hold_s=0.5, hold_margin=10.0))
    q.submit(a)                           # head: starts a long hold
    got = {}

    def consume():
        got["b1"] = q.pop_batch(8, lambda j: j.spec.batch_group())
        got["b2"] = q.pop_batch(8, lambda j: j.spec.batch_group())

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.05)                      # a is mid-hold
    # different group (different n_p via config), tighter deadline:
    # takes the head, its decision has no group history -> bypasses
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.jobs import Job, JobSpec

    b = Job(JobSpec(edges=np.array([[0, 1], [1, 2], [2, 3]],
                                   dtype=np.int64),
                    n_nodes=4, config=ConsensusConfig(n_p=7, seed=99),
                    slo_target_ms=10.0))
    q.submit(b)
    t.join(5.0)
    first = got["b1"]
    assert first == [b]                   # EDF: b preempted the head
    b.mark("done", result={})
    assert b.timing()["phases_ms"]["hold"] <= 0.011
    assert got["b2"][0] is a


def test_lone_tight_deadline_job_dispatches_immediately():
    """Integration form of the bound: a shaper-armed queue holding a
    single job whose deadline slack is gone pops it with no wait."""
    from fastconsensus_tpu.serve.queue import AdmissionQueue

    lat = _fresh_lat()
    now = time.monotonic()
    bucket_key = _spec().bucket().key()
    for k in range(16):
        lat.arrivals.mark(bucket_key, at=now - 0.005 * (16 - k))
    q = AdmissionQueue(8)
    q.set_shaper(_shaper(lat=lat, max_hold_s=0.5))
    q.submit(_job(slo_ms=1.0, seed=1))    # deadline already ~expired
    t0 = time.monotonic()
    batch = q.pop_batch(8, lambda j: j.spec.batch_group())
    took = time.monotonic() - t0
    assert len(batch) == 1
    assert took < 0.1                     # nowhere near max_hold_s


def test_pop_batch_holds_to_coalesce_and_stamps_hold_phase():
    """A shaper-armed pop_batch waits for predicted ride-alongs, the
    coalesced batch comes out bigger, and every member's fclat
    timeline carries the hold as its own phase (sum still == e2e)."""
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.queue import AdmissionQueue

    reg = obs_counters.get_registry()
    base = reg.counters()
    lat = _fresh_lat()
    now = time.monotonic()
    bucket_key = _spec().bucket().key()
    for k in range(32):
        lat.arrivals.mark(bucket_key, at=now - 0.01 * (32 - k))  # 100/s
    q = AdmissionQueue(16)
    q.set_shaper(_shaper(lat=lat, max_hold_s=0.3, hold_margin=3.0))
    gk = lambda j: j.spec.batch_group()  # noqa: E731
    q.submit(_job(seed=1))
    got = {}

    def consume():
        got["batch"] = q.pop_batch(4, gk)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.02)                      # inside the hold window
    for s in (2, 3, 4):
        q.submit(_job(seed=s))
    t.join(5.0)
    batch = got["batch"]
    assert len(batch) == 4
    since = reg.counters_since(base)
    assert since.get("serve.shape.holds", 0) >= 1
    head = batch[0]
    head.mark("done", result={})
    timing = head.timing()
    assert timing["phases_ms"]["hold"] > 0.0
    assert timing["phase_sum_ms"] == pytest.approx(timing["e2e_ms"],
                                                   abs=0.01)
    # a ride-along admitted mid-hold attributes only ITS share
    late = batch[-1]
    late.mark("done", result={})
    lt = late.timing()
    assert lt["phases_ms"]["hold"] <= timing["phases_ms"]["hold"] + 0.01
    assert lt["phase_sum_ms"] == pytest.approx(lt["e2e_ms"], abs=0.01)


def test_unheld_pop_has_zero_hold_phase():
    from fastconsensus_tpu.serve.queue import AdmissionQueue

    q = AdmissionQueue(8)                 # no shaper installed
    q.submit(_job(seed=1))
    job = q.pop()
    job.mark("done", result={})
    t = job.timing()
    assert t["phases_ms"]["hold"] == 0.0
    assert "queue_wait" in t["phases_ms"]


def test_closed_queue_never_holds():
    """Drain beats occupancy: close() during (or before) a hold pops
    whatever is queued immediately."""
    from fastconsensus_tpu.serve.queue import AdmissionQueue

    lat = _fresh_lat()
    now = time.monotonic()
    bucket_key = _spec().bucket().key()
    for k in range(32):
        lat.arrivals.mark(bucket_key, at=now - 0.01 * (32 - k))
    q = AdmissionQueue(8)
    q.set_shaper(_shaper(lat=lat, max_hold_s=5.0, hold_margin=50.0))
    q.submit(_job(seed=1))
    q.close()
    t0 = time.monotonic()
    batch = q.pop_batch(8, lambda j: j.spec.batch_group())
    assert len(batch) == 1
    assert time.monotonic() - t0 < 0.5
    assert q.pop_batch(8, lambda j: j.spec.batch_group()) is None


# -- service-time estimator + honest Retry-After -----------------------


def test_service_estimate_sums_phases_and_skips_cache_hits():
    lat = _fresh_lat()
    lat.hist("serve.phase.pack", bucket="b", rung=1).record(0.002)
    lat.hist("serve.phase.device", bucket="b", rung=1).record(0.010)
    lat.hist("serve.phase.fanout", bucket="b", rung=1).record(0.001)
    # cache-hit (rung 0) and queueing phases must not pollute it
    lat.hist("serve.phase.device", bucket="b", rung=0).record(9.0)
    lat.hist("serve.phase.queue_wait", bucket="b", rung=1).record(9.0)
    est = lat.service_estimate("b")
    assert est["count"] == 1
    assert est["mean_s"] == pytest.approx(0.013, rel=0.01)
    assert est["p95_s"] >= est["mean_s"]
    assert lat.service_estimate("unseen-bucket") is None


def test_service_estimate_excludes_cold_compiles_and_shed_no_fallback():
    """A first-in-bucket job's device phase is mostly XLA compile; one
    50 s compile in the mean would make should_shed refuse jobs a warm
    bucket serves in milliseconds (the tier-1 false-shed regression).
    Cold-tagged samples stay out of the estimate, and shedding never
    borrows another bucket's service time."""
    lat = _fresh_lat()
    lat.hist("serve.phase.device", bucket="b", rung=1,
             cold=1).record(50.0)          # the compile-inflated job
    for _ in range(16):
        lat.hist("serve.phase.device", bucket="b", rung=1).record(0.010)
    est = lat.service_estimate("b")
    assert est["count"] == 16
    assert est["mean_s"] == pytest.approx(0.010, rel=0.01)
    # shed: per-bucket history only — a bucket with no history never
    # sheds, even when other buckets have plenty
    sh = _shaper(lat=lat, min_estimate_count=8)
    now = time.monotonic()
    assert sh.should_shed("unseen", now + 0.001, depth=50,
                          now=now) is None
    # while hold/retry math may still borrow the all-bucket view
    assert sh.service_estimate("unseen") is not None
    assert sh.service_estimate("unseen", fallback=False) is None


def test_retry_after_derivation_and_defaults():
    lat = _fresh_lat()
    sh = _shaper(lat=lat, min_estimate_count=8)
    # no estimate yet: the honest default
    assert sh.retry_after_s(10) == 1.0
    _prime_service(lat, bucket="b", secs=0.010, n=16)
    sh2 = _shaper(lat=lat, min_estimate_count=8)
    # depth x mean service (30 ms/job) over 1 worker
    assert sh2.retry_after_s(10, "b") == pytest.approx(0.30, rel=0.05)
    sh2.set_parallelism(lambda: 4)
    sh3 = _shaper(lat=lat, min_estimate_count=8)
    sh3.set_parallelism(lambda: 4)
    assert sh3.retry_after_s(10, "b") == pytest.approx(0.075, rel=0.05)


def test_client_retry_after_parse_defaults():
    """Typed Backpressure.retry_after_s: body float wins, header next,
    absent/malformed falls back to the documented default."""
    from fastconsensus_tpu.serve.client import (DEFAULT_RETRY_AFTER_S,
                                                _retry_after_s)

    assert _retry_after_s("3", {}) == 3.0
    assert _retry_after_s("2", {"retry_after_s": 1.7}) == 1.7
    assert _retry_after_s(None, {}) == DEFAULT_RETRY_AFTER_S
    assert _retry_after_s("soon", {}) == DEFAULT_RETRY_AFTER_S
    assert _retry_after_s("-5", {"retry_after_s": "junk"}) \
        == DEFAULT_RETRY_AFTER_S


# -- deadline shedding -------------------------------------------------


def test_should_shed_only_when_provably_late():
    from fastconsensus_tpu.obs import counters as obs_counters

    reg = obs_counters.get_registry()
    base = reg.counters()
    lat = _fresh_lat()
    _prime_service(lat, bucket="b", secs=0.050, n=16)  # 150 ms/job
    sh = _shaper(lat=lat, min_estimate_count=8)
    now = time.monotonic()
    # 20 queued x 150 ms = 3 s of work; a 500 ms deadline is hopeless
    reason = sh.should_shed("b", now + 0.5, depth=20, now=now)
    assert reason is not None and "deadline shed" in reason
    since = reg.counters_since(base)
    assert since.get("serve.shape.deadline_sheds", 0) == 1
    # the same depth with a 60 s deadline sails through
    assert sh.should_shed("b", now + 60.0, depth=20, now=now) is None
    # an empty queue never sheds
    assert sh.should_shed("b", now + 0.5, depth=0, now=now) is None
    # cold estimator never sheds
    cold = _shaper(lat=_fresh_lat(), min_estimate_count=8)
    assert cold.should_shed("b", now + 0.001, depth=50, now=now) is None


def test_service_submit_sheds_and_answers_retry_after(monkeypatch):
    """End-to-end shed at the service layer: with a primed estimator
    and a deep queue, a tight-deadline submit raises DeadlineShed
    (-> HTTP 429) carrying a derived retry_after_s; QueueFull carries
    one too."""
    from fastconsensus_tpu.serve.queue import DeadlineShed, QueueFull
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    svc = ConsensusService(ServeConfig(queue_depth=8))
    svc._lat.reset()   # the process-global fclat registry: earlier
    bucket_key = _spec().bucket().key()   # tests primed this bucket
    _prime_service(svc._lat, bucket=bucket_key, secs=0.200, n=16)
    # no pool started: submits queue up and nothing drains
    for s in range(6):
        svc.submit(_spec(seed=s, slo_ms=600_000.0))
    with pytest.raises(DeadlineShed) as ei:
        svc.submit(_spec(seed=100, slo_ms=10.0))
    assert ei.value.retry_after_s is not None
    assert ei.value.retry_after_s > 0.0
    # fill the queue with loose-deadline work -> plain QueueFull, also
    # carrying the derived retry
    for s in range(200, 210):
        try:
            svc.submit(_spec(seed=s, slo_ms=600_000.0))
        except QueueFull as e:
            assert not isinstance(e, DeadlineShed)
            assert e.retry_after_s is not None and e.retry_after_s > 0
            break
    else:
        pytest.fail("queue never filled")


# -- /metricsz shaping block (typed, jax-free client) ------------------


def test_shaping_block_schema_and_typed_parse():
    from fastconsensus_tpu.serve.client import ShapingStats
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    svc = ConsensusService(ServeConfig(queue_depth=8))
    svc._lat.reset()   # isolate from earlier tests' global priming
    bucket_key = _spec().bucket().key()
    _prime_service(svc._lat, bucket=bucket_key, secs=0.010, n=16)
    svc.submit(_spec(seed=1))             # marks the arrival tracker
    block = svc.shaping_stats()
    assert set(block) == {"config", "counters", "estimates",
                          "retry_after_hint_s"}
    assert set(block["counters"]) == {"holds", "bypass",
                                      "edf_promotions",
                                      "deadline_sheds", "prior_seeded"}
    assert bucket_key in block["estimates"]
    typed = ShapingStats.from_payload(block)
    assert typed.edf and typed.hold and typed.shed
    assert typed.max_hold_s == svc.config.shaping.max_hold_s
    assert typed.estimates[bucket_key]["count"] == 16
    assert typed.retry_after_hint_s is not None
