"""ops layer vs numpy brute-force oracles on small random graphs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fastconsensus_tpu.graph import GraphSlab, host_edges, pack_edges
from fastconsensus_tpu.ops import consensus_ops as cops
from fastconsensus_tpu.ops import segment as seg


def random_graph(rng, n, p=0.2):
    mask = np.triu(rng.random((n, n)) < p, k=1)
    u, v = np.nonzero(mask)
    return np.stack([u, v], axis=1)


def test_node_label_runs_matches_bruteforce():
    rng = np.random.default_rng(0)
    n = 12
    e = 40
    node = rng.integers(0, n, e)
    label = rng.integers(0, 5, e)
    value = rng.random(e).astype(np.float32)
    valid = rng.random(e) < 0.8

    runs = seg.node_label_runs(jnp.asarray(node), jnp.asarray(label),
                               jnp.asarray(value), jnp.asarray(valid), n)
    got = {}
    for i in range(e):
        if bool(runs.valid[i]):
            got[(int(runs.node[i]), int(runs.label[i]))] = float(runs.total[i])
    want = {}
    for i in range(e):
        if valid[i]:
            k = (int(node[i]), int(label[i]))
            want[k] = want.get(k, 0.0) + float(value[i])
    assert set(got) == set(want)
    for k in want:
        assert abs(got[k] - want[k]) < 1e-4


def test_argmax_label_per_node():
    n = 4
    node = jnp.array([0, 0, 1, 2, 2, 2])
    label = jnp.array([7, 3, 5, 1, 2, 3])
    score = jnp.array([1.0, 2.0, 4.0, 9.0, 9.0, 1.0])
    valid = jnp.array([True, True, True, True, True, False])
    best_label, best_score, has_any = seg.argmax_label_per_node(
        node, score, label, valid, n)
    assert best_label.tolist() == [3, 5, 2, -1]  # node 2 tie -> larger label
    assert has_any.tolist() == [True, True, True, False]
    assert best_score[0] == 2.0


def test_compact_labels():
    labels = jnp.array([5, 5, 9, 2, 9])
    out = seg.compact_labels(labels, 10)
    assert out.tolist() == [1, 1, 2, 0, 2]


def test_comembership_counts():
    labels = jnp.array([[0, 0, 1, 1],
                        [0, 1, 1, 1],
                        [2, 2, 2, 2]])
    src = jnp.array([0, 1, 2])
    dst = jnp.array([1, 2, 3])
    counts = cops.comembership_counts(labels, src, dst)
    assert counts.tolist() == [2.0, 2.0, 3.0]


def test_update_and_threshold_weights():
    slab = pack_edges(np.array([[0, 1], [1, 2], [2, 3]]), 4)
    counts = jnp.array([5.0, 2.0, 0.0] + [0.0] * (slab.capacity - 3))
    slab2 = cops.update_weights(slab, counts, n_p=5)
    w = np.asarray(slab2.weight)[:3]
    assert w.tolist() == [5.0, 2.0, 0.0]
    slab3 = cops.threshold_weights(slab2, tau=0.5, n_p=5)
    alive = np.asarray(slab3.alive)[:3]
    assert alive.tolist() == [True, False, False]
    # frozen edge keeps weight n_p through the next update
    counts2 = jnp.array([1.0] * slab.capacity)
    slab4 = cops.update_weights(slab3, counts2, n_p=5)
    assert float(slab4.weight[0]) == 5.0


def test_convergence_stats():
    slab = pack_edges(np.array([[0, 1], [1, 2], [2, 3], [0, 3]]), 4)
    w = np.zeros(slab.capacity, np.float32)
    w[:4] = [5.0, 5.0, 3.0, 0.0]  # one mid edge of 4 alive
    slab = slab.with_weights(jnp.asarray(w))
    st = cops.convergence_stats(slab, n_p=5, delta=0.02)
    assert int(st.n_unconverged) == 1 and int(st.n_alive) == 4
    assert not bool(st.converged)
    st2 = cops.convergence_stats(slab, n_p=5, delta=0.25)
    assert bool(st2.converged)


def test_csr_and_wedges():
    edges = np.array([[0, 1], [0, 2], [0, 3], [1, 2]])
    slab = pack_edges(edges, 4)
    csr = cops.build_csr(slab)
    off = np.asarray(csr.offsets)
    nbrs = np.asarray(csr.neighbors)
    assert sorted(nbrs[off[0]:off[1]].tolist()) == [1, 2, 3]
    assert sorted(nbrs[off[3]:off[4]].tolist()) == [0]

    u, v, valid = cops.sample_wedges(jax.random.key(0), csr, 4, 64)
    u, v, valid = np.asarray(u), np.asarray(v), np.asarray(valid)
    adj = {i: set() for i in range(4)}
    for a, b in edges:
        adj[a].add(b)
        adj[b].add(a)
    assert valid.any()
    for i in range(64):
        if valid[i]:
            assert u[i] < v[i]
            # endpoints must share at least one common neighbor (the anchor)
            assert adj[u[i]] & adj[v[i]]


def test_insert_edges_dedup_and_capacity():
    slab = pack_edges(np.array([[0, 1], [1, 2]]), 5, capacity=4)
    cand_u = jnp.array([0, 0, 0, 3, 0])
    cand_v = jnp.array([1, 2, 2, 4, 3])
    cand_w = jnp.array([9.0, 8.0, 7.0, 6.0, 5.0])
    valid = jnp.array([True, True, True, True, True])
    out, dropped = cops.insert_edges(slab, cand_u, cand_v, cand_w, valid)
    u, v, w = host_edges(out)
    got = sorted(zip(u.tolist(), v.tolist(), w.tolist()))
    # (0,1) dup of existing; (0,2) first wins w=8; (3,4) and (0,3) fill the
    # two free slots (capacity 4) -> one of the three survivors dropped? No:
    # survivors are (0,2),(3,4),(0,3) = 3, free slots = 2 -> 1 dropped.
    assert int(dropped) == 1
    assert (0, 1, 1.0) in got and (1, 2, 1.0) in got
    assert len(got) == 4
    assert (0, 2, 8.0) in got


def test_insert_edges_hash_matches_lexsort_oracle():
    """The sort-free insert (consensus_tail's production path) must agree
    with the exact lexsort oracle whenever no hash collision occurs — and
    at these table load factors (<= 0.25 squared) collisions are absent on
    this deterministic input."""
    rng = np.random.default_rng(3)
    edges, _ = __import__(
        "fastconsensus_tpu.utils.synth", fromlist=["synth"]
    ).planted_partition(60, 4, 0.3, 0.05, seed=3)
    slab = pack_edges(edges, 60)
    # kill a third of the edges so there are free slots and live dedup
    alive = np.asarray(slab.alive).copy()
    kill = rng.random(alive.shape) < 0.33
    slab = slab.with_weights(slab.weight, alive=jnp.asarray(alive & ~kill))
    k = 80
    cu = rng.integers(0, 60, k)
    cv = rng.integers(0, 60, k)
    u = np.minimum(cu, cv).astype(np.int64)
    v = np.maximum(cu, cv).astype(np.int64)
    valid = u != v
    w = rng.random(k).astype(np.float32)
    # seed duplicates of existing edges and of other candidates
    u[:5], v[:5] = np.asarray(slab.src)[:5], np.asarray(slab.dst)[:5]
    u[5:8], v[5:8] = u[10:13], v[10:13]

    a, da = cops.insert_edges(slab, jnp.asarray(u), jnp.asarray(v),
                              jnp.asarray(w), jnp.asarray(valid))
    b, db = cops.insert_edges_hash(slab, jnp.asarray(u), jnp.asarray(v),
                                   jnp.asarray(w), jnp.asarray(valid))
    ea = sorted(zip(*[x.tolist() for x in host_edges(a)]))
    eb = sorted(zip(*[x.tolist() for x in host_edges(b)]))
    assert ea == eb
    assert int(da) == int(db)
    # exactness invariant regardless of collisions: no duplicate pairs
    eu, ev, _ = host_edges(b)
    pairs = list(zip(eu.tolist(), ev.tolist()))
    assert len(pairs) == len(set(pairs))


def test_sample_wedges_scatter_produces_real_wedges():
    edges = np.array([[0, 1], [0, 2], [0, 3], [1, 2], [3, 4]])
    slab = pack_edges(edges, 5)
    u, v, valid = cops.sample_wedges_scatter(jax.random.key(1), slab, 64)
    u, v, valid = np.asarray(u), np.asarray(v), np.asarray(valid)
    adj = {i: set() for i in range(5)}
    for a, b in edges:
        adj[a].add(b)
        adj[b].add(a)
    assert valid.any()
    for i in range(64):
        if valid[i]:
            assert u[i] < v[i]
            # endpoints share at least one common neighbor (the anchor)
            assert adj[u[i]] & adj[v[i]], (u[i], v[i])


def test_singleton_repair():
    # prev graph: 0-1 (w 2), 0-2 (w 7); current: only 1-2 alive, 0 isolated
    prev = pack_edges(np.array([[0, 1], [0, 2], [1, 2]]), 3,
                      weights=np.array([2.0, 7.0, 1.0]))
    cur_alive = np.asarray(prev.alive).copy()
    cur_alive[0] = False  # kill 0-1
    cur_alive[1] = False  # kill 0-2
    cur = GraphSlab(src=prev.src, dst=prev.dst,
                    weight=prev.weight, alive=jnp.asarray(cur_alive),
                    n_nodes=3)
    u, v, w, valid = cops.singleton_candidates(cur, prev)
    valid = np.asarray(valid)
    assert valid[0] and not valid[1] and not valid[2]
    # node 0 reattaches to its *strongest* previous neighbor: 2 (w=7)
    assert (int(u[0]), int(v[0]), float(w[0])) == (0, 2, 7.0)
    out, dropped = cops.insert_edges(cur, u, v, w, jnp.asarray(valid))
    eu, ev, ew = host_edges(out)
    assert sorted(zip(eu.tolist(), ev.tolist())) == [(0, 2), (1, 2)]
    assert int(dropped) == 0
