"""fcheck-concurrency runtime half: multi-threaded stress of the
serving primitives under the lock-order recorder (FCTPU_LOCK_ORDER /
analysis/lockorder.py), asserting no deadlock and an observed
acquisition digraph that is acyclic AND consistent with the static
graph (analysis/concurrency.py) — their union must be acyclic, which
is the contract that keeps the static model honest about edges it
cannot see (the queue's stored ``_extra_depth`` callable reaching the
worker deques, most prominently).

Two tiers: a jax-free queue/cache/scheduler stress that runs in tier-1,
and a slow-marked full-pool stress (4 device workers, real consensus
jobs) with a watchdog timeout.
"""

import os
import threading
import time

import numpy as np
import pytest


def _package_sources():
    pkg = os.path.join(os.path.dirname(__file__), "..",
                       "fastconsensus_tpu")
    sources = {}
    for root, dirs, names in os.walk(pkg):
        dirs[:] = [d for d in dirs if d not in ("__pycache__", "build",
                                                "src")]
        for f in sorted(names):
            if f.endswith(".py"):
                path = os.path.join(root, f)
                with open(path, encoding="utf-8") as fh:
                    sources[path] = fh.read()
    return sources


def _assert_consistent_with_static(rec):
    """Observed edges, mapped onto static lock keys, unioned with the
    static graph, must stay acyclic."""
    from fastconsensus_tpu.analysis.concurrency import (lock_sites,
                                                        static_lock_graph)

    sources = _package_sources()
    sites = lock_sites(sources)
    static = static_lock_graph(sources)
    rec.assert_acyclic(extra_edges=static, sites=sites)
    return rec.named_edges(sites), static


def _ring(n, chords=0):
    idx = np.arange(n)
    edges = [np.stack([idx, (idx + 1) % n], 1)]
    if chords:
        c = np.arange(chords)
        edges.append(np.stack([c % n, (c + 7) % n], 1))
    return np.concatenate(edges).astype(np.int64)


def _spec(edges, n_nodes, **over):
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.jobs import JobSpec

    kwargs = dict(algorithm="louvain", n_p=4, tau=0.2, delta=0.02,
                  max_rounds=2, seed=0)
    kwargs.update(over)
    return JobSpec(edges=np.asarray(edges, dtype=np.int64),
                   n_nodes=n_nodes, config=ConsensusConfig(**kwargs))


def test_lock_order_stress_queue_cache_scheduler(monkeypatch):
    """Tier-1 stress: submitter threads hammer AdmissionQueue while
    consumers pop_batch, probe the ResultCache and route through the
    StickyScheduler — the contended no-device core of the serving
    stack.  Watchdog: every thread must finish; recorder: the observed
    lock graph must be acyclic and compose with the static graph."""
    from fastconsensus_tpu.analysis import lockorder

    with lockorder.recording() as rec:
        from fastconsensus_tpu.obs import counters as obs_counters
        from fastconsensus_tpu.serve.cache import ResultCache
        from fastconsensus_tpu.serve.jobs import Job
        from fastconsensus_tpu.serve.queue import AdmissionQueue
        from fastconsensus_tpu.serve.scheduler import StickyScheduler

        # the process-global registry predates the recording block (its
        # lock is unwrapped); a fresh one constructed HERE records the
        # queue/cache/scheduler -> registry edges at their real
        # declaration site (counters.py), matching the static keys
        monkeypatch.setattr(obs_counters, "_REGISTRY",
                            obs_counters.ObsRegistry())

        queue = AdmissionQueue(max_depth=256)
        cache = ResultCache(max_entries=64)
        sched = StickyScheduler(spill_backlog=2)

        class _Stub:
            def __init__(self, idx):
                self.idx = idx
                self._lock = threading.Lock()
                self._warm = set()
                self._n = 0

            def eligible(self, exclude=frozenset()):
                return self.idx not in exclude

            def load(self):
                with self._lock:
                    return self._n

            def is_warm(self, bucket):
                with self._lock:
                    return bucket in self._warm

            def note(self, bucket):
                with self._lock:
                    self._warm.add(bucket)
                    self._n += 1

        workers = [_Stub(i) for i in range(4)]
        edges = _ring(24, 12)
        n_sub, per_thread = 6, 40
        errors = []

        def submitter(tid):
            try:
                for i in range(per_thread):
                    job = Job(_spec(edges, 24, seed=tid * 1000 + i))
                    queue.submit(job)
                    cache.get(job.key)           # miss probe
                    if i % 5 == 0:
                        cache.put(f"k{tid}:{i}", {"partitions": []})
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        def consumer():
            try:
                while True:
                    batch = queue.pop_batch(
                        4, group_key=lambda j: j.spec.batch_group())
                    if batch is None:
                        return
                    for job in batch:
                        w = sched.route(job.spec.bucket().key(),
                                        workers)
                        w.note(job.spec.bucket().key())
                        cache.get(job.key, count_miss=False)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        subs = [threading.Thread(target=submitter, args=(t,))
                for t in range(n_sub)]
        cons = [threading.Thread(target=consumer) for _ in range(2)]
        for t in cons + subs:
            t.start()
        deadline = time.monotonic() + 60.0      # the watchdog
        for t in subs:
            t.join(max(0.1, deadline - time.monotonic()))
        queue.close()
        for t in cons:
            t.join(max(0.1, deadline - time.monotonic()))
        stuck = [t.name for t in subs + cons if t.is_alive()]
        assert not stuck, f"deadlock watchdog: threads stuck: {stuck}"
        assert not errors, errors
        total = sum(w._n for w in workers)
        assert total == n_sub * per_thread, total

        rec.assert_acyclic()                    # observed graph alone
        observed, static = _assert_consistent_with_static(rec)
        # the stress genuinely exercised nested acquisition
        assert observed, "recorder saw no nested acquisitions"


@pytest.mark.slow
def test_pool_stress_lock_order_full_service(monkeypatch):
    """Full-pool stress under the recorder: N submitter threads against
    a 4-worker ConsensusService (real device calls on the 8-device
    virtual CPU mesh), watchdog-bounded drain, then the acyclicity +
    static-consistency assertion over everything observed — including
    the queue->worker-deque edge only the runtime can see."""
    from fastconsensus_tpu.analysis import lockorder

    with lockorder.recording() as rec:
        from fastconsensus_tpu.obs import counters as obs_counters
        from fastconsensus_tpu.serve.server import (ConsensusService,
                                                    ServeConfig)

        # fresh registry inside the recording block (see the tier-1
        # stress): pre-imported singleton locks are unwrapped
        monkeypatch.setattr(obs_counters, "_REGISTRY",
                            obs_counters.ObsRegistry())

        service = ConsensusService(ServeConfig(
            queue_depth=64, devices=4, max_batch=4,
            cache_entries=64)).start()
        edges_a, edges_b = _ring(40, 40), _ring(100, 60)
        errors, jobs = [], []
        jobs_lock = threading.Lock()

        def submitter(tid):
            try:
                for i in range(3):
                    edges = edges_a if tid % 2 else edges_b
                    n = 40 if tid % 2 else 100
                    job = service.submit(
                        _spec(edges, n, seed=tid * 100 + i))
                    with jobs_lock:
                        jobs.append(job)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        subs = [threading.Thread(target=submitter, args=(t,))
                for t in range(6)]
        for t in subs:
            t.start()
        for t in subs:
            t.join(60.0)
        assert not any(t.is_alive() for t in subs), "submitters stuck"
        assert not errors, errors
        assert service.drain(timeout=300.0), \
            "pool drain watchdog expired (deadlock?)"
        done = [j for j in jobs if j.state == "done"]
        assert len(done) == len(jobs), \
            [(j.job_id, j.state, j.error) for j in jobs if
             j.state != "done"]

        rec.assert_acyclic()
        observed, static = _assert_consistent_with_static(rec)
        assert observed, "recorder saw no nested acquisitions"
