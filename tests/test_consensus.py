"""Consensus engine: unit properties and karate end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fastconsensus_tpu.consensus import (ConsensusConfig, consensus_round,
                                         run_consensus)
from fastconsensus_tpu.graph import pack_edges
from fastconsensus_tpu.models.lpm import lpm
from fastconsensus_tpu.utils.metrics import nmi


def constant_detector(labels_row):
    """Detector returning the same fixed partition for every key."""
    row = jnp.asarray(labels_row, dtype=jnp.int32)

    def detect(slab, keys):
        return jnp.broadcast_to(row, (keys.shape[0], row.shape[0]))

    return detect


def test_identical_partitions_converge_one_round(karate_slab):
    # n_p identical partitions: every intra-community edge gets weight n_p,
    # inter-community edges get 0 -> thresholded away -> converged round 1.
    labels = np.zeros(34, np.int32)
    labels[16:] = 1
    det = constant_detector(labels)
    cfg = ConsensusConfig(n_p=10, tau=0.2, delta=0.02, max_rounds=5)
    res = run_consensus(karate_slab, det, cfg)
    assert res.converged and res.rounds == 1
    # final partitions are the constant partition itself
    assert nmi(res.partitions[0], labels) == 1.0


def test_tau_zero_keeps_all_edges(karate_slab):
    labels = np.arange(34, dtype=np.int32)  # all singleton communities
    det = constant_detector(labels)
    key = jax.random.key(0)
    slab = karate_slab.with_weights(
        jnp.where(karate_slab.alive, 1.0, 0.0))
    out, _, stats = consensus_round(slab, key, det, n_p=4, tau=0.0,
                                    delta=0.02, n_closure=78)
    # all weights 0 (nobody co-clustered), but tau=0 deletes nothing
    assert int(stats.n_alive) >= 78
    # all-zero weights means zero mid-weight edges -> converged
    assert bool(stats.converged)


def test_delta_one_converges_immediately(karate_slab):
    cfg = ConsensusConfig(n_p=4, tau=0.2, delta=1.0, max_rounds=5)
    res = run_consensus(karate_slab, lpm, cfg)
    assert res.converged and res.rounds == 1


def test_karate_lpm_end_to_end(karate_slab, karate_truth):
    cfg = ConsensusConfig(algorithm="lpm", n_p=20, tau=0.5, delta=0.02,
                          max_rounds=30, seed=3)
    res = run_consensus(karate_slab, lpm, cfg)
    assert res.converged, f"no convergence in {res.rounds} rounds"
    assert len(res.partitions) == 20
    # consensus partitions should agree strongly with each other ...
    pairwise = nmi(res.partitions[0], res.partitions[1])
    assert pairwise > 0.8
    # ... and match the known two-faction structure reasonably
    quality = np.mean([nmi(p, karate_truth) for p in res.partitions])
    assert quality > 0.25, f"mean NMI vs factions {quality}"
    # observability: every round reported stats
    assert len(res.history) == res.rounds
    assert all("n_alive" in h for h in res.history)


def test_consensus_graph_stays_within_capacity():
    rng = np.random.default_rng(0)
    n = 60
    mask = np.triu(rng.random((n, n)) < 0.12, k=1)
    u, v = np.nonzero(mask)
    slab = pack_edges(np.stack([u, v], 1), n)
    cfg = ConsensusConfig(n_p=8, tau=0.4, delta=0.05, max_rounds=10)
    res = run_consensus(slab, lpm, cfg)
    assert res.graph.capacity >= slab.capacity
    for h in res.history:
        assert h["n_alive"] <= h["capacity"]
        assert h["n_dropped"] == 0  # self-sizing never sheds survivors


def test_auto_grow_matches_generous_capacity():
    """A slab packed tight enough to saturate must grow+replay to the same
    final result (partitions AND history) as one packed with room to spare
    (graph.grow_slab preserves slot-fill order; consensus.grow_and_replay
    replays the saturated round deterministically)."""
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    # mixed enough that the n_p=8 ensemble stays contested for several
    # rounds, so triadic closure actually saturates the tight slab (the
    # original 0.5/0.03 planted split converges in one round under this
    # jax version's draws and never exercised growth)
    edges, _ = planted_partition(120, 4, 0.25, 0.12, seed=4)
    n_e = edges.shape[0]
    det = get_detector("louvain")
    cfg = ConsensusConfig(algorithm="louvain", n_p=8, tau=0.2, delta=0.02,
                          max_rounds=8, seed=1)

    tight = run_consensus(pack_edges(edges, 120, capacity=n_e + 4), det, cfg)
    roomy = run_consensus(pack_edges(edges, 120, capacity=8 * n_e), det, cfg)

    assert tight.graph.capacity > n_e + 4, "tight run never grew"
    for h in tight.history:
        assert h["n_dropped"] == 0
    assert tight.rounds == roomy.rounds
    strip = lambda h: {k: v for k, v in h.items() if k != "capacity"}
    for a, b in zip(tight.history, roomy.history):
        assert strip(a) == strip(b)
    for pa, pb in zip(tight.partitions, roomy.partitions):
        np.testing.assert_array_equal(pa, pb)


def test_growth_identity_on_hash_path(monkeypatch):
    """Growth must not flip capacity-derived detection heuristics (move
    path, hash bucket count — louvain._cap_hint): a slab grown with
    grow_slab must detect identically to the tight original."""
    import jax

    from fastconsensus_tpu.graph import grow_slab
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    monkeypatch.setenv("FCTPU_MOVE_PATH", "hash")
    edges, _ = planted_partition(150, 5, 0.4, 0.02, seed=6)
    tight = pack_edges(edges, 150, capacity=edges.shape[0] + 4)
    grown = grow_slab(tight, 4 * edges.shape[0])
    roomy = pack_edges(edges, 150, capacity=4 * edges.shape[0])

    det = get_detector("louvain")
    keys = jax.random.split(jax.random.key(3), 4)
    want = np.asarray(det(tight, keys))
    np.testing.assert_array_equal(want, np.asarray(det(grown, keys)))
    # cap_hint is content-derived, so a generous pack is also identical
    np.testing.assert_array_equal(want, np.asarray(det(roomy, keys)))


def test_growth_identity_lpm_sparse_path():
    """LPM's sparse vote (d_cap=0 slabs) must also be layout-independent
    (pair-keyed jitter, segment.pair_jitter)."""
    import dataclasses

    import jax

    from fastconsensus_tpu.graph import grow_slab
    from fastconsensus_tpu.models.lpm import lpm
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, _ = planted_partition(150, 5, 0.4, 0.02, seed=8)
    tight = dataclasses.replace(
        pack_edges(edges, 150, capacity=edges.shape[0] + 4), d_cap=0)
    grown = grow_slab(tight, 4 * edges.shape[0])
    keys = jax.random.split(jax.random.key(2), 4)
    np.testing.assert_array_equal(np.asarray(lpm(tight, keys)),
                                  np.asarray(lpm(grown, keys)))


def test_hybrid_path_through_driver():
    """A hub-heavy graph must take the hybrid path end-to-end through
    run_consensus (call sizing included — round-2 review caught a KeyError
    reachable only via the driver, not the raw detector)."""
    from fastconsensus_tpu.models import louvain as lv
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    rng = np.random.default_rng(1)
    edges, truth = planted_partition(1500, 6, 0.02, 0.001, seed=3)
    hubs = rng.choice(1500, 4, replace=False)
    extra = np.array([[h, int(o)] for h in hubs
                      for o in rng.choice(1500, 1200, replace=False)
                      if int(o) != h])
    slab = pack_edges(np.vstack([edges, extra]), 1500)
    assert lv.select_move_path(slab) == "hybrid", lv.select_move_path(slab)
    cfg = ConsensusConfig(algorithm="louvain", n_p=4, tau=0.2, delta=0.05,
                          max_rounds=2, seed=0)
    res = run_consensus(slab, get_detector("louvain"), cfg)
    assert len(res.partitions) == 4
    assert all(p.shape == (1500,) for p in res.partitions)


def test_no_grow_reports_drops():
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    # same contested split as test_auto_grow_matches_generous_capacity —
    # closure must actually overflow the tight slab for drops to happen
    edges, _ = planted_partition(120, 4, 0.25, 0.12, seed=4)
    slab = pack_edges(edges, 120, capacity=edges.shape[0] + 4)
    cfg = ConsensusConfig(algorithm="louvain", n_p=8, tau=0.2, delta=0.02,
                          max_rounds=8, seed=1, auto_grow=False)
    res = run_consensus(slab, get_detector("louvain"), cfg)
    assert res.graph.capacity == slab.capacity  # round-1 behavior: static
    assert any(h["n_dropped"] > 0 for h in res.history)


def test_fused_rounds_match_single_rounds(monkeypatch):
    """Blocked device-side rounds derive per-round keys identically, so
    fusion must never change results (consensus.py:consensus_rounds_block)."""
    import numpy as np

    from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
    from fastconsensus_tpu.graph import pack_edges
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, _ = planted_partition(120, 4, 0.4, 0.02, seed=9)
    slab = pack_edges(edges, 120)
    cfg = ConsensusConfig(algorithm="lpm", n_p=6, tau=0.5, delta=0.02,
                          max_rounds=5, seed=3)
    det = get_detector("lpm")

    monkeypatch.setenv("FCTPU_DETECT_CALL_MEMBERS", "0")  # no splitting
    fused = run_consensus(slab, det, cfg)

    # force per-round execution by making the round estimate enormous
    from fastconsensus_tpu import sizing as szmod
    monkeypatch.setitem(szmod.NS_PER_TEMP_BYTE, "matmul", 1e6)
    single = run_consensus(slab, det, cfg)

    assert fused.rounds == single.rounds
    assert fused.converged == single.converged
    assert len(fused.history) == len(single.history)
    for a, b in zip(fused.history, single.history):
        assert a == b
    for pa, pb in zip(fused.partitions, single.partitions):
        np.testing.assert_array_equal(pa, pb)


def test_fused_rounds_match_single_rounds_aligned(monkeypatch):
    """Fused blocks re-derive the endgame-alignment flag per round from
    their own stats, so fusion stays result-invariant even when alignment
    engages mid-run (round-3 review: a timing-dependent fused/unfused
    choice must never change partitions)."""
    from fastconsensus_tpu import sizing as szmod
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, _ = planted_partition(150, 5, 0.4, 0.02, seed=9)
    slab = pack_edges(edges, 150)
    cfg = ConsensusConfig(algorithm="louvain", n_p=8, tau=0.2, delta=0.0,
                          max_rounds=6, seed=3, align_frac=0.5)
    det = get_detector("louvain")

    monkeypatch.setenv("FCTPU_DETECT_CALL_MEMBERS", "0")  # no splitting
    fused = run_consensus(slab, det, cfg)
    assert any(h["n_unconverged"] <= 0.5 * h["n_alive"]
               for h in fused.history[:-1]), "alignment never engaged"

    monkeypatch.setitem(szmod.NS_PER_TEMP_BYTE, "matmul", 1e6)
    single = run_consensus(slab, det, cfg)

    assert fused.rounds == single.rounds
    for a, b in zip(fused.history, single.history):
        assert a == b
    for pa, pb in zip(fused.partitions, single.partitions):
        np.testing.assert_array_equal(pa, pb)


def test_consensus_improves_on_single_runs():
    """The paper's core claim (arXiv:1902.04014, reference README.md:14):
    consensus partitions are at least as accurate as direct single runs of
    the base algorithm, on an LFR graph with planted communities."""
    import jax
    import numpy as np

    from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
    from fastconsensus_tpu.graph import pack_edges
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.metrics import nmi
    from fastconsensus_tpu.utils.synth import lfr_graph

    edges, truth = lfr_graph(400, 0.45, seed=11)
    slab = pack_edges(edges, 400)
    det = get_detector("louvain")

    singles = np.asarray(det(slab, jax.random.split(jax.random.key(7), 8)))
    single_nmi = float(np.mean([nmi(s, truth) for s in singles]))

    cfg = ConsensusConfig(algorithm="louvain", n_p=16, tau=0.2, delta=0.02,
                          seed=7)
    res = run_consensus(slab, det, cfg)
    cons_nmi = float(np.mean([nmi(p, truth) for p in res.partitions[:4]]))
    assert cons_nmi >= single_nmi - 0.02, (cons_nmi, single_nmi)


def test_warm_round0_bit_matches_cold():
    """Round 0 under warm start is seeded with singletons — exactly every
    kernel's cold start — so the first round (graph AND stats) must be
    bit-identical to a cold run (consensus.py round-0 warm init)."""
    import dataclasses

    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, _ = planted_partition(200, 5, 0.3, 0.02, seed=12)
    slab = pack_edges(edges, 200)
    det = get_detector("louvain")
    cfg_w = ConsensusConfig(algorithm="louvain", n_p=8, tau=0.2, delta=0.02,
                            max_rounds=1, seed=4, warm_start=True)
    cfg_c = dataclasses.replace(cfg_w, warm_start=False)
    warm = run_consensus(slab, det, cfg_w)
    cold = run_consensus(slab, det, cfg_c)
    assert warm.history == cold.history
    np.testing.assert_array_equal(np.asarray(warm.graph.alive),
                                  np.asarray(cold.graph.alive))
    np.testing.assert_array_equal(np.asarray(warm.graph.weight),
                                  np.asarray(cold.graph.weight))


def _warm_vs_cold(alg, slab, truth, seed):
    import dataclasses

    from fastconsensus_tpu.models.registry import get_detector

    det = get_detector(alg)
    cfg_w = ConsensusConfig(algorithm=alg, n_p=16, tau=0.2, delta=0.02,
                            max_rounds=16, seed=seed, warm_start=True)
    cfg_c = dataclasses.replace(cfg_w, warm_start=False)
    warm = run_consensus(slab, det, cfg_w)
    cold = run_consensus(slab, det, cfg_c)
    q = lambda r: float(np.mean([nmi(p, truth) for p in r.partitions[:4]]))
    return warm, cold, q(warm), q(cold)


@pytest.mark.slow
def test_warm_start_quality_and_rounds_louvain():
    """Warm start exists to cut sweeps, not quality: final NMI must stay
    within 0.02 of a cold run, and the round count must not blow up
    (round-2 VERDICT Weak #4 — warm label lock-in would erode the
    ensemble's independent-draw character).  Measured on this config:
    warm 5 rounds ending *fully* converged (0 unconverged edges) vs cold
    4 rounds with 96 mid-weight edges left under delta — warm's stability
    buys a cleaner consensus, occasionally at one extra round, so the
    bound is cold+1 (the per-round sweep saving is what pays)."""
    from fastconsensus_tpu.utils.synth import lfr_graph

    edges, truth = lfr_graph(1000, 0.3, seed=2)
    slab = pack_edges(edges, 1000)
    warm, cold, nmi_w, nmi_c = _warm_vs_cold("louvain", slab, truth, seed=5)
    assert nmi_w >= nmi_c - 0.02, (nmi_w, nmi_c)
    assert warm.rounds <= cold.rounds + 1, (warm.rounds, cold.rounds)


@pytest.mark.slow
def test_warm_start_quality_and_rounds_leiden():
    from fastconsensus_tpu.utils.synth import lfr_graph

    edges, truth = lfr_graph(1000, 0.3, seed=2)
    slab = pack_edges(edges, 1000)
    warm, cold, nmi_w, nmi_c = _warm_vs_cold("leiden", slab, truth, seed=5)
    assert nmi_w >= nmi_c - 0.02, (nmi_w, nmi_c)
    assert warm.rounds <= cold.rounds + 1, (warm.rounds, cold.rounds)


def test_warm_stagnation_triggers_cold_refresh(tmp_path, caplog):
    """A warm run whose disagreement stops shrinking must re-detect cold
    (stagnation refresh) instead of grinding on: warm members locked into
    diverse local optima keep the same mid-weight edges forever while
    closure densifies the graph (observed on lfr10k/leiden, round 3)."""
    import logging

    from fastconsensus_tpu.utils.synth import planted_partition

    class StickyDetector:
        """Cold (singleton init — the kernels' cold-start convention the
        stagnation refresh relies on): per-member random split, permanent
        disagreement.  Warm: returns the init labels unchanged (a fully
        locked-in member)."""

        supports_init = True

        def __call__(self, slab, keys, init_labels=None):
            rand = jax.vmap(lambda k: jax.random.bernoulli(
                k, 0.5, (slab.n_nodes,)).astype(jnp.int32))(keys)
            if init_labels is None:
                return rand
            is_sing = jnp.all(
                init_labels == jnp.arange(slab.n_nodes)[None, :])
            return jnp.where(is_sing, rand, init_labels.astype(jnp.int32))

    edges, _ = planted_partition(120, 4, 0.35, 0.02, seed=8)
    slab = pack_edges(edges, 120)
    cfg = ConsensusConfig(algorithm="sticky", n_p=8, tau=0.4, delta=0.0,
                          max_rounds=6, seed=2)
    det = StickyDetector()
    with caplog.at_level(logging.WARNING, logger="fastconsensus_tpu"):
        # checkpoint_path forces the per-round path
        single = run_consensus(slab, det, cfg,
                               checkpoint_path=str(tmp_path / "ck.npz"))
    assert any("stagnation" in m for m in caplog.messages), caplog.messages
    colds = [h["cold"] for h in single.history]
    assert colds[0] and any(colds[1:]), colds       # refresh actually ran
    assert not all(colds[1:]), colds                # ...and state resets

    # fused blocks implement the same stall rule in-traced: bit parity
    # (capacity stripped — a block records its post-growth capacity for
    # every round it contains, the per-round path records it pre-growth)
    fused = run_consensus(slab, det, cfg)
    assert fused.rounds == single.rounds
    strip = lambda h: {k: v for k, v in h.items() if k != "capacity"}
    for a, b in zip(fused.history, single.history):
        assert strip(a) == strip(b)
    for pa, pb in zip(fused.partitions, single.partitions):
        np.testing.assert_array_equal(pa, pb)


def test_limit_cycle_stale_refresh_karate(tmp_path, karate_slab):
    """Measured: louvain consensus on karate with run key 123 enters a
    warm limit cycle (unconverged 26 -> 34 -> 28 -> 31 -> ... for 64
    rounds) that neither the one-step stall rule (the count never clears
    its floor) nor alignment breaks — only the stale-fraction refresh
    does.  The rule must fire (converge with cold refreshes present) and
    the fused block must implement it bit-identically to the per-round
    path."""
    from fastconsensus_tpu.models.registry import get_detector

    det = get_detector("louvain")
    cfg = ConsensusConfig(algorithm="louvain", n_p=20, tau=0.2, delta=0.02,
                          seed=0, max_rounds=64)
    key = jax.random.key(123)
    fused = run_consensus(karate_slab, det, cfg, key=key)
    assert fused.converged and fused.rounds < 30, fused.rounds
    assert sum(1 for h in fused.history if h.get("cold")) >= 2

    single = run_consensus(karate_slab, det, cfg, key=key,
                           checkpoint_path=str(tmp_path / "ck.npz"))
    assert single.rounds == fused.rounds
    strip = lambda h: {k: v for k, v in h.items() if k != "capacity"}
    for a, b in zip(fused.history, single.history):
        assert strip(a) == strip(b)
    for pa, pb in zip(fused.partitions, single.partitions):
        np.testing.assert_array_equal(pa, pb)


def test_endgame_alignment_converges_no_slower(tmp_path):
    """ConsensusConfig.align_frac: once nearly converged, members share one
    detection key so content-keyed tie-breaks (louvain._community_reps)
    collapse degenerate disagreements.  Must never cost rounds or quality
    vs unaligned on a planted graph."""
    import dataclasses

    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, truth = planted_partition(300, 6, 0.25, 0.02, seed=2)
    slab = pack_edges(edges, 300)
    det = get_detector("louvain")
    base = ConsensusConfig(algorithm="louvain", n_p=12, tau=0.2, delta=0.005,
                           max_rounds=20, seed=1, align_frac=0.0)
    aligned_cfg = dataclasses.replace(base, align_frac=0.3)
    # checkpoint_path disables round fusion so this exercises the
    # per-round alignment path (fused blocks implement alignment too —
    # see test_fused_rounds_match_single_rounds_aligned)
    plain = run_consensus(slab, det, base,
                          checkpoint_path=str(tmp_path / "a.npz"))
    aligned = run_consensus(slab, det, aligned_cfg,
                            checkpoint_path=str(tmp_path / "b.npz"))
    q = lambda r: float(np.mean([nmi(p, truth) for p in r.partitions[:4]]))
    assert aligned.rounds <= plain.rounds, (aligned.rounds, plain.rounds)
    assert q(aligned) >= q(plain) - 0.02, (q(aligned), q(plain))


def test_detect_chunk_cache_resume(tmp_path):
    """Elastic recovery: chunks persisted by an interrupted run are reused
    (and produce identical labels) on the retry."""
    import jax
    import numpy as np

    from fastconsensus_tpu.consensus import _detect_chunked
    from fastconsensus_tpu.graph import pack_edges
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, _ = planted_partition(80, 4, 0.5, 0.05, seed=2)
    slab = pack_edges(edges, 80)
    det = get_detector("lpm")
    keys = jax.random.split(jax.random.key(5), 9)

    d = str(tmp_path)
    a = np.asarray(_detect_chunked(det, slab, keys, 4, cache_dir=d,
                                   cache_tag="t"))
    files = sorted(p.name for p in tmp_path.iterdir())
    assert files == ["t_c0.npy", "t_c1.npy", "t_c2.npy"], files
    # poison one chunk on disk; the "resumed" run must READ it (proving the
    # cache path is taken), others identical
    poisoned = np.load(tmp_path / "t_c1.npy")
    np.save(tmp_path / "t_c1", poisoned * 0 + 7)
    b = np.asarray(_detect_chunked(det, slab, keys, 4, cache_dir=d,
                                   cache_tag="t"))
    np.testing.assert_array_equal(b[4:8], 7)
    np.testing.assert_array_equal(a[:4], b[:4])
    np.testing.assert_array_equal(a[8:], b[8:])


def test_budget_regrowth_under_densification(monkeypatch):
    """Static move-candidate budgets must grow when the graph densifies
    past them (VERDICT r3 Weak #4): a slab packed with a starved d_cap
    re-derives its sizing from the live degree histogram once the
    per-round overflow breaches policy.budgets_stale."""
    import dataclasses

    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, _ = planted_partition(200, 4, 0.3, 0.02, seed=0)
    slab = pack_edges(edges, 200)
    assert slab.d_cap > 8
    starved = dataclasses.replace(slab, d_cap=8, d_hyb=0, hub_cap=0)
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.02,
                          max_rounds=3, seed=1)
    res = run_consensus(starved, get_detector("lpm"), cfg)
    assert any(h["n_overflow"] > 0 for h in res.history)
    assert res.graph.d_cap > 8, \
        "driver never re-derived the starved dense budget"


def test_budget_regrowth_fused_matches_single(monkeypatch):
    """A mid-run budget re-derivation must happen at the same round under
    fused blocks and per-round execution (the block stops at the breach
    round via the shared policy.budgets_stale rule)."""
    import dataclasses

    from fastconsensus_tpu import sizing as szmod
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, _ = planted_partition(200, 4, 0.3, 0.02, seed=2)
    slab = pack_edges(edges, 200)
    starved = dataclasses.replace(slab, d_cap=8, d_hyb=0, hub_cap=0)
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.02,
                          max_rounds=4, seed=3)
    det = get_detector("lpm")

    monkeypatch.setenv("FCTPU_DETECT_CALL_MEMBERS", "0")  # no splitting
    fused = run_consensus(starved, det, cfg)

    monkeypatch.setitem(szmod.NS_PER_TEMP_BYTE, "matmul", 1e6)
    monkeypatch.setitem(szmod.NS_PER_TEMP_BYTE, "dense", 1e6)
    monkeypatch.setitem(szmod.NS_PER_TEMP_BYTE, "hash", 1e6)
    monkeypatch.setitem(szmod.NS_PER_TEMP_BYTE, "hybrid", 1e6)
    monkeypatch.setitem(szmod.NS_PER_TEMP_BYTE, "runs", 1e6)
    single = run_consensus(starved, det, cfg)

    assert fused.rounds == single.rounds
    assert fused.graph.d_cap == single.graph.d_cap
    for a, b in zip(fused.history, single.history):
        assert a == b
    for pa, pb in zip(fused.partitions, single.partitions):
        np.testing.assert_array_equal(pa, pb)


def test_closure_tau_drops_weak_inserts():
    """Threshold-at-insert (ConsensusConfig.closure_tau): closure
    candidates below the bar never enter the slab, so the consensus graph
    stays lean (densification control, VERDICT r3 Missing #1)."""
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, _ = planted_partition(150, 5, 0.35, 0.03, seed=6)
    slab = pack_edges(edges, 150)
    det = get_detector("lpm")
    base_cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.02,
                               max_rounds=3, seed=4)
    bar_cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.02,
                              max_rounds=3, seed=4, closure_tau=0.5)
    base = run_consensus(slab, det, base_cfg)
    barred = run_consensus(slab, det, bar_cfg)
    tot = lambda r: sum(h["n_closure_added"] for h in r.history)  # noqa: E731
    assert tot(barred) <= tot(base)
    # the bar must not stop the run from converging on an easy graph
    assert barred.converged or barred.rounds == 3
