"""fcflight: incident observability — the always-on flight recorder
(bounded per-thread event rings), the hang watchdog with its
cordon-on-stall path, post-mortem bundles with the jax-free reader, and
the tail-latency exemplar surface (``/debugz/slowest``).

Everything above the "end to end" section is jax-free and fake-clocked:
the recorder, the watchdog verdict and the bundle reader are stdlib
modules by construction, so their units run without touching a device.
The e2e tests reuse the suite's forced 8-device virtual CPU mesh
(conftest.py) and the test hang hook (``FCTPU_TEST_HANG_S``) the server
bakes in for exactly this purpose.
"""

import itertools
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest


def _ring(n, chords=0, shift=7):
    idx = np.arange(n)
    edges = [np.stack([idx, (idx + 1) % n], 1)]
    if chords:
        c = np.arange(chords)
        edges.append(np.stack([c % n, (c + shift) % n], 1))
    return np.concatenate(edges).astype(np.int64)


def _spec(edges, n_nodes, **over):
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.jobs import JobSpec

    kwargs = dict(algorithm="louvain", n_p=4, tau=0.2, delta=0.02,
                  max_rounds=2, seed=0)
    kwargs.update(over)
    return JobSpec(edges=np.asarray(edges, dtype=np.int64),
                   n_nodes=n_nodes, config=ConsensusConfig(**kwargs))


def _wait(jobs, timeout=180.0):
    deadline = time.monotonic() + timeout
    for j in jobs:
        while j.state not in ("done", "failed"):
            assert time.monotonic() < deadline, j.describe()
            time.sleep(0.02)


# -- the flight recorder (unit, jax-free) ------------------------------


def test_ring_bound_and_drop_accounting():
    """A ring retains exactly ``capacity`` events, oldest-overwrite,
    and reports how many it dropped — the hard memory cap."""
    from fastconsensus_tpu.obs.flight import FlightRecorder

    rec = FlightRecorder(capacity=8, max_rings=4)
    for i in range(100):
        rec.record("unit", job=f"j{i}", i=i)
    snap = rec.snapshot()
    assert snap["capacity"] == 8 and snap["max_rings"] == 4
    assert len(snap["rings"]) == 1
    ring = snap["rings"][0]
    assert ring["dropped"] == 92 and snap["dropped"] == 92
    assert snap["n_events"] == 8
    assert [e["i"] for e in ring["events"]] == list(range(92, 100))
    for e in ring["events"]:
        assert e["kind"] == "unit" and e["ts"] > 0.0
        assert e["job"] == f"j{e['i']}"


def test_concurrent_writers_keep_ring_integrity():
    """N writer threads, each with its own ring; snapshots taken WHILE
    they write must always see each ring as a consistent window —
    well-formed events, per-writer sequence numbers strictly
    increasing, never more than ``capacity`` retained."""
    from fastconsensus_tpu.obs.flight import FlightRecorder

    rec = FlightRecorder(capacity=64, max_rings=8)
    n_threads, n_events = 6, 500
    start = threading.Event()

    def writer(k):
        start.wait()
        for i in range(n_events):
            rec.record("w", job=f"t{k}", i=i)

    threads = [threading.Thread(target=writer, args=(k,),
                                name=f"fl-writer-{k}")
               for k in range(n_threads)]
    for t in threads:
        t.start()
    start.set()
    for _ in range(50):    # racing snapshots: the atomicity contract
        for ring in rec.snapshot()["rings"]:
            assert len(ring["events"]) <= 64
            seqs = [e["i"] for e in ring["events"]]
            assert seqs == sorted(seqs)
            assert len(set(seqs)) == len(seqs)
            for e in ring["events"]:
                assert e["kind"] == "w" and "ts" in e and "job" in e
    for t in threads:
        t.join()
    snap = rec.snapshot()
    assert len(snap["rings"]) == n_threads
    for ring in snap["rings"]:
        assert len(ring["events"]) == 64
        assert ring["dropped"] == n_events - 64
        assert [e["i"] for e in ring["events"]] == \
            list(range(n_events - 64, n_events))


def test_thread_storm_shares_one_overflow_ring():
    """Threads past ``max_rings`` share one ring: the memory cap holds
    in a thread storm, and no event is silently unrecorded."""
    from fastconsensus_tpu.obs.flight import FlightRecorder

    rec = FlightRecorder(capacity=256, max_rings=2)

    def writer(k):
        for i in range(10):
            rec.record("storm", k=k, i=i)

    threads = [threading.Thread(target=writer, args=(k,))
               for k in range(5)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = rec.snapshot()
    assert len(snap["rings"]) <= 3    # max_rings + the shared overflow
    assert any(r["thread"] == "<overflow>" for r in snap["rings"])
    assert snap["n_events"] == 50     # all retained (under capacity)


def test_merge_events_filters_and_limit():
    from fastconsensus_tpu.obs.flight import FlightRecorder

    rec = FlightRecorder(capacity=128, max_rings=4)
    for i in range(10):
        rec.record("admit" if i % 2 else "pop",
                   job=f"j{i % 3}", i=i)
    tl = rec.events()
    assert [e["i"] for e in tl] == list(range(10))    # ts-sorted
    assert all(e["thread"] for e in tl)
    only_j0 = rec.events(job="j0")
    assert {e["job"] for e in only_j0} == {"j0"}
    admits = rec.events(kinds=("admit",))
    assert {e["kind"] for e in admits} == {"admit"}
    last3 = rec.events(limit=3)
    assert [e["i"] for e in last3] == [7, 8, 9]    # most recent kept


# -- the hang watchdog (unit, fake clock) ------------------------------


class _StubLatency:
    """service_estimate stub: fixed estimate, or None (no history)."""

    def __init__(self, est):
        self.est = est

    def service_estimate(self, bucket=None, min_count=1):
        return self.est


def _wd(est, trips=None, **cfg_over):
    from fastconsensus_tpu.serve.watchdog import (HangWatchdog,
                                                  WatchdogConfig)

    now = [0.0]
    cfg = dict(k=2.0, floor_s=1.0, min_history=8, poll_s=0.5)
    cfg.update(cfg_over)
    wd = HangWatchdog(_StubLatency(est), WatchdogConfig(**cfg),
                      clock=lambda: now[0], on_trip=trips)
    return wd, now


def test_watchdog_trips_once_per_episode_and_clears_on_beat():
    est = {"count": 20, "mean_s": 0.05, "p95_s": 0.1}
    wd, now = _wd(est)
    wd.beat(0, "device", job="j1", bucket="n64_e96")
    assert wd.check(now=0.5) == []            # under the floor
    trips = wd.check(now=1.5)                 # threshold = max(.2, 1.0)
    assert len(trips) == 1
    t = trips[0]
    assert t["device"] == 0 and t["job"] == "j1"
    assert t["bucket"] == "n64_e96"
    assert t["threshold_s"] == 1.0 and t["elapsed_s"] == 1.5
    assert t["history"] == 20
    assert wd.check(now=50.0) == []           # one trip per episode
    assert wd.trips() == 1
    assert [s["device"] for s in wd.suspects()] == [0]
    wd.beat(0, "device_done")                 # the call returned late
    assert wd.suspects() == []
    now[0] = 100.0
    wd.beat(0, "device", job="j2", bucket="n64_e96")
    assert len(wd.check(now=200.0)) == 1      # a NEW episode re-trips
    assert wd.trips() == 2
    d = wd.describe()
    assert d["trips"] == 2 and d["beats"][0]["tripped"]


def test_watchdog_cold_and_min_history_guards():
    """The two structural false-positive guards: a dispatch expected to
    compile never trips, and a bucket with no trusted distribution
    never trips — and non-device states are never candidates."""
    est = {"count": 20, "mean_s": 0.05, "p95_s": 0.1}
    wd, _ = _wd(est)
    wd.beat(0, "device", job="cold", bucket="b", cold=True)
    assert wd.check(now=1e6) == []            # XLA may take minutes
    wd.beat(1, "dequeue", job="q")
    wd.beat(2, "idle")
    assert wd.check(now=1e6) == []            # only device windows trip
    wd_none, _ = _wd(None)                    # no history at all
    wd_none.beat(0, "device", job="j", bucket="b")
    assert wd_none.check(now=1e6) == []
    assert wd_none.trips() == 0


def test_watchdog_no_false_trip_below_threshold():
    est = {"count": 50, "mean_s": 0.5, "p95_s": 1.0}
    wd, _ = _wd(est, k=8.0, floor_s=0.5)      # threshold = 8 x p95
    wd.beat(3, "device", job="slowish", bucket="b")
    assert wd.check(now=7.9) == []
    assert wd.suspects() == []
    assert len(wd.check(now=8.1)) == 1


def test_watchdog_config_validation_and_disabled_singleton():
    from fastconsensus_tpu.serve.watchdog import (DISABLED_WATCHDOG,
                                                  WatchdogConfig)

    for bad in (dict(k=0.0), dict(floor_s=-1.0), dict(min_history=0),
                dict(poll_s=0.0)):
        with pytest.raises(ValueError):
            WatchdogConfig(**bad).validate()
    DISABLED_WATCHDOG.beat(0, "device", job="j")
    assert DISABLED_WATCHDOG.check(now=1e9) == []
    assert DISABLED_WATCHDOG.suspects() == []
    assert DISABLED_WATCHDOG.trips() == 0
    assert DISABLED_WATCHDOG.describe()["config"]["enabled"] is False
    DISABLED_WATCHDOG.start()
    DISABLED_WATCHDOG.stop()


def test_watchdog_poll_thread_delivers_trips_and_survives_bad_handler():
    """The real poll thread: delivers each trip to ``on_trip`` exactly
    once, and a throwing handler does not kill the watchdog."""
    est = {"count": 20, "mean_s": 0.05, "p95_s": 0.1}
    got = []
    seen = threading.Event()

    def on_trip(trip):
        got.append(trip)
        seen.set()
        raise RuntimeError("handler bug (must not kill the thread)")

    wd, now = _wd(est, trips=on_trip, poll_s=0.01)
    wd.beat(0, "device", job="j1", bucket="b")
    wd.start()
    try:
        now[0] = 10.0                         # wedge, by fake clock
        assert seen.wait(5.0)
        time.sleep(0.05)                      # a few more polls
        assert len(got) == 1                  # once per episode
        wd.beat(0, "device_done")
        seen.clear()
        now[0] = 20.0
        wd.beat(0, "device", job="j2", bucket="b")
        now[0] = 40.0                         # second episode, after a
        assert seen.wait(5.0)                 # handler that raised
        assert [t["job"] for t in got] == ["j1", "j2"]
    finally:
        wd.stop()


# -- post-mortem bundles (jax-free round-trip) -------------------------


def test_bundle_write_schema_and_listing(tmp_path):
    """One ``write_bundle`` call produces a complete, self-contained
    directory: auto sections + caller sections + thread stacks, with
    the MANIFEST (written last) indexing exactly what landed — and an
    unserializable payload degrades to its repr instead of throwing."""
    from fastconsensus_tpu.obs import flight as obs_flight
    from fastconsensus_tpu.obs import postmortem

    base = str(tmp_path)
    obs_flight.record("unit_marker", job="jB", note="bundle-test")
    before = postmortem.bundles_written()
    path = postmortem.write_bundle(
        "unit_test",
        sections={"jobs": {"jobs": [{"job_id": "jB", "state": "running",
                                     "bucket": "n64_e96",
                                     "phases_s": {"device": 1.5}}]},
                  "weird": {"obj": object()}},    # repr, not a raise
        base_dir=base)
    assert postmortem.bundles_written() == before + 1
    assert os.path.basename(path).startswith("fcflight_")
    with open(os.path.join(path, "MANIFEST.json")) as fh:
        manifest = json.load(fh)
    assert manifest["schema"] == 1 and manifest["reason"] == "unit_test"
    assert manifest["pid"] == os.getpid()
    for section in ("flight.json", "counters.json", "latency.json",
                    "stacks.txt", "jobs.json", "weird.json"):
        assert section in manifest["sections"]
        assert os.path.exists(os.path.join(path, section))
    with open(os.path.join(path, "flight.json")) as fh:
        flight = json.load(fh)
    assert any(e.get("kind") == "unit_marker"
               for r in flight["rings"] for e in r["events"])
    with open(os.path.join(path, "weird.json")) as fh:
        assert "object object" in json.load(fh)["obj"]
    # listing: manifest presence defines completeness
    os.makedirs(os.path.join(base, "fcflight_partial_no_manifest"))
    os.makedirs(os.path.join(base, "unrelated_dir"))
    assert postmortem.list_bundles(base) == [path]
    assert postmortem.list_bundles(str(tmp_path / "missing")) == []


def test_bundle_render_and_diff(tmp_path):
    """The reader round-trip: ``render`` names the in-flight job with
    its phase timeline, shows the flight tail and the thread stacks;
    ``diff`` reports counter deltas between two dumps."""
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.obs import flight as obs_flight
    from fastconsensus_tpu.obs import postmortem

    base = str(tmp_path)
    jobs = {"jobs": [{"job_id": "j-wedged", "state": "running",
                      "bucket": "n64_e96",
                      "phases_s": {"queue": 0.002, "device": 312.4}},
                     {"job_id": "j-done", "state": "done",
                      "bucket": "n64_e96",
                      "phases_s": {"device": 0.04}}]}
    obs_flight.record("device", job="j-wedged", device=3)
    old = postmortem.write_bundle("first", {"jobs": jobs},
                                  base_dir=base)
    obs_counters.get_registry().inc("serve.flight.watchdog_trips")
    obs_flight.record("watchdog_trip", job="j-wedged", device=3)
    new = postmortem.write_bundle(
        "watchdog_d3",
        {"jobs": jobs, "watchdog": {"trips": 1},
         "config": {"queue_depth": 8}},
        base_dir=base)
    text = postmortem.render(new)
    assert "reason   : watchdog_d3" in text
    assert "j-wedged state=running bucket=n64_e96" in text
    assert "device=312400.0ms" in text        # the open device phase
    assert "watchdog_trip job=j-wedged" in text
    assert "thread stacks (faulthandler)" in text
    assert "serve.flight.watchdog_trips" in text
    delta = postmortem.diff(old, new)
    assert "serve.flight.watchdog_trips" in delta
    assert "watchdog_trip: 0 -> 1" in delta
    # an incomplete dir renders a refusal, not a crash
    assert "not a complete bundle" in postmortem.render(str(tmp_path))


def test_postmortem_reader_is_jax_free(tmp_path):
    """The incident reader must work on the box where jax is exactly
    what is broken: render a real bundle in a subprocess with jax
    POISONED in sys.modules."""
    from fastconsensus_tpu.obs import postmortem

    path = postmortem.write_bundle(
        "poison_test",
        {"jobs": {"jobs": [{"job_id": "jP", "state": "running",
                            "bucket": "b", "phases_s": {"device": 9.0}}]}},
        base_dir=str(tmp_path))
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "from fastconsensus_tpu.obs import postmortem\n"
        f"sys.exit(postmortem.main(['render', {path!r}]))\n")
    root = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ, PYTHONPATH=os.path.abspath(root))
    res = subprocess.run([sys.executable, "-c", code], cwd=root, env=env,
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "jP state=running" in res.stdout
    assert "reason   : poison_test" in res.stdout


# -- tail exemplars (unit, jax-free) -----------------------------------


def test_histogram_exemplar_slots_are_bounded_largest_win():
    from fastconsensus_tpu.obs.latency import (EXEMPLAR_SLOTS,
                                               LatencyHistogram,
                                               merge_snapshots)

    h = LatencyHistogram()
    h.record(0.010)                           # no exemplar attached
    assert "exemplars" not in h.snapshot()    # byte-identical contract
    h.record(0.0101, exemplar="jA")           # same log2 bucket:
    h.record(0.0103, exemplar="jB")           # only the largest
    h.record(0.0102, exemplar="jC")           # EXEMPLAR_SLOTS survive
    h.record(5.0, exemplar="jSlow")           # a different bucket
    snap = h.snapshot()
    slots = snap["exemplars"]
    per_bucket = {tuple(e for e, _ in v) for v in slots.values()}
    assert ("jSlow",) in per_bucket
    assert ("jB", "jC") in per_bucket         # largest two, desc
    assert all(len(v) <= EXEMPLAR_SLOTS for v in slots.values())
    merged = merge_snapshots([snap, snap])    # exact-merge keeps bound
    assert all(len(v) <= EXEMPLAR_SLOTS
               for v in merged["exemplars"].values())
    assert merged["count"] == 2 * snap["count"]


def test_slow_exemplar_typed_parse_is_jax_free():
    """``ServeClient.slowest()``'s typed row must parse on a thin
    client: poisoned-jax subprocess builds a SlowJobExemplar from a
    canned ``/debugz/slowest`` payload."""
    payload = {"job_id": "j9", "e2e_s": 1.25, "bucket": "n64_e96",
               "rung": "1", "priority": "0", "device": "3",
               "events": [{"ts": 1.0, "kind": "admit", "job": "j9"},
                          {"ts": 2.0, "kind": "finish", "job": "j9"}],
               "timing": None}
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "from fastconsensus_tpu.serve.client import SlowJobExemplar\n"
        f"r = SlowJobExemplar.from_payload({payload!r})\n"
        "assert r.job_id == 'j9' and r.e2e_s == 1.25\n"
        "assert r.bucket == 'n64_e96' and r.device == '3'\n"
        "assert [e['kind'] for e in r.events] == ['admit', 'finish']\n"
        "assert r.timing is None\n"
        "print('typed parse ok')\n")
    root = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ, PYTHONPATH=os.path.abspath(root))
    res = subprocess.run([sys.executable, "-c", code], cwd=root, env=env,
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "typed parse ok" in res.stdout


# -- end to end (the virtual 8-device CPU mesh) ------------------------


def test_slowest_endpoint_joins_exemplars_to_flight_timelines(
        karate_edges):
    """Submit real jobs over HTTP, then ask ``/debugz/slowest``: the
    worst ``serve.e2e`` exemplars come back typed, slowest first, each
    joined to its retained flight-recorder timeline."""
    from fastconsensus_tpu.serve.client import ServeClient
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig,
                                                make_http_server)

    edges, _, ids = karate_edges
    svc = ConsensusService(ServeConfig(queue_depth=8, pin_sizing=False))
    httpd = make_http_server(svc, "127.0.0.1", 0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    svc.start()
    client = ServeClient(f"http://127.0.0.1:{port}", timeout=30.0)
    try:
        payload = dict(edges=edges.tolist(), n_nodes=len(ids),
                       algorithm="louvain", n_p=4, delta=0.02,
                       max_rounds=2, seed=1)
        sub = [client.submit(**dict(payload, seed=s))
               for s in (1, 2)]
        done = [client.wait(s["job_id"], timeout=120) for s in sub]
        assert all(len(r["partitions"]) == 4 for r in done)
        rows = client.slowest()
        assert rows, "no serve.e2e exemplars after two finished jobs"
        assert [r.e2e_s for r in rows] == \
            sorted((r.e2e_s for r in rows), reverse=True)
        ids_seen = {r.job_id for r in rows}
        assert ids_seen & {s["job_id"] for s in sub}
        top = rows[0]
        assert top.e2e_s > 0.0 and isinstance(top.events, tuple)
        kinds = {e["kind"] for e in top.events}
        assert {"admit", "finish"} & kinds    # timeline joined by job
        # the incident fields ride /healthz for the fleet scraper
        h = client.healthz()
        assert h["suspect_devices"] == [] and h["watchdog_trips"] == 0
        assert h["last_bundle"] is None
    finally:
        httpd.shutdown()
        httpd.server_close()
        assert svc.drain(60)


def test_cordon_on_stall_end_to_end(tmp_path, monkeypatch):
    """ISSUE 13 acceptance: a device call wedged via the baked-in test
    hook (``FCTPU_TEST_HANG_S``) trips the hang watchdog, writes a
    post-mortem bundle, and cordons the stuck worker through the PR 6
    machinery — while the rest of the burst still completes.  The
    wedged call then returns late: its job finishes, the worker stays
    cordoned."""
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.obs import postmortem
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)
    from fastconsensus_tpu.serve.watchdog import WatchdogConfig

    monkeypatch.setenv("FCTPU_TEST_HANG_S", "2.5")
    monkeypatch.setenv("FCTPU_TEST_HANG_AFTER", "0")
    svc = ConsensusService(ServeConfig(
        queue_depth=32, pin_sizing=False, devices=2,
        flight_dir=str(tmp_path),
        watchdog=WatchdogConfig(k=2.0, floor_s=0.4, min_history=1,
                                poll_s=0.05, cordon=True)))
    svc._hang_s = 0.0                 # hold the hook while warming up
    svc.start()
    base = obs_counters.get_registry().counters()
    try:
        # warm up SEQUENTIALLY: coalesced submissions would ride the
        # first (cold) device call and leave no warm service history
        # for the estimator the watchdog thresholds against
        warm = []
        for s in range(1, 4):
            j = svc.submit(_spec(_ring(40, chords=40), 40, seed=s))
            _wait([j])
            warm.append(j)
        assert all(j.state == "done" for j in warm), \
            [j.error for j in warm]
        # arm the hook: the very next device dispatch sleeps 2.5s
        # inside the watchdog's device heartbeat window
        svc._hang_s = 2.5
        svc._hang_seq = itertools.count()
        burst = [svc.submit(_spec(_ring(40, chords=40), 40, seed=s))
                 for s in range(10, 14)]
        _wait(burst)
        assert all(j.state == "done" for j in burst), \
            [j.error for j in burst]
        since = obs_counters.get_registry().counters_since(base)
        assert since.get("serve.flight.watchdog_trips", 0) >= 1, since
        assert since.get("serve.pool.worker_cordons", 0) >= 1, since
        assert since.get("serve.flight.bundles", 0) >= 1, since
        stats = svc.stats()
        assert stats["watchdog_trips"] >= 1
        assert stats["cordoned_devices"], stats
        assert stats["last_bundle"] and \
            stats["last_bundle"].startswith(str(tmp_path))
        bundles = postmortem.list_bundles(str(tmp_path))
        assert bundles                # complete (manifest present)
        assert "watchdog" in os.path.basename(bundles[-1])
        text = postmortem.render(bundles[-1])
        assert "reason   : watchdog" in text
        assert "watchdog_trip" in text
    finally:
        assert svc.drain(90)


def test_flight_surfaces_add_zero_compiles_and_zero_host_syncs(
        karate_edges):
    """The overhead pin: with the server warm, a same-bucket request
    through the fully instrumented path still compiles nothing, and
    the fcflight surfaces themselves (record / snapshot / watchdog
    beats / slowest) perform zero deliberate host syncs."""
    from fastconsensus_tpu.analysis import assert_max_compiles
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.obs import flight as obs_flight
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)
    from fastconsensus_tpu.serve.watchdog import (HangWatchdog,
                                                  WatchdogConfig)

    edges, _, ids = karate_edges
    svc = ConsensusService(ServeConfig(queue_depth=4, pin_sizing=False))
    r1 = svc.run_spec(_spec(edges, len(ids)))
    assert not r1["cached"]
    with assert_max_compiles(0):      # warm bucket: instrumentation
        r2 = svc.run_spec(_spec(_ring(40, chords=40), 40))  # adds none
    assert r2["bucket"] == r1["bucket"]
    base = obs_counters.get_registry().counters()
    rec = obs_flight.get_flight_recorder()
    wd = HangWatchdog(_StubLatency({"count": 9, "p95_s": 0.1,
                                    "mean_s": 0.05}),
                      WatchdogConfig(poll_s=0.5), clock=lambda: 0.0)
    with assert_max_compiles(0):
        for i in range(2000):
            rec.record("pin", job=f"j{i % 7}", i=i)
        rec.snapshot()
        rec.events(job="j0", limit=16)
        for i in range(100):
            wd.beat(0, "device", job="j", bucket="b")
            wd.check(now=0.0)
            wd.beat(0, "device_done")
        svc.slowest()
    since = obs_counters.get_registry().counters_since(base)
    assert since.get("host_sync.total", 0) == 0, since
