"""fcobs observability subsystem: span tracer semantics, disabled-path
overhead, counter folding from a real consensus run, Perfetto/JSONL
export round-trips, CompileGuard registry attachment, and the CLI
``--trace`` surface."""

import json
import os

import pytest

KARATE = os.path.join(os.path.dirname(__file__), "..", "examples",
                      "karate_club.txt")


@pytest.fixture()
def registry():
    """The process-global registry, reset around each test so counts
    never leak across tests (or from earlier engine activity)."""
    from fastconsensus_tpu.obs import get_registry

    reg = get_registry()
    reg.reset()
    yield reg
    reg.reset()


# ---------------------------------------------------------------- tracer

def test_span_nesting_ordering_and_args():
    from fastconsensus_tpu.obs import Tracer

    tr = Tracer()
    with tr.span("outer"):
        with tr.span("inner", k=1):
            pass
        with tr.span("inner2"):
            pass
    events = tr.events()
    # children close before their parents
    assert [e["name"] for e in events] == ["inner", "inner2", "outer"]
    by = {e["name"]: e for e in events}
    assert by["outer"]["depth"] == 0 and by["outer"]["parent"] is None
    assert by["inner"]["depth"] == 1 and by["inner"]["parent"] == "outer"
    assert by["inner"]["args"] == {"k": 1}
    # interval containment: inner spans lie inside outer's [ts, ts+dur]
    for name in ("inner", "inner2"):
        assert by[name]["ts"] >= by["outer"]["ts"]
        assert (by[name]["ts"] + by[name]["dur"]
                <= by["outer"]["ts"] + by["outer"]["dur"])
    # sibling ordering
    assert by["inner2"]["ts"] >= by["inner"]["ts"] + by["inner"]["dur"]
    assert all(e["dur"] >= 0 and e["cpu_us"] >= 0 for e in events)


def test_disabled_tracer_allocates_and_records_nothing():
    from fastconsensus_tpu.obs import Tracer, get_tracer
    from fastconsensus_tpu.obs.tracer import _NULL_SPAN

    tr = Tracer(enabled=False)
    s1 = tr.span("a", x=1)
    s2 = tr.span("b")
    # the disabled path hands out ONE shared no-op span — no per-call
    # allocation, no clock reads
    assert s1 is s2 is _NULL_SPAN
    with s1:
        tr.instant("marker")
    assert tr.events() == []
    # the ambient default is the disabled singleton
    assert not get_tracer().enabled


def test_traced_decorator_uses_the_ambient_tracer():
    from fastconsensus_tpu.obs import Tracer, traced, use_tracer

    calls = []

    @traced("work")
    def fn(x):
        calls.append(x)
        return x + 1

    assert fn(1) == 2  # ambient tracer disabled: plain call
    tr = Tracer()
    with use_tracer(tr):
        assert fn(2) == 3
    assert fn(3) == 4  # restored on exit
    assert [e["name"] for e in tr.events()] == ["work"]
    assert calls == [1, 2, 3]


def test_tracer_is_thread_safe():
    import threading

    from fastconsensus_tpu.obs import Tracer

    tr = Tracer()

    def worker(i):
        with tr.span(f"w{i}"):
            with tr.span(f"w{i}.child"):
                pass

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = tr.events()
    assert len(events) == 16
    # per-thread nesting survived the interleaving
    for i in range(8):
        by = {e["name"]: e for e in events
              if e["name"].startswith(f"w{i}")}
        assert by[f"w{i}.child"]["parent"] == f"w{i}"


# ----------------------------------------------------- device attribution

def test_annotated_tracer_still_records_host_spans():
    """Tracer(annotate=True) wraps spans in jax.profiler annotations
    (available on every backend) without changing the host-span record;
    step_span records the step in the span args."""
    from fastconsensus_tpu.obs import Tracer
    from fastconsensus_tpu.obs import device as obs_device

    assert obs_device.available()
    tr = Tracer(annotate=True)
    assert tr.annotate
    with tr.step_span("round", 3, mode="warm"):
        with tr.span("detect", r=3):
            pass
    events = tr.events()
    assert [e["name"] for e in events] == ["detect", "round"]
    by = {e["name"]: e for e in events}
    assert by["round"]["args"] == {"step": 3, "mode": "warm"}
    assert by["detect"]["parent"] == "round"
    # disabled tracers never pay the annotation path
    from fastconsensus_tpu.obs.tracer import _NULL_SPAN

    off = Tracer(enabled=False, annotate=True)
    assert off.step_span("round", 0) is _NULL_SPAN


def test_profiler_session_merge_host_only(tmp_path, registry):
    """ProfilerSession + annotated spans + merge_profiler_trace: on CPU
    the merged blob parses, carries both the fcobs spans and the
    profiler's (host-only) events, says device_track=False, and drops
    the per-python-frame noise."""
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.obs import Tracer
    from fastconsensus_tpu.obs import export as obs_export
    from fastconsensus_tpu.obs.device import (ProfilerSession,
                                              merge_profiler_trace)

    prof_dir = str(tmp_path / "prof")
    tr = Tracer(annotate=True)
    f = jax.jit(lambda a: a * 2 + 1)
    with ProfilerSession(prof_dir) as prof:
        assert prof.active and prof.start_pc is not None
        with tr.step_span("round", 0):
            f(jnp.ones((32,))).block_until_ready()
    blob = obs_export.to_perfetto(tr.events(), registry.snapshot())
    merged, info = merge_profiler_trace(blob, prof_dir,
                                        offset_us=prof.offset_us(tr.t0))
    assert info["merged"] and not info["device_track"]
    assert info["python_frames_dropped"] > 0
    attrib = merged["otherData"]["device_attribution"]
    assert attrib == info
    cats = {e.get("cat") for e in merged["traceEvents"]}
    assert "fcobs" in cats
    # profiler events survived the merge alongside the fcobs track
    assert any(e.get("cat") != "fcobs" and e.get("ph") == "X"
               and not str(e.get("name", "")).startswith("$")
               for e in merged["traceEvents"])
    json.dumps(merged)  # artifact stays JSON-serializable


def test_finalize_merge_skips_stale_traces_and_stamps_no_start(
        tmp_path, registry):
    """finalize_merge (the cli.py/bench.py policy): a trace file left by
    an EARLIER session in a reused --profile-dir is never grafted (it
    would land at the wrong offset), and a session that never started is
    stamped rather than merged."""
    import gzip
    import os
    import time

    from fastconsensus_tpu.obs import export as obs_export
    from fastconsensus_tpu.obs.device import ProfilerSession, finalize_merge

    prof_dir = tmp_path / "prof"
    run_dir = prof_dir / "plugins" / "profile" / "2020_01_01"
    run_dir.mkdir(parents=True)
    stale = run_dir / "host.trace.json.gz"
    with gzip.open(stale, "wt") as fh:
        fh.write(json.dumps({"traceEvents": [
            {"ph": "X", "pid": 7, "tid": 1, "ts": 0, "dur": 1,
             "name": "stale"}]}))
    old = time.time() - 3600
    os.utime(stale, (old, old))

    blob = obs_export.to_perfetto(_sample_events(), registry.snapshot())
    # a "started" session whose stop produced no NEW trace file
    sess = ProfilerSession(str(prof_dir))
    sess.start_pc = time.perf_counter()
    sess.start_wall = time.time()
    merged, info = finalize_merge(blob, sess, sess.start_pc)
    assert not info["merged"] and "fresh" in info["reason"]
    assert not any(e.get("name") == "stale"
                   for e in merged["traceEvents"])
    # never-started session: stamped with the start-failure reason
    merged, info = finalize_merge(blob, ProfilerSession(str(prof_dir)),
                                  0.0)
    assert not info["merged"] and "failed to start" in info["reason"]
    assert merged["otherData"]["device_attribution"] == info


def test_merge_degrades_gracefully_without_profile(tmp_path, registry):
    """No profiler output under the dir: the blob comes back unmerged
    but *annotated* with the reason — never an exception."""
    from fastconsensus_tpu.obs import export as obs_export
    from fastconsensus_tpu.obs.device import merge_profiler_trace

    blob = obs_export.to_perfetto(_sample_events(), registry.snapshot())
    merged, info = merge_profiler_trace(blob, str(tmp_path / "empty"))
    assert not info["merged"] and "reason" in info
    assert merged["otherData"]["device_attribution"] == info
    assert len(merged["traceEvents"]) == len(blob["traceEvents"])


# -------------------------------------------------------------- registry

def test_registry_counters_gauges_series(registry):
    registry.inc("a")
    registry.inc("a", 2)
    registry.gauge("g", 3.5)
    for v in range(1, 101):
        registry.observe("lat", v / 100.0)
    assert registry.counters()["a"] == 3
    s = registry.summary("lat")
    assert s["count"] == 100
    assert s["p50"] == pytest.approx(0.5)
    assert s["p95"] == pytest.approx(0.95)
    assert s["max"] == pytest.approx(1.0)
    assert registry.summary("missing") is None
    snap = registry.snapshot()
    assert snap["gauges"]["g"] == 3.5
    json.dumps(snap)  # JSON-ready by construction


def test_compile_guard_attaches_to_registry(registry):
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.analysis import CompileGuard

    @jax.jit
    def f(x):
        return x * 2 + 1

    with CompileGuard(registry=registry, counter="xla.compiles") as g:
        f(jnp.ones((9,)))
    assert g.count >= 1
    assert registry.counters().get("xla.compiles", 0) == g.count
    # the post-construction attach() hook feeds the same counter
    with CompileGuard().attach(registry, counter="xla.compiles2") as g2:
        f(jnp.ones((11,)))  # new shape: compiles again
    assert g2.count >= 1
    assert registry.counters().get("xla.compiles2", 0) == g2.count


# --------------------------------------------- consensus-run integration

def test_counter_folding_from_karate_run(karate_slab, registry):
    """A real 2-round karate run populates spans AND counters: round
    totals match the result history, every deliberate host sync is
    counted, and the per-round latency series has one sample per round."""
    from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
    from fastconsensus_tpu.obs import Tracer, use_tracer
    from fastconsensus_tpu.models.registry import get_detector

    cfg = ConsensusConfig(algorithm="louvain", n_p=6, tau=0.2, delta=0.02,
                          max_rounds=2, seed=0)
    tr = Tracer()
    with use_tracer(tr):
        res = run_consensus(karate_slab, get_detector("louvain"), cfg)
    counters = registry.counters()
    assert counters["rounds.total"] == res.rounds == len(res.history)
    assert counters["rounds.cold"] >= 1  # round 0 detects cold
    assert counters["closure.edges_added"] == \
        sum(h["n_closure_added"] for h in res.history)
    assert counters["host_sync.total"] >= 2  # stats readback(s) + labels
    assert counters["host_sync.final_labels"] == 1
    assert counters["engine.setup_executables"] >= 1
    assert len(registry.series("round.seconds")) == res.rounds
    names = {e["name"] for e in tr.events()}
    assert "setup_executables" in names and "final_detect" in names
    # rounds run either fused (small graphs) or one call per round
    assert names & {"round", "rounds_block"}
    # converged-edge fraction is a valid fraction series
    assert all(0.0 <= v <= 1.0
               for v in registry.series("round.converged_frac"))


def test_disabled_tracing_records_no_spans_but_counters_flow(
        karate_slab, registry):
    """With the ambient tracer disabled (the default), a run must record
    zero span events — the hot path's no-op contract — while the always-on
    registry still counts rounds."""
    from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
    from fastconsensus_tpu.obs import get_tracer
    from fastconsensus_tpu.models.registry import get_detector

    tracer = get_tracer()
    assert not tracer.enabled
    before = len(tracer.events())
    cfg = ConsensusConfig(algorithm="louvain", n_p=6, tau=0.2, delta=0.02,
                          max_rounds=2, seed=0)
    res = run_consensus(karate_slab, get_detector("louvain"), cfg)
    assert len(tracer.events()) == before == 0
    assert registry.counters()["rounds.total"] == res.rounds


# -------------------------------------------------------------- exports

def _sample_events():
    from fastconsensus_tpu.obs import Tracer

    tr = Tracer()
    with tr.span("run"):
        for i in range(3):
            with tr.span("round", r=i):
                pass
        tr.instant("grown", dropped=7)
    return tr.events()


def test_perfetto_export_roundtrips_with_ordered_ts(tmp_path, registry):
    from fastconsensus_tpu.obs import export as obs_export

    registry.inc("rounds.total", 3)
    path = str(tmp_path / "trace.json")
    obs_export.write_perfetto(path, _sample_events(),
                              registry.snapshot())
    blob = json.load(open(path))
    assert blob["displayTimeUnit"] == "ms"
    xs = [e for e in blob["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 4
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)
    for e in xs:
        assert set(e) >= {"name", "cat", "ph", "ts", "dur", "pid", "tid"}
    # instants, metadata, and the counter snapshot ride along
    assert any(e.get("ph") == "i" for e in blob["traceEvents"])
    assert any(e.get("ph") == "M" for e in blob["traceEvents"])
    assert blob["otherData"]["counters"]["counters"]["rounds.total"] == 3
    assert blob["otherData"]["span_stats"]["round"]["count"] == 3


def test_jsonl_export_roundtrips(tmp_path, registry):
    from fastconsensus_tpu.obs import export as obs_export

    registry.inc("x", 5)
    path = str(tmp_path / "events.jsonl")
    obs_export.write_jsonl(path, _sample_events(), registry.snapshot())
    lines = [json.loads(line) for line in open(path)]
    spans = [ln for ln in lines if ln["kind"] == "span"]
    assert len(spans) == 5  # 4 X + 1 instant
    assert [s["ts"] for s in spans] == sorted(s["ts"] for s in spans)
    assert lines[-1]["kind"] == "counters"
    assert lines[-1]["counters"]["x"] == 5


def test_jsonl_chain_reader_rebases_ts_across_segments(tmp_path,
                                                       registry):
    """Rotated JSONL segments (supervise restarts) read back as ONE
    stream: attempt numbers attach, span timestamps chain monotonically
    even though each process's tracer clock restarted at zero."""
    from fastconsensus_tpu.obs import export as obs_export

    path = str(tmp_path / "trace.json.jsonl")
    # two dead attempts + the live file, each with its own zero-based ts
    registry.inc("rounds.total", 1)
    obs_export.write_jsonl(path + ".1", _sample_events(),
                           registry.snapshot())
    registry.inc("rounds.total", 1)
    obs_export.write_jsonl(path + ".2", _sample_events(),
                           registry.snapshot())
    registry.inc("rounds.total", 1)
    obs_export.write_jsonl(path, _sample_events(), registry.snapshot())

    assert obs_export.chain_segments(path) == [path + ".1", path + ".2",
                                               path]
    records = obs_export.read_jsonl_chain(path)
    assert {r["attempt"] for r in records} == {1, 2, 3}
    spans = [r for r in records if r["kind"] == "span"]
    ts = [r["ts"] for r in spans]
    assert ts == sorted(ts), "chained ts not rebased monotonically"
    # later attempts start after earlier ones end
    first_of = {a: min(r["ts"] for r in spans if r["attempt"] == a)
                for a in (1, 2, 3)}
    last_of = {a: max(r["ts"] + r.get("dur", 0) for r in spans
                      if r["attempt"] == a) for a in (1, 2, 3)}
    assert first_of[2] >= last_of[1] and first_of[3] >= last_of[2]
    # the final counters record is the cumulative truth
    counters = [r for r in records if r["kind"] == "counters"]
    assert counters[-1]["attempt"] == 3
    assert counters[-1]["counters"]["rounds.total"] == 3


def test_jsonl_chain_reader_picks_up_profiler_sidecars(tmp_path,
                                                       registry):
    """ROADMAP follow-up: a supervised --trace --profile-dir run leaves
    rotated Perfetto blobs (with merged profiler events) NEXT TO the
    rotated JSONL segments — supervise --rotate moves both in lockstep.
    ``read_jsonl_chain(with_profiler=True)`` splices each attempt's
    profiler events back in, attempt-tagged and ts-rebased; metadata
    rows and fcobs spans (already in the JSONL) are not duplicated."""
    import json as _json

    from fastconsensus_tpu.obs import export as obs_export

    jsonl = str(tmp_path / "trace.json.jsonl")
    perfetto = str(tmp_path / "trace.json")

    def perfetto_blob(dev_ts):
        return {"traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 7, "tid": 0,
             "ts": 0, "args": {"name": "/device:TPU:0"}},
            {"name": "fusion.1", "ph": "X", "cat": "tpu", "ts": dev_ts,
             "dur": 10, "pid": 7, "tid": 1},
            {"name": "round", "ph": "X", "cat": "fcobs", "ts": 100,
             "dur": 50, "pid": 1, "tid": 1},
        ]}

    # attempt 1 (rotated pair .1): spans end at ts 150
    obs_export.write_jsonl(jsonl + ".1", _sample_events(),
                           registry.snapshot())
    with open(perfetto + ".1", "w") as fh:
        _json.dump(perfetto_blob(dev_ts=120), fh)
    # live attempt 2
    obs_export.write_jsonl(jsonl, _sample_events(), registry.snapshot())
    with open(perfetto, "w") as fh:
        _json.dump(perfetto_blob(dev_ts=30), fh)

    records = obs_export.read_jsonl_chain(jsonl, with_profiler=True)
    prof = [r for r in records if r["kind"] == "profiler"]
    assert [p["attempt"] for p in prof] == [1, 2]
    assert all(p["name"] == "fusion.1" for p in prof), prof
    # attempt 1's device event keeps its own clock; attempt 2's rebases
    # by attempt 1's span end — same offset the spans got
    seg1_end = max(r["ts"] + r.get("dur", 0) for r in records
                   if r["kind"] == "span" and r["attempt"] == 1)
    assert prof[0]["ts"] == 120
    assert prof[1]["ts"] == 30 + seg1_end
    # no metadata rows, no duplicated fcobs spans
    assert all(p.get("ph") != "M" and p.get("cat") != "fcobs"
               for p in prof)
    # default stays profiler-free (backwards compatible)
    assert all(r["kind"] != "profiler"
               for r in obs_export.read_jsonl_chain(jsonl))
    # a corrupt sidecar contributes nothing rather than failing the read
    with open(perfetto, "w") as fh:
        fh.write("{not json")
    records = obs_export.read_jsonl_chain(jsonl, with_profiler=True)
    assert [r["attempt"] for r in records if r["kind"] == "profiler"] \
        == [1]


def test_jsonl_streamer_survives_abrupt_death(tmp_path, registry):
    """The CLI's .jsonl sidecar streams per flush: a SIGKILLed process
    (no close(), no finally) still leaves every flushed span on disk,
    and the chain reader copes with the counters-less segment."""
    from fastconsensus_tpu.obs import Tracer
    from fastconsensus_tpu.obs import export as obs_export

    path = str(tmp_path / "t.jsonl")
    tr = Tracer()
    streamer = obs_export.JsonlStreamer(path, tr)
    with tr.span("round", r=0):
        pass
    streamer.flush()
    with tr.span("round", r=1):
        pass
    streamer.flush()
    streamer.flush()  # nothing new: no-op, no duplicate lines
    # process dies here — close() never runs
    lines = [json.loads(ln) for ln in open(path)]
    assert [ln["kind"] for ln in lines] == ["span", "span"]
    assert [ln["args"]["r"] for ln in lines] == [0, 1]
    records = obs_export.read_jsonl_chain(path)
    assert len(records) == 2 and all(r["attempt"] == 1 for r in records)
    # graceful path: close() appends the counters record
    registry.inc("rounds.total", 2)
    streamer.close(registry.snapshot())
    lines = [json.loads(ln) for ln in open(path)]
    assert lines[-1]["kind"] == "counters"
    assert lines[-1]["counters"]["rounds.total"] == 2


def test_restore_counters_is_a_delta_restore(registry):
    """restore_counters raises counters to at least the saved totals —
    full restore into a fresh registry, no double-count when the counts
    are already present (the in-process re-resume case)."""
    saved = {"rounds.total": 5, "host_sync.total": 9}
    applied = registry.restore_counters(saved)
    assert applied == saved
    assert registry.counters() == saved
    # already-present counts: nothing re-applied
    assert registry.restore_counters(saved) == {}
    assert registry.counters() == saved
    # partially-present: only the missing delta lands
    registry.inc("rounds.total", 2)   # 7 now
    applied = registry.restore_counters({"rounds.total": 10, "new": 1})
    assert applied == {"rounds.total": 3, "new": 1}
    assert registry.counters()["rounds.total"] == 10


def test_summary_table_formats(registry):
    from fastconsensus_tpu.obs import export as obs_export

    registry.inc("rounds.total", 2)
    text = obs_export.summary_table(_sample_events(),
                                    registry.snapshot())
    assert "span" in text and "round" in text
    assert "rounds.total = 2" in text
    assert obs_export.summary_table([]) == "(no spans recorded)"


# ------------------------------------------------------------------ CLI

def test_cli_trace_writes_perfetto_and_jsonl(tmp_path, registry):
    from fastconsensus_tpu.cli import main

    trace = tmp_path / "run_trace.json"
    rc = main(["-f", KARATE, "--alg", "lpm", "-np", "4", "-d", "0.1",
               "--max-rounds", "2", "--seed", "1",
               "--out-dir", str(tmp_path), "--quiet",
               "--trace", str(trace)])
    assert rc == 0
    assert trace.is_file() and trace.stat().st_size > 0
    blob = json.load(open(trace))
    xs = [e for e in blob["traceEvents"] if e.get("ph") == "X"]
    assert xs, "trace recorded no spans"
    assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)
    sidecar = str(trace) + ".jsonl"
    assert os.path.getsize(sidecar) > 0
    lines = [json.loads(line) for line in open(sidecar)]
    assert lines[-1]["kind"] == "counters"
    assert lines[-1]["counters"]["rounds.total"] >= 1
    # the ambient tracer was restored to the disabled default
    from fastconsensus_tpu.obs import get_tracer

    assert not get_tracer().enabled


def test_cli_trace_with_profile_dir_merges_one_timeline(tmp_path,
                                                        registry):
    """--trace + --profile-dir on CPU: one Perfetto artifact that
    parses, keeps the fcobs spans ts-ordered, and records the
    device-attribution outcome (host-only here — no device track)."""
    from fastconsensus_tpu.cli import main

    trace = tmp_path / "merged_trace.json"
    rc = main(["-f", KARATE, "--alg", "lpm", "-np", "4", "-d", "0.1",
               "--max-rounds", "2", "--seed", "1",
               "--out-dir", str(tmp_path), "--quiet",
               "--trace", str(trace),
               "--profile-dir", str(tmp_path / "prof")])
    assert rc == 0
    blob = json.load(open(trace))
    fcobs = [e for e in blob["traceEvents"]
             if e.get("ph") == "X" and e.get("cat") == "fcobs"]
    assert fcobs
    assert [e["ts"] for e in fcobs] == sorted(e["ts"] for e in fcobs)
    attrib = blob["otherData"]["device_attribution"]
    assert attrib["merged"] and not attrib["device_track"]
    # per-round step annotation made it into the span args
    stepped = [e for e in fcobs
               if (e.get("args") or {}).get("step") is not None]
    assert stepped, "no step spans recorded"
