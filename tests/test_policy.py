"""Unit tests for the single-source control policy (policy.py).

The fused block evaluates these rules with jnp inside a while_loop and
the host driver with numpy between device calls; the fused-vs-single
parity tests in test_consensus.py check the integration, these check the
rules themselves (including the division-free forms' agreement across
array namespaces).
"""

import jax.numpy as jnp
import numpy as np

from fastconsensus_tpu import policy


def _hist(entries):
    return [{"n_unconverged": u, "n_alive": a, "cold": c}
            for u, a, c in entries]


def test_state_from_history_matches_incremental_observe():
    hist = _hist([(90, 100, True), (70, 110, False), (70, 120, False),
                  (65, 130, False), (80, 140, True), (60, 150, False)])
    batch = policy.state_from_history(hist)
    inc = policy.PolicyState(*(np.int32(v) for v in policy.INITIAL))
    for h in hist:
        inc = policy.observe(np, inc, np.bool_(h["cold"]),
                             np.int32(h["n_unconverged"]),
                             np.int32(h["n_alive"]))
    for a, b in zip(batch, inc):
        assert int(a) == int(b), (batch, inc)


def test_observe_np_jnp_agree():
    state_np = policy.PolicyState(*(np.int32(v) for v in policy.INITIAL))
    state_j = policy.PolicyState(*(jnp.int32(v) for v in policy.INITIAL))
    rounds = [(True, 90, 100), (False, 70, 110), (False, 71, 111),
              (False, 72, 112), (False, 5, 120)]
    for cold, u, a in rounds:
        state_np = policy.observe(np, state_np, np.bool_(cold),
                                  np.int32(u), np.int32(a))
        state_j = policy.observe(jnp, state_j, jnp.bool_(cold),
                                 jnp.int32(u), jnp.int32(a))
        for x, y in zip(state_np, state_j):
            assert int(x) == int(y)
        for aligned in (False, True):
            assert bool(policy.stalled(np, 0.02, state_np, aligned)) == \
                bool(policy.stalled(jnp, 0.02, state_j,
                                    jnp.bool_(aligned)))
        assert bool(policy.stale(np, 0.02, state_np)) == \
            bool(policy.stale(jnp, 0.02, state_j))
        assert bool(policy.align_now(np, 0.5, state_np)) == \
            bool(policy.align_now(jnp, 0.5, state_j))


def test_stalled_requires_two_warm_rounds():
    s = policy.PolicyState(*(np.int32(v) for v in policy.INITIAL))
    assert not bool(policy.stalled(np, 0.0, s, False))
    s = policy.observe(np, s, np.bool_(True), np.int32(500), np.int32(1000))
    # one round only: u2 sentinel
    assert not bool(policy.stalled(np, 0.0, s, False))
    # second warm round with NO progress: stall fires
    s = policy.observe(np, s, np.bool_(False), np.int32(500),
                       np.int32(1000))
    s = policy.observe(np, s, np.bool_(False), np.int32(500),
                       np.int32(1000))
    assert bool(policy.stalled(np, 0.0, s, False))
    # a cold round resets the window
    s = policy.observe(np, s, np.bool_(True), np.int32(500), np.int32(1000))
    assert not bool(policy.stalled(np, 0.0, s, False))


def test_stalled_aligned_threshold_gentler():
    """7% relative progress: short of the 10% unaligned bar (stalls) but
    enough under alignment's gentler 5% bar (no stall)."""
    s = policy.PolicyState(*(np.int32(v) for v in policy.INITIAL))
    s = policy.observe(np, s, np.bool_(True), np.int32(1000),
                       np.int32(10000))
    s = policy.observe(np, s, np.bool_(False), np.int32(1000),
                       np.int32(10000))
    s = policy.observe(np, s, np.bool_(False), np.int32(930),
                       np.int32(10000))
    assert bool(policy.stalled(np, 0.0, s, False))
    assert not bool(policy.stalled(np, 0.0, s, True))


def test_stall_floor_blocks_endgame_counts():
    """Near the convergence bar, stagnation must not fire (a cold restart
    would blow away nearly-converged state)."""
    s = policy.PolicyState(*(np.int32(v) for v in policy.INITIAL))
    s = policy.observe(np, s, np.bool_(True), np.int32(12), np.int32(1000))
    s = policy.observe(np, s, np.bool_(False), np.int32(12), np.int32(1000))
    s = policy.observe(np, s, np.bool_(False), np.int32(12), np.int32(1000))
    assert not bool(policy.stalled(np, 0.02, s, False))  # 12 < floor 64


def test_stale_fires_on_limit_cycle():
    s = policy.PolicyState(*(np.int32(v) for v in policy.INITIAL))
    s = policy.observe(np, s, np.bool_(True), np.int32(300), np.int32(1000))
    # oscillation that never sets a new fraction minimum
    for u in (340, 280, 310, 290, 320, 300):
        s = policy.observe(np, s, np.bool_(False), np.int32(u),
                           np.int32(1000))
    # 280 set a minimum at step 2; the four rounds after it did not
    assert int(s.scount) >= policy.STALE_ROUNDS
    assert bool(policy.stale(np, 0.0, s))


def test_budgets_stale_thresholds():
    # hub: fires only past 1/8 of hub_cap, and only when hub path sized
    assert not bool(policy.budgets_stale(np, 0, 100, 0, 800, 1000))
    assert bool(policy.budgets_stale(np, 0, 101, 0, 800, 1000))
    assert not bool(policy.budgets_stale(np, 0, 10_000, 0, 0, 1000))
    # dense: budget is n_nodes * d_cap
    assert not bool(policy.budgets_stale(np, 1000, 0, 8, 0, 1000))
    assert bool(policy.budgets_stale(np, 1001, 0, 8, 0, 1000))
    # jnp agreement
    assert bool(policy.budgets_stale(jnp, 101, 0, 8, 800, 1000)) == \
        bool(policy.budgets_stale(np, 101, 0, 8, 800, 1000))
