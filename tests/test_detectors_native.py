"""CNM / Infomap detectors through the jitted consensus engine.

The host kernels cross the jit boundary via jax.pure_callback (models/cnm.py)
— these tests pin that integration: full consensus runs end-to-end and the
quality matches the planted partition (reference behavior: fc:312-411 cnm,
fc:260-309 infomap).
"""

import numpy as np
import pytest

from fastconsensus_tpu import native
from fastconsensus_tpu.utils.metrics import nmi
from fastconsensus_tpu.utils.synth import planted_partition

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no native toolchain")


@pytest.mark.parametrize("alg,tau", [("cnm", 0.7), ("infomap", 0.6)])
def test_consensus_with_native_detector(alg, tau):
    from fastconsensus_tpu.consensus import fast_consensus

    edges, truth = planted_partition(300, 6, 0.3, 0.01, seed=5)
    result = fast_consensus(edges, 300, algorithm=alg, n_p=6, tau=tau,
                            delta=0.02, max_rounds=8)
    assert result.converged
    assert len(result.partitions) == 6
    assert nmi(result.partitions[0], truth) > 0.85


def test_native_detector_runs_under_jit(karate_slab):
    import jax

    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils import prng

    detect = get_detector("infomap")
    keys = prng.partition_keys(jax.random.key(0), 4)
    labels = jax.jit(detect)(karate_slab, keys)
    assert labels.shape == (4, karate_slab.n_nodes)
    assert labels.dtype == np.int32
    # labels must describe a real partition: between 2 and N communities
    for row in np.asarray(labels):
        assert 2 <= len(np.unique(row)) <= karate_slab.n_nodes
