"""Label-propagation kernel."""

import jax
import numpy as np

from fastconsensus_tpu.graph import pack_edges
from fastconsensus_tpu.models.lpm import lpm_single, make_lpm
from fastconsensus_tpu.utils.metrics import nmi


def two_cliques(k=6):
    edges = []
    for a in range(k):
        for b in range(a + 1, k):
            edges.append([a, b])
            edges.append([k + a, k + b])
    edges.append([0, k])  # single bridge
    return np.array(edges), 2 * k


def test_lpm_two_cliques_exact():
    edges, n = two_cliques()
    slab = pack_edges(edges, n)
    labels = np.asarray(lpm_single(slab, jax.random.key(0)))
    # the two cliques must each be uniform, and distinct
    assert len(set(labels[:6])) == 1
    assert len(set(labels[6:])) == 1
    assert labels[0] != labels[6]


def test_lpm_ensemble_shapes_and_validity(karate_slab):
    det = make_lpm()
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        jax.random.key(1), jax.numpy.arange(8, dtype=jax.numpy.uint32))
    labels = np.asarray(det(karate_slab, keys))
    assert labels.shape == (8, 34)
    assert labels.min() >= 0
    # compacted: ids are 0..k-1
    for row in labels:
        assert set(row) == set(range(row.max() + 1))


def test_lpm_seed_sensitivity_and_determinism(karate_slab):
    a = np.asarray(lpm_single(karate_slab, jax.random.key(0)))
    b = np.asarray(lpm_single(karate_slab, jax.random.key(0)))
    assert (a == b).all()  # same key -> same partition (reproducibility)


def test_lpm_quality_on_karate(karate_slab, karate_truth):
    # LPA on karate is noisy; require decent agreement on the best of a few
    # seeds, mirroring the ensemble usage (never a single run).
    best = 0.0
    for s in range(5):
        labels = np.asarray(lpm_single(karate_slab, jax.random.key(s)))
        best = max(best, nmi(labels, karate_truth))
    assert best > 0.3
