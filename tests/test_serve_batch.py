"""Cross-request ensemble batching (ISSUE 5): coalescing pop, batch
ladder, per-job bit-parity with solo execution, failure isolation,
pre-warm, and result-cache persistence.

Test ORDER in this file is deliberate: the ladder-compile pin runs
before the parity tests so its cold counts are honest, and the later
engine tests reuse the executables it compiled (same bucket statics +
same n_p/tau/delta — max_rounds and seeds are traced and free)."""

import os
import threading
from collections import deque

import numpy as np
import pytest


def _ring_graph(n, chords=0, shift=7):
    idx = np.arange(n)
    edges = [np.stack([idx, (idx + 1) % n], 1)]
    if chords:
        c = np.arange(chords)
        edges.append(np.stack([c % n, (c + shift) % n], 1))
    return np.concatenate(edges).astype(np.int64)


# Four distinct graphs that all land in the n64_e96 bucket (canonical
# edge counts 68 / 78 / 66 / 72 — verified same class).
def _bucket_graphs():
    return [(_ring_graph(34, 40), 34),
            (_ring_graph(40, 38, shift=5), 40),
            (_ring_graph(33, 52, shift=13), 33),
            (_ring_graph(36, 44, shift=11), 36)]


def _spec(edges, n_nodes, priority=None, weights=None, **over):
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.jobs import PRIORITY_NORMAL, JobSpec

    kwargs = dict(algorithm="louvain", n_p=4, tau=0.2, delta=0.02,
                  max_rounds=2, seed=0)
    kwargs.update(over)
    return JobSpec(edges=np.asarray(edges, dtype=np.int64),
                   n_nodes=n_nodes, config=ConsensusConfig(**kwargs),
                   weights=weights,
                   priority=PRIORITY_NORMAL if priority is None
                   else priority)


# -- ladder / grouping (pure host) ------------------------------------


def test_batch_rung_ladder():
    from fastconsensus_tpu.serve.bucketer import BATCH_LADDER, batch_rung

    assert BATCH_LADDER == (1, 2, 4, 8)
    assert [batch_rung(n) for n in (1, 2, 3, 4, 5, 6, 7, 8, 9, 100)] == \
        [1, 2, 2, 4, 4, 4, 4, 8, 8, 8]
    assert batch_rung(0) == 1


def test_bucket_from_key_roundtrip_and_rejects():
    from fastconsensus_tpu.serve.bucketer import (Bucket, bucket_for,
                                                  bucket_from_key)

    b = bucket_for(34, 78)
    assert bucket_from_key(b.key()) == b
    assert bucket_from_key("n64_e96") == Bucket(64, 96)
    with pytest.raises(ValueError):
        bucket_from_key("n64_e97")     # off-grid class
    with pytest.raises(ValueError):
        bucket_from_key("64x96")       # malformed


def test_probe_edges_land_exactly_in_bucket():
    from fastconsensus_tpu.serve.bucketer import (bucket_for,
                                                  bucket_from_key,
                                                  probe_edges)
    from fastconsensus_tpu.serve.jobs import canonical_edges

    for key in ("n64_e64", "n64_e96", "n128_e96", "n1024_e6144"):
        bucket = bucket_from_key(key)
        seen = set()
        for variant in range(3):
            edges = probe_edges(bucket, variant=variant)
            u, v, _ = canonical_edges(edges, bucket.n_class, None)
            assert int(u.shape[0]) == bucket.e_class, key
            assert bucket_for(bucket.n_class, int(u.shape[0])) == bucket
            content = tuple(map(tuple, np.stack([u, v], 1)))
            assert content not in seen  # variants genuinely differ
            seen.add(content)


def test_batch_group_excludes_seed_only():
    from fastconsensus_tpu.serve.jobs import Job

    edges, n = _bucket_graphs()[0]
    g1 = Job(_spec(edges, n, seed=1)).spec.batch_group()
    g2 = Job(_spec(edges, n, seed=2)).spec.batch_group()
    assert g1 == g2                    # seed is traced, coalesces
    g3 = Job(_spec(edges, n, seed=1, n_p=8)).spec.batch_group()
    assert g3 != g1                    # any other config field splits
    big = _ring_graph(200, 100)
    g4 = Job(_spec(big, 200, seed=1)).spec.batch_group()
    assert g4 != g1                    # different bucket splits


def test_pop_batch_coalesces_same_group_without_priority_starvation():
    """The head pop stays strict (priority, seq); coalescing only pulls
    same-group ride-alongs; different-group higher-priority work is
    never skipped as a head."""
    from fastconsensus_tpu.serve.jobs import (PRIORITY_BATCH,
                                              PRIORITY_INTERACTIVE, Job)
    from fastconsensus_tpu.serve.queue import AdmissionQueue

    graphs = _bucket_graphs()
    q = AdmissionQueue(max_depth=16)
    group = [Job(_spec(e, n, seed=i, priority=PRIORITY_BATCH))
             for i, (e, n) in enumerate(graphs)]
    other = Job(_spec(_ring_graph(200, 100), 200, seed=9,
                      priority=PRIORITY_INTERACTIVE))
    for j in group[:2]:
        q.submit(j)
    q.submit(other)
    for j in group[2:]:
        q.submit(j)
    gk = lambda job: job.spec.batch_group()  # noqa: E731
    first = q.pop_batch(8, gk)
    # the interactive job is the strict head; nothing shares its group
    assert [j.job_id for j in first] == [other.job_id]
    second = q.pop_batch(8, gk)
    # the batch-priority group coalesces FIFO by admission order
    assert [j.job_id for j in second] == [j.job_id for j in group]
    # cap respected
    for j in group:
        q.submit(j)
    capped = q.pop_batch(2, gk)
    assert len(capped) == 2
    assert q.depth() == 2
    q.close()
    while q.pop_batch(8, gk) is not None:
        pass
    assert q.pop_batch(8, gk) is None  # drain-complete signal


def test_cache_spill_and_reload_roundtrip(tmp_path):
    from fastconsensus_tpu.serve.cache import ResultCache

    now = [100.0]
    c = ResultCache(max_entries=8, ttl_seconds=50.0, clock=lambda: now[0])
    fresh = {"content_hash": "aaa", "rounds": 3, "converged": True,
             "cached": False,
             "partitions": [np.arange(5, dtype=np.int32),
                            np.ones(5, dtype=np.int32)]}
    c.put("aaa", fresh)
    now[0] = 130.0
    c.put("bbb", dict(fresh, content_hash="bbb"))
    c.put("skipme", "not-a-result-payload")  # non-standard: skipped
    path = str(tmp_path / "cache.npz")
    assert c.spill(path) == 2
    # a restarted process: fresh cache, fresh (shifted) clock
    now2 = [7.0]
    c2 = ResultCache(max_entries=8, ttl_seconds=50.0,
                     clock=lambda: now2[0])
    assert c2.load(path) == 2
    got = c2.get("aaa")
    assert got["rounds"] == 3 and got["converged"] is True
    assert np.array_equal(got["partitions"][0], fresh["partitions"][0])
    # TTL persists as REMAINING lifetime: "aaa" was 30s old at spill,
    # so it expires 20s into the new process's clock
    now2[0] = 7.0 + 21.0
    assert c2.get("aaa") is None
    assert c2.get("bbb") is not None
    # corrupt file loads nothing, does not raise
    bad = str(tmp_path / "bad.npz")
    with open(bad, "wb") as fh:
        fh.write(b"garbage")
    c3 = ResultCache(max_entries=8, ttl_seconds=50.0)
    assert c3.load(bad) == 0


# -- engine: ladder compile pin + bit-parity --------------------------


def test_batch_ladder_compiles_once_per_rung(monkeypatch):
    """ISSUE 5 acceptance: the {1, 2, 4} ladder rungs each compile on
    first use and compile ZERO on warm replay with DIFFERENT same-bucket
    graphs/seeds (rung 8 rides the same vmapped wrapper — covered by
    the slow marker's B=8 path in bench.py serve_batch)."""
    import jax

    from fastconsensus_tpu.analysis import CompileGuard, \
        assert_max_compiles
    from fastconsensus_tpu.consensus import (ConsensusConfig,
                                             run_consensus,
                                             run_consensus_batch)
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.serve import bucketer

    # the resident server's sizing posture: stable executables
    monkeypatch.setenv("FCTPU_DETECT_CALL_MEMBERS", "0")
    monkeypatch.setenv("FCTPU_ROUNDS_BLOCK", "8")
    graphs = _bucket_graphs()
    slabs, bucket = [], None
    for e, n in graphs:
        s, bucket = bucketer.pad_to_bucket(e, n)
        slabs.append(s)
    cfg = ConsensusConfig(algorithm="louvain", n_p=4, tau=0.2,
                          delta=0.02, max_rounds=2, seed=0)
    det = get_detector("louvain")
    nc = bucket.n_closure
    cold_counts = {}
    for rung in (1, 2, 4):
        with CompileGuard() as g:
            if rung == 1:
                run_consensus(slabs[0], det, cfg,
                              key=jax.random.key(0), n_closure=nc)
            else:
                run_consensus_batch(slabs[:rung], det, cfg,
                                    n_closure=nc,
                                    seeds=list(range(rung)))
        cold_counts[rung] = g.count
        assert g.count > 0, f"rung {rung} compiled nothing cold?"
    # warm replay: different graphs (rotated), different seeds -> 0
    for rung in (1, 2, 4):
        with assert_max_compiles(0):
            if rung == 1:
                run_consensus(slabs[1], det, cfg,
                              key=jax.random.key(5), n_closure=nc)
            else:
                rot = slabs[1:] + slabs[:1]
                run_consensus_batch(rot[:rung], det, cfg,
                                    n_closure=nc,
                                    seeds=[7 + i for i in range(rung)])


def test_batch_bit_parity_with_solo_warm(monkeypatch):
    """ISSUE 5 acceptance: every job in a coalesced batch produces
    partitions identical to running it alone at the same seed — across
    early convergence, batched stagnation refreshes, and the final
    re-detection (the PRNG tree keys per job, never per batch)."""
    import jax

    from fastconsensus_tpu.consensus import (ConsensusConfig,
                                             run_consensus,
                                             run_consensus_batch)
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve import bucketer

    monkeypatch.setenv("FCTPU_DETECT_CALL_MEMBERS", "0")
    monkeypatch.setenv("FCTPU_ROUNDS_BLOCK", "8")
    graphs = _bucket_graphs()
    slabs, bucket = [], None
    for e, n in graphs:
        s, bucket = bucketer.pad_to_bucket(e, n)
        slabs.append(s)
    # max_rounds=10: the ring graphs' warm runs hit stagnation refreshes
    # around rounds 7-8 and convergence at 8-9 (measured), so this
    # window exercises refresh masking AND early-converged freezing
    cfg = ConsensusConfig(algorithm="louvain", n_p=4, tau=0.2,
                          delta=0.02, max_rounds=10, seed=0)
    det = get_detector("louvain")
    nc = bucket.n_closure
    seeds = [11, 22, 33, 44]
    solo = [run_consensus(s, det, cfg, key=jax.random.key(sd),
                          n_closure=nc)
            for s, sd in zip(slabs, seeds)]
    base = obs_counters.get_registry().counters()
    batch = run_consensus_batch(slabs, det, cfg, n_closure=nc,
                                seeds=seeds)
    since = obs_counters.get_registry().counters_since(base)
    assert since.get("batch.solo_splits", 0) == 0, \
        "nothing here should fall off the batched path"
    rounds = [r.rounds for r in batch]
    assert len(set(rounds)) > 1, \
        f"want convergence at different rounds to exercise masking, " \
        f"got {rounds}"
    for i, (a, b) in enumerate(zip(solo, batch)):
        assert a.rounds == b.rounds, (i, a.rounds, b.rounds)
        assert a.converged == b.converged, i
        assert a.history == b.history, i
        for p, q in zip(a.partitions, b.partitions):
            assert np.array_equal(p, q), f"job {i}: partition mismatch"


def test_batch_bit_parity_with_solo_scratch():
    """warm_start=False (the reference's only mode): the all-cold
    scratch block must match solo round for round too."""
    import jax

    from fastconsensus_tpu.consensus import (ConsensusConfig,
                                             run_consensus,
                                             run_consensus_batch)
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.serve import bucketer

    graphs = _bucket_graphs()[:2]
    slabs, bucket = [], None
    for e, n in graphs:
        s, bucket = bucketer.pad_to_bucket(e, n)
        slabs.append(s)
    cfg = ConsensusConfig(algorithm="louvain", n_p=4, tau=0.2,
                          delta=0.02, max_rounds=3, seed=0,
                          warm_start=False)
    det = get_detector("louvain")
    nc = bucket.n_closure
    seeds = [5, 6]
    solo = [run_consensus(s, det, cfg, key=jax.random.key(sd),
                          n_closure=nc)
            for s, sd in zip(slabs, seeds)]
    batch = run_consensus_batch(slabs, det, cfg, n_closure=nc,
                                seeds=seeds)
    for i, (a, b) in enumerate(zip(solo, batch)):
        assert a.history == b.history, i
        for p, q in zip(a.partitions, b.partitions):
            assert np.array_equal(p, q), f"job {i}: partition mismatch"


# -- serving layer: isolation, metadata, pre-warm ---------------------


@pytest.fixture
def service():
    from fastconsensus_tpu.serve.server import ConsensusService, \
        ServeConfig

    return ConsensusService(ServeConfig(queue_depth=8, pin_sizing=False,
                                        max_batch=4))


def test_batch_failure_isolation_and_metadata(service):
    """One NaN-weight graph in a coalesced group of 4 -> exactly 1
    failed job, 3 completed (2 batched at rung 2 + 1 solo), with
    batch_id/batch_size surfaced on /status and the serve.batch.*
    counters moving."""
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.jobs import Job

    graphs = _bucket_graphs()
    w_nan = np.ones(graphs[1][0].shape[0], dtype=np.float32)
    w_nan[3] = np.nan
    jobs = [Job(_spec(graphs[0][0], graphs[0][1], seed=1)),
            Job(_spec(graphs[1][0], graphs[1][1], seed=2,
                      weights=w_nan)),
            Job(_spec(graphs[2][0], graphs[2][1], seed=3)),
            Job(_spec(graphs[3][0], graphs[3][1], seed=4))]
    base = obs_counters.get_registry().counters()
    service._run_batch(jobs)
    since = obs_counters.get_registry().counters_since(base)
    states = [j.state for j in jobs]
    assert states[1] == "failed" and "non-finite" in jobs[1].error
    assert [s for i, s in enumerate(states) if i != 1] == ["done"] * 3
    # 3 survivors -> rung 2 batched + 1 solo (the ladder pin holds
    # through pack failures)
    sizes = sorted(j.batch_size for i, j in enumerate(jobs) if i != 1)
    assert sizes == [1, 2, 2], sizes
    coalesced = [j for j in jobs if j.batch_size == 2]
    assert coalesced[0].batch_id == coalesced[1].batch_id
    for j in coalesced:
        d = j.describe()
        assert d["batch_id"] == j.batch_id and d["batch_size"] == 2
        assert j.result["batch_id"] == j.batch_id
        assert j.result["batch_size"] == 2
    assert since.get("serve.batch.coalesced", 0) == 1
    assert since.get("serve.batch.occupancy", 0) == 2
    assert since.get("serve.jobs.failed", 0) == 1
    assert since.get("serve.jobs.completed", 0) == 3


def test_batched_results_match_solo_service_results(service):
    """Service-level parity: the batched worker path returns the same
    partitions the solo run_spec path returns for the same specs."""
    from fastconsensus_tpu.serve.jobs import Job

    graphs = _bucket_graphs()[:2]
    specs = [_spec(e, n, seed=50 + i)
             for i, (e, n) in enumerate(graphs)]
    solo = [service.run_spec(s) for s in specs]
    service.cache._entries.clear()  # force real re-execution
    jobs = [Job(s) for s in specs]
    service._run_batch(jobs)
    for job, ref in zip(jobs, solo):
        assert job.state == "done", job.error
        assert len(job.result["partitions"]) == len(ref["partitions"])
        for p, q in zip(job.result["partitions"], ref["partitions"]):
            assert np.array_equal(p, q)


def test_worker_coalesces_queued_burst():
    """End-to-end: jobs queued before the worker starts pop as ONE
    coalesced batch; results land per job and the queue counter moves."""
    import time

    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.server import ConsensusService, \
        ServeConfig

    svc = ConsensusService(ServeConfig(queue_depth=8, pin_sizing=False,
                                       max_batch=4))
    graphs = _bucket_graphs()
    base = obs_counters.get_registry().counters()
    jobs = [svc.submit(_spec(e, n, seed=80 + i))
            for i, (e, n) in enumerate(graphs)]
    svc.start()
    try:
        deadline = time.monotonic() + 180
        while any(j.state not in ("done", "failed") for j in jobs):
            assert time.monotonic() < deadline, \
                [j.describe() for j in jobs]
            time.sleep(0.02)
        assert all(j.state == "done" for j in jobs), \
            [j.error for j in jobs]
        assert all(j.batch_size == 4 for j in jobs)
        since = obs_counters.get_registry().counters_since(base)
        assert since.get("serve.queue.coalesced_pops", 0) >= 1
        assert since.get("serve.batch.coalesced", 0) >= 1
        assert since.get("serve.batch.occupancy", 0) >= 4
    finally:
        assert svc.drain(30)


def test_prewarm_then_zero_compiles(monkeypatch):
    """--warm contract: after pre-warming a bucket's ladder, a request
    landing in it (solo or coalesced) compiles NOTHING."""
    from fastconsensus_tpu.analysis import assert_max_compiles
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.jobs import Job
    from fastconsensus_tpu.serve.server import ConsensusService, \
        ServeConfig

    monkeypatch.setenv("FCTPU_DETECT_CALL_MEMBERS", "0")
    monkeypatch.setenv("FCTPU_ROUNDS_BLOCK", "8")
    # n_p=5: executables distinct from every other test in this module,
    # so the pre-warm is genuinely the first compile of these shapes
    svc = ConsensusService(ServeConfig(
        pin_sizing=False, max_batch=4, prewarm=("n64_e96:2",),
        prewarm_config={"n_p": 5, "max_rounds": 2}))
    base = obs_counters.get_registry().counters()
    svc._prewarm_all()
    since = obs_counters.get_registry().counters_since(base)
    assert since.get("serve.prewarm.compiles", 0) > 0
    assert since.get("serve.prewarm.buckets", 0) == 1
    assert svc._prewarm_finished
    graphs = _bucket_graphs()
    with assert_max_compiles(0):
        r = svc.run_spec(_spec(graphs[0][0], graphs[0][1], n_p=5))
    assert r["bucket"]["key"] == "n64_e96"
    jobs = [Job(_spec(e, n, seed=60 + i, n_p=5))
            for i, (e, n) in enumerate(graphs[:2])]
    with assert_max_compiles(0):
        svc._run_batch(jobs)
    assert all(j.state == "done" for j in jobs)


def test_worker_drain_group_answers_cache_hits(service):
    """A coalesced pop whose members were answered meanwhile must fan
    the cache hits out without a device call for them."""
    from fastconsensus_tpu.serve.jobs import Job

    edges, n = _bucket_graphs()[0]
    spec = _spec(edges, n, seed=99)
    ref = service.run_spec(spec)           # fills the cache
    j1, j2 = Job(spec), Job(_spec(edges, n, seed=98))
    service._drain_group(deque([j1, j2]))
    assert j1.state == "done" and j1.result["cached"]
    assert np.array_equal(j1.result["partitions"][0],
                          ref["partitions"][0])
    assert j2.state == "done" and not j2.result["cached"]
    assert j2.batch_size == 1              # solo remainder
