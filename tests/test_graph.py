"""GraphSlab packing, degrees/strengths, and edgelist I/O."""

import os
import tempfile

import numpy as np
import pytest

from fastconsensus_tpu.graph import GraphSlab, host_edges, pack_edges
from fastconsensus_tpu.utils.io import (labels_to_communities, read_edgelist,
                                        read_partition_file,
                                        write_partition_dirs)


def test_pack_karate(karate_slab):
    assert karate_slab.n_nodes == 34
    assert int(karate_slab.num_alive()) == 78
    u, v, w = host_edges(karate_slab)
    assert np.all(u < v)
    assert np.all(w == 1.0)
    deg = np.asarray(karate_slab.degrees())
    assert deg.sum() == 2 * 78
    assert deg[0] == 16 and deg[33] == 17  # the two hubs


def test_pack_dedup_and_selfloops():
    edges = np.array([[0, 1], [1, 0], [1, 1], [2, 1], [0, 1]])
    slab = pack_edges(edges, n_nodes=3)
    u, v, w = host_edges(slab)
    assert sorted(zip(u.tolist(), v.tolist())) == [(0, 1), (1, 2)]


def test_strengths_weighted():
    edges = np.array([[0, 1], [1, 2]])
    slab = pack_edges(edges, 3, weights=np.array([2.0, 3.0]))
    s = np.asarray(slab.strengths())
    assert np.allclose(s, [2.0, 5.0, 3.0])


def test_capacity_padding():
    edges = np.array([[0, 1]])
    slab = pack_edges(edges, 2, capacity=8)
    assert slab.capacity == 8
    assert int(slab.num_alive()) == 1


def test_read_edgelist_formats(tmp_path):
    p = tmp_path / "g.txt"
    p.write_text("# comment\n10 20\n20 30 2.5\n\n10 30\n")
    edges, weights, ids = read_edgelist(str(p))
    assert ids.tolist() == [10, 20, 30]
    assert edges.tolist() == [[0, 1], [1, 2], [0, 2]]
    assert weights is not None and np.allclose(weights, [1.0, 2.5, 1.0])


def test_read_edgelist_unweighted(tmp_path):
    p = tmp_path / "g.txt"
    p.write_text("0 1\n1 2\n")
    edges, weights, ids = read_edgelist(str(p))
    assert weights is None
    assert len(ids) == 3


def test_labels_to_communities():
    labels = np.array([5, 5, 2, 2, 9])
    comms = labels_to_communities(labels)
    assert comms == [[0, 1], [2, 3], [4]]


def test_partition_writers_roundtrip(tmp_path):
    ids = np.array([100, 200, 300, 400])
    labels = np.array([0, 0, 1, 1])
    out = str(tmp_path / "parts")
    mem = str(tmp_path / "mems")
    write_partition_dirs(out, mem, [labels], ids)
    comms = read_partition_file(os.path.join(out, "1"))
    assert comms == [[100, 200], [300, 400]]
    # memberships use 1-indexed compact ids regardless of original ids
    lines = open(os.path.join(mem, "0")).read().splitlines()
    assert lines[0] == "1\t1" and lines[2] == "3\t2"


def test_compact_alive_preserves_edges():
    from fastconsensus_tpu.graph import compact_alive
    import jax.numpy as jnp

    edges = np.array([[0, 1], [1, 2], [2, 3], [3, 4], [0, 4]])
    slab = pack_edges(edges, n_nodes=5)  # capacity 2*5+16 = 26
    # kill one edge to make the alive set non-prefix
    alive = np.asarray(slab.alive).copy()
    alive[1] = False
    import dataclasses
    slab = dataclasses.replace(slab, alive=jnp.asarray(alive))
    c = compact_alive(slab, 8)
    assert c.capacity == 8
    assert int(c.num_alive()) == 4
    got = sorted(zip(np.asarray(c.src)[:4].tolist(),
                     np.asarray(c.dst)[:4].tolist()))
    u, v, w = host_edges(slab)
    assert got == sorted(zip(u.tolist(), v.tolist()))
    # compact slab carries no dense/hybrid sizing; cap_hint tracks cap
    assert (c.d_cap, c.d_hyb, c.hub_cap, c.agg_cap) == (0, 0, 0, 0)
    assert c.cap_hint == 8
    # weights survive, dead tail is inert
    assert np.asarray(c.weight)[:4].sum() == w.sum()
    assert not np.asarray(c.alive)[4:].any()


def test_compact_alive_overflow_drops_tail_ranks():
    from fastconsensus_tpu.graph import compact_alive

    edges = np.array([[i, i + 1] for i in range(10)])
    slab = pack_edges(edges, n_nodes=11)
    c = compact_alive(slab, 6)
    assert int(c.num_alive()) == 6
    # first six alive ranks kept, in slot order
    assert np.asarray(c.src)[:6].tolist() == list(range(6))


def test_derive_agg_sizing_bounds():
    from fastconsensus_tpu.graph import derive_agg_sizing

    assert derive_agg_sizing(0) == 0
    for e in (100, 58_712, 313_765):
        cap = derive_agg_sizing(e)
        assert cap >= e            # lossless at derivation time
        assert cap % 4096 == 0
        assert cap <= e + e // 8 + 1024 + 4096  # tight slack


def test_members_per_call_grid_quantization(monkeypatch):
    """Call sizing must land on the {2^k, 3*2^k} shape grid (round 5):
    raw rate-derived counts compiled a fresh executable per run."""
    from fastconsensus_tpu import sizing

    edges = np.array([[i, i + 1] for i in range(200)])
    slab = pack_edges(edges, 201)
    monkeypatch.delenv("FCTPU_DETECT_CALL_MEMBERS", raising=False)
    grid = {1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128}
    for per in (0.9, 1.3, 1.9, 3.7, 0.37, 0.25, 0.16):
        m = sizing.members_per_call(slab, 100, measured_s=per)
        assert m in grid or m == 100, (per, m)
    # whole-ensemble calls pass through un-snapped (stable shape already)
    assert sizing.members_per_call(slab, 7, measured_s=0.01) == 7
