"""Golden wire-schema test (fcheck-contract, ISSUE 14): snapshot a
LIVE loopback server's ``/metricsz`` / ``/healthz`` / ``/status`` /
``/debugz/slowest`` payloads after real traffic, then validate them
field-for-field against the typed client parsers in a subprocess where
any jax import raises — pinning that (a) every field the server emits
is consumed by the matching parser (no silently-dropped keys), (b) the
parsers run jax-free, and (c) the live metric names union cleanly with
the committed static writer inventory (``runs/contract_r19.json``)."""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def wire_snapshots(karate_edges):
    """Raw endpoint payloads from a live loopback server that ran one
    real job (so timing/quality/latency/flight blocks are populated)."""
    from fastconsensus_tpu.serve.client import ServeClient
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig,
                                                make_http_server)

    edges, _, ids = karate_edges
    svc = ConsensusService(ServeConfig(queue_depth=4, pin_sizing=False))
    httpd = make_http_server(svc, "127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    svc.start()
    client = ServeClient(f"http://127.0.0.1:{port}", timeout=30.0)
    try:
        sub = client.submit(edges=edges.tolist(), n_nodes=len(ids),
                            algorithm="lpm", n_p=4, delta=0.1,
                            max_rounds=2, seed=1)
        client.wait(sub["job_id"], timeout=300)
        snaps = {
            "healthz": client.healthz(),
            "metricsz": client.metricsz(),
            "status": client.status(sub["job_id"]),
            "slowest": client._request("/debugz/slowest"),
        }
    finally:
        httpd.shutdown()
        httpd.server_close()
        assert svc.drain(30)
    return snaps


def test_snapshots_carry_the_full_observability_surface(wire_snapshots):
    """The fixture traffic must light up every block the golden check
    below validates — an empty block would vacuously pass."""
    m = wire_snapshots["metricsz"]
    assert wire_snapshots["healthz"]["workers"]
    assert m["latency"]["histograms"]
    assert m["fcobs"]["counters"]
    assert "shaping" in m and "devices" in m
    assert wire_snapshots["status"].get("timing") is not None


_VALIDATOR = textwrap.dedent("""\
    import json
    import sys

    sys.modules["jax"] = None   # any jax import now raises ImportError

    snap_path, repo = sys.argv[1], sys.argv[2]
    sys.path.insert(0, repo)
    with open(snap_path, encoding="utf-8") as fh:
        snaps = json.load(fh)

    from fastconsensus_tpu.analysis import contracts
    from fastconsensus_tpu.serve import client as sc

    cpath = repo + "/fastconsensus_tpu/serve/client.py"
    with open(cpath, encoding="utf-8") as fh:
        facts = contracts._scan_module(cpath, fh.read())
    parser_keys = {cls: keys for cls, (_, keys) in facts.parsers.items()}

    def field_for_field(cls_name, payload):
        extra = sorted(set(payload) - parser_keys[cls_name])
        assert not extra, (
            f"{cls_name} silently drops live field(s) {extra} — "
            f"consume them in from_payload or stop emitting them")

    h = snaps["healthz"]
    assert h["workers"], "no workers in /healthz"
    for w in h["workers"]:
        sc.WorkerState.from_payload(w)
        field_for_field("WorkerState", w)

    m = snaps["metricsz"]
    lat = m["latency"]
    assert lat["histograms"], "no latency histograms after a real job"
    for row in lat["histograms"]:
        sc.PhaseLatency.from_payload(row)
        field_for_field("PhaseLatency", row)
    for name, row in (lat.get("slo") or {}).items():
        sc.SloStats.from_payload(name, row)
        field_for_field("SloStats", row)
    shaping = m["shaping"]
    sc.ShapingStats.from_payload(shaping)
    field_for_field("ShapingStats", shaping)
    field_for_field("ShapingStats", shaping.get("counters") or {})

    st = snaps["status"]
    timing = st["timing"]
    sc.JobTiming.from_payload(timing)
    field_for_field("JobTiming", timing)
    quality = st.get("quality")
    if quality is not None:
        sc.JobQuality.from_payload(quality)
        field_for_field("JobQuality", quality)

    for row in snaps["slowest"].get("slowest") or ():
        sc.SlowJobExemplar.from_payload(row)
        field_for_field("SlowJobExemplar", row)

    # runtime half of the contract: live names vs the committed
    # static writer inventory
    inv_path = repo + "/runs/contract_r19.json"
    n = contracts.assert_covered(m, inv_path)
    assert n >= 10, f"suspiciously few live metrics ({n})"

    # every top-level endpoint field is a known wire key
    inv = contracts.load_inventory(inv_path)
    wire = set(inv["wire_keys"])
    for ep in ("healthz", "metricsz", "status"):
        unknown = sorted(k for k in snaps[ep] if k not in wire)
        assert not unknown, (
            f"/{ep} emits top-level field(s) {unknown} missing from "
            f"the wire-key universe — regenerate the inventory")
    print(f"wire schema golden: {n} live metric name(s) covered")
    """)


def test_typed_parsers_cover_live_payloads_jax_free(wire_snapshots,
                                                    tmp_path):
    snap_path = tmp_path / "wire_snapshots.json"
    snap_path.write_text(json.dumps(wire_snapshots))
    proc = subprocess.run(
        [sys.executable, "-c", _VALIDATOR, str(snap_path), REPO],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "wire schema golden" in proc.stdout
