"""fcheck-footprint: liveness sweep, ladder mirrors, surface/padding
rules, fixture postures, the derived chip ceiling, and the serve-side
warm-spec validation that rides on it."""

import os

import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


# -- jax-free half: grid mirrors, enumeration, padding -----------------


def test_grid_mirror_matches_sizing():
    """footprint.py mirrors sizing.grid_up / bucketer / graph sizing
    locally (the pre-commit hook must not import jax); the mirrors must
    track the real functions exactly."""
    from fastconsensus_tpu import sizing
    from fastconsensus_tpu.analysis import footprint as fp
    from fastconsensus_tpu.serve import bucketer

    for v in list(range(1, 600)) + [4095, 4096, 4097, 1 << 20,
                                    (1 << 20) + 1, 3 << 19]:
        assert fp.grid_up(v) == sizing.grid_up(v), v
        assert fp.grid_up(v, 64) == sizing.grid_up(v, 64), v
    for e in (64, 96, 313, 5000):
        b = bucketer.bucket_for(64, e)
        assert fp.bucket_capacity(b.e_class) == b.capacity
        assert fp.bucket_agg_cap(b.e_class) == b.agg_cap
    assert fp.BATCH_RUNGS == bucketer.BATCH_LADDER
    assert fp.MIN_NODE_CLASS == bucketer.MIN_NODE_CLASS
    assert fp.MIN_EDGE_CLASS == bucketer.MIN_EDGE_CLASS
    from fastconsensus_tpu.models.louvain import MATMUL_MAX_N

    assert fp.MATMUL_MAX_N == MATMUL_MAX_N


def test_surface_spec_mirrors_serve_defaults():
    """The default posture must be the one ServeConfig actually serves —
    a drifted mirror would gate a surface nobody runs."""
    from fastconsensus_tpu.analysis import footprint as fp
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.server import ServeConfig

    spec, cfg = fp.SurfaceSpec(), ServeConfig()
    assert spec.max_nodes == cfg.max_nodes
    assert spec.max_edges == cfg.max_edges
    assert spec.max_batch == cfg.max_batch
    assert spec.n_p == ConsensusConfig().n_p


def test_prev_class_closed_form():
    from fastconsensus_tpu.analysis import footprint as fp

    for minimum in (1, 64):
        grid = fp.grid_values(minimum, 1 << 14)
        for lo, hi in zip(grid, grid[1:]):
            assert fp.prev_class(hi, minimum) == lo, (minimum, lo, hi)
        assert fp.prev_class(grid[0], minimum) is None


def test_surface_enumeration_and_budget_rule():
    from fastconsensus_tpu.analysis import footprint as fp

    spec = fp.SurfaceSpec()
    count = fp.surface_count(spec)
    # the CI pin: the default posture must fit its own budget with
    # headroom, and doubling it (a new static axis) must NOT
    assert count <= fp.SURFACE_BUDGET_DEFAULT < 2 * count
    assert not fp.check_surface(spec)
    # an unreachable corner is excluded: 4M edges cannot land on a
    # 64-node bucket (the complete graph caps at ~2k edges)
    assert (64, fp.grid_up(spec.max_edges)) not in \
        fp.surface_buckets(spec)
    tiny = fp.SurfaceSpec(surface_budget=10)
    diags = fp.check_surface(tiny)
    assert len(diags) == 1 and diags[0].rule == "surface-count"
    assert str(count) in diags[0].message


def test_padding_rule_defaults_clean_gaps_fire():
    from fastconsensus_tpu.analysis import footprint as fp

    spec = fp.SurfaceSpec()
    # the {2^k, 3*2^k} geometry bounds worst-case waste under 50%
    assert fp.max_pad_fraction(spec) < 0.5
    assert not fp.check_padding(spec)
    # floor buckets are exempt (deliberate floors, unbounded waste)
    assert fp.pad_fraction(fp.MIN_NODE_CLASS, fp.MIN_EDGE_CLASS) is None
    gappy = fp.SurfaceSpec(grid=(64, 96, 128, 1024))
    diags = fp.check_padding(gappy)
    assert diags and all(d.rule == "padding-waste" for d in diags)
    assert "e1024" in diags[0].message


def test_fixture_specs_fire_their_rule_only():
    """The bad_/ok_ FOOTPRINT_SPEC fixtures drive each rule in
    isolation through the same evaluate() path the CLI uses."""
    from fastconsensus_tpu.analysis import footprint as fp

    def run(name):
        specs = fp.find_specs([os.path.join(FIXTURES, name)])
        assert len(specs) == 1, name
        diags, _ = fp.evaluate(specs[0])
        return {d.rule for d in diags}

    assert run("bad_surface_budget.py") == {"surface-count"}
    assert run("ok_surface_budget.py") == set()
    assert run("bad_padding_ladder.py") == {"padding-waste"}
    assert run("ok_padding_ladder.py") == set()
    assert run("bad_footprint_budget.py") == {"jaxpr-peak-bytes"}
    assert run("ok_footprint_budget.py") == set()


def test_find_specs_rejects_junk(tmp_path):
    from fastconsensus_tpu.analysis import footprint as fp

    (tmp_path / "bad.py").write_text("FOOTPRINT_SPEC = {'no_such': 1}\n")
    with pytest.raises(ValueError, match="no_such"):
        fp.find_specs([str(tmp_path)])


# -- the liveness sweep ------------------------------------------------


def test_peak_live_bytes_known_high_water():
    """Hand-built jaxpr with a hand-computed high-water mark: x (4 KB,
    non-donated so pinned for the whole program) + a (4 KB) + b (4 KB)
    all live while b materializes -> 12 KB; donating x lets it die
    after its last use -> 8 KB."""
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.analysis.footprint import peak_live_bytes

    def f(x):
        a = x * 2.0     # x, a live
        b = a + 1.0     # a dies after; x pinned unless donated
        return b

    closed = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((1024,), jnp.float32))
    res = peak_live_bytes(closed)
    assert res["peak"] == 3 * 4096
    assert res["arg_bytes"] == 4096 and res["out_bytes"] == 4096
    assert peak_live_bytes(closed, donated=frozenset({0}))["peak"] \
        == 2 * 4096


def test_peak_live_bytes_recurses_into_calls():
    """The peak inside a pjit/scan sub-jaxpr must surface: a jitted
    body materializing a 3x temporary dominates the outer program."""
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.analysis.footprint import peak_live_bytes

    @jax.jit
    def inner(x):
        big = jnp.concatenate([x, x, x])    # 3x temp
        return big.sum()

    closed = jax.make_jaxpr(lambda x: inner(x) + 1.0)(
        jax.ShapeDtypeStruct((1024,), jnp.float32))
    res = peak_live_bytes(closed)
    assert res["peak"] >= 4 * 4096          # x + the 3x concat


def test_peak_monotone_along_ladder_within_regime():
    """The satellite pin: peak bytes are non-decreasing under
    sizing.grid_up WITHIN one detection-path regime (matmul: n <= 1024;
    hash above) — the gate's scan exists precisely because the claim is
    only regime-local (the chunk-budgeted detectors make it false
    globally; see footprint.check_peak_bytes)."""
    from fastconsensus_tpu.analysis import footprint as fp

    spec = fp.SurfaceSpec(n_p=4)
    for regime in (((64, 96), (96, 128), (128, 192)),        # matmul
                   ((2048, 4096), (3072, 6144), (4096, 8192))):  # hash
        peaks = [fp._trace_peak("batch", n, e, 2, "warm", spec)["peak"]
                 for n, e in regime]
        assert peaks == sorted(peaks), (regime, peaks)


# -- the ceiling -------------------------------------------------------


def test_derive_chip_ceiling_small_posture():
    from fastconsensus_tpu.analysis import footprint as fp

    spec = fp.SurfaceSpec(max_nodes=512, max_edges=1024, max_batch=2,
                          n_p=4)
    ladder = fp.edge_classes(spec)
    # a generous budget serves the whole ladder...
    top = fp.derive_chip_ceiling(1 << 30, spec)
    assert top == ladder[-1]
    # ...nothing fits a absurd one...
    assert fp.derive_chip_ceiling(1000, spec) is None
    # ...and a budget equal to the floor bucket's own peak admits at
    # least the floor, lands ON the ladder, and stays monotone in budget
    floor_peak = fp._trace_peak("batch", fp.grid_up(128, 64),
                                ladder[0], 2, "warm", spec)["peak"]
    mid = fp.derive_chip_ceiling(floor_peak, spec)
    assert mid is not None and mid in ladder
    assert mid <= top


# -- serve integration: warm-spec validation & the auto ceiling --------


def test_validate_warm_specs_rejects_bad_postures():
    from fastconsensus_tpu.serve.server import (ServeConfig,
                                                validate_warm_specs)

    ok = ServeConfig(prewarm=("n64_e96:4", "n128_e192"))
    validate_warm_specs(ok)                      # must not raise
    with pytest.raises(ValueError, match="rung"):
        validate_warm_specs(ServeConfig(prewarm=("n64_e96:0",)))
    with pytest.raises(ValueError, match="n<N>_e<E>"):
        validate_warm_specs(ServeConfig(prewarm=("nonsense",)))
    with pytest.raises(ValueError, match="ladder grid"):
        validate_warm_specs(ServeConfig(prewarm=("n65_e96",)))
    # a bucket no admissible request can reach
    with pytest.raises(ValueError, match="admission"):
        validate_warm_specs(ServeConfig(max_edges=64,
                                        prewarm=("n64_e96",)))
    # the ceiling-crossing spec: its traffic runs SOLO sharded on the
    # mesh tier, so the single-chip ladder pre-warm is wasted compiles
    with pytest.raises(ValueError, match="mesh tier"):
        validate_warm_specs(ServeConfig(chip_max_edges=64,
                                        huge_devices=1,
                                        prewarm=("n64_e96",)))


def test_service_start_fails_fast_on_bad_warm_spec():
    """ConsensusService.start() must raise BEFORE building the pool —
    the CLI maps this to exit 2 at startup, not a warm-time log line."""
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    svc = ConsensusService(ServeConfig(max_edges=64,
                                       prewarm=("n64_e96",)))
    with pytest.raises(ValueError, match="admission"):
        svc.start()
    assert svc.pool is None


def test_serve_cli_parses_auto_ceiling():
    from fastconsensus_tpu.serve.__main__ import build_parser

    args = build_parser().parse_args(["--chip-max-edges", "auto",
                                      "--huge-devices", "1",
                                      "--hbm-bytes", "1000000"])
    assert args.chip_max_edges == "auto"
    assert args.hbm_bytes == 1000000


# -- the report block --------------------------------------------------


def test_evaluate_block_schema():
    """The footprint block the --json report and the
    runs/footprint_rNN.json artifact carry (the documented schema
    scripts/bench_report.py consumes)."""
    from fastconsensus_tpu.analysis import footprint as fp

    spec = fp.SurfaceSpec(max_nodes=256, max_edges=512, max_batch=2,
                          n_p=4)
    diags, block = fp.evaluate(spec, with_table=True, with_ceiling=True)
    assert not diags
    assert block["tool"] == "fcheck-footprint" and block["version"] == 1
    assert block["surface_count"] == fp.surface_count(spec)
    assert block["chip_ceiling_edges"] in fp.edge_classes(spec)
    assert block["gate"] and block["buckets"]
    for row in block["buckets"]:
        assert row["peak_bytes"] >= row["solo_peak_bytes"] > 0
        assert set(row) >= {"bucket", "batch", "arg_bytes", "out_bytes",
                            "pad_frac"}
    # jax-free selection never touches the traced half
    d2, b2 = fp.evaluate(fp.SurfaceSpec(),
                         rules=["surface-count", "padding-waste"])
    assert not d2 and b2["gate"] == [] and b2["buckets"] == []
