"""Test configuration: run the suite on an 8-device virtual CPU mesh.

Multi-chip sharding is exercised without TPU hardware the standard JAX way
(SURVEY.md §4): force 8 host-platform devices before jax initializes.
"""

import os

# Force, don't setdefault: the shell environment pins JAX_PLATFORMS to the
# TPU plugin, and running the suite against one real chip (with remote
# compiles) is both slow and a shared-resource hazard.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent compile cache, keyed by a host-CPU fingerprint: an XLA:CPU
# AOT executable loaded on a host with different CPU features can ABORT
# the process (observed: a cache dir shared across machines through this
# container image crashed the suite inside
# compilation_cache.get_executable).  The cache also sidesteps an
# XLA:CPU compiler segfault seen when one process compiles the whole
# suite's kernels back-to-back (cache hits skip those compiles entirely;
# populate a fresh cache with scripts/populate_test_cache.sh, which runs
# one process per test file).
import hashlib  # noqa: E402


def _host_tag() -> str:
    # keep in sync with bench.py:_host_tag — both must run BEFORE any jax
    # import, and every fastconsensus_tpu module imports jax, so a shared
    # helper module cannot host this
    try:
        with open("/proc/cpuinfo") as fh:
            flags = next(line for line in fh if line.startswith("flags"))
        return hashlib.sha1(flags.encode()).hexdigest()[:8]
    except (OSError, StopIteration):
        return "generic"


os.environ["JAX_COMPILATION_CACHE_DIR"] = \
    f"/tmp/fctpu_jax_cache_{_host_tag()}"
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

# No persisted rate calibration under test (utils/calibrate.py): rates
# written by one test run would re-size detection calls in the next,
# coupling outcomes across runs.  Tests that exercise calibration set
# FCTPU_CALIBRATE_DIR to a tmp dir and re-enable explicitly.
os.environ["FCTPU_CALIBRATE"] = "0"

# Flight bundles out of the repo: incident dumps default to ./fcflight
# (obs/postmortem.py), so a worker-death or watchdog test run from the
# checkout would litter the tree.  Tests that assert on bundle paths
# pass ServeConfig(flight_dir=tmp_path) and override this anyway.
os.environ.setdefault(
    "FCTPU_FLIGHT_DIR", f"/tmp/fctpu_flight_{_host_tag()}_{os.getpid()}")

# The TPU-tunnel plugin registers itself from sitecustomize at interpreter
# start (before this file runs) and hijacks backend selection even under
# JAX_PLATFORMS=cpu; drop its factory so the suite can never touch (or hang
# on) the shared TPU tunnel.
try:  # pragma: no cover - environment-specific
    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    # sitecustomize imported jax before this file ran, so the env vars above
    # were already latched into jax.config — re-point them explicitly.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Opt-in lock-order recording (analysis/lockorder.py): FCTPU_LOCK_ORDER=1
# wraps threading.Lock/RLock/Condition for locks created from package
# code, so the whole suite runs with the observed acquisition digraph
# accumulating; the stress test asserts it stays acyclic.  Must install
# BEFORE test modules import serve/obs classes that construct locks.
from fastconsensus_tpu.analysis import lockorder as _lockorder  # noqa: E402

_lockorder.maybe_install_from_env()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: slow statistical / integration tests")


@pytest.fixture(autouse=True, scope="module")
def _release_jax_executables():
    """Drop compiled executables after each test module.

    One pytest process compiling/loading the whole suite's kernels
    accumulates ~65k memory maps and ABORTS at the kernel's default
    vm.max_map_count (65530) — measured: the process died at 64,763 maps,
    always ~64 tests in.  Executables a later module re-needs reload from
    the persistent compile cache, so this costs little.
    """
    yield
    import jax

    jax.clear_caches()


@pytest.fixture(scope="session")
def karate_edges():
    from fastconsensus_tpu.utils.io import read_edgelist

    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "karate_club.txt")
    edges, weights, ids = read_edgelist(path)
    return edges, weights, ids


@pytest.fixture(scope="session")
def karate_slab(karate_edges):
    from fastconsensus_tpu.graph import pack_edges

    edges, _, ids = karate_edges
    return pack_edges(edges, n_nodes=len(ids))


# Zachary karate club ground truth (the two-faction split; Zachary 1977).
KARATE_FACTIONS = np.array(
    [0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 0, 0, 0, 0, 1, 1, 0, 0, 1, 0, 1, 0,
     1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1])


@pytest.fixture(scope="session")
def karate_truth():
    return KARATE_FACTIONS
