"""Test configuration: run the suite on an 8-device virtual CPU mesh.

Multi-chip sharding is exercised without TPU hardware the standard JAX way
(SURVEY.md §4): force 8 host-platform devices before jax initializes.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def karate_edges():
    from fastconsensus_tpu.utils.io import read_edgelist

    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "karate_club.txt")
    edges, weights, ids = read_edgelist(path)
    return edges, weights, ids


@pytest.fixture(scope="session")
def karate_slab(karate_edges):
    from fastconsensus_tpu.graph import pack_edges

    edges, _, ids = karate_edges
    return pack_edges(edges, n_nodes=len(ids))


# Zachary karate club ground truth (the two-faction split; Zachary 1977).
KARATE_FACTIONS = np.array(
    [0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 0, 0, 0, 0, 1, 1, 0, 0, 1, 0, 1, 0,
     1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1])


@pytest.fixture(scope="session")
def karate_truth():
    return KARATE_FACTIONS
