"""fcpool: sticky bucket->device scheduling, worker failure isolation,
the mesh-sharded huge tier, and the per-device observability surface —
all under the suite's forced 8-device virtual CPU mesh (conftest.py),
so every contract here runs in tier-1 without hardware."""

import threading
import time

import numpy as np
import pytest


def _ring(n, chords=0, shift=7):
    idx = np.arange(n)
    edges = [np.stack([idx, (idx + 1) % n], 1)]
    if chords:
        c = np.arange(chords)
        edges.append(np.stack([c % n, (c + shift) % n], 1))
    return np.concatenate(edges).astype(np.int64)


def _spec(edges, n_nodes, **over):
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.jobs import JobSpec

    kwargs = dict(algorithm="louvain", n_p=4, tau=0.2, delta=0.02,
                  max_rounds=2, seed=0)
    kwargs.update(over)
    return JobSpec(edges=np.asarray(edges, dtype=np.int64),
                   n_nodes=n_nodes, config=ConsensusConfig(**kwargs))


def _wait(jobs, timeout=180.0):
    deadline = time.monotonic() + timeout
    for j in jobs:
        while j.state not in ("done", "failed"):
            assert time.monotonic() < deadline, j.describe()
            time.sleep(0.02)


# -- scheduler (unit, jax-free stubs) ----------------------------------


class _StubWorker:
    def __init__(self, idx, load=0, warm=(), cordoned=False):
        self.idx = idx
        self._load = load
        self.warm_buckets = set(warm)
        self.cordoned = cordoned

    def eligible(self, exclude=frozenset()):
        return not self.cordoned and self.idx not in exclude

    def load(self):
        return self._load

    def is_warm(self, bucket):
        return bucket in self.warm_buckets


def test_scheduler_sticky_home_and_spill():
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.scheduler import StickyScheduler

    base = obs_counters.get_registry().counters()
    sched = StickyScheduler(spill_backlog=2)
    ws = [_StubWorker(0), _StubWorker(1), _StubWorker(2)]
    # first route mints the home on the least-loaded worker...
    assert sched.route("b1", ws).idx == 0
    assert sched.affinity() == {"b1": 0}
    # ...and stays sticky while the home's backlog is at the threshold
    ws[0]._load = 2
    assert sched.route("b1", ws).idx == 0
    # past the threshold it spills to the least-loaded other worker
    ws[0]._load = 3
    ws[1]._load = 1
    assert sched.route("b1", ws).idx == 2
    # the home does NOT move on a spill
    assert sched.affinity() == {"b1": 0}
    ws[0]._load = 0
    assert sched.route("b1", ws).idx == 0
    since = obs_counters.get_registry().counters_since(base)
    assert since.get("serve.sched.assigns", 0) == 1
    assert since.get("serve.sched.sticky_hits", 0) == 2
    assert since.get("serve.sched.spills", 0) == 1


def test_scheduler_spill_prefers_warm_workers():
    from fastconsensus_tpu.serve.scheduler import StickyScheduler

    sched = StickyScheduler(spill_backlog=0)
    ws = [_StubWorker(0, load=5), _StubWorker(1, load=3),
          _StubWorker(2, load=4, warm=("b1",))]
    sched.route("b1", [ws[0]])          # home = 0
    # worker 1 is less loaded, but worker 2 already holds b1's
    # executables — spilling there compiles nothing
    assert sched.route("b1", ws).idx == 2


def test_scheduler_cordon_exclusion_and_rehome():
    from fastconsensus_tpu.serve.scheduler import (NoEligibleWorker,
                                                   StickyScheduler)

    sched = StickyScheduler()
    ws = [_StubWorker(0), _StubWorker(1)]
    assert sched.route("b1", ws).idx == 0
    # excluded-for-this-job routing never lands on the excluded device
    assert sched.route("b1", ws, exclude=frozenset({0})).idx == 1
    # a cordoned home re-homes the bucket
    ws[0].cordoned = True
    assert sched.route("b1", ws).idx == 1
    assert sched.affinity() == {"b1": 1}
    ws[1].cordoned = True
    with pytest.raises(NoEligibleWorker):
        sched.route("b1", ws)


# -- sticky affinity through the real pool -----------------------------


def test_same_bucket_burst_lands_on_one_device_zero_foreign_compiles():
    """ISSUE 6 acceptance: a same-bucket burst routes to ONE sticky
    device; every other worker compiles nothing (executables are
    per-device, so any foreign compile means routing leaked)."""
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    svc = ConsensusService(ServeConfig(queue_depth=32, pin_sizing=False,
                                       devices=4)).start()
    base = obs_counters.get_registry().counters()
    try:
        jobs = [svc.submit(_spec(_ring(40, chords=40), 40, seed=s))
                for s in range(1, 5)]
        _wait(jobs)
        assert all(j.state == "done" for j in jobs), \
            [j.error for j in jobs]
        homes = {j.device for j in jobs}
        assert len(homes) == 1, [j.describe() for j in jobs]
        home = homes.pop()
        since = obs_counters.get_registry().counters_since(base)
        for w in svc.pool.chip_workers:
            if w.idx != home:
                assert since.get(
                    f"serve.device.{w.idx}.xla_compiles", 0) == 0, since
        assert svc.stats()["affinity"] == {"n64_e96": home}
    finally:
        assert svc.drain(60)


def test_worker_death_requeues_with_exclusion_and_cordons():
    """A worker that dies mid-batch: its job completes on another
    device, the dead device is cordoned in /healthz, and the job
    carries the exclusion + requeue metadata."""
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    svc = ConsensusService(ServeConfig(queue_depth=8, pin_sizing=False,
                                       devices=2)).start()
    base = obs_counters.get_registry().counters()
    w0 = svc.pool.chip_workers[0]

    def boom(batch):
        raise RuntimeError("injected infrastructure failure")

    w0._run = boom
    try:
        job = svc.submit(_spec(_ring(12, chords=6), 12, seed=3))
        _wait([job])
        assert job.state == "done", job.error
        assert job.device == 1
        assert job.excluded() == frozenset({0})
        assert job.describe()["requeues"] == 1
        stats = svc.stats()
        assert stats["cordoned_devices"] == [0]
        dead = next(w for w in stats["workers"] if w["device"] == 0)
        assert dead["cordoned"] and "injected" in dead["error"]
        since = obs_counters.get_registry().counters_since(base)
        assert since.get("serve.pool.worker_deaths", 0) == 1
        assert since.get("serve.device.0.deaths", 0) == 1
        assert since.get("serve.pool.requeued_jobs", 0) == 1
    finally:
        assert svc.drain(60)


def test_job_that_cordons_every_device_fails_as_itself():
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    svc = ConsensusService(ServeConfig(queue_depth=8, pin_sizing=False,
                                       devices=1)).start()

    def boom(batch):
        raise RuntimeError("dies everywhere")

    svc.pool.chip_workers[0]._run = boom
    try:
        job = svc.submit(_spec(_ring(12, chords=6), 12, seed=4))
        _wait([job])
        assert job.state == "failed"
        assert "no eligible worker" in job.error
    finally:
        svc.drain(30)   # the lone worker is dead; queue still closes


def test_backpressure_counts_worker_backlogs():
    """The 429 contract survives the pool: the dispatcher eagerly moves
    admitted jobs into per-worker deques, and those parked jobs must
    still count against the queue's depth bound — otherwise a depth-1
    queue would absorb an unbounded burst into worker backlogs."""
    from fastconsensus_tpu.serve.queue import QueueFull
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    svc = ConsensusService(ServeConfig(queue_depth=1, pin_sizing=False,
                                       devices=2)).start()
    entered, release = threading.Event(), threading.Event()
    for w in svc.pool.chip_workers:
        orig = w._run

        def slow(batch, _orig=orig):
            entered.set()
            release.wait()
            _orig(batch)

        w._run = slow
    try:
        j1 = svc.submit(_spec(_ring(40, chords=40), 40, seed=11))
        assert entered.wait(60), "worker never picked up the first job"
        j2 = svc.submit(_spec(_ring(40, chords=40), 40, seed=12))
        deadline = time.monotonic() + 30
        while svc.pool.backlog() < 1:   # dispatch is asynchronous
            assert time.monotonic() < deadline
            time.sleep(0.01)
        with pytest.raises(QueueFull):
            svc.submit(_spec(_ring(40, chords=40), 40, seed=13))
    finally:
        release.set()
        _wait([j1, j2])
        assert svc.drain(60)
    assert j1.state == "done" and j2.state == "done", (j1.error, j2.error)


def test_busy_worker_recoalesces_deque_burst_into_one_batch():
    """Stall-then-burst through the pool: while the sticky worker is
    busy, the eager dispatcher parks a same-group burst as single-job
    deque batches — the worker must re-merge them into ONE batched
    device call (serve.pool.deque_coalesced), or PR 5's coalescing
    would only survive a deep admission heap.  Runs the no-hold
    posture deliberately: with fcshape holding on, the dispatcher
    coalesces this burst upstream at the admission heap and the deque
    re-merge layer (still the only coalescer when holds are off or
    bypassed) would go unexercised."""
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)
    from fastconsensus_tpu.serve.shaping import ShapingConfig

    svc = ConsensusService(ServeConfig(
        queue_depth=16, pin_sizing=False, devices=1, max_batch=4,
        shaping=ShapingConfig(hold=False))).start()
    w = svc.pool.chip_workers[0]
    entered, release = threading.Event(), threading.Event()
    orig = w._run

    def slow(batch):
        entered.set()
        release.wait()
        orig(batch)

    w._run = slow
    base = obs_counters.get_registry().counters()
    try:
        # the stall runs a DIFFERENT batch group (n_p=8), so it can
        # never merge with the burst behind it
        stall = svc.submit(_spec(_ring(40, chords=40), 40, n_p=8,
                                 seed=90))
        assert entered.wait(60), "worker never picked up the stall job"
        # park each burst job in the worker's deque before submitting
        # the next: pop_batch coalesces same-group ride-alongs straight
        # off the admission heap whenever MORE than one is queued (hold
        # or no hold), and a burst coalesced upstream would leave the
        # deque re-merge — the layer under test — nothing to do
        burst = []
        for k, s in enumerate((91, 92, 93, 94), start=1):
            burst.append(svc.submit(_spec(_ring(40, chords=40), 40,
                                          seed=s)))
            deadline = time.monotonic() + 30
            while svc.pool.backlog() < k:   # dispatch is asynchronous
                assert time.monotonic() < deadline
                time.sleep(0.005)
    finally:
        release.set()
    try:
        _wait([stall] + burst)
        assert all(j.state == "done" for j in [stall] + burst), \
            [j.error for j in [stall] + burst]
        assert stall.batch_size == 1
        batch_ids = {j.batch_id for j in burst}
        assert len(batch_ids) == 1 and None not in batch_ids, \
            [j.describe() for j in burst]
        assert all(j.batch_size == 4 for j in burst)
        since = obs_counters.get_registry().counters_since(base)
        assert since.get("serve.pool.deque_coalesced", 0) == 3, since
        # every merge happened at the deque, none at the heap
        assert since.get("serve.queue.coalesced_pops", 0) == 0, since
    finally:
        assert svc.drain(120)


# -- the huge tier -----------------------------------------------------


def test_huge_bucket_routes_to_mesh_and_matches_solo_bitwise():
    """ISSUE 6 acceptance: a graph past the single-chip bucket ceiling
    runs edge-sharded on the reserved mesh group, with partitions
    bit-identical to the solo (unsharded) reference at the same seed.
    closure_sampler pinned to "scatter" on both sides — the sharded
    tail requires the sort-free engine (test_parallel.py parity)."""
    from fastconsensus_tpu.consensus import run_consensus
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.serve import bucketer
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    edges = _ring(100, chords=60)   # 160 canonical edges -> n128_e192
    spec = _spec(edges, 100, seed=5, closure_sampler="scatter")
    assert spec.bucket().key() == "n128_e192"
    svc = ConsensusService(ServeConfig(
        queue_depth=8, pin_sizing=False, devices=4, huge_devices=2,
        chip_max_edges=96)).start()
    try:
        job = svc.submit(spec)
        _wait([job])
        assert job.state == "done", job.error
        assert job.result["tier"] == "mesh"
        mesh_worker = svc.pool.mesh_workers[0]
        assert job.device == mesh_worker.idx
        assert len(mesh_worker.devices) == 2
        wstats = [w for w in svc.stats()["workers"]
                  if w["kind"] == "mesh"]
        assert wstats and wstats[0]["buckets"] == {"n128_e192": 1}
    finally:
        assert svc.drain(120)
    slab, bucket = bucketer.pad_to_bucket(edges, 100)
    ref = run_consensus(slab, get_detector("louvain"), spec.config,
                        n_closure=bucket.n_closure)
    for served, raw in zip(job.result["partitions"], ref.partitions):
        lab = np.asarray(raw)[:100]
        _, compact = np.unique(lab, return_inverse=True)
        np.testing.assert_array_equal(served, compact.astype(np.int32))


def test_chip_ceiling_requires_huge_tier():
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    svc = ConsensusService(ServeConfig(pin_sizing=False,
                                       chip_max_edges=96))
    with pytest.raises(ValueError, match="huge"):
        svc.start()
    # ...and the mirror: a huge tier with no ceiling is unreachable
    svc = ConsensusService(ServeConfig(pin_sizing=False,
                                       huge_devices=2))
    with pytest.raises(ValueError, match="chip_max_edges"):
        svc.start()


# -- per-device observability ------------------------------------------


def test_healthz_workers_and_device_metrics_over_http():
    """The typed client view of /healthz worker state and the /metricsz
    per-device breakdown (jobs, compiles, busy-fraction)."""
    from fastconsensus_tpu.serve.client import ServeClient, WorkerState
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig,
                                                make_http_server)

    svc = ConsensusService(ServeConfig(queue_depth=8, pin_sizing=False,
                                       devices=2)).start()
    httpd = make_http_server(svc, "127.0.0.1", 0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    client = ServeClient(f"http://127.0.0.1:{port}", timeout=30.0)
    try:
        res = client.run(_ring(40, chords=40).tolist(), n_nodes=40,
                         n_p=4, max_rounds=2, seed=21, timeout=180)
        assert res["device"] is not None
        workers = client.workers()
        assert len(workers) == 2
        assert all(isinstance(w, WorkerState) for w in workers)
        assert {w.device for w in workers} == {0, 1}
        ran = next(w for w in workers if w.device == res["device"])
        assert ran.jobs >= 1 and ran.buckets.get("n64_e96") >= 1
        assert not ran.cordoned and ran.alive
        devs = client.device_metrics()
        assert set(devs) == {"0", "1"}
        hot = devs[str(res["device"])]
        assert hot["jobs"] >= 1
        assert hot["xla_compiles"] > 0
        assert 0.0 <= hot["busy_frac"] <= 1.0
        cold = devs[str(1 - res["device"])]
        # jobs/busy are service-scoped: the idle worker shows zero
        # (compile counters are process-scoped, so earlier tests in
        # this pytest process may have charged this device ordinal)
        assert cold["jobs"] == 0 and cold["busy_s"] == 0.0
    finally:
        httpd.shutdown()
        httpd.server_close()
        assert svc.drain(60)


def test_drain_trace_has_per_device_tracks(tmp_path):
    """One merged drain-time trace with named per-device thread tracks
    (obs/export.py thread_names) and device-tagged spans."""
    import json

    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    svc = ConsensusService(ServeConfig(
        queue_depth=8, pin_sizing=False, devices=2,
        trace_dir=str(tmp_path))).start()
    try:
        jobs = [svc.submit(_spec(_ring(40, chords=40), 40, seed=s))
                for s in (31, 32)]
        _wait(jobs)
        assert all(j.state == "done" for j in jobs)
    finally:
        assert svc.drain(60)
    blob = json.load(open(tmp_path / "fcserve_trace.json"))
    names = [e["args"]["name"] for e in blob["traceEvents"]
             if e.get("name") == "thread_name"]
    assert any(n.startswith("device-") for n in names), names
    tagged = [e for e in blob["traceEvents"]
              if e.get("cat") == "fcobs"
              and e.get("args", {}).get("device") is not None]
    assert tagged, "no device-tagged spans in the drain trace"
