"""fcdelta: incremental evolving-graph consensus (serve/delta.py).

Covers the jax-free half (delta parsing/canonicalization set
semantics, the warm-start-vs-fallback policy, the derived cache key,
the lineage pin that holds a parent entry against LRU/TTL during the
resolve window), the serving path (incremental delta runs warm-start
and cache under the derived key; oversized and bucket-crossing deltas
fall back; quality parity vs a from-scratch twin on karate), the HTTP
wire (ack/status/result ``delta`` blocks, line-numbered 400s, 404 on
an unresolvable parent), and the typed client (DeltaInfo parses with
jax poisoned — thin front-ends never pay the engine import).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest


def _ring_graph(n, chords=0, shift=7):
    idx = np.arange(n)
    edges = [np.stack([idx, (idx + 1) % n], 1)]
    if chords:
        c = np.arange(chords)
        edges.append(np.stack([c % n, (c + shift) % n], 1))
    return np.concatenate(edges).astype(np.int64)


def _spec(edges, n_nodes, **over):
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.jobs import JobSpec

    kwargs = dict(algorithm="louvain", n_p=4, tau=0.2, delta=0.02,
                  max_rounds=2, seed=0)
    kwargs.update(over)
    return JobSpec(edges=np.asarray(edges, dtype=np.int64),
                   n_nodes=n_nodes, config=ConsensusConfig(**kwargs))


@pytest.fixture
def service():
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)
    from fastconsensus_tpu.serve.shaping import ShapingConfig

    return ConsensusService(ServeConfig(queue_depth=8, pin_sizing=False,
                                        shaping=ShapingConfig(shed=False)))


def _wait(job, timeout=120.0):
    deadline = time.monotonic() + timeout
    # fcheck: ok=sync-in-loop (host-side completion poll in a test)
    while job.state not in ("done", "failed"):
        assert time.monotonic() < deadline, f"job stuck in {job.state}"
        time.sleep(0.01)
    assert job.state == "done", job.error
    return job


# -- delta canonicalization -------------------------------------------


def test_parse_edge_pairs_order_and_orientation_invariant():
    from fastconsensus_tpu.serve.delta import parse_edge_pairs

    a = parse_edge_pairs([[3, 7], [1, 0], [9, 2]], "adds", 16)
    b = parse_edge_pairs([[2, 9], [7, 3], [0, 1]], "adds", 16)
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.int64 and a.shape == (3, 2)
    assert (a[:, 0] < a[:, 1]).all()
    # sorted by canonical edge key
    key = a[:, 0] * 16 + a[:, 1]
    assert (np.diff(key) > 0).all()
    # empty / None both canonicalize to [0, 2]
    assert parse_edge_pairs(None, "adds", 16).shape == (0, 2)
    assert parse_edge_pairs([], "adds", 16).shape == (0, 2)


def test_parse_edge_pairs_rejections_name_the_index():
    from fastconsensus_tpu.serve.delta import DeltaError, parse_edge_pairs

    with pytest.raises(DeltaError, match=r"adds\[1\]: self-loop"):
        parse_edge_pairs([[0, 1], [5, 5]], "adds", 16)
    with pytest.raises(DeltaError,
                       match=r"removes\[0\]: node 99 out of range"):
        parse_edge_pairs([[0, 99]], "removes", 16)
    with pytest.raises(DeltaError, match=r"adds\[2\]: duplicate edge"):
        parse_edge_pairs([[0, 1], [2, 3], [1, 0]], "adds", 16)
    with pytest.raises(DeltaError, match=r"adds\[0\]: expected a"):
        parse_edge_pairs([[1, 2, 3]], "adds", 16)
    with pytest.raises(DeltaError, match=r"adds\[0\]: endpoints"):
        parse_edge_pairs([["x", 2]], "adds", 16)
    with pytest.raises(DeltaError, match="must be a list"):
        parse_edge_pairs("nope", "adds", 16)


def test_parse_delta_rejects_empty_and_contradiction():
    from fastconsensus_tpu.serve.delta import DeltaError, parse_delta

    with pytest.raises(DeltaError, match="empty delta"):
        parse_delta({}, 16)
    with pytest.raises(DeltaError, match="both adds and removes"):
        parse_delta({"adds": [[0, 1]], "removes": [[1, 0]]}, 16)
    adds, removes = parse_delta({"adds": [[0, 1]],
                                 "removes": [[2, 3]]}, 16)
    assert adds.shape == (1, 2) and removes.shape == (1, 2)


def test_apply_delta_set_semantics():
    from fastconsensus_tpu.serve.delta import (DeltaError, apply_delta,
                                               parse_edge_pairs)

    # parent: path 0-1-2-3 (canonical sorted)
    u = np.array([0, 1, 2], np.int64)
    v = np.array([1, 2, 3], np.int64)
    adds = parse_edge_pairs([[0, 3]], "adds", 4)
    removes = parse_edge_pairs([[1, 2]], "removes", 4)
    cu, cv, cw = apply_delta(u, v, None, 4, adds, removes)
    assert cw is None
    np.testing.assert_array_equal(cu, [0, 0, 2])
    np.testing.assert_array_equal(cv, [1, 3, 3])
    # canonical ascending order is preserved without a second sort
    assert (np.diff(cu * 4 + cv) > 0).all()
    # weighted parent: adds arrive at weight 1.0
    w = np.array([2.0, 3.0, 4.0], np.float32)
    _, _, cw2 = apply_delta(u, v, w, 4, adds, removes)
    np.testing.assert_allclose(cw2, [2.0, 1.0, 4.0])
    with pytest.raises(DeltaError, match=r"removes\[0\].*not present"):
        apply_delta(u, v, None, 4,
                    parse_edge_pairs([], "adds", 4),
                    parse_edge_pairs([[0, 2]], "removes", 4))
    with pytest.raises(DeltaError, match=r"adds\[0\].*already present"):
        apply_delta(u, v, None, 4,
                    parse_edge_pairs([[1, 2]], "adds", 4),
                    parse_edge_pairs([], "removes", 4))
    with pytest.raises(DeltaError, match="empty the graph"):
        apply_delta(np.array([0], np.int64), np.array([1], np.int64),
                    None, 4, parse_edge_pairs([], "adds", 4),
                    parse_edge_pairs([[0, 1]], "removes", 4))


def test_neighborhood_mask_is_one_hop_in_child():
    from fastconsensus_tpu.serve.delta import (neighborhood_mask,
                                               parse_edge_pairs)

    # child graph: ring of 8.  Change touches edge (0, 1).
    e = _ring_graph(8)
    u = np.minimum(e[:, 0], e[:, 1]).astype(np.int64)
    v = np.maximum(e[:, 0], e[:, 1]).astype(np.int64)
    adds = parse_edge_pairs([[0, 1]], "adds", 8)
    mask = neighborhood_mask(u, v, 8, adds,
                             parse_edge_pairs([], "removes", 8))
    # endpoints 0,1 plus their ring neighbors 7 and 2 — nothing else
    assert mask.dtype == np.bool_ and mask.shape == (8,)
    assert set(np.flatnonzero(mask).tolist()) == {0, 1, 2, 7}


def test_delta_cache_key_never_shadows_content_hash():
    from fastconsensus_tpu.serve.delta import delta_cache_key

    key = delta_cache_key("c" * 32, "p" * 32)
    assert key.startswith("c" * 32 + ":delta:")
    assert key != "c" * 32
    # parent prefix is bounded, so keys stay short and scannable
    assert key.endswith("p" * 16)


# -- policy ------------------------------------------------------------


def _good_parent(n_p=4):
    return {
        "partitions": [[0, 0, 1]] * n_p,
        "converged": True,
        "quality": {"final_agreement": 0.9, "final_churn_frac": 0.1},
    }


def test_policy_reasons_in_precedence_order():
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.delta import DeltaPolicy

    pol = DeltaPolicy()
    cfg = ConsensusConfig(n_p=4)
    ok = dict(n_changed=1, n_parent_edges=100, parent=_good_parent(),
              config=cfg, parent_bucket_key="n64_e96",
              child_bucket_key="n64_e96", warm_capable=True)
    d = pol.decide(**ok)
    assert d.mode == "incremental" and d.reason is None
    assert d.delta_frac == 0.01

    assert pol.decide(**dict(ok, warm_capable=False)).reason == \
        "detector_no_warm"
    assert pol.decide(**dict(ok, huge=True)).reason == "huge_tier"
    assert pol.decide(**dict(ok, n_changed=11)).reason == \
        "delta_too_large"
    assert pol.decide(**dict(ok, child_bucket_key="n64_e128")).reason \
        == "bucket_boundary"
    assert pol.decide(**dict(
        ok, parent=dict(_good_parent(), partitions=[[0]]))).reason == \
        "ensemble_mismatch"
    assert pol.decide(**dict(
        ok, parent=dict(_good_parent(), converged=False))).reason == \
        "parent_unconverged"
    assert pol.decide(**dict(
        ok, parent=dict(_good_parent(), quality=None))).reason == \
        "parent_quality_missing"
    low = dict(_good_parent(),
               quality={"final_agreement": 0.2, "final_churn_frac": 0.1})
    assert pol.decide(**dict(ok, parent=low)).reason == \
        "low_parent_agreement"
    churny = dict(_good_parent(),
                  quality={"final_agreement": 0.9,
                           "final_churn_frac": 0.9})
    assert pol.decide(**dict(ok, parent=churny)).reason == \
        "high_parent_churn"


# -- cache lineage pins ------------------------------------------------


def test_pin_holds_parent_against_lru_eviction_under_contention():
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.cache import ResultCache

    reg = obs_counters.get_registry()
    base = reg.counters()
    cache = ResultCache(max_entries=2)
    cache.put("parent", {"v": 1})
    assert cache.pin("parent") is True
    assert cache.pinned() == {"parent": 1}
    # contention: pour entries through a 2-slot cache; the pinned
    # parent is the LRU victim every time and must survive anyway
    for i in range(6):
        cache.put(f"k{i}", {"v": i})
    assert cache.get("parent", count_miss=False) == {"v": 1}
    assert len(cache) <= 2 + 1  # transient overshoot bounded by pins
    since = reg.counters_since(base)
    assert since.get("serve.cache.parent_pins", 0) == 1
    # release: the parent becomes ordinary LRU fodder again
    cache.unpin("parent")
    assert cache.pinned() == {}
    for i in range(6, 9):
        cache.put(f"k{i}", {"v": i})
    assert cache.get("parent", count_miss=False) is None
    assert len(cache) == 2


def test_pin_holds_parent_against_ttl_and_refcounts():
    from fastconsensus_tpu.serve.cache import ResultCache

    now = [0.0]
    cache = ResultCache(max_entries=4, ttl_seconds=10.0,
                        clock=lambda: now[0])
    cache.put("parent", {"v": 1})
    assert cache.pin("parent") and cache.pin("parent")
    assert cache.pinned() == {"parent": 2}
    now[0] = 100.0                      # far past the TTL
    assert cache.get("parent", count_miss=False) == {"v": 1}
    cache.unpin("parent")
    assert cache.pinned() == {"parent": 1}  # refcounted: one pin left
    assert cache.get("parent", count_miss=False) == {"v": 1}
    cache.unpin("parent")
    # last unpin: the overdue entry drops on the next touch
    assert cache.get("parent", count_miss=False) is None


def test_pin_refuses_absent_and_expired_entries():
    from fastconsensus_tpu.serve.cache import ResultCache

    now = [0.0]
    cache = ResultCache(max_entries=4, ttl_seconds=10.0,
                        clock=lambda: now[0])
    assert cache.pin("ghost") is False
    cache.put("old", {"v": 1})
    now[0] = 100.0
    assert cache.pin("old") is False    # expired: not pinnable
    assert cache.pinned() == {}
    cache.unpin("ghost")                # unknown unpin is a no-op


# -- serving path ------------------------------------------------------


def _nonedge(edges, n_nodes, want=1, forbid=()):
    """Deterministic [u, v] pairs absent from ``edges``."""
    eset = {(min(a, b), max(a, b)) for a, b in np.asarray(edges).tolist()}
    eset.update((min(a, b), max(a, b)) for a, b in forbid)
    out = []
    for a in range(n_nodes):
        for b in range(a + 1, n_nodes):
            if (a, b) not in eset:
                out.append([a, b])
                eset.add((a, b))
                if len(out) == want:
                    return out
    raise AssertionError("graph is complete")


def test_incremental_delta_warm_starts_and_caches_under_derived_key(
        service, karate_edges):
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.delta import delta_cache_key

    edges, _, ids = karate_edges
    n = len(ids)
    service.start()
    try:
        parent = _wait(service.submit(_spec(edges, n, max_rounds=32)))
        assert parent.result["converged"]
        # the cached parent carries its lineage blocks
        assert sorted(parent.result["graph"]) == ["u", "v", "w"]
        assert parent.result["config"]["n_p"] == 4

        reg = obs_counters.get_registry()
        base = reg.counters()
        add = _nonedge(edges, n)[0]
        job = service.submit_delta({"parent": parent.key,
                                    "adds": [add],
                                    "removes": [[0, 1]]})
        _wait(job)
        info = job.spec.delta
        assert info["mode"] == "incremental" and info["reason"] is None
        assert info["parent"] == parent.key
        assert info["n_adds"] == 1 and info["n_removes"] == 1
        assert 0 < info["delta_frac"] < 0.10
        # delta submissions get their own SLO class and never coalesce
        assert job.spec.slo_class() == "delta"
        assert "delta-solo" in job.spec.batch_group()
        since = reg.counters_since(base)
        assert since.get("serve.delta.incremental", 0) == 1
        assert since.get("serve.cache.parent_pins", 0) == 1
        # resolve window closed: no pin leaks
        assert service.cache.pinned() == {}
        # cached under the DERIVED key — the approximate answer must
        # never shadow the child graph's exact content hash
        assert job.key == delta_cache_key(
            job.key.split(":delta:")[0], parent.key)
        assert ":delta:" in job.key
        child_hash = job.key.split(":delta:")[0]
        assert service.cache.get(job.key, count_miss=False) is not None
        assert service.cache.get(child_hash, count_miss=False) is None
        # an identical delta resubmit dedups exactly
        again = service.submit_delta({"parent": parent.key,
                                      "adds": [add],
                                      "removes": [[0, 1]]})
        assert again.state == "done" and again.result["cached"]
    finally:
        assert service.drain(60)


def test_incremental_quality_parity_with_scratch_on_karate(
        service, karate_edges, karate_truth):
    from fastconsensus_tpu.utils.metrics import nmi

    edges, _, ids = karate_edges
    n = len(ids)
    service.start()
    try:
        parent = _wait(service.submit(_spec(edges, n, max_rounds=32)))
        add = _nonedge(edges, n)[0]
        inc = _wait(service.submit_delta({"parent": parent.key,
                                          "adds": [add],
                                          "removes": [[0, 1]]}))
        assert inc.spec.delta["mode"] == "incremental"
        # the from-scratch twin of the SAME child graph + config: runs
        # fresh because the incremental result lives under the derived
        # key, never under the child's content hash
        child = np.concatenate([edges[~((edges[:, 0] == 0) &
                                        (edges[:, 1] == 1)) &
                                      ~((edges[:, 0] == 1) &
                                        (edges[:, 1] == 0))],
                                np.asarray([add], np.int64)])
        scratch = _wait(service.submit(_spec(child, n, max_rounds=32)))
        assert not scratch.result["cached"]
        truth = np.asarray(karate_truth)
        inc_nmi = float(nmi(np.asarray(inc.result["partitions"][0]),
                            truth))
        scr_nmi = float(nmi(np.asarray(scratch.result["partitions"][0]),
                            truth))
        # the ISSUE acceptance band: warm-start + frontier restriction
        # must not cost more than 0.02 NMI vs recomputing
        assert inc_nmi >= scr_nmi - 0.02, (inc_nmi, scr_nmi)
    finally:
        assert service.drain(60)


def test_bucket_boundary_delta_falls_back(service):
    from fastconsensus_tpu.serve import bucketer

    # sit the parent EXACTLY on an edge-class boundary so one net-add
    # crosses into the next bucket (different executables + padding)
    n = 64
    edges = _ring_graph(n, chords=32)           # 96 edges
    b_parent = bucketer.bucket_for(n, 96)
    b_child = bucketer.bucket_for(n, 97)
    assert b_parent.key() != b_child.key()
    service.start()
    try:
        parent = _wait(service.submit(_spec(edges, n, max_rounds=32)))
        adds = _nonedge(edges, n)[:1]
        job = _wait(service.submit_delta({"parent": parent.key,
                                          "adds": adds}))
        assert job.spec.delta["mode"] == "fallback"
        assert job.spec.delta["reason"] == "bucket_boundary"
        # fallback is a full run: cached under the PLAIN content hash
        assert ":delta:" not in job.key
    finally:
        assert service.drain(60)


def test_oversized_delta_falls_back(service, karate_edges):
    edges, _, ids = karate_edges
    n = len(ids)
    service.start()
    try:
        parent = _wait(service.submit(_spec(edges, n, max_rounds=32)))
        adds = _nonedge(edges, n, want=20)      # 20/78 > 10%
        job = _wait(service.submit_delta({"parent": parent.key,
                                          "adds": adds}))
        assert job.spec.delta["mode"] == "fallback"
        assert job.spec.delta["reason"] == "delta_too_large"
    finally:
        assert service.drain(60)


def test_unknown_parent_raises_and_counts(service):
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.delta import ParentNotCached

    reg = obs_counters.get_registry()
    base = reg.counters()
    service.start()
    try:
        with pytest.raises(ParentNotCached):
            service.submit_delta({"parent": "feedfeedfeedfeed",
                                  "adds": [[0, 1]]})
        assert reg.counters_since(base).get(
            "serve.delta.parent_miss", 0) == 1
    finally:
        assert service.drain(60)


# -- HTTP wire + typed client ------------------------------------------


def test_delta_http_roundtrip(service, karate_edges):
    import threading

    from fastconsensus_tpu.serve.client import (DeltaInfo, ServeClient,
                                                ServeError)
    from fastconsensus_tpu.serve.server import make_http_server

    edges, _, ids = karate_edges
    n = len(ids)
    service.start()
    httpd = make_http_server(service, "127.0.0.1", 0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    client = ServeClient(f"http://127.0.0.1:{port}", timeout=30.0)
    try:
        sub = client.submit(edges=edges.tolist(), n_nodes=n,
                            algorithm="louvain", n_p=4, tau=0.2,
                            delta=0.02, max_rounds=32, seed=0)
        client.wait(sub["job_id"], timeout=120)
        add = _nonedge(edges, n)[0]
        ack = client.submit_delta(sub["content_hash"], adds=[add],
                                  removes=[[0, 1]])
        # the ack itself carries the provenance block
        assert ack["delta"]["mode"] == "incremental"
        res = client.wait(ack["job_id"], timeout=120)
        # /result: delta block present, lineage graph block STRIPPED
        assert res["delta"]["parent"] == sub["content_hash"]
        assert "graph" not in res
        assert res["timing"]["slo"] == "delta"
        # typed accessor over /status
        info = client.delta_info(ack["job_id"])
        assert isinstance(info, DeltaInfo) and info.incremental
        assert info.parent == sub["content_hash"]
        assert info.n_adds == 1 and info.n_removes == 1
        # plain jobs carry no delta block
        assert client.delta_info(sub["job_id"]) is None

        # 404: unresolvable parent names the hash
        with pytest.raises(ServeError) as e404:
            client.submit_delta("feedfeedfeedfeed", adds=[[0, 1]])
        assert e404.value.status == 404
        assert e404.value.payload["parent"] == "feedfeedfeedfeed"
        # 400: malformed delta names the offending index
        with pytest.raises(ServeError) as e400:
            client.submit_delta(sub["content_hash"], adds=[[5, 5]])
        assert e400.value.status == 400
        assert "adds[0]" in e400.value.payload["error"]
    finally:
        httpd.shutdown()
        httpd.server_close()
        assert service.drain(60)


def test_delta_info_parses_in_jax_free_client():
    """The typed client must parse the delta block with jax poisoned —
    delta submitters are thin front-ends (cli.py --server posture)."""
    canned = {"parent": "ab" * 16, "mode": "incremental",
              "reason": None, "delta_frac": 0.0123,
              "n_adds": 3, "n_removes": 1}
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "import json\n"
        "from fastconsensus_tpu.serve.client import (DeltaInfo,\n"
        "                                            ServeClient)\n"
        f"d = json.loads({json.dumps(json.dumps(canned))})\n"
        "di = DeltaInfo.from_payload(d)\n"
        "assert di.incremental and di.reason is None\n"
        "assert di.parent == 'ab' * 16 and di.n_adds == 3\n"
        "assert di.delta_frac == 0.0123\n"
        "c = ServeClient('http://example.invalid')\n"
        "assert callable(c.submit_delta)\n"
        "print('jax-free delta parse ok')\n")
    root = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ, PYTHONPATH=os.path.abspath(root))
    res = subprocess.run([sys.executable, "-c", code], cwd=root, env=env,
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "jax-free delta parse ok" in res.stdout


# -- router routing key ------------------------------------------------


def test_router_route_key_uses_parent_hash_for_deltas():
    from fastconsensus_tpu.serve.router import route_key

    k1 = route_key({"parent": "ab" * 16, "adds": [[0, 1]]})
    k2 = route_key({"parent": "ab" * 16, "removes": [[2, 3]]})
    k3 = route_key({"parent": "cd" * 16, "adds": [[0, 1]]})
    # every delta evolving one graph routes together; different
    # lineages may land elsewhere
    assert k1 == k2 and k1 != k3
    assert k1.startswith("delta|")
