"""Pallas row-aggregation kernel: equivalence with the sort-based path.

Runs in interpret mode on the CPU suite; on real TPU the same kernel is the
default lowering for the detection sweeps (ops/dense_adj.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fastconsensus_tpu.graph import pack_edges
from fastconsensus_tpu.ops import dense_adj as da
from fastconsensus_tpu.ops import pallas_kernels as pk
from fastconsensus_tpu.utils.synth import planted_partition


def _candidate_sets(tot: da.RowTotals):
    """Order-independent view: per row, {label: total} over head slots."""
    L = np.asarray(tot.label)
    T = np.asarray(tot.total)
    H = np.asarray(tot.is_head)
    out = []
    for r in range(L.shape[0]):
        out.append({int(L[r, i]): float(T[r, i])
                    for i in range(L.shape[1]) if H[r, i]})
    return out


def test_row_totals_matches_sort_path():
    edges, _ = planted_partition(200, 5, 0.3, 0.02, seed=6)
    slab = pack_edges(edges, 200)
    adj = da.build_dense_adjacency(slab)
    labels = jax.random.randint(jax.random.key(3), (200,), 0, 23,
                                dtype=jnp.int32)

    sort_tot = da.row_label_totals(adj, labels, use_pallas=False)

    # pallas path, interpret mode (no TPU in the suite)
    n = 200
    sentinel = jnp.int32(2**31 - 1)
    lab_n = jnp.where(adj.valid, labels[jnp.clip(adj.nbr, 0, n - 1)],
                      sentinel)
    w = jnp.where(adj.valid, adj.w, 0.0)
    lab_ext = jnp.concatenate([lab_n, labels[:, None]], axis=1)
    w_ext = jnp.concatenate([w, jnp.zeros((n, 1), jnp.float32)], axis=1)
    total, head = pk.row_totals(lab_ext, w_ext, interpret=True)

    pallas_tot = da.RowTotals(
        label=jnp.where(lab_ext != sentinel, lab_ext, 0),
        total=jnp.where(lab_ext != sentinel, total, 0.0),
        is_head=head)

    a, b = _candidate_sets(sort_tot), _candidate_sets(pallas_tot)
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert set(ra) == set(rb)
        for k in ra:
            assert abs(ra[k] - rb[k]) < 1e-4


def test_fits_vmem_guards_wide_rows():
    # Narrow rows (the common case) stay on the Pallas path; wide rows must
    # not: at the Mosaic-minimum 8-row block the [8, D', D'] compare temps
    # exceed VMEM past D ~ 500 and fault the TPU worker (regression: the
    # LFR-10k config, d_cap=1036).
    assert pk.fits_vmem(128)
    assert pk.fits_vmem(256)
    assert not pk.fits_vmem(1037)
    assert not pk.fits_vmem(4096)
    # padded width is what counts: 513 pads to 640 -> 8*6*640^2 = 19.7MB
    assert pk.fits_vmem(512)
    assert not pk.fits_vmem(513)


def test_row_totals_padding_and_sentinels():
    # ragged: 5 rows, width 7 (pads to 128 lanes, 32-row blocks)
    lab = jnp.array([[1, 1, 2, pk.SENTINEL, 2, 1, 3]] * 5, jnp.int32)
    w = jnp.array([[1., 2., 3., 0., 4., 5., 6.]] * 5, jnp.float32)
    total, head = pk.row_totals(lab, w, interpret=True)
    np.testing.assert_allclose(np.asarray(total[0]),
                               [8., 8., 7., 0., 7., 8., 6.])
    assert np.asarray(head[0]).tolist() == [True, False, True, False,
                                            False, False, True]
