"""Stall-watchdog supervisor (utils/supervise.py): failure-detection layer."""

import os
import sys

from fastconsensus_tpu.utils.supervise import run_supervised


def test_success_passes_through(tmp_path):
    prog = tmp_path / "p.txt"
    rc = run_supervised(
        [sys.executable, "-c",
         f"open({str(prog)!r}, 'w').write('x')"],
        str(prog), stall_seconds=30, recover_seconds=0, poll_seconds=0.1,
        log=lambda *a: None)
    assert rc == 0


def test_retry_until_success(tmp_path):
    # first attempt fails, second succeeds (state via a marker file)
    prog = tmp_path / "p.txt"
    marker = tmp_path / "m"
    script = (
        "import os, sys\n"
        f"open({str(prog)!r}, 'a').write('tick')\n"
        f"if not os.path.exists({str(marker)!r}):\n"
        f"    open({str(marker)!r}, 'w').close()\n"
        "    sys.exit(3)\n")
    rc = run_supervised([sys.executable, "-c", script], str(prog),
                        stall_seconds=30, recover_seconds=0.1,
                        poll_seconds=0.1, log=lambda *a: None)
    assert rc == 0
    assert marker.exists()


def test_stall_kill_and_give_up(tmp_path):
    # child never writes progress and sleeps forever -> killed each attempt
    prog = tmp_path / "p.txt"
    rc = run_supervised(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        str(prog), stall_seconds=1.0, recover_seconds=0.1,
        poll_seconds=0.2, max_attempts=2, log=lambda *a: None)
    assert rc == -9
