"""Stall-watchdog supervisor (utils/supervise.py): failure-detection layer."""

import os
import sys

from fastconsensus_tpu.utils.supervise import run_supervised


def test_success_passes_through(tmp_path):
    prog = tmp_path / "p.txt"
    rc = run_supervised(
        [sys.executable, "-c",
         f"open({str(prog)!r}, 'w').write('x')"],
        str(prog), stall_seconds=30, recover_seconds=0, poll_seconds=0.1,
        log=lambda *a: None)
    assert rc == 0


def test_retry_until_success(tmp_path):
    # first attempt fails, second succeeds (state via a marker file)
    prog = tmp_path / "p.txt"
    marker = tmp_path / "m"
    script = (
        "import os, sys\n"
        f"open({str(prog)!r}, 'a').write('tick')\n"
        f"if not os.path.exists({str(marker)!r}):\n"
        f"    open({str(marker)!r}, 'w').close()\n"
        "    sys.exit(3)\n")
    rc = run_supervised([sys.executable, "-c", script], str(prog),
                        stall_seconds=30, recover_seconds=0.1,
                        poll_seconds=0.1, log=lambda *a: None)
    assert rc == 0
    assert marker.exists()


def test_rotation_chains_telemetry_across_restarts(tmp_path):
    """A killed-and-restarted child's fcobs JSONL log survives as a
    rotated chain: attempt 1's log moves to .1 before the relaunch, and
    obs/export.read_jsonl_chain stitches the fragments back into one
    cumulative stream (the 13-attempt lfr100k scenario in miniature)."""
    from fastconsensus_tpu.obs import export as obs_export

    prog = tmp_path / "p.txt"
    marker = tmp_path / "m"
    log = tmp_path / "trace.json.jsonl"
    # the child writes a fresh fcobs-shaped JSONL each attempt ("w" mode,
    # exactly like cli.py --trace), dies once, succeeds on attempt 2
    script = (
        "import json, os, sys\n"
        f"open({str(prog)!r}, 'a').write('tick')\n"
        f"attempt = 2 if os.path.exists({str(marker)!r}) else 1\n"
        f"with open({str(log)!r}, 'w') as fh:\n"
        "    fh.write(json.dumps({'kind': 'span', 'name': 'round',\n"
        "        'ph': 'X', 'ts': 10, 'dur': 5, 'a': attempt}) + '\\n')\n"
        "    fh.write(json.dumps({'kind': 'counters',\n"
        "        'counters': {'rounds.total': attempt}}) + '\\n')\n"
        f"if attempt == 1:\n"
        f"    open({str(marker)!r}, 'w').close()\n"
        "    sys.exit(3)\n")
    rc = run_supervised([sys.executable, "-c", script], str(prog),
                        stall_seconds=30, recover_seconds=0.1,
                        poll_seconds=0.1, rotate=[str(log)],
                        log=lambda *a: None)
    assert rc == 0
    # the dead attempt's log was rotated, not overwritten
    assert (tmp_path / "trace.json.jsonl.1").exists()
    records = obs_export.read_jsonl_chain(str(log))
    spans = [r for r in records if r["kind"] == "span"]
    assert [r["attempt"] for r in spans] == [1, 2]
    assert [r["a"] for r in spans] == [1, 2]
    # attempt 2's span was rebased past attempt 1's end (15us)
    assert spans[1]["ts"] >= spans[0]["ts"] + spans[0]["dur"]
    # the last counters record carries the (checkpoint-restored)
    # cumulative totals
    counters = [r for r in records if r["kind"] == "counters"]
    assert counters[-1]["counters"]["rounds.total"] == 2


def test_rotation_cli_flag_parses(tmp_path):
    """--rotate wires through main() to run_supervised."""
    from fastconsensus_tpu.utils.supervise import main

    prog = tmp_path / "p.txt"
    log = tmp_path / "log.jsonl"
    log.write_text("{}\n")
    marker = tmp_path / "m"
    script = (
        "import os, sys\n"
        f"open({str(prog)!r}, 'a').write('tick')\n"
        f"if not os.path.exists({str(marker)!r}):\n"
        f"    open({str(marker)!r}, 'w').close()\n"
        "    sys.exit(3)\n")
    rc = main(["--progress", str(prog), "--stall-seconds", "30",
               "--recover-seconds", "0.1", "--poll-seconds", "0.1",
               "--rotate", str(log), "--",
               sys.executable, "-c", script])
    assert rc == 0
    assert (tmp_path / "log.jsonl.1").exists()


def test_stall_kill_and_give_up(tmp_path):
    # child never writes progress and sleeps forever -> killed each attempt
    prog = tmp_path / "p.txt"
    rc = run_supervised(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        str(prog), stall_seconds=1.0, recover_seconds=0.1,
        poll_seconds=0.2, max_attempts=2, log=lambda *a: None)
    assert rc == -9
