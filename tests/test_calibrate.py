"""Persisted on-device rate calibration (utils/calibrate.py)."""

import numpy as np
import pytest

from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
from fastconsensus_tpu.graph import pack_edges
from fastconsensus_tpu.models.registry import get_detector
from fastconsensus_tpu.utils import calibrate
from fastconsensus_tpu.utils.synth import planted_partition


@pytest.fixture
def calib_dir(tmp_path, monkeypatch):
    from fastconsensus_tpu import sizing as szmod

    monkeypatch.setenv("FCTPU_CALIBRATE", "1")
    monkeypatch.setenv("FCTPU_CALIBRATE_DIR", str(tmp_path))
    # CPU test runs are sub-second per call; drop the latency gate so they
    # still exercise the persistence path
    monkeypatch.setattr(szmod, "MIN_PERSIST_CALL_S", 0.0)
    calibrate._cache = calibrate._cache_path = None
    yield tmp_path
    calibrate._cache = calibrate._cache_path = None


def test_rate_roundtrip_and_blend(calib_dir):
    assert calibrate.get_rate("cpu", "matmul", "louvain") is None
    # warm-only entries are scaled conservatively for cold first calls
    calibrate.update_rate("cpu", "matmul", "louvain", 0.5, "warm")
    assert calibrate.get_rate("cpu", "matmul", "louvain") == \
        pytest.approx(0.5 * calibrate.COLD_OVER_WARM)
    # a cold measurement takes precedence
    calibrate.update_rate("cpu", "matmul", "louvain", 0.1, "cold")
    assert calibrate.get_rate("cpu", "matmul", "louvain") == \
        pytest.approx(0.1)
    # repeat measurements blend 50/50 (one noisy call can't swing sizing)
    calibrate.update_rate("cpu", "matmul", "louvain", 0.3, "cold")
    assert calibrate.get_rate("cpu", "matmul", "louvain") == \
        pytest.approx(0.2)
    # other keys unaffected
    assert calibrate.get_rate("cpu", "hash", "louvain") is None
    assert calibrate.get_rate("tpu", "matmul", "louvain") is None


def test_disabled_is_inert(tmp_path, monkeypatch):
    monkeypatch.setenv("FCTPU_CALIBRATE", "0")
    monkeypatch.setenv("FCTPU_CALIBRATE_DIR", str(tmp_path))
    calibrate.update_rate("cpu", "matmul", "louvain", 0.5, "cold")
    assert calibrate.get_rate("cpu", "matmul", "louvain") is None
    assert list(tmp_path.iterdir()) == []


def test_restart_reuses_chunks_despite_calibration_drift(calib_dir, tmp_path,
                                                         monkeypatch):
    """Round-3 review: first-call sizing consults the mutable calibration
    file, but a restarted process must reuse the killed run's chunking —
    the sizing actually used is persisted next to the chunks and adopted
    on restart, so persisted chunks are never orphaned."""
    edges, _ = planted_partition(120, 4, 0.35, 0.02, seed=8)
    slab = pack_edges(edges, 120)
    det = get_detector("lpm")
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.0,
                          max_rounds=2, seed=3)
    cache = tmp_path / "cache"
    monkeypatch.setenv("FCTPU_DETECT_CALL_MEMBERS", "4")
    run_consensus(slab, det, cfg, detect_cache_dir=str(cache))
    files0 = sorted(p.name for p in cache.iterdir())
    assert any(f.endswith("_c1.npy") for f in files0)  # split happened
    # The first run measured+persisted rates that would size members=n_p
    # (tiny graph, no split) — without adoption the retry would derive a
    # different cache_fp and write a fresh set of chunk files.
    monkeypatch.delenv("FCTPU_DETECT_CALL_MEMBERS")
    run_consensus(slab, det, cfg, detect_cache_dir=str(cache))
    files1 = sorted(p.name for p in cache.iterdir())
    assert files0 == files1


def test_run_persists_measured_rate(calib_dir, tmp_path):
    """VERDICT round-2 #6: a run on a fresh backend measures its rate and
    persists it, so the hardcoded prior stops being load-bearing after the
    first run; the next process's first-call sizing consults it."""
    import jax

    from fastconsensus_tpu.sizing import est_member_seconds

    edges, _ = planted_partition(120, 4, 0.35, 0.02, seed=8)
    slab = pack_edges(edges, 120)
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.0,
                          max_rounds=3, seed=3)
    # checkpoint_path disables round fusion -> per-round calls, so round 2
    # onward measures a compile-free rate
    run_consensus(slab, get_detector("lpm"), cfg,
                  checkpoint_path=str(tmp_path / "ck.npz"))

    backend = jax.default_backend()
    rate = calibrate.get_rate(backend, "matmul", "lpm")
    assert rate is not None and rate > 0
    # the estimator prefers the measured rate over the static table
    est = est_member_seconds(slab, get_detector("lpm"), alg="lpm")
    from fastconsensus_tpu.models.louvain import sweep_temp_bytes
    assert est == pytest.approx(96 * sweep_temp_bytes(slab) * rate * 1e-9)
