"""Native C++ kernels: CNM fast-greedy, Infomap, edgelist parser.

These replace the reference's third-party igraph C routines
(fast_consensus.py:268, :270, :335); correctness is checked against known
results (karate club max-modularity Q ~ 0.3807 for fastgreedy) and planted
partitions (SURVEY.md §4's statistical protocol).
"""

import os

import numpy as np
import pytest

from fastconsensus_tpu import native
from fastconsensus_tpu.utils.metrics import modularity, nmi
from fastconsensus_tpu.utils.synth import planted_partition

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no native toolchain")


def test_cnm_karate_matches_known_quality(karate_edges):
    edges, _, ids = karate_edges
    lab = native.cnm_labels(edges[:, 0], edges[:, 1], None, len(ids),
                            np.arange(4, dtype=np.uint64))
    assert lab.shape == (4, 34)
    q = modularity(edges[:, 0], edges[:, 1],
                   np.ones(edges.shape[0]), lab[0])
    # igraph community_fastgreedy on karate: Q = 0.3807, 3 communities
    assert q >= 0.375
    assert len(np.unique(lab[0])) == 3


def test_cnm_recovers_planted_partition():
    edges, truth = planted_partition(500, 10, 0.3, 0.005, seed=11)
    lab = native.cnm_labels(edges[:, 0], edges[:, 1], None, 500,
                            np.arange(3, dtype=np.uint64))
    for row in lab:
        assert nmi(row, truth) > 0.9


def test_infomap_recovers_planted_partition():
    edges, truth = planted_partition(500, 10, 0.3, 0.005, seed=11)
    lab = native.infomap_labels(edges[:, 0], edges[:, 1], None, 500,
                                np.arange(3, dtype=np.uint64))
    for row in lab:
        assert nmi(row, truth) > 0.9


def test_infomap_weighted_graph_respects_weights():
    # two cliques bridged by a heavy edge: with tiny intra weights the
    # map equation should still split on the (structural) communities
    edges, truth = planted_partition(200, 4, 0.4, 0.01, seed=3)
    w = np.ones(edges.shape[0], dtype=np.float32)
    lab = native.infomap_labels(edges[:, 0], edges[:, 1], w, 200,
                                np.arange(2, dtype=np.uint64))
    assert nmi(lab[0], truth) > 0.9


def _two_triangles():
    """Two triangles bridged by one edge — small enough to hand-compute the
    map equation.  For the triangle partition: q_A = q_B = 1/14,
    p_A = p_B = 1/2, node visit rates {2,2,3,3,2,2}/14, giving
    L = plogp(1/7) - 2*2*plogp(1/14) + 2*plogp(8/14) - [4*plogp(1/7)
    + 2*plogp(3/14)] = 2.320731 bits (worked by hand, VERDICT #8)."""
    edges = np.array([[0, 1], [0, 2], [1, 2], [3, 4], [3, 5], [4, 5],
                      [2, 3]])
    truth = np.array([0, 0, 0, 1, 1, 1])
    return edges, truth


def test_map_equation_hand_computed_fixture():
    from fastconsensus_tpu.utils.metrics import map_equation

    edges, truth = _two_triangles()
    w = np.ones(edges.shape[0])
    L = map_equation(edges[:, 0], edges[:, 1], w, truth)
    assert abs(L - 2.320731) < 2e-3, L
    # the partition-quality ordering the optimizer must respect
    L_one = map_equation(edges[:, 0], edges[:, 1], w, np.zeros(6, int))
    L_single = map_equation(edges[:, 0], edges[:, 1], w, np.arange(6))
    L_bad = map_equation(edges[:, 0], edges[:, 1], w,
                         np.array([0, 0, 1, 0, 1, 1]))
    assert L < L_one < L_single
    assert L < L_bad


def test_infomap_minimizes_map_equation():
    """The native optimizer's output must reach the hand-known optimum on
    the fixture and beat trivial/perturbed partitions on a planted graph —
    a deliberately sign-flipped delta-L in infomap.cpp fails this."""
    from fastconsensus_tpu.utils.metrics import map_equation

    edges, truth = _two_triangles()
    lab = native.infomap_labels(edges[:, 0], edges[:, 1], None, 6,
                                np.arange(3, dtype=np.uint64))
    w = np.ones(edges.shape[0])
    for row in lab:
        assert nmi(row, truth) == 1.0, row
        assert abs(map_equation(edges[:, 0], edges[:, 1], w, row)
                   - 2.320731) < 2e-3

    edges, truth = planted_partition(400, 8, 0.25, 0.01, seed=7)
    w = np.ones(edges.shape[0])
    lab = native.infomap_labels(edges[:, 0], edges[:, 1], None, 400,
                                np.arange(2, dtype=np.uint64))
    L_opt = map_equation(edges[:, 0], edges[:, 1], w, lab[0])
    rng = np.random.default_rng(0)
    perturbed = lab[0].copy()
    flip = rng.choice(400, 40, replace=False)
    perturbed[flip] = rng.integers(0, perturbed.max() + 1, 40)
    assert L_opt <= map_equation(edges[:, 0], edges[:, 1], w, truth) + 1e-6
    assert L_opt < map_equation(edges[:, 0], edges[:, 1], w, perturbed)
    assert L_opt < map_equation(edges[:, 0], edges[:, 1], w,
                                np.zeros(400, int))


def test_infomap_hard_mixing_regime():
    """Near-detectability planted case (VERDICT #8: round 1 validated only
    p_in/p_out = 30x regimes where any method succeeds).

    Chosen at the measured map-equation detectability edge: at
    p_in/p_out = 0.075/0.025 the one-module partition has LOWER L than the
    planted truth (9.18 vs 9.55 bits) so collapse is *correct* there; at
    0.09/0.02 truth wins (L 9.16) and the optimizer recovers it
    (NMI 0.93-0.97 measured) — a collapse here is a real regression."""
    edges, truth = planted_partition(600, 4, 0.09, 0.02, seed=13)
    lab = native.infomap_labels(edges[:, 0], edges[:, 1], None, 600,
                                np.arange(4, dtype=np.uint64))
    scores = [nmi(row, truth) for row in lab]
    assert max(scores) > 0.5, scores


def test_cnm_weighted_heap_uses_weights():
    """Weights must drive the merge heap: heavy bridges between triangles
    flip the best partition relative to the unweighted graph."""
    edges, _ = _two_triangles()
    # bridge (2,3) heavy, triangle edges light: weighted modularity is
    # maximized by grouping across the bridge
    w = np.where((edges[:, 0] == 2) & (edges[:, 1] == 3), 10.0, 1.0)
    lab_w = native.cnm_labels(edges[:, 0], edges[:, 1],
                              w.astype(np.float32), 6,
                              np.arange(2, dtype=np.uint64))
    lab_u = native.cnm_labels(edges[:, 0], edges[:, 1], None, 6,
                              np.arange(2, dtype=np.uint64))
    q_w = modularity(edges[:, 0], edges[:, 1], w, lab_w[0])
    q_u_on_w = modularity(edges[:, 0], edges[:, 1], w, lab_u[0])
    assert lab_w[0][2] == lab_w[0][3], lab_w[0]  # heavy bridge co-clustered
    assert q_w >= q_u_on_w - 1e-9, (q_w, q_u_on_w)


def test_cnm_hub_heavy_graph():
    """Hub-dominated graph exercises the lazy-invalidation heap: a hub
    touching every community invalidates many pending merges."""
    rng = np.random.default_rng(5)
    edges, truth = planted_partition(400, 8, 0.3, 0.004, seed=9)
    hub = 400  # one extra node wired to 200 random nodes
    extra = np.stack([np.full(200, hub),
                      rng.choice(400, 200, replace=False)], 1)
    all_edges = np.vstack([edges, extra])
    lab = native.cnm_labels(all_edges[:, 0], all_edges[:, 1], None, 401,
                            np.arange(2, dtype=np.uint64))
    for row in lab:
        assert nmi(row[:400], truth) > 0.85
        q = modularity(all_edges[:, 0], all_edges[:, 1],
                       np.ones(all_edges.shape[0]), row)
        assert q > 0.4, q


def test_parser_matches_python_reader(tmp_path):
    p = tmp_path / "g.txt"
    p.write_text("# comment\n1 2\n2 3 0.5\n\n3 9\n")
    u, v, w = native.parse_edgelist(str(p))
    assert u.tolist() == [1, 2, 3]
    assert v.tolist() == [2, 3, 9]
    assert w is not None and w.tolist() == [1.0, 0.5, 1.0]


def test_parser_unweighted(tmp_path):
    p = tmp_path / "g.txt"
    p.write_text("0 1\n1 2\n")
    u, v, w = native.parse_edgelist(str(p))
    assert w is None
    assert u.tolist() == [0, 1]


def test_parser_agrees_with_io_on_karate():
    from fastconsensus_tpu.utils.io import read_edgelist

    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "karate_club.txt")
    edges, weights, ids = read_edgelist(path)
    assert edges.shape == (78, 2)
    assert len(ids) == 34


def test_detectors_are_seed_deterministic():
    edges, _ = planted_partition(300, 6, 0.3, 0.01, seed=2)
    s = np.array([42, 42], dtype=np.uint64)
    a = native.infomap_labels(edges[:, 0], edges[:, 1], None, 300, s)
    assert np.array_equal(a[0], a[1])
    b = native.cnm_labels(edges[:, 0], edges[:, 1], None, 300, s)
    assert np.array_equal(b[0], b[1])
