"""Native C++ kernels: CNM fast-greedy, Infomap, edgelist parser.

These replace the reference's third-party igraph C routines
(fast_consensus.py:268, :270, :335); correctness is checked against known
results (karate club max-modularity Q ~ 0.3807 for fastgreedy) and planted
partitions (SURVEY.md §4's statistical protocol).
"""

import os

import numpy as np
import pytest

from fastconsensus_tpu import native
from fastconsensus_tpu.utils.metrics import modularity, nmi
from fastconsensus_tpu.utils.synth import planted_partition

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no native toolchain")


def test_cnm_karate_matches_known_quality(karate_edges):
    edges, _, ids = karate_edges
    lab = native.cnm_labels(edges[:, 0], edges[:, 1], None, len(ids),
                            np.arange(4, dtype=np.uint64))
    assert lab.shape == (4, 34)
    q = modularity(edges[:, 0], edges[:, 1],
                   np.ones(edges.shape[0]), lab[0])
    # igraph community_fastgreedy on karate: Q = 0.3807, 3 communities
    assert q >= 0.375
    assert len(np.unique(lab[0])) == 3


def test_cnm_recovers_planted_partition():
    edges, truth = planted_partition(500, 10, 0.3, 0.005, seed=11)
    lab = native.cnm_labels(edges[:, 0], edges[:, 1], None, 500,
                            np.arange(3, dtype=np.uint64))
    for row in lab:
        assert nmi(row, truth) > 0.9


def test_infomap_recovers_planted_partition():
    edges, truth = planted_partition(500, 10, 0.3, 0.005, seed=11)
    lab = native.infomap_labels(edges[:, 0], edges[:, 1], None, 500,
                                np.arange(3, dtype=np.uint64))
    for row in lab:
        assert nmi(row, truth) > 0.9


def test_infomap_weighted_graph_respects_weights():
    # two cliques bridged by a heavy edge: with tiny intra weights the
    # map equation should still split on the (structural) communities
    edges, truth = planted_partition(200, 4, 0.4, 0.01, seed=3)
    w = np.ones(edges.shape[0], dtype=np.float32)
    lab = native.infomap_labels(edges[:, 0], edges[:, 1], w, 200,
                                np.arange(2, dtype=np.uint64))
    assert nmi(lab[0], truth) > 0.9


def test_parser_matches_python_reader(tmp_path):
    p = tmp_path / "g.txt"
    p.write_text("# comment\n1 2\n2 3 0.5\n\n3 9\n")
    u, v, w = native.parse_edgelist(str(p))
    assert u.tolist() == [1, 2, 3]
    assert v.tolist() == [2, 3, 9]
    assert w is not None and w.tolist() == [1.0, 0.5, 1.0]


def test_parser_unweighted(tmp_path):
    p = tmp_path / "g.txt"
    p.write_text("0 1\n1 2\n")
    u, v, w = native.parse_edgelist(str(p))
    assert w is None
    assert u.tolist() == [0, 1]


def test_parser_agrees_with_io_on_karate():
    from fastconsensus_tpu.utils.io import read_edgelist

    path = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "karate_club.txt")
    edges, weights, ids = read_edgelist(path)
    assert edges.shape == (78, 2)
    assert len(ids) == 34


def test_detectors_are_seed_deterministic():
    edges, _ = planted_partition(300, 6, 0.3, 0.01, seed=2)
    s = np.array([42, 42], dtype=np.uint64)
    a = native.infomap_labels(edges[:, 0], edges[:, 1], None, 300, s)
    assert np.array_equal(a[0], a[1])
    b = native.cnm_labels(edges[:, 0], edges[:, 1], None, 300, s)
    assert np.array_equal(b[0], b[1])
