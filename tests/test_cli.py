"""CLI surface: flags, validation, output trees."""

import os

import numpy as np
import pytest

from fastconsensus_tpu.cli import DEFAULT_TAU, build_parser, check_arguments, main
from fastconsensus_tpu.utils.io import read_partition_file

KARATE = os.path.join(os.path.dirname(__file__), "..", "examples",
                      "karate_club.txt")


def test_default_tau_table_covers_all_algorithms():
    # leiden included explicitly (the reference omits it, fc:426-428)
    assert set(DEFAULT_TAU) == {"louvain", "lpm", "cnm", "infomap", "leiden"}


def test_validation_rejects_bad_ranges():
    p = build_parser()
    a = p.parse_args(["-f", "x", "-t", "2.0"])
    assert check_arguments(a) is not None
    a = p.parse_args(["-f", "x", "-t", "0.5", "-d", "-0.1"])
    assert check_arguments(a) is not None
    a = p.parse_args(["-f", "x", "-t", "0.5", "-np", "0"])
    assert check_arguments(a) is not None


def test_cli_bad_file_returns_2(tmp_path):
    rc = main(["-f", str(tmp_path / "missing.txt"), "--alg", "lpm"])
    assert rc == 2


def test_cli_end_to_end_lpm(tmp_path):
    rc = main(["-f", KARATE, "--alg", "lpm", "-np", "4", "-d", "0.1",
               "--seed", "1", "--out-dir", str(tmp_path), "--quiet"])
    assert rc == 0
    out = tmp_path / "out_partitions_t0.8_d0.1_np4"
    mem = tmp_path / "memberships_t0.8_d0.1_np4"
    assert out.is_dir() and mem.is_dir()
    files = sorted(os.listdir(out))
    assert files == ["1", "2", "3", "4"]
    # every partition covers all 34 nodes exactly once
    for f in files:
        comms = read_partition_file(str(out / f))
        nodes = sorted(n for c in comms for n in c)
        assert nodes == list(range(34))
    # membership format: node\tcomm, 1-indexed
    first = open(mem / "0").read().splitlines()
    assert len(first) == 34
    node, comm = first[0].split("\t")
    assert int(node) == 1 and int(comm) >= 1
