"""fcheck-contract suite: per-rule fixtures through lint_paths, the
template resolver/matcher, the shell lexer, the committed inventory
artifact + runtime cross-check, the README tables, and the
bench_report phantom-key fast-fail."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
INVENTORY = os.path.join(REPO, "runs", "contract_r19.json")


def _lint(name):
    from fastconsensus_tpu.analysis import Report, lint_paths

    return lint_paths([os.path.join(FIXTURES, name)], Report())


# -- fixture pairs: each rule fires on bad_, stays silent on ok_ ------

CONTRACT_FIXTURES = [
    # (bad, ok, rule, n_bad): the expected finding counts pin the
    # direction coverage — schema-drift fires both ways (phantom client
    # key + dropped emitter key), event-vocab both ways (unknown kind +
    # stale entry), doc-drift three ways (missing, stale, wrong kind)
    ("bad_phantom_reader.py", "ok_phantom_reader.py", "phantom-reader", 1),
    ("bad_schema_drift.py", "ok_schema_drift.py", "schema-drift", 2),
    ("bad_dead_counter.py", "ok_dead_counter.py", "dead-counter", 1),
    ("bad_event_vocab.py", "ok_event_vocab.py", "event-vocab", 2),
    ("bad_doc_drift.py", "ok_doc_drift.py", "doc-drift", 3),
]


@pytest.mark.parametrize("bad,ok,rule,n_bad", CONTRACT_FIXTURES,
                         ids=[r[2] for r in CONTRACT_FIXTURES])
def test_contract_rule_fires_on_bad_and_not_on_ok(bad, ok, rule, n_bad):
    report = _lint(bad)
    hits = [d for d in report.diagnostics if d.rule == rule]
    assert len(hits) == n_bad, [d.format() for d in report.diagnostics]
    ok_report = _lint(ok)
    assert not [d for d in ok_report.diagnostics if d.rule == rule], \
        [d.format() for d in ok_report.diagnostics]


def test_contract_spec_must_be_a_literal_dict(tmp_path):
    from fastconsensus_tpu.analysis.contracts import check_contracts

    p = tmp_path / "bad_spec.py"
    p.write_text("CONTRACT_SPEC = ['not-a-dict']\n")
    with pytest.raises(ValueError, match="must be a dict"):
        check_contracts({str(p): p.read_text()})
    p.write_text("CONTRACT_SPEC = {'rules': ['no-such-rule']}\n")
    with pytest.raises(ValueError, match="no-such-rule"):
        check_contracts({str(p): p.read_text()})
    p.write_text("CONTRACT_SPEC = {'surprise': 1}\n")
    with pytest.raises(ValueError, match="surprise"):
        check_contracts({str(p): p.read_text()})


# -- template resolution & matching -----------------------------------

def test_constant_propagation_resolves_serve_style_writers(tmp_path):
    """The write-site shapes the serve stack actually uses — f-string
    over a loop index, IfExp over two literals, a param default, and a
    module constant — all resolve to bounded templates."""
    from fastconsensus_tpu.analysis import contracts

    src = textwrap.dedent("""\
        PREFIX = "serve.pool"

        def tick(reg, klass="interactive"):
            for arm in ("met", "missed"):
                reg.inc(f"serve.slo.{klass}.{arm}")
            for i in range(4):
                reg.gauge(f"serve.device.{i}.jobs", i)
            reg.inc(PREFIX + ".spawns")
            reg.inc("a.b" if klass else "a.c")
        """)
    facts = contracts._scan_module("m.py", src)
    tpls = set(facts.metrics)
    assert "serve.slo.interactive.met" in tpls
    assert "serve.slo.interactive.missed" in tpls
    assert "serve.device.*.jobs" in tpls      # loop index -> wildcard
    assert "serve.pool.spawns" in tpls        # module-const prefix
    assert {"a.b", "a.c"} <= tpls             # IfExp union
    assert facts.metrics["serve.device.*.jobs"]["kind"] == "gauge"


def test_template_matching_is_segment_wise():
    from fastconsensus_tpu.analysis.contracts import template_matches

    assert template_matches("serve.device.*.jobs", "serve.device.3.jobs")
    assert not template_matches("serve.device.*.jobs", "serve.device.jobs")
    assert not template_matches("serve.device.*", "serve.device.3.jobs")
    # wildcard is segment-local: it never swallows a dot
    assert not template_matches("serve.*", "serve.cache.hit")
    # template-vs-template (a templated read against a templated write)
    assert template_matches("serve.slo.*.met", "serve.slo.*.met")
    assert template_matches("host_sync.*", "host_sync.barrier")


def test_dict_comprehension_and_subscript_store_emit_wire_keys():
    """Regression for the fcshape counters block: a dict comprehension
    over a literal tuple, and ``out[name] = ...`` with a loop-bound
    name, both declare wire keys (first triaged as false-positive
    phantoms of the real repo scan)."""
    from fastconsensus_tpu.analysis import contracts

    src = textwrap.dedent("""\
        def stats(counters):
            out = {name: counters.get(f"serve.shape.{name}", 0)
                   for name in ("holds", "bypass", "edf_promotions")}
            for extra in ("deadline_sheds",):
                out[extra] = 0
            return out
        """)
    facts = contracts._scan_module("m.py", src)
    assert {"holds", "bypass", "edf_promotions",
            "deadline_sheds"} <= set(facts.wire_keys)
    # ...and the f-string reads resolved to real metric names
    assert ("serve.shape.holds", 2) in facts.reads


def test_module_vocabulary_tuple_declares_wire_keys():
    """PHASE_STAMPS-style nested (name, stamp) tuples declare the plain
    keys their consumers build dicts from."""
    from fastconsensus_tpu.analysis import contracts

    src = textwrap.dedent("""\
        PHASES = (("queue_wait", "t_admit"), ("device", "t_start"))
        """)
    facts = contracts._scan_module("m.py", src)
    assert {"queue_wait", "t_admit", "device",
            "t_start"} <= set(facts.wire_keys)


# -- the shell lexer (scripts/ci_check.sh reader inventory) -----------

def test_shell_lexer_heredocs_quotes_and_comments():
    from fastconsensus_tpu.analysis.contracts import _scan_shell

    src = textwrap.dedent("""\
        grep -q "serve.cache.hit" out.log
        python - <<'PYEOF'
        m = snapshot()
        x = m.get("serve.queue.depth", 0)
        PYEOF
        echo done  # a comment quoting "serve.not.a.read"
        cp artifact runs/bench_r9.json
        """)
    reads = dict(_scan_shell(src))
    assert "serve.cache.hit" in reads
    assert "serve.queue.depth" in reads          # heredoc parsed as python
    assert "serve.not.a.read" not in reads       # trailing comment stripped
    assert not any(n.endswith(".json") for n in reads)  # file names skipped


# -- repo mode: the acceptance gate, jax-free --------------------------

def test_repo_contract_gate_is_clean_with_jax_poisoned():
    """ISSUE 14 acceptance: the five contract rules over the live repo
    exit 0 in a process where any jax import raises."""
    code = (
        "import sys; sys.modules['jax'] = None; "
        "from fastconsensus_tpu.analysis.__main__ import main; "
        "sys.exit(main(['fastconsensus_tpu/', '--no-jaxpr', '--quiet', "
        "'--only', 'phantom-reader,schema-drift,dead-counter,"
        "event-vocab,doc-drift']))")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_gate_module_pragmas_keep_phantom_reads_for_clean():
    """The two deliberate external-schema reads (obs/history.py VMESH
    wrapper shapes) are pragma'd — the bench_report fast-fail helper
    must honor those pragmas and report nothing on the live gate."""
    from fastconsensus_tpu.analysis import contracts

    path = os.path.join(REPO, "fastconsensus_tpu", "obs", "history.py")
    assert contracts.phantom_reads_for(path, INVENTORY) == []


def test_phantom_reads_for_detects_and_suppresses(tmp_path):
    from fastconsensus_tpu.analysis import contracts

    gate = tmp_path / "gate.py"
    gate.write_text(textwrap.dedent("""\
        def check(counters):
            a = counters.get("serve.cache.hit", 0)
            b = counters.get("serve.cache.hitz", 0)
            return a + b
        """))
    assert [n for n, _ in
            contracts.phantom_reads_for(str(gate), INVENTORY)] == \
        ["serve.cache.hitz"]
    gate.write_text(textwrap.dedent("""\
        def check(counters):
            a = counters.get("serve.cache.hit", 0)
            # fcheck: ok=phantom-reader (external artifact schema)
            b = counters.get("serve.cache.hitz", 0)
            return a + b
        """))
    assert contracts.phantom_reads_for(str(gate), INVENTORY) == []


# -- the committed inventory artifact ---------------------------------

def test_committed_inventory_schema_and_coverage():
    from fastconsensus_tpu.analysis import contracts

    inv = contracts.load_inventory(INVENTORY)
    assert inv["version"] == contracts.INVENTORY_VERSION
    assert inv["rules"] == sorted(contracts.CONTRACT_RULES)
    names = {m["name"] for m in inv["metrics"]}
    # anchors across the serve/obs surface, including wildcard templates
    assert "serve.cache.hit" in names
    assert "serve.device.*.jobs" in names
    assert "serve.slo.*.met" in names
    for m in inv["metrics"]:
        assert m["writers"], m  # every metric names its write sites
        assert not m["writers"][0].startswith("/"), "paths must be repo-relative"
    assert set(inv["events"]) <= set(inv["event_vocab"])
    assert "watchdog_trip" in inv["event_vocab"]
    assert inv["readers"]["gate"] and inv["readers"]["client"]


def test_event_kinds_vocabulary_matches_flight_module():
    from fastconsensus_tpu.analysis import contracts
    from fastconsensus_tpu.obs import flight

    inv = contracts.load_inventory(INVENTORY)
    assert sorted(flight.EVENT_KINDS) == inv["event_vocab"]


def test_assert_covered_accepts_known_and_names_strays():
    from fastconsensus_tpu.analysis import contracts

    snapshot = {
        "fcobs": {"counters": {"serve.cache.hit": 3,
                               "serve.slo.interactive.met": 1},
                  "gauges": {"serve.queue.depth": 0}, "series": {}},
        "latency": {"histograms": [{"name": "serve.e2e"}],
                    "arrivals": {}, "dispatches": {}},
    }
    assert contracts.assert_covered(snapshot, INVENTORY) == 4
    stray = {"fcobs": {"counters": {"serve.cache.hit": 1,
                                    "serve.totally.unknown": 2}}}
    assert contracts.uncovered(stray, INVENTORY) == \
        ["serve.totally.unknown"]
    with pytest.raises(AssertionError, match="serve.totally.unknown"):
        contracts.assert_covered(stray, INVENTORY)


def test_load_inventory_rejects_foreign_artifacts(tmp_path):
    from fastconsensus_tpu.analysis import contracts

    p = tmp_path / "other.json"
    p.write_text(json.dumps({"tool": "something-else"}))
    with pytest.raises(ValueError, match="not a fcheck-contract"):
        contracts.load_inventory(str(p))


# -- README tables (the doc-drift triage finds, pinned) ----------------

def test_readme_rule_table_documents_every_rule_id():
    """Triage regression: the README table documented the retired
    ``jaxpr-huge-gather`` id and missed ``syntax-error`` /
    ``trace-error`` entirely — every id in the analyzer vocabulary must
    have a row, under its real name."""
    from fastconsensus_tpu.analysis import contracts

    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as fh:
        facts = contracts._scan_readme(fh.read())
    missing = contracts._rule_universe() - facts["rule_ids"]
    assert not missing, f"README rule table missing: {sorted(missing)}"
    assert "jaxpr-huge-gather" not in facts["rule_ids"]


def test_readme_counters_appendix_matches_committed_inventory():
    """The appendix between the fcheck-contract markers is generated
    from the inventory — both are committed, so they must agree exactly
    (CI regenerates the inventory itself; this pins the render)."""
    from fastconsensus_tpu.analysis import contracts

    inv = contracts.load_inventory(INVENTORY)
    rendered = contracts.render_counters_appendix(inv).strip()
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as fh:
        readme = fh.read()
    section = readme.split(contracts.APPENDIX_BEGIN, 1)[1] \
                    .split(contracts.APPENDIX_END, 1)[0].strip()
    assert section == rendered


# -- bench_report --check fast-fail -----------------------------------

def _run_bench_report(*extra):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_report.py"),
         "--check", "--quiet", *extra],
        cwd=REPO, capture_output=True, text=True, timeout=120)


def test_bench_report_check_passes_with_committed_inventory():
    proc = _run_bench_report()
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_bench_report_check_fast_fails_on_phantom_gate_keys(tmp_path):
    """With an inventory that knows no writers, every gate key is a
    phantom: the gate must refuse to judge (exit 2) naming them,
    instead of running vacuously green."""
    from fastconsensus_tpu.analysis import contracts

    stripped = {"tool": contracts.INVENTORY_TOOL,
                "version": contracts.INVENTORY_VERSION,
                "rules": sorted(contracts.CONTRACT_RULES),
                "metrics": [], "wire_keys": [], "events": [],
                "event_vocab": [], "readers": {"gate": [], "client": []}}
    p = tmp_path / "contract_stripped.json"
    p.write_text(json.dumps(stripped))
    proc = _run_bench_report("--inventory", str(p))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "PHANTOM" in proc.stderr
    assert "history.py" in proc.stderr


def test_bench_report_check_skips_on_missing_inventory(tmp_path):
    proc = _run_bench_report("--inventory",
                             str(tmp_path / "nope.json"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "skipping the phantom-key check" in proc.stderr
