"""fcheck-cost suite: the eqn-level cost visitor on hand-computed
jaxprs, the jax-free ladder mirror vs the traced visitor, the three
cost rules + their fixture postures, the committed cost artifact, the
history trend/calibration gates, and the runtime feedback paths (the
shaper/429 prior seeding and the cost-weighted sticky spill)."""

import json
import os
import subprocess
import sys

import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COST_ARTIFACT = os.path.join(REPO, "runs", "cost_r16.json")
SERVE_LOAD = os.path.join(REPO, "runs", "bench_serve_load_r10.json")
QUALITY = os.path.join(REPO, "runs", "bench_lfr1k_quality_r12.json")


# -- jax-free half: posture mirrors, the closed-form ladder mirror ----


def test_cost_spec_mirrors_serve_defaults():
    """Same contract as footprint.SurfaceSpec: the default posture the
    cost pass prices must be the one ServeConfig actually serves, and
    the sweep bound baked into the mirror coefficients must be the one
    the kernels enforce."""
    import inspect

    from fastconsensus_tpu.analysis import cost
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.models import louvain
    from fastconsensus_tpu.serve.server import ServeConfig

    spec, cfg = cost.CostSpec(), ServeConfig()
    assert spec.max_nodes == cfg.max_nodes
    assert spec.max_edges == cfg.max_edges
    assert spec.max_batch == cfg.max_batch
    assert spec.n_p == ConsensusConfig().n_p
    for fn in (louvain.local_move, louvain.modularity_levels):
        sig = inspect.signature(fn)
        assert sig.parameters["max_sweeps"].default == cost.MAX_SWEEPS


def test_frontier_series_matches_committed_quality_artifact():
    """The dead-compute bill prices the measured lfr1k frontier decay,
    not an invented one: the default series is the committed fcqual
    telemetry, verbatim."""
    from fastconsensus_tpu.analysis import cost

    with open(QUALITY, encoding="utf-8") as fh:
        doc = json.load(fh)
    series = doc["telemetry"]["quality"]["frontier_frac_by_round"]
    assert tuple(series) == cost.FRONTIER_SERIES_DEFAULT


def test_mirror_cost_shapes_and_mode_suffix():
    from fastconsensus_tpu.analysis import cost

    solo = cost.mirror_cost("rounds", 64, 96, b=1, n_p=4)
    assert solo["flops"] > 0 and solo["hbm_bytes"] > 0
    # warm/cold/scratch share one traced program: the suffix never
    # changes the modeled cost
    assert cost.mirror_cost("rounds[warm]", 64, 96, n_p=4) == \
        cost.mirror_cost("rounds[scratch]", 64, 96, n_p=4)
    # linear in ensemble width and batch rung
    p8 = cost.mirror_cost("rounds", 64, 96, b=1, n_p=8)
    assert p8["flops"] == pytest.approx(2 * solo["flops"])
    b2 = cost.mirror_cost("batch", 64, 96, b=2, n_p=4)
    assert b2["flops"] == pytest.approx(2 * solo["flops"])
    with pytest.raises(ValueError, match="unknown surface kind"):
        cost.mirror_cost("nonsense", 64, 96)


def test_static_prior_and_spill_weight():
    from fastconsensus_tpu.analysis import cost

    # the prior is exactly the mirrored solo rounds roofline
    assert cost.static_service_prior("n64_e96", n_p=4) == \
        pytest.approx(cost.mirror_est_s("rounds", 64, 96, b=1, n_p=4))
    # non-ladder keys (group keys, mesh tags, junk) have no prior
    for key in ("b", "unseen", "mesh:n64", "n64e96", "", None):
        assert cost.static_service_prior(key) is None
        assert cost.spill_weight(key) == 1.0
    # interactive buckets keep weight 1.0 — identical routing to the
    # unweighted era (the fcpool CI smoke pins this)
    assert cost.spill_weight("n64_e96") == 1.0
    assert cost.spill_weight("n128_e192") == 1.0
    # minute-scale buckets clamp to the cap and spill early
    assert cost.spill_weight("n1024_e1536") == cost.SPILL_WEIGHT_MAX
    for key in ("n64_e96", "n512_e1024", "n4096_e8192"):
        w = cost.spill_weight(key)
        assert 1.0 <= w <= cost.SPILL_WEIGHT_MAX


def test_dead_compute_bill_hand_math():
    """The bill is pure arithmetic over the committed frontier series:
    dead fraction per round = 1 - frontier_frac, run fraction = the
    mean, late = the mean of the second half."""
    from fastconsensus_tpu.analysis import cost

    spec = cost.CostSpec()
    bill = cost.dead_compute_bill(spec)
    series = spec.frontier_series
    assert bill["bucket"] == "n1024_e6144" and bill["n_p"] == 20
    assert bill["rounds"] == len(series)
    expect_run = sum(1.0 - f for f in series) / len(series)
    assert bill["run_dead_frac"] == pytest.approx(expect_run, abs=1e-6)
    late = [1.0 - f for f in series[len(series) // 2:]]
    assert bill["late_round_dead_frac"] == \
        pytest.approx(sum(late) / len(late), abs=1e-6)
    rf = cost.mirror_cost("rounds", 1024, 6144, b=1, n_p=20)["flops"]
    assert bill["round_flops"] == int(rf)
    for row, frac in zip(bill["per_round"], series):
        assert row["dead_flops"] == int(rf * (1.0 - frac))


def test_cost_rules_fire_and_stay_silent():
    from fastconsensus_tpu.analysis import cost

    # dead-compute: default budget holds, a tightened one fires
    assert not cost.check_dead_compute(cost.CostSpec())[0]
    diags, bill = cost.check_dead_compute(
        cost.CostSpec(waste_budget=0.25))
    assert len(diags) == 1 and diags[0].rule == "cost-dead-compute"
    assert f"{bill['run_dead_frac']:.2f}" in diags[0].message
    # duality: batching always amortizes dispatch, so the 0.0 floor
    # holds; an absurd floor fires once (one finding prices the posture)
    diags, rows = cost.check_duality(cost.CostSpec())
    assert not diags and rows
    assert all(r["per_job_saving_frac"] >= 0.0 for r in rows)
    diags, _ = cost.check_duality(cost.CostSpec(duality_min_saving=0.9))
    assert len(diags) == 1 and diags[0].rule == "cost-duality"
    # roofline regress: a stale baseline fires, a generous one holds
    fired = cost.check_regress(cost.CostSpec(
        baseline={"rounds[warm]@n64_e96": 0.001}))
    assert len(fired) == 1 and fired[0].rule == "cost-roofline-regress"
    assert not cost.check_regress(cost.CostSpec(
        baseline={"rounds[warm]@n64_e96": 1.0}))
    with pytest.raises(ValueError, match="kind@n"):
        cost.check_regress(cost.CostSpec(baseline={"junk": 1.0}))


def test_fixture_specs_fire_their_rule_only():
    """The bad_/ok_ COST_SPEC fixtures drive each rule in isolation
    through the same evaluate() path the CLI uses."""
    from fastconsensus_tpu.analysis import cost

    def run(name):
        specs = cost.find_specs([os.path.join(FIXTURES, name)])
        assert len(specs) == 1, name
        diags, _ = cost.evaluate(specs[0])
        return {d.rule for d in diags}

    assert run("bad_cost_waste.py") == {"cost-dead-compute"}
    assert run("ok_cost_waste.py") == set()
    assert run("bad_cost_duality.py") == {"cost-duality"}
    assert run("ok_cost_duality.py") == set()
    assert run("bad_cost_regress.py") == {"cost-roofline-regress"}
    assert run("ok_cost_regress.py") == set()


def test_find_specs_rejects_junk(tmp_path):
    from fastconsensus_tpu.analysis import cost

    (tmp_path / "bad.py").write_text("COST_SPEC = {'no_such': 1}\n")
    with pytest.raises(ValueError, match="no_such"):
        cost.find_specs([str(tmp_path)])
    (tmp_path / "bad.py").write_text(
        "COST_SPEC = {'rules': ['surface-count']}\n")
    with pytest.raises(ValueError, match="not cost rules"):
        cost.find_specs([str(tmp_path)])
    (tmp_path / "bad.py").write_text("COST_SPEC = {'baseline': 3}\n")
    with pytest.raises(ValueError, match="baseline"):
        cost.find_specs([str(tmp_path)])


def test_cost_rules_jax_free_subprocess():
    """ISSUE 16 acceptance: the three cost rules over the live repo in
    a process where any jax import raises — exit 0 clean, and a
    tightened waste budget fires the dead-compute bill (exit 1)."""
    def run(extra):
        code = (
            "import sys; sys.modules['jax'] = None; "
            "from fastconsensus_tpu.analysis.__main__ import main; "
            "sys.exit(main(['fastconsensus_tpu/', '--no-jaxpr', "
            "'--only', 'cost-dead-compute,cost-duality,"
            "cost-roofline-regress'] + %r))" % (extra,))
        return subprocess.run([sys.executable, "-c", code], cwd=REPO,
                              capture_output=True, text=True,
                              timeout=300)

    proc = run(["--quiet"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = run(["--waste-budget", "0.1"])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "[cost-dead-compute]" in proc.stdout


# -- the eqn-level visitor on hand-computed jaxprs --------------------


def test_eqn_cost_dot_general_hand_computed():
    """(8,16) @ (16,4): 2*M*N*K = 1024 flops; bytes = operands +
    result = (128 + 64 + 32) * 4."""
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.analysis.cost import eqn_cost

    closed = jax.make_jaxpr(lambda a, b: a @ b)(
        jax.ShapeDtypeStruct((8, 16), jnp.float32),
        jax.ShapeDtypeStruct((16, 4), jnp.float32))
    c = eqn_cost(closed)
    assert c["flops"] == 2 * 8 * 4 * 16
    assert c["hbm_bytes"] == (8 * 16 + 16 * 4 + 8 * 4) * 4


def test_eqn_cost_scatter_add_counts_updates():
    """Scatter-add prices one combine op per UPDATE element, never per
    operand slot: 4 updates into a 32-slot operand is 4 scatter flops
    plus the jnp negative-index wrap (lt + add over the 4 indices,
    select_n is movement) = 12 total — not 32+."""
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.analysis.cost import eqn_cost

    closed = jax.make_jaxpr(lambda x, i, u: x.at[i].add(u))(
        jax.ShapeDtypeStruct((32,), jnp.float32),
        jax.ShapeDtypeStruct((4,), jnp.int32),
        jax.ShapeDtypeStruct((4,), jnp.float32))
    assert eqn_cost(closed)["flops"] == 4 + 4 + 4


def test_eqn_cost_while_prices_the_sweep_budget():
    """A data-dependent while is priced at the budget the kernel
    enforces: bound x (cond + body) — here 1 flop each, so exactly
    2 * bound, linear in the bound."""
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.analysis.cost import eqn_cost

    closed = jax.make_jaxpr(lambda x: jax.lax.while_loop(
        lambda c: c < 10.0, lambda c: c + 1.0, x))(
        jax.ShapeDtypeStruct((), jnp.float32))
    assert eqn_cost(closed, while_bound=7)["flops"] == 14.0
    assert eqn_cost(closed, while_bound=14)["hbm_bytes"] == \
        2 * eqn_cost(closed, while_bound=7)["hbm_bytes"]


def test_eqn_cost_scan_prices_length_times_body():
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.analysis.cost import eqn_cost

    def f(x, xs):
        return jax.lax.scan(lambda c, v: (c + v, c * v), x, xs)

    closed = jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((9,), jnp.float32))
    assert eqn_cost(closed)["flops"] == 9 * 2


def test_eqn_cost_movement_is_bytes_only():
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.analysis.cost import eqn_cost

    closed = jax.make_jaxpr(lambda x: x.reshape(4, 8).T)(
        jax.ShapeDtypeStruct((32,), jnp.float32))
    c = eqn_cost(closed)
    assert c["flops"] == 0.0 and c["hbm_bytes"] > 0


# -- traced half: mirror band, the report block -----------------------


def test_mirror_tracks_traced_visitor_within_band():
    """The closed-form coefficients are least-squares fits of the
    traced visitor; at the ladder floor they must sit within a tight
    ratio band of the real trace (the pre-commit hook prices postures
    with the mirror alone)."""
    from fastconsensus_tpu.analysis import cost

    spec = cost.CostSpec(max_nodes=256, max_edges=512, max_batch=2,
                         n_p=4)
    traced = cost._trace_cost("rounds", 64, 96, 1, "warm", spec)
    mirror = cost.mirror_cost("rounds", 64, 96, b=1, n_p=4)
    assert mirror["flops"] == pytest.approx(traced["flops"], rel=0.25)
    assert mirror["hbm_bytes"] == \
        pytest.approx(traced["hbm_bytes"], rel=0.25)


def test_evaluate_block_schema():
    """The cost block the --json report and the runs/cost_rNN.json
    artifact carry (the documented schema scripts/bench_report.py
    consumes)."""
    from fastconsensus_tpu.analysis import cost

    spec = cost.CostSpec(max_nodes=256, max_edges=512, max_batch=2,
                         n_p=4)
    diags, block = cost.evaluate(spec, with_table=True)
    assert not diags
    assert block["tool"] == "fcheck-cost" and block["version"] == 1
    assert block["dead_compute"]["run_dead_frac"] > 0
    assert block["duality"] and block["gate"] and block["buckets"]
    for row in block["gate"]:
        assert set(row) >= {"kind", "bucket", "batch", "flops",
                            "hbm_bytes", "arith_intensity",
                            "est_device_s"}
        assert row["est_device_s"] > 0
    cal = block["calibration"]
    assert cal["bucket"] == "n64_e96" and cal["est_device_ms"] > 0
    # jax-free selection never touches the traced half
    d2, b2 = cost.evaluate(cost.CostSpec(), rules=list(cost.COST_RULES))
    assert not d2
    assert b2["gate"] == [] and b2["buckets"] == []
    assert b2["calibration"] is None


# -- the committed artifact + history gates ---------------------------


def test_committed_cost_artifact_is_consistent():
    """runs/cost_r16.json is the mirror's own output: the dead-compute
    bill re-derives exactly, the lfr1k late rounds are majority-dead
    (the ISSUE 16 headline), and the artifact passes its own budget."""
    from fastconsensus_tpu.analysis import cost

    with open(COST_ARTIFACT, encoding="utf-8") as fh:
        doc = json.load(fh)
    assert doc["tool"] == "fcheck-cost" and doc["version"] == 1
    bill = cost.dead_compute_bill(cost.CostSpec())
    assert doc["dead_compute"] == bill
    assert doc["dead_compute"]["late_round_dead_frac"] >= 0.5
    assert doc["dead_compute"]["run_dead_frac"] <= \
        doc["dead_compute"]["waste_budget"]
    assert doc["duality"] == cost.duality_table(cost.CostSpec())
    assert doc["gate"] and doc["calibration"]


def test_history_cost_trend_and_regress_gate(tmp_path):
    from fastconsensus_tpu.obs import history

    with open(COST_ARTIFACT, encoding="utf-8") as fh:
        doc = json.load(fh)
    a = tmp_path / "cost_r16.json"
    a.write_text(json.dumps(doc))
    junk = tmp_path / "cost_rX.json"
    junk.write_text('{"tool": "something-else"}')
    # a stable successor passes
    b = tmp_path / "cost_r17.json"
    b.write_text(json.dumps(doc))
    costs = history.load_costs([str(b), str(junk), str(a)])
    assert [c["seq"] for c in costs] == [16, 17]
    table = history.cost_table(costs, markdown=False)
    assert "fcheck-cost trend" in table and "cost duality" in table
    assert history.check_costs(costs) == []
    # a 10x roofline blowup in the newest artifact fires per row
    worse = json.loads(json.dumps(doc))
    for g in worse["gate"]:
        g["est_device_s"] = g["est_device_s"] * 10.0
    b.write_text(json.dumps(worse))
    probs = history.check_costs(history.load_costs([str(a), str(b)]))
    assert probs and all("cost-roofline-regress" in p for p in probs)
    # a dead-compute bill over its own pinned budget fires too
    breach = json.loads(json.dumps(doc))
    breach["dead_compute"]["waste_budget"] = 0.1
    b.write_text(json.dumps(breach))
    probs = history.check_costs(history.load_costs([str(a), str(b)]))
    assert any("cost-dead-compute" in p for p in probs)


def test_calibration_gate_vs_committed_serve_load(tmp_path):
    """The model's honesty gate: the committed artifact's predicted
    device time for the serve_load reference executable lands within
    the band of the measured committed curve — and a drifted model is
    named."""
    from fastconsensus_tpu.obs import history

    costs = history.load_costs([COST_ARTIFACT])
    groups = history.build_history([SERVE_LOAD])
    assert history.check_cost_calibration(costs, groups) == []
    # a model off by 100x is outside any honest band
    with open(COST_ARTIFACT, encoding="utf-8") as fh:
        doc = json.load(fh)
    doc["calibration"]["est_device_ms"] *= 100.0
    drifted = tmp_path / "cost_r17.json"
    drifted.write_text(json.dumps(doc))
    probs = history.check_cost_calibration(
        history.load_costs([str(drifted)]), groups)
    assert len(probs) == 1 and "calibration drift" in probs[0]


# -- runtime feedback: prior-seeded shaping, cost-weighted spill ------


def _fresh_lat():
    from fastconsensus_tpu.obs.latency import LatencyRegistry

    return LatencyRegistry()


def _shaper(lat=None, **kw):
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.shaping import (ShapingConfig,
                                                 TrafficShaper)

    cfg_over = {k: v for k, v in kw.items() if k != "cost_prior"}
    return TrafficShaper(ShapingConfig(**cfg_over),
                         lat=lat if lat is not None else _fresh_lat(),
                         reg=obs_counters.get_registry(),
                         cost_prior=kw.get("cost_prior"))


def test_latency_service_estimate_accepts_prior():
    from fastconsensus_tpu.obs.latency import LatencyRegistry

    lat = LatencyRegistry()
    assert lat.service_estimate("b") is None
    est = lat.service_estimate("b", prior=0.05)
    assert est == {"count": 0, "mean_s": 0.05, "p95_s": 0.05,
                   "prior": True}
    # any measured history beats the model
    for phase in ("pack", "device", "fanout"):
        lat.hist(f"serve.phase.{phase}", bucket="b", rung=1).record(0.01)
    est = lat.service_estimate("b", prior=9.9)
    assert est["count"] == 1 and not est.get("prior")


def test_shaper_cold_bucket_consumes_static_prior():
    """ISSUE 16 acceptance: a cold ladder bucket's Retry-After and shed
    decision derive from the static cost prior instead of the 1.0 s
    constant, and serve.shape.prior_seeded counts the bucket once."""
    from fastconsensus_tpu.analysis import cost
    from fastconsensus_tpu.obs import counters as obs_counters

    reg = obs_counters.get_registry()
    base = reg.counters()
    sh = _shaper(lat=_fresh_lat())          # real default prior
    prior = cost.static_service_prior("n64_e96")
    # retry: depth x prior / workers, not retry_after_default_s
    assert sh.retry_after_s(10, "n64_e96") == \
        pytest.approx(10 * prior, rel=1e-6)
    # shed: 50 queued jobs at ~52 ms each provably miss a 1 ms deadline
    import time as _time
    now = _time.monotonic()
    reason = sh.should_shed("n64_e96", now + 0.001, depth=50, now=now)
    assert reason is not None and "deadline shed" in reason
    # ...while a generous deadline still admits
    assert sh.should_shed("n64_e96", now + 60.0, depth=50,
                          now=now) is None
    # the counter counts buckets, not lookups
    sh.retry_after_s(10, "n64_e96")
    since = reg.counters_since(base)
    assert since.get("serve.shape.prior_seeded", 0) == 1
    assert "prior_seeded" in sh.describe()["counters"]


def test_shaper_disabled_prior_restores_cold_defaults():
    """lambda b: None disables seeding outright: the pre-prior cold
    behavior (constant Retry-After, never shed) is one injection away."""
    sh = _shaper(lat=_fresh_lat(), cost_prior=lambda b: None)
    assert sh.retry_after_s(10, "n64_e96") == 1.0
    import time as _time
    now = _time.monotonic()
    assert sh.should_shed("n64_e96", now + 0.001, depth=50,
                          now=now) is None
    # an injected model is consumed verbatim
    sh2 = _shaper(lat=_fresh_lat(), cost_prior=lambda b: 0.2)
    assert sh2.retry_after_s(10, "anything") == pytest.approx(2.0)
    # a throwing prior means "no prior", never a broken admission path
    def boom(bucket):
        raise RuntimeError("broken analyzer")
    sh3 = _shaper(lat=_fresh_lat(), cost_prior=boom)
    assert sh3.retry_after_s(10, "n64_e96") == 1.0


def test_scheduler_weights_backlog_by_cost():
    """A queued minute-scale job must weigh its drain time: with weight
    8 a single queued job spills off the home; unit weight preserves
    the sticky era exactly."""
    from fastconsensus_tpu.serve.scheduler import StickyScheduler

    class W:
        def __init__(self, idx, load=0):
            self.idx, self._load = idx, load

        def eligible(self, exclude=frozenset()):
            return self.idx not in exclude

        def load(self):
            return self._load

        def is_warm(self, bucket):
            return False

    heavy = StickyScheduler(spill_backlog=2, cost_weight=lambda b: 8.0)
    ws = [W(0), W(1)]
    assert heavy.route("n1024_e1536", ws).idx == 0      # mints home
    ws[0]._load = 1
    # 1 queued job x weight 8 > backlog 2: spill where unweighted stuck
    assert heavy.route("n1024_e1536", ws).idx == 1
    unit = StickyScheduler(spill_backlog=2, cost_weight=lambda b: 1.0)
    assert unit.route("n64_e96", ws).idx == 1           # least loaded
    ws[1]._load = 2
    assert unit.route("n64_e96", ws).idx == 1           # sticky at 2x1
    # a throwing weight degrades to the unweighted era
    bad = StickyScheduler(spill_backlog=2,
                          cost_weight=lambda b: 1 / 0)
    ws[0]._load, ws[1]._load = 0, 0
    assert bad.route("n64_e96", ws).idx == 0
    ws[0]._load = 2
    assert bad.route("n64_e96", ws).idx == 0            # sticky at 2

def test_pool_wires_real_spill_weight():
    from fastconsensus_tpu.serve import pool as pool_mod

    fn = pool_mod._cost_spill_weight()
    assert fn is not None
    assert fn("n64_e96") == 1.0
    assert fn("n1024_e1536") > 1.0
