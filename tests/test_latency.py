"""fclat request-lifecycle latency layer (obs/latency.py + the serve
phase timeline): log2-histogram exactness and the cross-worker merge
property, window-truncation stamping in obs/counters.py, monotonic
phase math on Jobs, SLO classes, and the loopback phase-sum/e2e
consistency pin."""

import json
import math
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from fastconsensus_tpu.obs import latency


# -- the log2 histogram ------------------------------------------------


def test_bucket_index_boundaries():
    # exact powers of two land in the bucket whose UPPER edge they are
    assert latency.bucket_index(1.0) == -latency.MIN_EXP  # 2^0 bucket
    assert latency.bucket_edge(latency.bucket_index(1.0)) == 1.0
    assert latency.bucket_edge(latency.bucket_index(0.5)) == 0.5
    # one past an edge spills into the next bucket
    assert latency.bucket_index(1.0001) == latency.bucket_index(2.0)
    # underflow and overflow clamp to the end buckets
    assert latency.bucket_index(0.0) == 0
    assert latency.bucket_index(1e-12) == 0
    assert latency.bucket_edge(latency.bucket_index(1e9)) == math.inf


def test_histogram_counts_sums_and_quantiles():
    h = latency.LatencyHistogram()
    values = [0.001, 0.002, 0.004, 0.1, 0.5, 1.5]
    for v in values:
        h.record(v)
    s = h.snapshot()
    assert s["count"] == 6
    assert s["sum_s"] == pytest.approx(sum(values))
    assert s["min_s"] == 0.001 and s["max_s"] == 1.5
    # quantiles are bucket upper edges: conservative, never below the
    # true value, within 2x of it, and clamped to the exact max
    assert s["p50_s"] >= 0.004 and s["p50_s"] <= 0.008
    assert s["p99_s"] == 1.5
    # empty histogram has no quantiles
    assert latency.LatencyHistogram().snapshot()["p95_s"] is None


def test_exact_merge_across_four_concurrent_writers():
    """The merge contract: 4 threads each record into their OWN
    histogram and into one SHARED histogram concurrently; merging the
    four snapshots must reproduce the shared histogram's buckets,
    count, and quantiles exactly (sums up to float addition order)."""
    shared = latency.LatencyHistogram()
    own = [latency.LatencyHistogram() for _ in range(4)]
    rngs = [np.random.default_rng(seed) for seed in range(4)]

    def writer(i):
        for _ in range(2000):
            v = float(rngs[i].lognormal(mean=-5.0, sigma=2.0))
            own[i].record(v)
            shared.record(v)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    merged = latency.merge_snapshots([h.snapshot() for h in own])
    ref = shared.snapshot()
    assert merged["count"] == ref["count"] == 8000
    assert merged["buckets"] == ref["buckets"]
    assert merged["min_s"] == ref["min_s"]
    assert merged["max_s"] == ref["max_s"]
    for q in ("p50_s", "p95_s", "p99_s"):
        assert merged[q] == ref[q], q
    assert merged["sum_s"] == pytest.approx(ref["sum_s"])


def test_diff_snapshots_attributes_a_window():
    """diff is merge's inverse: (before, after) snapshots of one
    histogram yield the histogram of exactly the samples recorded
    between them — the per-RPS-point attribution bench.py serve_load
    uses against the process-global registry."""
    h = latency.LatencyHistogram()
    for v in (0.001, 0.002):
        h.record(v)
    before = h.snapshot()
    for v in (0.5, 1.5, 3.0):
        h.record(v)
    window = latency.diff_snapshots(h.snapshot(), before)
    assert window["count"] == 3
    assert window["sum_s"] == pytest.approx(5.0)
    assert window["p50_s"] >= 0.5        # none of the small pre-window
    w2 = latency.LatencyHistogram()      # samples leak in
    for v in (0.5, 1.5, 3.0):
        w2.record(v)
    assert window["buckets"] == w2.snapshot()["buckets"]


def test_registry_tags_and_text_exposition():
    reg = latency.LatencyRegistry()
    reg.hist("serve.phase.device", bucket="n64_e96", rung=2,
             priority=1, device=0).record(0.03)
    reg.hist("serve.phase.device", bucket="n64_e96", rung=1,
             priority=1, device=0).record(0.01)
    # same (name, tags) -> the same histogram
    assert reg.hist("serve.phase.device", bucket="n64_e96", rung=2,
                    priority=1, device=0) is reg.hist(
        "serve.phase.device", device=0, priority=1, rung=2,
        bucket="n64_e96")
    snap = reg.snapshot()
    assert len(snap["histograms"]) == 2
    text = latency.render_text(snap)
    line = next(ln for ln in text.splitlines() if "rung=2" in ln)
    assert line.startswith("serve.phase.device{")
    assert "bucket=n64_e96" in line and "count=1" in line
    assert "p95=0.03" in line


def test_rate_tracker_windows_and_decay():
    tr = latency.RateTracker()
    for i in range(5):
        tr.mark("n64_e96", at=float(i))      # 1 arrival/s
    rates = tr.rates(now=4.0)["n64_e96"]
    assert rates["count"] == 5 and rates["window"] == 5
    assert rates["rate_per_s"] == pytest.approx(1.0)
    # a bucket whose traffic STOPPED must decay toward zero (the
    # hold-for-coalesce consumer would otherwise hold jobs for phantom
    # ride-alongs forever), not report the burst rate indefinitely
    stale = tr.rates(now=4000.0)["n64_e96"]
    assert stale["rate_per_s"] == pytest.approx(4 / 4000.0)
    tr.mark("lonely", at=0.0)
    assert tr.rates(now=10.0)["lonely"]["rate_per_s"] == 0.0


# -- the counters window footgun (satellite) ---------------------------


def test_series_window_truncation_is_stamped():
    """A summary over a set_series_limit-truncated series must SAY it
    describes the recent window (window_truncated + dropped), not
    present window stats as run totals."""
    from fastconsensus_tpu.obs.counters import ObsRegistry

    reg = ObsRegistry()
    for i in range(10):
        reg.observe("s", float(i))
    assert "window_truncated" not in reg.summary("s")
    reg.set_series_limit(4)                  # retroactive trim: 6 drop
    s = reg.summary("s")
    assert s["window_truncated"] is True and s["dropped"] == 6
    reg.observe("s", 10.0)                   # steady-state: 1 more
    s = reg.summary("s")
    assert s["dropped"] == 7 and s["count"] == 4
    assert reg.snapshot()["series"]["s"]["window_truncated"] is True
    reg.reset()
    reg.observe("s", 1.0)
    assert "window_truncated" not in reg.summary("s")


# -- Job phase math (monotonic, not wall clock) ------------------------


def _job(monkeypatch=None, **spec_over):
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.jobs import Job, JobSpec

    spec = JobSpec(edges=np.array([[0, 1], [1, 2]], dtype=np.int64),
                   n_nodes=3, config=ConsensusConfig(), **spec_over)
    return Job(spec, key="k" * 64)


def test_durations_survive_wall_clock_steps(monkeypatch):
    """The satellite contract: wall stamps are display-only; durations
    derive from time.monotonic, so an NTP step between submit and
    finish cannot produce negative (or inflated) latencies."""
    from fastconsensus_tpu.serve import jobs as jobs_mod

    wall = [1_000_000.0]
    monkeypatch.setattr(jobs_mod.time, "time", lambda: wall[0])
    job = _job()
    job.mark("running")
    wall[0] -= 3600.0                       # NTP steps back an hour
    job.mark("done", result={})
    d = job.describe()
    assert d["finished_at"] < d["submitted_at"]   # wall shows the step
    t = job.timing()
    assert t is not None
    assert 0.0 <= t["e2e_ms"] < 1000.0            # monotonic does not
    assert t["phases_ms"]["respond"] >= 0.0


def test_phase_sum_equals_e2e_with_missing_stamps():
    """Phases are consecutive differences of one monotonic clock, so
    their sum equals the end-to-end latency BY CONSTRUCTION, whatever
    subset of stamps a path recorded (cache hits never pack, solo jobs
    never batch...).  Every pop path stamps hold_start alongside
    dispatched (jobs.py stamp_hold), so the real-path subset always
    includes both."""
    import time as _time

    job = _job()
    job.stamp_hold(_time.monotonic())
    job.stamp("dispatched")
    job.stamp("dequeued")       # no "enqueued": folds into deque_wait
    job.stamp("device_done")    # no "packed": folds into device
    job.mark("done", result={})
    t = job.timing()
    assert set(t["phases_ms"]) == {"queue_wait", "hold", "deque_wait",
                                   "device", "respond"}
    assert t["phase_sum_ms"] == pytest.approx(t["e2e_ms"], abs=0.01)


def test_slo_classes_and_targets():
    from fastconsensus_tpu.serve.jobs import (PRIORITY_INTERACTIVE,
                                              SLO_CLASSES)

    j = _job()
    assert j.spec.slo_class() == "normal"
    assert j.spec.slo_target() == SLO_CLASSES["normal"]
    j = _job(priority=PRIORITY_INTERACTIVE)
    assert j.spec.slo_class() == "interactive"
    j = _job(slo="batch", slo_target_ms=5.0)
    assert j.spec.slo_class() == "batch"
    assert j.spec.slo_target() == 5.0
    j.mark("done", result={})
    assert j.timing()["slo"] == "batch"


# -- the loopback consistency pin (satellite) --------------------------


def test_loopback_phase_sum_and_metricsz_schema(karate_edges):
    """The fclat acceptance pin on a REAL loopback run: every finished
    job's phase sum agrees with its end-to-end latency within 5%, the
    /metricsz latency block carries per-phase histograms + arrival
    rates + SLO attainment in the documented schema (typed by the
    jax-free client), and a deliberately impossible SLO target counts
    one miss."""
    from fastconsensus_tpu.serve.client import ServeClient
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig,
                                                make_http_server)

    edges, _, ids = karate_edges
    svc = ConsensusService(ServeConfig(queue_depth=8, pin_sizing=False))
    svc.start()
    httpd = make_http_server(svc, "127.0.0.1", 0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    client = ServeClient(f"http://127.0.0.1:{port}", timeout=30.0)
    try:
        payload = dict(edges=edges.tolist(), n_nodes=len(ids),
                       algorithm="lpm", n_p=4, delta=0.1, max_rounds=2)
        subs = [client.submit(**dict(payload, seed=71)),
                client.submit(**dict(payload, seed=72,
                                     slo="interactive",
                                     slo_target_ms=0.001))]
        results = [client.wait(s["job_id"], timeout=120) for s in subs]
        for res in results:
            t = res["timing"]
            assert abs(t["phase_sum_ms"] - t["e2e_ms"]) <= \
                0.05 * t["e2e_ms"] + 0.01, t
            assert t["phases_ms"]["device"] > 0.0
        # an impossible target is a counted miss, not an enforcement
        t2 = client.timing(subs[1]["job_id"])
        assert t2 is not None and t2.slo == "interactive"
        assert t2.slo_met is False
        lat = client.latency()
        names = {h.name for h in lat["histograms"]}
        assert "serve.e2e" in names
        assert "serve.phase.device" in names
        e2e = next(h for h in lat["histograms"]
                   if h.name == "serve.e2e"
                   and h.tags.get("bucket") == "n64_e96")
        assert e2e.count >= 1 and e2e.p95_s > 0
        assert e2e.tags["rung"] == "1"
        assert lat["arrivals"]["n64_e96"]["count"] >= 2
        slo = {s.slo_class: s for s in lat["slo"]}
        assert slo["interactive"].missed >= 1
        assert 0.0 <= slo["interactive"].attainment <= 1.0
        # bad slo inputs answer 400, not a crash
        from fastconsensus_tpu.serve.client import ServeError

        with pytest.raises(ServeError) as e:
            client.submit(**dict(payload, seed=73, slo="platinum"))
        assert e.value.status == 400 and "slo" in str(e.value)
        with pytest.raises(ServeError) as e:
            client.submit(**dict(payload, seed=74, slo_target_ms=-1))
        assert e.value.status == 400
        # the raw block stays JSON end to end
        json.dumps(client.metricsz())
    finally:
        httpd.shutdown()
        httpd.server_close()
        assert svc.drain(30)


def test_metricsz_typed_parse_is_jax_free():
    """The client-contract satellite: parsing the /metricsz latency
    block and a /result timing block into the typed client objects
    must work with jax POISONED in sys.modules — thin dashboards
    never pay the engine import."""
    canned_latency = {
        "histograms": [{"name": "serve.phase.device",
                        "tags": {"bucket": "n64_e96", "rung": 2,
                                 "priority": 1, "device": 0},
                        "count": 3, "sum_s": 0.09, "min_s": 0.02,
                        "max_s": 0.04, "p50_s": 0.03125,
                        "p95_s": 0.04, "p99_s": 0.04,
                        "buckets": {"-5": 3}}],
        "slo": {"interactive": {"met": 5, "missed": 1,
                                "attainment": 0.8333,
                                "target_default_ms": 1000.0}},
        "arrivals": {"n64_e96": {"count": 6, "window": 6,
                                 "window_s": 2.0, "rate_per_s": 2.5}},
        "dispatches": {},
    }
    canned_timing = {"e2e_ms": 12.5,
                     "phases_ms": {"queue_wait": 1.0, "device": 11.0,
                                   "respond": 0.5},
                     "phase_sum_ms": 12.5, "slo": "interactive",
                     "slo_target_ms": 1000.0, "slo_met": True}
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "import json\n"
        "from fastconsensus_tpu.serve.client import (JobTiming,\n"
        "    PhaseLatency, SloStats)\n"
        f"block = json.loads({json.dumps(json.dumps(canned_latency))})\n"
        f"t = json.loads({json.dumps(json.dumps(canned_timing))})\n"
        "hs = [PhaseLatency.from_payload(h)\n"
        "      for h in block['histograms']]\n"
        "assert hs[0].tags == {'bucket': 'n64_e96', 'rung': '2',\n"
        "                      'priority': '1', 'device': '0'}, hs\n"
        "assert hs[0].count == 3 and hs[0].p95_s == 0.04\n"
        "assert hs[0].buckets == {'-5': 3}\n"
        "s = SloStats.from_payload('interactive',\n"
        "                          block['slo']['interactive'])\n"
        "assert s.met == 5 and s.missed == 1\n"
        "jt = JobTiming.from_payload(t)\n"
        "assert jt.slo_met and jt.phases_ms['device'] == 11.0\n"
        "assert abs(jt.phase_sum_ms - jt.e2e_ms) < 1e-9\n"
        "print('jax-free latency parse ok')\n")
    root = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ, PYTHONPATH=os.path.abspath(root))
    res = subprocess.run([sys.executable, "-c", code], cwd=root, env=env,
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "jax-free latency parse ok" in res.stdout


def test_failed_job_counts_as_slo_miss():
    """An outage must crater attainment, not hide behind the surviving
    successes: a FAILED job counts serve.slo.<class>.missed and
    records into serve.e2e.failed — never into the served
    distributions."""
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.obs.latency import get_latency_registry
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    reg = obs_counters.get_registry()
    base = reg.counters()
    svc = ConsensusService(ServeConfig(queue_depth=4,
                                       pin_sizing=False)).start()
    try:
        # closure_tau out of range fails inside run_consensus — the
        # canonical job-level failure (test_serve.py uses the same)
        from fastconsensus_tpu.consensus import ConsensusConfig
        from fastconsensus_tpu.serve.jobs import JobSpec

        spec = JobSpec(edges=np.array([[0, 1]], dtype=np.int64),
                       n_nodes=2,
                       config=ConsensusConfig(closure_tau=5.0, seed=91))
        job = svc.submit(spec)
        deadline = time.monotonic() + 120
        while job.state not in ("done", "failed"):
            assert time.monotonic() < deadline
            time.sleep(0.02)
        assert job.state == "failed"
    finally:
        assert svc.drain(30)
    since = reg.counters_since(base)
    assert since.get("serve.slo.missed", 0) >= 1
    assert since.get("serve.slo.normal.missed", 0) >= 1
    failed_hists = [h for h in
                    get_latency_registry().snapshot()["histograms"]
                    if h["name"] == "serve.e2e.failed"]
    assert failed_hists and sum(h["count"] for h in failed_hists) >= 1


# -- timeline recording through the embedded service -------------------


def test_queue_and_pool_stamps_reach_the_histograms(karate_edges):
    """A job driven through the real queue -> dispatcher -> worker path
    records every phase (queue_wait through respond) into the tagged
    fclat histograms, and arrivals/dispatch rates both mark."""
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.obs.latency import get_latency_registry
    from fastconsensus_tpu.serve.jobs import JobSpec
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig)

    edges, _, ids = karate_edges
    lat = get_latency_registry()
    before = {(h["name"], tuple(sorted(h["tags"].items()))): h
              for h in lat.snapshot()["histograms"]}
    svc = ConsensusService(ServeConfig(queue_depth=4,
                                       pin_sizing=False)).start()
    try:
        spec = JobSpec(edges=np.asarray(edges, dtype=np.int64),
                       n_nodes=len(ids),
                       config=ConsensusConfig(algorithm="lpm", n_p=4,
                                              tau=0.8, delta=0.1,
                                              max_rounds=2, seed=81))
        job = svc.submit(spec)
        deadline = time.monotonic() + 120
        while job.state not in ("done", "failed"):
            assert time.monotonic() < deadline, job.describe()
            time.sleep(0.02)
        assert job.state == "done", job.error
    finally:
        assert svc.drain(30)
    from fastconsensus_tpu.obs.latency import diff_snapshots

    grew = set()
    for h in lat.snapshot()["histograms"]:
        key = (h["name"], tuple(sorted(h["tags"].items())))
        if diff_snapshots(h, before.get(key, {}))["count"]:
            grew.add(h["name"])
    for phase in ("queue_wait", "dispatch", "deque_wait", "pack",
                  "device", "fanout", "respond"):
        assert f"serve.phase.{phase}" in grew, (phase, sorted(grew))
    assert "serve.e2e" in grew
    assert lat.dispatches.rates().get("n64_e96", {}).get("count", 0) >= 1
