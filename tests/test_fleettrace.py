"""fctrace: trace propagation, exact fleet aggregation, incident merge.

Pins the PR-18 observability contracts: a router-minted trace id rides
one submission end-to-end (router route event -> forwarded header ->
replica JobSpec -> replica flight events); ``/fleetz`` merges replica
histograms bit-exactly (cross-process reuse of the PR-9 fixed-bucket
merge); and ``fleettrace render`` aligns N per-process bundle dirs
onto one wall clock.  The reader side (fleettrace CLI, typed client
blocks) must all run with jax poisoned — incident tooling runs on
boxes where the engine cannot import.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from fastconsensus_tpu.obs import fleettrace, latency


# -- exact-merge aggregation (the /fleetz payload) ---------------------


def test_three_concurrent_registries_merge_bit_exact():
    """The tentpole merge contract, cross-process shaped: 3 replica
    registries record concurrently (own registry + one combined
    reference), then fold through aggregate_fleet — the fleet view's
    counts, buckets, and quantiles must be IDENTICAL to the single
    registry that saw every sample."""
    import numpy as np

    regs = [latency.LatencyRegistry() for _ in range(3)]
    combined = latency.LatencyRegistry()
    lock = threading.Lock()
    rngs = [np.random.default_rng(seed) for seed in range(3)]

    def writer(i):
        for k in range(1500):
            v = float(rngs[i].lognormal(mean=-5.0, sigma=2.0))
            bucket = f"n64_e{96 + 32 * (k % 2)}"
            regs[i].hist("serve.e2e", bucket=bucket).record(v)
            with lock:
                combined.hist("serve.e2e", bucket=bucket).record(v)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    fz = fleettrace.aggregate_fleet({
        f"r{i}": {"scope": "replica", "latency": regs[i].snapshot(),
                  "fcobs": {"counters": {}}}
        for i in range(3)})
    assert all(v["ok"] for v in fz["replicas"].values())
    merged = {(h["name"], tuple(sorted(h["tags"].items()))): h
              for h in fz["latency"]["histograms"]}
    ref = {(h["name"], tuple(sorted(h["tags"].items()))): h
           for h in combined.snapshot()["histograms"]}
    assert set(merged) == set(ref) and len(ref) == 2
    for key, want in ref.items():
        got = merged[key]
        assert got["sources"] == 3
        assert got["count"] == want["count"] == 2250
        assert got["buckets"] == want["buckets"]
        assert got["min_s"] == want["min_s"]
        assert got["max_s"] == want["max_s"]
        for q in ("p50_s", "p95_s", "p99_s"):
            assert got[q] == want[q], (key, q)


def test_aggregate_fleet_reports_down_replicas_and_sums_slo():
    """An unscrapable replica must surface as ok:false (never vanish),
    SLO met/missed must ADD per class with attainment recomputed from
    the sums, and numeric counters must sum (bools excluded)."""
    r0 = latency.LatencyRegistry()
    r0.hist("serve.e2e", bucket="b").record(0.01)
    m0 = {"scope": "replica", "latency": dict(
        r0.snapshot(), slo={"interactive": {
            "met": 8, "missed": 2, "attainment": 0.8,
            "target_default_ms": 1000.0}}),
        "fcobs": {"counters": {"serve.jobs": 10, "flag": True}}}
    m1 = {"scope": "replica", "latency": {
        "histograms": [], "slo": {"interactive": {
            "met": 9, "missed": 1, "attainment": 0.9,
            "target_default_ms": 1000.0}}},
        "fcobs": {"counters": {"serve.jobs": 4}}}
    fz = fleettrace.aggregate_fleet({"a": m0, "b": m1, "dead": None})
    assert fz["scope"] == "fleet" and fz["schema"] == fleettrace.SCHEMA
    assert fz["replicas"]["dead"] == {"ok": False}
    assert fz["replicas"]["a"]["ok"] and fz["replicas"]["a"][
        "scope"] == "replica"
    slo = fz["slo"]["interactive"]
    assert (slo["met"], slo["missed"]) == (17, 3)
    assert slo["attainment"] == pytest.approx(0.85)
    # the class target must survive the fold: the typed client parses
    # the fleet slo rows with the same SloStats block as a replica's
    assert slo["target_default_ms"] == 1000.0
    assert fz["counters"]["serve.jobs"] == 14
    assert "flag" not in fz["counters"]


def test_proxy_overhead_attribution_per_replica():
    """router.phase.proxy histograms tagged replica=<name> become the
    per-replica overhead table — the router-side cost no replica
    histogram can see."""
    rl = latency.LatencyRegistry()
    for v in (0.001, 0.002, 0.004):
        rl.hist("router.phase.proxy", replica="r0").record(v)
    rl.hist("router.phase.proxy", replica="r1").record(0.5)
    rl.hist("router.phase.admit").record(0.0001)  # not proxy: ignored
    oh = fleettrace.proxy_overhead(rl.snapshot())
    assert set(oh) == {"r0", "r1"}
    assert oh["r0"]["count"] == 3 and oh["r1"]["count"] == 1
    assert oh["r1"]["p95_s"] >= 0.25


# -- incident merge (collected bundles -> one timeline) ----------------


def _write_bundle(root, name, anchor_unix, anchor_mono, events,
                  manifest_only_anchor=False, no_anchor=False):
    d = os.path.join(root, name)
    os.makedirs(d)
    manifest = {"pid": 4242}
    flight = {"capacity": 2048, "n_events": len(events), "dropped": 0,
              "rings": [{"thread": "MainThread", "dropped": 0,
                         "events": events}]}
    if not no_anchor:
        if manifest_only_anchor:
            manifest.update(time_unix=anchor_unix, time_mono=anchor_mono)
        else:
            flight.update(time_unix=anchor_unix, time_mono=anchor_mono)
    with open(os.path.join(d, "MANIFEST.json"), "w") as fh:
        json.dump(manifest, fh)
    with open(os.path.join(d, "flight.json"), "w") as fh:
        json.dump(flight, fh)
    return d


def test_merged_timeline_aligns_clocks_dedups_and_filters(tmp_path):
    """Two replicas with DIFFERENT monotonic epochs must interleave on
    the shared wall clock; duplicate events from repeated snapshots of
    one ring dedup; --trace filters to one request across tracks; a
    bundle with no recoverable anchor is skipped, not mis-ordered."""
    root = str(tmp_path)
    # r0's monotonic epoch: wall = ts + 1000; r1's: wall = ts + 500
    _write_bundle(root, "r0__fcflight_a", 2000.0, 1000.0, [
        {"ts": 1.0, "kind": "route", "job": "f1", "trace": "tr-1"},
        {"ts": 3.0, "kind": "proxy", "job": "f1", "trace": "tr-1"}])
    # same replica, second snapshot of the SAME ring: pure duplicates
    _write_bundle(root, "r0__fcflight_b", 2000.0, 1000.0, [
        {"ts": 1.0, "kind": "route", "job": "f1", "trace": "tr-1"}])
    _write_bundle(root, "r1__fcflight_c", 1500.0, 1000.0, [
        {"ts": 502.0, "kind": "admit", "job": "j1", "trace": "tr-1"},
        {"ts": 502.5, "kind": "admit", "job": "j2", "trace": "tr-2"}],
        manifest_only_anchor=True)
    _write_bundle(root, "r2__fcflight_d", 0.0, 0.0, [
        {"ts": 9.0, "kind": "finish", "job": "zz"}], no_anchor=True)

    tl = fleettrace.merged_timeline(root)
    assert tl["replicas"] == ["r0", "r1"]
    assert tl["skipped_bundles"] == ["r2__fcflight_d"]
    assert tl["n_events"] == 4  # duplicate deduped, r2 skipped
    walls = [e["t_wall"] for e in tl["events"]]
    assert walls == sorted(walls)
    # the r1 admits (wall 1002, 1002.5) land BETWEEN r0's route (1001)
    # and proxy (1003): cross-process interleave is the whole point
    assert [(e["replica"], e["kind"]) for e in tl["events"]] == [
        ("r0", "route"), ("r1", "admit"), ("r1", "admit"),
        ("r0", "proxy")]

    one = fleettrace.merged_timeline(root, trace="tr-1")
    assert one["n_events"] == 3
    assert {e["replica"] for e in one["events"]} == {"r0", "r1"}
    assert all(e["trace"] == "tr-1" for e in one["events"])

    text = fleettrace.render_timeline(one)
    assert "r0/MainThread: route" in text and "job=j1" in text


def test_fleettrace_cli_renders_with_jax_poisoned(tmp_path):
    """``python -m ...fleettrace render`` is incident tooling: it must
    produce the merged timeline (and valid --json) in a process where
    importing jax raises."""
    root = str(tmp_path / "collected")
    os.makedirs(root)
    _write_bundle(root, "r0__fcflight_a", 100.0, 0.0,
                  [{"ts": 1.0, "kind": "route", "job": "f1",
                    "trace": "tr-9"}])
    _write_bundle(root, "r1__fcflight_b", 100.0, 0.0,
                  [{"ts": 2.0, "kind": "finish", "job": "j1",
                    "trace": "tr-9"}])
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "from fastconsensus_tpu.obs import fleettrace\n"
        "rc = fleettrace.main(['render', sys.argv[1], '--json'])\n"
        "sys.exit(rc)\n")
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env = dict(os.environ, PYTHONPATH=repo)
    res = subprocess.run([sys.executable, "-c", code, root], cwd=repo,
                         env=env, capture_output=True, text=True,
                         timeout=60)
    assert res.returncode == 0, res.stdout + res.stderr
    payload = json.loads(res.stdout)
    assert payload["tool"] == "fctrace-timeline"
    assert payload["replicas"] == ["r0", "r1"]
    assert payload["n_events"] == 2
    # empty dir: exit 2, not a traceback
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    res2 = subprocess.run([sys.executable, "-c", code, empty], cwd=repo,
                          env=env, capture_output=True, text=True,
                          timeout=60)
    assert res2.returncode == 2, res2.stdout + res2.stderr


def test_collect_bundles_lays_out_replica_tracks(tmp_path):
    """FleetManager.collect_bundles (no live procs needed): bundles
    land as <replica>__<bundle>, manifest-less partials are skipped,
    and the source dirs stay intact (copy, not move)."""
    from fastconsensus_tpu.serve.fleet import FleetManager

    fleet = FleetManager(str(tmp_path / "fleet"))

    class _Stub:
        def __init__(self, dirs):
            self._dirs = dirs

        def bundles(self):
            return self._dirs

    src = tmp_path / "r0_flight"
    good = _write_bundle(str(src), "fcflight_good", 10.0, 0.0,
                         [{"ts": 0.5, "kind": "admit", "job": "j"}])
    partial = str(src / "fcflight_partial")
    os.makedirs(partial)  # no MANIFEST.json: incomplete dump
    fleet.replicas = {"r0": _Stub([good, partial])}

    dest = str(tmp_path / "collected")
    out = fleet.collect_bundles(dest_dir=dest, snapshot=False)
    assert [os.path.basename(p) for p in out["r0"]] == [
        "r0__fcflight_good"]
    assert os.path.isfile(os.path.join(
        dest, "r0__fcflight_good", "flight.json"))
    assert os.path.isdir(good)  # source untouched
    pairs = fleettrace.discover_bundles(dest)
    assert [(r, os.path.basename(d)) for r, d in pairs] == [
        ("r0", "r0__fcflight_good")]


# -- live trace propagation (router -> replica) ------------------------


@pytest.fixture
def replica():
    """One real loopback replica with its worker NOT started, so queue
    contents are observable and deterministic."""
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig,
                                                make_http_server)
    from fastconsensus_tpu.serve.shaping import ShapingConfig

    svc = ConsensusService(ServeConfig(queue_depth=16, pin_sizing=False,
                                       shaping=ShapingConfig(shed=False)))
    httpd = make_http_server(svc, "127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        yield svc, f"http://127.0.0.1:{port}"
    finally:
        httpd.shutdown()
        svc.queue.close()


def _submit_body(seed, trace=None):
    payload = {"edges": [[0, 1], [1, 2], [2, 0]], "n_nodes": 8,
               "algorithm": "lpm", "n_p": 2, "max_rounds": 2,
               "seed": seed}
    if trace is not None:
        payload["trace"] = trace
    return json.dumps(payload).encode("utf-8")


def test_trace_id_spans_router_and_replica(replica):
    """The tentpole end-to-end: one submission's trace id must appear
    on the router's route event, in the forwarded header (-> JobSpec),
    and on the replica's admit flight event — the join key fleettrace
    stitches cross-process timelines on."""
    from fastconsensus_tpu.obs import flight as obs_flight
    from fastconsensus_tpu.serve.router import FleetRouter

    svc, url = replica
    router = FleetRouter({"r0": url}, poll_s=60.0)
    router.poll_once()
    status, out, _ = router.submit(_submit_body(seed=1))
    assert status == 202, out
    trace = out["trace"]
    assert trace and trace.startswith("tr-")
    job = svc.queue.pop(timeout=5.0)
    assert job.spec.trace == trace
    assert job.describe()["trace"] == trace
    # both tiers run in THIS process here, so one recorder holds both
    # sides' events — exactly what the kill drill checks across real
    # processes via /debugz/flight
    events = obs_flight.get_flight_recorder().events()
    kinds = {e["kind"] for e in events if e.get("trace") == trace}
    assert "route" in kinds and "admit" in kinds

    # client-supplied trace wins over minting, body-level trace too
    status, out2, _ = router.submit(_submit_body(seed=2),
                                    trace="tr-client-7")
    assert status == 202 and out2["trace"] == "tr-client-7"
    assert svc.queue.pop(timeout=5.0).spec.trace == "tr-client-7"
    status, out3, _ = router.submit(_submit_body(seed=3,
                                                 trace="tr-body-8"))
    assert status == 202 and out3["trace"] == "tr-body-8"
    svc.queue.pop(timeout=5.0)


def test_trace_is_outside_the_content_hash(replica):
    """Two traced submissions of the SAME graph must share one content
    hash (a trace names a submission, never a result) — and a bogus
    oversize trace is a 400, not a new cache entry."""
    svc, url = replica
    from fastconsensus_tpu.serve.client import ServeClient, ServeError

    client = ServeClient(url, timeout=10.0)
    a = client._request("/submit", json.loads(
        _submit_body(seed=5, trace="tr-a").decode()))
    b = client._request("/submit", json.loads(
        _submit_body(seed=5, trace="tr-b").decode()))
    assert a["trace"] == "tr-a" and b["trace"] == "tr-b"
    assert a["content_hash"] == b["content_hash"]
    with pytest.raises(ServeError) as err:
        client._request("/submit", json.loads(
            _submit_body(seed=6, trace="x" * 200).decode()))
    assert err.value.status == 400
    for _ in range(2):
        svc.queue.pop(timeout=5.0)


def test_fleetz_scrapes_live_replica_and_merges_exactly(replica):
    """router.fleetz() over a live replica: scopes self-describe, the
    fleet merge's per-histogram counts equal the replica's own
    /metricsz counts, and the router's phase histograms ride along."""
    import urllib.request

    from fastconsensus_tpu.serve.router import FleetRouter

    svc, url = replica
    router = FleetRouter({"r0": url}, poll_s=60.0)
    router.poll_once()
    for seed in range(3):
        status, _, _ = router.submit(_submit_body(seed=seed + 10))
        assert status == 202
        svc.queue.pop(timeout=5.0)
    with urllib.request.urlopen(url + "/metricsz", timeout=10.0) as r:
        replica_m = json.loads(r.read())
    assert replica_m["scope"] == "replica"
    fz = router.fleetz()
    assert fz["scope"] == "fleet"
    assert fz["replicas"]["r0"]["ok"]
    assert fz["replicas"]["r0"]["scope"] == "replica"
    want = {(h["name"], tuple(sorted(
        (str(k), str(v)) for k, v in (h.get("tags") or {}).items()))):
        h["count"]
        for h in (replica_m.get("latency") or {}).get("histograms", ())}
    got = {(h["name"], tuple(sorted(
        (str(k), str(v)) for k, v in (h.get("tags") or {}).items()))):
        h["count"]
        for h in fz["latency"]["histograms"]}
    # one replica: exact merge means count-identity with its scrape
    # (quiescent between the two reads — the worker never ran)
    assert got == want
    router_hists = {h["name"]
                    for h in fz["router"]["latency"]["histograms"]}
    assert "router.phase.admit" in router_hists
    assert "router.phase.ring_lookup" in router_hists
    # /debugz/flight: the replica's half of the cross-process join
    with urllib.request.urlopen(url + "/debugz/flight",
                                timeout=10.0) as r:
        fl = json.loads(r.read())
    assert fl["scope"] == "replica"
    assert fl["flight"].get("time_unix") is not None
    assert fl["flight"].get("time_mono") is not None


# -- typed client blocks (jax-free) ------------------------------------


def test_typed_fleet_blocks_parse_with_jax_poisoned():
    """FleetLatency / TraceTimeline from_payload in a process where
    jax is poisoned — the fleet dashboard never pays the engine
    import."""
    canned_fleetz = {
        "schema": 1, "tool": "fctrace-fleetz", "scope": "fleet",
        "replicas": {"r0": {"ok": True, "scope": "replica",
                            "histograms": 2, "slo": {}},
                     "r1": {"ok": False}},
        "latency": {"histograms": [
            {"name": "serve.e2e", "tags": {"bucket": "n64_e96"},
             "sources": 2, "count": 10, "sum_s": 0.5, "min_s": 0.01,
             "max_s": 0.2, "p50_s": 0.03125, "p95_s": 0.25,
             "p99_s": 0.25, "buckets": {"-5": 10}}]},
        "slo": {"interactive": {"met": 9, "missed": 1,
                                "attainment": 0.9,
                                "target_default_ms": 1000.0}},
        "counters": {"serve.jobs": 10},
        "router": {
            "latency": {"histograms": [
                {"name": "router.phase.admit", "tags": {}, "count": 10,
                 "sum_s": 0.001, "min_s": 0.0001, "max_s": 0.0002,
                 "p50_s": 0.0001, "p95_s": 0.0002, "p99_s": 0.0002,
                 "buckets": {"-13": 10}}]},
            "proxy_overhead": {"r0": {"count": 10, "sum_s": 0.02,
                                      "p50_s": 0.001, "p95_s": 0.003}}},
    }
    canned_timeline = {
        "schema": 1, "tool": "fctrace-timeline", "trace": "tr-1",
        "replicas": ["r0", "r1"], "n_events": 2,
        "events_per_replica": {"r0": 1, "r1": 1},
        "skipped_bundles": ["r2__fcflight_x"],
        "events": [
            {"t_wall": 1001.0, "replica": "r0", "thread": "t",
             "ts": 1.0, "kind": "route", "job": "f1", "trace": "tr-1"},
            {"t_wall": 1002.0, "replica": "r1", "thread": "t",
             "ts": 2.0, "kind": "admit", "job": "j1", "trace": "tr-1"}],
    }
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "import json\n"
        "from fastconsensus_tpu.serve.client import (FleetLatency,\n"
        "    TraceTimeline)\n"
        f"fz = json.loads({json.dumps(json.dumps(canned_fleetz))})\n"
        f"tl = json.loads({json.dumps(json.dumps(canned_timeline))})\n"
        "f = FleetLatency.from_payload(fz)\n"
        "assert f.scope == 'fleet'\n"
        "assert f.replicas_ok == {'r0': True, 'r1': False}\n"
        "assert f.replicas_down == ('r1',)\n"
        "h = f.histogram('serve.e2e', bucket='n64_e96')\n"
        "assert h is not None and h.count == 10\n"
        "assert f.histogram('serve.e2e', bucket='nope') is None\n"
        "assert f.slo[0].met == 9 and f.counters['serve.jobs'] == 10\n"
        "assert f.router_histograms[0].name == 'router.phase.admit'\n"
        "assert f.proxy_overhead['r0']['p95_s'] == 0.003\n"
        "t = TraceTimeline.from_payload(tl)\n"
        "assert t.trace == 'tr-1' and t.n_events == 2\n"
        "assert t.replicas == ('r0', 'r1')\n"
        "assert t.skipped_bundles == ('r2__fcflight_x',)\n"
        "assert [e['kind'] for e in t.for_replica('r1')] == ['admit']\n"
        "print('jax-free fleet parse ok')\n")
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env = dict(os.environ, PYTHONPATH=repo)
    res = subprocess.run([sys.executable, "-c", code], cwd=repo, env=env,
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "jax-free fleet parse ok" in res.stdout


# -- the CI gate (history.check_fleet_latency) -------------------------


def _fl_rec(seq, fl):
    return {"seq": seq, "source": f"bench_serve_fleet_r{seq}.json",
            "fleet_latency": fl}


def _healthy_fl(**over):
    fl = {"replicas_scraped": 3, "replicas_down": [],
          "merge_exact": True,
          "router_phase_p95_ms": {"admit": 0.05, "ring_lookup": 0.01,
                                  "proxy": 2.0, "replay": None},
          "proxy_overhead_p95_ms": {"r0": 2.0, "r1": 2.5},
          "fleet_e2e_p95_ms": 40.0,
          "worst_replica_e2e_p95_ms": 45.0}
    fl.update(over)
    return fl


def test_check_fleet_latency_absolute_rules():
    from fastconsensus_tpu.obs import history

    clean = {"c": [_fl_rec(18, _healthy_fl())]}
    assert history.check_fleet_latency(clean) == []

    down = {"c": [_fl_rec(18, _healthy_fl(replicas_down=["r2"]))]}
    assert any("could not scrape" in p
               for p in history.check_fleet_latency(down))

    inexact = {"c": [_fl_rec(18, _healthy_fl(merge_exact=False))]}
    assert any("inexact" in p
               for p in history.check_fleet_latency(inexact))

    # merged fleet p95 above the worst component: impossible for a
    # correct mixture quantile, so the gate calls the merge wrong
    broken = {"c": [_fl_rec(18, _healthy_fl(
        fleet_e2e_p95_ms=80.0, worst_replica_e2e_p95_ms=45.0))]}
    assert any("mixture quantile" in p
               for p in history.check_fleet_latency(broken))

    # pre-fctrace artifacts pass vacuously
    assert history.check_fleet_latency(
        {"c": [{"seq": 17, "source": "s", "fleet_latency": None}]}) == []


def test_check_fleet_latency_trajectory_rules():
    from fastconsensus_tpu.obs import history

    hist = [_fl_rec(16, _healthy_fl()), _fl_rec(17, _healthy_fl())]
    ok = {"c": hist + [_fl_rec(18, _healthy_fl(
        fleet_e2e_p95_ms=60.0, worst_replica_e2e_p95_ms=62.0))]}
    assert history.check_fleet_latency(ok) == []

    # e2e p95 more than doubles the prior median: finding
    slow = {"c": hist + [_fl_rec(18, _healthy_fl(
        fleet_e2e_p95_ms=90.0, worst_replica_e2e_p95_ms=95.0))]}
    assert any("tail regressed" in p
               for p in history.check_fleet_latency(slow))

    # worst-replica proxy overhead grows past its own bound: finding
    hop = {"c": hist + [_fl_rec(18, _healthy_fl(
        proxy_overhead_p95_ms={"r0": 2.0, "r1": 9.0}))]}
    assert any("proxy overhead" in p
               for p in history.check_fleet_latency(hop))

    # only the NEWEST sequence is judged: an old bad record is history
    old_bad = {"c": [_fl_rec(16, _healthy_fl(merge_exact=False)),
                     _fl_rec(18, _healthy_fl())]}
    assert history.check_fleet_latency(old_bad) == []
