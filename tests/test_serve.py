"""fcserve: admission queue, shape buckets, result cache, and the
serving contract — same-bucket requests reuse executables (0 warm
compiles), identical resubmissions answer from the cache (no detect
spans), overload rejects with explicit backpressure."""

import os
import threading

import numpy as np
import pytest


def _ring_graph(n, chords=0, shift=7):
    """Deterministic ring (+ optional chord family): n nodes,
    n + chords edges."""
    idx = np.arange(n)
    edges = [np.stack([idx, (idx + 1) % n], 1)]
    if chords:
        c = np.arange(chords)
        edges.append(np.stack([c % n, (c + shift) % n], 1))
    return np.concatenate(edges).astype(np.int64)


def _spec(edges, n_nodes, priority=None, **over):
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.jobs import PRIORITY_NORMAL, JobSpec

    kwargs = dict(algorithm="louvain", n_p=4, tau=0.2, delta=0.02,
                  max_rounds=2, seed=0)
    kwargs.update(over)
    return JobSpec(edges=np.asarray(edges, dtype=np.int64),
                   n_nodes=n_nodes, config=ConsensusConfig(**kwargs),
                   priority=PRIORITY_NORMAL if priority is None
                   else priority)


@pytest.fixture
def service():
    from fastconsensus_tpu.serve.server import ConsensusService, ServeConfig
    from fastconsensus_tpu.serve.shaping import ShapingConfig

    # pin_sizing=False: the env pins are the resident server's posture;
    # tests must not leak FCTPU_* into the rest of the suite.
    # shed=False: on a loaded CI box a slow sample can push the deadline
    # predictor past the default SLO slack and 429 an unrelated
    # admission/cache test; shedding has its own coverage in
    # test_shaping.py with primed estimators
    return ConsensusService(ServeConfig(queue_depth=4, pin_sizing=False,
                                        shaping=ShapingConfig(shed=False)))


# -- sizing ladder / buckets ------------------------------------------


def test_grid_up_ladder_boundaries():
    from fastconsensus_tpu.sizing import grid_up

    assert [grid_up(v) for v in (1, 2, 3, 4, 5, 6, 7)] == \
        [1, 2, 3, 4, 6, 6, 8]
    # exactly at a class stays; one past jumps to the next rung
    assert grid_up(48) == 48 and grid_up(49) == 64
    assert grid_up(64) == 64 and grid_up(65) == 96
    assert grid_up(96) == 96 and grid_up(97) == 128
    assert grid_up(10, minimum=64) == 64


def test_bucket_for_boundaries_and_limits():
    from fastconsensus_tpu.serve.bucketer import (MIN_EDGE_CLASS,
                                                  MIN_NODE_CLASS, Bucket,
                                                  BucketTooLarge,
                                                  bucket_for)

    assert bucket_for(5, 4) == Bucket(MIN_NODE_CLASS, MIN_EDGE_CLASS)
    assert bucket_for(96, 96) == Bucket(96, 96)       # exactly at class
    assert bucket_for(97, 96).n_class == 128          # one over: next rung
    assert bucket_for(96, 97).e_class == 128
    with pytest.raises(BucketTooLarge):
        bucket_for(1000, 10, max_nodes=512)
    with pytest.raises(BucketTooLarge):
        bucket_for(10, 1000, max_edges=512)
    with pytest.raises(ValueError):
        bucket_for(0, 0)


def test_pad_to_bucket_canonicalizes_statics(karate_edges):
    """Two distinct graphs in one bucket must produce slabs with
    IDENTICAL static metadata — that identity IS the executable-sharing
    contract (jit cache keys include every static field)."""
    from fastconsensus_tpu.serve.bucketer import pad_to_bucket

    edges, _, ids = karate_edges           # 34 nodes, 78 edges
    g2 = _ring_graph(40, chords=40)        # 40 nodes, 80 edges
    s1, b1 = pad_to_bucket(edges, len(ids))
    s2, b2 = pad_to_bucket(g2, 40)
    assert b1 == b2
    statics = lambda s: (s.n_nodes, s.capacity, s.d_cap, s.cap_hint,  # noqa: E731
                         s.d_hyb, s.hub_cap, s.agg_cap)
    assert statics(s1) == statics(s2)
    assert s1.d_cap == 0 and s1.d_hyb == 0 and s1.hub_cap == 0
    # content still belongs to each graph
    assert int(np.asarray(s1.alive).sum()) == 78
    assert int(np.asarray(s2.alive).sum()) == 80


def test_content_hash_is_order_invariant(karate_edges):
    from fastconsensus_tpu.consensus import ConsensusConfig
    from fastconsensus_tpu.serve.jobs import content_hash

    edges, _, ids = karate_edges
    cfg = ConsensusConfig()
    h1 = content_hash(edges, len(ids), cfg)
    rng = np.random.default_rng(0)
    shuffled = edges[rng.permutation(edges.shape[0])]
    flipped = np.stack([shuffled[:, 1], shuffled[:, 0]], 1)
    assert content_hash(flipped, len(ids), cfg) == h1
    # any result-relevant config field changes the address
    assert content_hash(edges, len(ids),
                        ConsensusConfig(seed=1)) != h1


# -- admission queue ---------------------------------------------------


def test_queue_rejects_when_full_and_when_closed(karate_edges):
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.jobs import Job
    from fastconsensus_tpu.serve.queue import (AdmissionQueue, QueueClosed,
                                               QueueFull)

    edges, _, ids = karate_edges
    base = obs_counters.get_registry().counters()
    q = AdmissionQueue(max_depth=2)
    q.submit(Job(_spec(edges, len(ids), seed=1)))
    q.submit(Job(_spec(edges, len(ids), seed=2)))
    with pytest.raises(QueueFull) as e:
        q.submit(Job(_spec(edges, len(ids), seed=3)))
    assert e.value.depth == 2 and e.value.max_depth == 2
    assert q.depth() == 2   # the bound held — nothing was absorbed
    q.close()
    with pytest.raises(QueueClosed):
        q.submit(Job(_spec(edges, len(ids), seed=4)))
    since = obs_counters.get_registry().counters_since(base)
    assert since.get("serve.queue.rejected_full", 0) >= 1
    assert since.get("serve.queue.rejected_draining", 0) >= 1
    # drain: admitted jobs still pop, then None
    assert q.pop() is not None and q.pop() is not None
    assert q.pop() is None


def test_queue_priority_order_under_contention(karate_edges):
    """Concurrent submitters; pops must come out priority-major,
    admission-order (seq) minor — the heap contract under contention."""
    from fastconsensus_tpu.serve.jobs import (PRIORITY_BATCH,
                                              PRIORITY_INTERACTIVE,
                                              PRIORITY_NORMAL, Job)
    from fastconsensus_tpu.serve.queue import AdmissionQueue

    edges, _, ids = karate_edges
    q = AdmissionQueue(max_depth=64)
    prios = (PRIORITY_BATCH, PRIORITY_INTERACTIVE, PRIORITY_NORMAL)
    start = threading.Barrier(4)

    def submitter(tid):
        start.wait()
        for i in range(8):
            q.submit(Job(_spec(edges, len(ids), seed=tid * 100 + i,
                               priority=prios[(tid + i) % 3])))

    threads = [threading.Thread(target=submitter, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    popped = []
    while True:
        job = q.pop(timeout=0.01)
        if job is None:
            break
        popped.append(job)
    assert len(popped) == 32
    prios_out = [j.spec.priority for j in popped]
    assert prios_out == sorted(prios_out)


# -- result cache ------------------------------------------------------


def test_cache_lru_eviction_and_recency():
    from fastconsensus_tpu.serve.cache import ResultCache

    c = ResultCache(max_entries=2, ttl_seconds=60.0)
    c.put("a", 1)
    c.put("b", 2)
    assert c.get("a") == 1          # refresh a's recency
    c.put("c", 3)                   # evicts b (LRU), not a
    assert c.get("b") is None
    assert c.get("a") == 1 and c.get("c") == 3
    assert len(c) == 2


def test_cache_ttl_expiry_deterministic():
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.cache import ResultCache

    now = [0.0]
    c = ResultCache(max_entries=4, ttl_seconds=10.0, clock=lambda: now[0])
    base = obs_counters.get_registry().counters()
    c.put("k", "v")
    now[0] = 9.9
    assert c.get("k") == "v"
    now[0] = 10.1
    assert c.get("k") is None       # expired, dropped on touch
    assert len(c) == 0
    since = obs_counters.get_registry().counters_since(base)
    assert since.get("serve.cache.expired", 0) == 1
    assert since.get("serve.cache.hit", 0) == 1
    assert since.get("serve.cache.miss", 0) == 1


def test_thin_client_imports_are_jax_free():
    """The cli.py --server contract: a client process imports
    serve.client + utils.io (and the packages above them) without
    importing jax — thin clients must not require (or pay for) the
    engine.  jax is POISONED in sys.modules (None makes any
    `import jax` raise), so a regression that re-eagers the package
    inits fails loudly even though sitecustomize preloads jax."""
    import subprocess
    import sys

    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "from fastconsensus_tpu.serve.client import ServeClient\n"
        "from fastconsensus_tpu.utils.io import read_edgelist\n"
        "import fastconsensus_tpu.serve\n"
        "print('jax-free ok')\n")
    root = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ, PYTHONPATH=os.path.abspath(root))
    res = subprocess.run([sys.executable, "-c", code], cwd=root, env=env,
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "jax-free ok" in res.stdout


def test_jobspec_canonical_is_memoized(karate_edges):
    edges, _, ids = karate_edges
    spec = _spec(edges, len(ids))
    u1, v1, _ = spec.canonical()
    u2, v2, _ = spec.canonical()
    assert u1 is u2 and v1 is v2  # one O(E log E) pass per spec
    # and pad_to_bucket accepts it without re-canonicalizing
    from fastconsensus_tpu.serve.bucketer import pad_to_bucket

    slab, _ = pad_to_bucket(spec.edges, spec.n_nodes,
                            canonical=spec.canonical())
    assert int(np.asarray(slab.alive).sum()) == 78


def test_registry_series_window_bounds_memory():
    """A resident server must not grow RSS with every observed latency
    sample: set_series_limit keeps the most recent window only (and the
    summary describes that window)."""
    from fastconsensus_tpu.obs.counters import ObsRegistry

    reg = ObsRegistry()
    for i in range(10):
        reg.observe("s", float(i))
    reg.set_series_limit(4)
    assert reg.series("s") == [6.0, 7.0, 8.0, 9.0]  # retroactive trim
    reg.observe("s", 10.0)
    assert reg.series("s") == [7.0, 8.0, 9.0, 10.0]
    reg.set_series_limit(None)
    for i in range(6):
        reg.observe("s", float(i))
    assert len(reg.series("s")) == 10  # unbounded again


# -- the serving contract ---------------------------------------------


def test_same_bucket_zero_warm_compiles(service, karate_edges):
    """ISSUE 4 acceptance: with the server warm, a DISTINCT graph that
    maps into the same size bucket compiles nothing — bucket-canonical
    shapes + memoized detectors make the first request's executables
    serve the whole bucket."""
    from fastconsensus_tpu.analysis import assert_max_compiles

    edges, _, ids = karate_edges
    g2 = _ring_graph(40, chords=40)
    r1 = service.run_spec(_spec(edges, len(ids)))
    assert not r1["cached"] and r1["rounds"] >= 1
    with assert_max_compiles(0):
        r2 = service.run_spec(_spec(g2, 40))
    assert r2["bucket"] == r1["bucket"]
    assert not r2["cached"]
    assert len(r2["partitions"]) == 4
    assert r2["partitions"][0].shape == (40,)   # padding sliced off
    assert r1["partitions"][0].shape == (34,)


def test_cache_hit_increments_counter_and_records_no_detect_spans(
        service, karate_edges):
    from fastconsensus_tpu.obs import Tracer, use_tracer
    from fastconsensus_tpu.obs import counters as obs_counters

    edges, _, ids = karate_edges
    service.run_spec(_spec(edges, len(ids), seed=7))
    base = obs_counters.get_registry().counters()
    with use_tracer(Tracer()) as tr:
        r2 = service.run_spec(_spec(edges, len(ids), seed=7))
    assert r2["cached"]
    since = obs_counters.get_registry().counters_since(base)
    assert since.get("serve.cache.hit", 0) == 1
    names = {e["name"] for e in tr.events()}
    assert not any(n.startswith(("detect", "round", "serve.job",
                                 "setup_executables"))
                   for n in names), names


def test_worker_and_submit_path(service, karate_edges):
    """submit -> queue -> worker -> done; identical resubmission is DONE
    at submit time (cache hit bypasses the queue entirely); one computed
    admission counts exactly ONE cache miss (the worker's pre-run
    re-probe must not double it — /metricsz hit-rate accuracy)."""
    import time

    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.jobs import STATE_DONE

    edges, _, ids = karate_edges
    base = obs_counters.get_registry().counters()
    service.start()
    try:
        job = service.submit(_spec(edges, len(ids), seed=11))
        deadline = time.monotonic() + 120
        while job.state not in ("done", "failed"):
            assert time.monotonic() < deadline, job.describe()
            time.sleep(0.02)
        assert job.state == STATE_DONE, job.error
        assert job.result["partitions"][0].shape == (len(ids),)
        again = service.submit(_spec(edges, len(ids), seed=11))
        assert again.state == STATE_DONE and again.result["cached"]
        since = obs_counters.get_registry().counters_since(base)
        assert since.get("serve.cache.miss", 0) == 1, since
        assert since.get("serve.cache.hit", 0) == 1, since
    finally:
        assert service.drain(30)


def test_ignored_gamma_does_not_fragment_the_cache(service, karate_edges):
    """lpm has no gamma parameter: gamma=1.5 and gamma=1.0 compute
    identical partitions, so they must share one content address
    (the fingerprint normalization cli.py applies locally)."""
    edges, _, ids = karate_edges
    j_gamma = service.submit(_spec(edges, len(ids), algorithm="lpm",
                                   delta=0.1, seed=5, gamma=1.5))
    j_plain = service.submit(_spec(edges, len(ids), algorithm="lpm",
                                   delta=0.1, seed=5, gamma=1.0))
    assert j_gamma.key == j_plain.key
    # louvain DOES take gamma: distinct addresses stay distinct
    k1 = service.submit(_spec(edges, len(ids), seed=6, gamma=1.5)).key
    k2 = service.submit(_spec(edges, len(ids), seed=6, gamma=1.0)).key
    assert k1 != k2


def test_submit_rejects_oversized_graphs(karate_edges):
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                GraphTooLarge, ServeConfig)

    edges, _, ids = karate_edges
    svc = ConsensusService(ServeConfig(max_nodes=16, pin_sizing=False))
    with pytest.raises(GraphTooLarge):
        svc.submit(_spec(edges, len(ids)))


def test_failed_job_does_not_kill_worker(service):
    """A bad spec fails ITS job; the worker survives to run the next."""
    import time

    service.start()
    try:
        # closure_tau out of range raises inside run_consensus — a
        # config error the HTTP layer can't pre-screen fails the job,
        # not the worker
        bad = _spec(np.array([[0, 1]]), 2, closure_tau=5.0)
        good = _spec(_ring_graph(12, chords=6), 12, seed=3)
        jb = service.submit(bad)
        jg = service.submit(good)
        deadline = time.monotonic() + 120
        while jg.state not in ("done", "failed"):
            assert time.monotonic() < deadline
            time.sleep(0.02)
        assert jb.state == "failed" and "closure_tau" in jb.error
        assert jg.state == "done", jg.error
    finally:
        assert service.drain(30)


def test_pin_sizing_env_defaults(monkeypatch):
    from fastconsensus_tpu.serve.server import ConsensusService, ServeConfig

    monkeypatch.delenv("FCTPU_DETECT_CALL_MEMBERS", raising=False)
    monkeypatch.delenv("FCTPU_ROUNDS_BLOCK", raising=False)
    svc = ConsensusService(ServeConfig(pin_sizing=True))
    svc.start()
    try:
        assert os.environ["FCTPU_DETECT_CALL_MEMBERS"] == "0"
        assert os.environ["FCTPU_ROUNDS_BLOCK"] == "8"
    finally:
        assert svc.drain(10)
        monkeypatch.delenv("FCTPU_DETECT_CALL_MEMBERS", raising=False)
        monkeypatch.delenv("FCTPU_ROUNDS_BLOCK", raising=False)


# -- HTTP front end ----------------------------------------------------


def test_http_endpoints_roundtrip(karate_edges):
    """submit / 429 backpressure / status / result / healthz / metricsz
    / 503-on-drain over a real loopback socket.  The worker is started
    only AFTER the queue is full, so the 429 is deterministic."""
    import json

    from fastconsensus_tpu.serve.client import (Backpressure, ServeClient,
                                                ServeError)
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig,
                                                make_http_server)

    edges, _, ids = karate_edges
    svc = ConsensusService(ServeConfig(queue_depth=1, pin_sizing=False))
    httpd = make_http_server(svc, "127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    client = ServeClient(f"http://127.0.0.1:{port}", timeout=30.0)
    try:
        payload = dict(edges=edges.tolist(), n_nodes=len(ids),
                       algorithm="lpm", n_p=4, delta=0.1, max_rounds=2,
                       seed=1)
        sub = client.submit(**payload)
        assert sub["state"] == "queued"
        with pytest.raises(Backpressure) as e:
            client.submit(**dict(payload, seed=2))
        assert e.value.payload["backpressure"] is True
        # unknown routes / ids and malformed bodies answer, not crash
        with pytest.raises(ServeError):
            client.status("nope")
        with pytest.raises(ServeError):
            client._request("/submit", {"edges": []})
        with pytest.raises(ServeError) as e:    # one-token edgelist line
            client._request("/submit", {"edgelist": "0 1\n5\n"})
        assert e.value.status == 400 and "line 2" in str(e.value)
        with pytest.raises(ServeError) as e:    # priority out of range
            client.submit(**dict(payload, seed=9, priority=-1_000_000))
        assert e.value.status == 400 and "priority" in str(e.value)
        svc.start()
        res = client.wait(sub["job_id"], timeout=120)
        assert res["n_nodes"] == len(ids)
        assert len(res["partitions"]) == 4
        assert client.status(sub["job_id"])["state"] == "done"
        again = client.submit(**payload)
        assert again["cached"] is True
        h = client.healthz()
        assert h["ok"] and not h["draining"]
        m = client.metricsz()
        json.dumps(m)  # fully JSON-serializable
        assert m["fcobs"]["counters"].get("serve.cache.hit", 0) >= 1
        assert m["serve"]["buckets"]
        svc.begin_drain()
        with pytest.raises(ServeError) as e:
            client.submit(**dict(payload, seed=3))
        assert e.value.status == 503
    finally:
        httpd.shutdown()
        httpd.server_close()
        assert svc.drain(30)
