"""fcfleet: consistent-hash ring, router forwarding, typed client
stats, and the cache-persistence pins the fleet's death-inheritance
path rides on (serve/router.py, serve/fleet.py, serve/client.py,
serve/cache.py)."""

import math
import os
import random
import subprocess
import sys
import threading

import pytest

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


# -- HashRing ----------------------------------------------------------


def _keys(n):
    return [f"bucket-{i:04d}" for i in range(n)]


def test_ring_join_moves_at_most_a_fair_share():
    """Consistent hashing's whole point: a joiner takes ~1/(N+1) of the
    keyspace, and NOTHING else moves — every re-homed key moves TO the
    joiner.  At the default vnode count the movement must stay within
    the fair share ceil(B/(N+1)) for every probed keyspace size."""
    from fastconsensus_tpu.serve.router import DEFAULT_VNODES, HashRing

    members = ("r0", "r1", "r2")
    for n_keys in (120, 200, 256):
        keys = _keys(n_keys)
        ring = HashRing(members, vnodes=DEFAULT_VNODES)
        before = {k: ring.route(k) for k in keys}
        ring.add("r3")
        after = {k: ring.route(k) for k in keys}
        moved = {k for k in keys if before[k] != after[k]}
        cap = math.ceil(n_keys / (len(members) + 1))
        assert len(moved) <= cap, (
            f"{len(moved)} of {n_keys} keys moved on join; "
            f"fair-share cap is {cap}")
        assert all(after[k] == "r3" for k in moved), \
            "a join must only move keys TO the joiner"


def test_ring_exclusion_rehomes_minimally_and_is_reversible():
    """Cordon = exclusion at lookup, not ring surgery: only the
    excluded member's keys move (to ring successors), and lifting the
    exclusion restores every original home — recovery must not
    trigger a second re-home."""
    from fastconsensus_tpu.serve.router import HashRing, NoEligibleReplica

    ring = HashRing(("a", "b", "c"))
    keys = _keys(150)
    before = {k: ring.route(k) for k in keys}
    excluded = frozenset({"b"})
    for k in keys:
        owner = ring.route(k, excluded)
        assert owner != "b"
        if before[k] != "b":
            assert owner == before[k], \
                "exclusion moved a key the excluded member never owned"
    assert {ring.route(k) for k in keys} == {before[k] for k in keys}
    assert all(ring.route(k) == before[k] for k in keys)
    with pytest.raises(NoEligibleReplica):
        ring.route("anything", frozenset({"a", "b", "c"}))


def test_ring_preview_owner_names_the_donor():
    """preview_owner must name the CURRENT owner of exactly the keys a
    joiner would take (the prewarm-shipping donor), and None for keys
    that stay put."""
    from fastconsensus_tpu.serve.router import HashRing

    ring = HashRing(("a", "b", "c"))
    keys = _keys(200)
    before = {k: ring.route(k) for k in keys}
    trial = HashRing(("a", "b", "c", "d"), vnodes=ring.vnodes)
    for k in keys:
        donor = ring.preview_owner(k, "d")
        if trial.route(k) == "d":
            assert donor == before[k]
        else:
            assert donor is None


def test_ring_placement_is_cross_process_deterministic():
    """Two routers (two PROCESSES) with the same member set must agree
    on every placement — the ring must be sha1-stable, never
    PYTHONHASHSEED-dependent.  The child also runs with jax poisoned:
    the ring is part of the jax-free router tier."""
    from fastconsensus_tpu.serve.router import HashRing

    members = ("r0", "r1", "r2", "r3")
    keys = _keys(64)
    local = [HashRing(members).route(k) for k in keys]
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "from fastconsensus_tpu.serve.router import HashRing\n"
        f"ring = HashRing({members!r})\n"
        f"print(';'.join(ring.route(k) for k in {keys!r}))\n")
    env = dict(os.environ, PYTHONPATH=REPO_ROOT, PYTHONHASHSEED="77")
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO_ROOT,
                         env=env, capture_output=True, text=True,
                         timeout=60)
    assert res.returncode == 0, res.stdout + res.stderr
    assert res.stdout.strip().split(";") == local


# -- route_key ---------------------------------------------------------


def test_route_key_matches_bucketer_grid_and_ignores_seed():
    """route_key's shape classes must agree with the grid the replica
    actually pads onto (serve/bucketer.py) — affinity that disagrees
    with bucketing warms every bucket everywhere — and distinct seeds
    of one config must share a key (they coalesce into one batched
    call on the replica)."""
    from fastconsensus_tpu.serve import bucketer
    from fastconsensus_tpu.serve.router import route_key

    for n_nodes, n_edges in ((34, 78), (64, 96), (100, 500), (65, 193)):
        payload = {"edges": [[0, 1]] * n_edges, "n_nodes": n_nodes,
                   "algorithm": "louvain", "n_p": 4, "seed": 1}
        b = bucketer.bucket_for(n_nodes, n_edges)
        assert route_key(payload).startswith(b.key() + "|")
        assert route_key(payload) == route_key(dict(payload, seed=99))
    # config-minus-seed fields keep traffic apart
    base = {"edges": [[0, 1]] * 64, "n_nodes": 34, "n_p": 4}
    assert route_key(base) != route_key(dict(base, n_p=8))
    assert route_key(base) != route_key(dict(base, tau=0.3))
    # edgelist payloads count raw lines, comments/blanks excluded
    el = "# header\n0 1\n1 2\n\n2 3\n"
    assert route_key({"edgelist": el, "n_nodes": 34}) == \
        route_key({"edges": [[0, 1]] * 3, "n_nodes": 34})


# -- jax-free tier + typed stats --------------------------------------


def test_fleet_tier_is_jax_free_and_stats_parse():
    """The whole router tier (router.py, fleet.py) plus the typed
    FleetStats/ReplicaState client views must import and work with jax
    POISONED — the front-end ships to boxes with no accelerator
    stack."""
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "from fastconsensus_tpu.serve.router import (\n"
        "    FleetRouter, HashRing, route_key)\n"
        "from fastconsensus_tpu.serve.fleet import FleetManager\n"
        "from fastconsensus_tpu.serve.client import (\n"
        "    FleetStats, ReplicaState)\n"
        "fs = FleetStats.from_payload({\n"
        "    'replicas': [\n"
        "        {'name': 'a', 'url': 'http://h:1', 'state': 'up',\n"
        "         'queue_depth': 3, 'queue_max_depth': 64,\n"
        "         'watchdog_trips': 0},\n"
        "        {'name': 'b', 'url': 'http://h:2',\n"
        "         'state': 'cordoned', 'cordon_reason': 'trip',\n"
        "         'retry_after_hint_s': 1.5}],\n"
        "    'ring': {'members': ['a', 'b'], 'vnodes': 128},\n"
        "    'assignments': {'n64_e96|': 'a'},\n"
        "    'jobs_tracked': 7, 'jobs_in_flight': 2,\n"
        "    'content_hash_index': 5,\n"
        "    'counters': {'serve.fleet.cordons': 1}})\n"
        "assert [r.name for r in fs.up] == ['a']\n"
        "assert fs.replicas[1].cordoned\n"
        "assert fs.replicas[1].retry_after_hint_s == 1.5\n"
        "assert fs.ring_members == ('a', 'b') and fs.vnodes == 128\n"
        "assert fs.counters['serve.fleet.cordons'] == 1\n"
        "assert fs.assignments == {'n64_e96|': 'a'}\n"
        "print('fleet jax-free ok')\n")
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO_ROOT,
                         env=env, capture_output=True, text=True,
                         timeout=60)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "fleet jax-free ok" in res.stdout


# -- ServeClient.retry -------------------------------------------------


def test_client_retry_honors_typed_hint_with_backoff_and_jitter():
    """retry() must sleep the server's TYPED retry_after_s scaled by
    backoff**attempt plus bounded jitter — never a blind fixed
    backoff — and re-raise the final Backpressure."""
    from fastconsensus_tpu.serve.client import Backpressure, ServeClient

    client = ServeClient("http://127.0.0.1:1")   # never dialed
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise Backpressure(429, {"backpressure": True},
                               retry_after_s=2.0)
        return "served"

    out = client.retry(flaky, attempts=4, backoff=1.5, jitter_frac=0.1,
                       sleep=sleeps.append, rng=random.Random(7))
    assert out == "served" and calls["n"] == 3
    assert len(sleeps) == 2
    for attempt, s in enumerate(sleeps):
        base = 2.0 * (1.5 ** attempt)
        assert base <= s <= base * 1.1, \
            f"sleep {s} outside [{base}, {base * 1.1}]"

    def always_shedding():
        raise Backpressure(429, {"backpressure": True, "shed": True},
                           retry_after_s=0.5)

    sleeps.clear()
    with pytest.raises(Backpressure):
        client.retry(always_shedding, attempts=3,
                     sleep=sleeps.append, rng=random.Random(7))
    assert len(sleeps) == 2          # final attempt re-raises, no sleep
    with pytest.raises(ValueError):
        client.retry(flaky, attempts=0)
    with pytest.raises(ValueError):
        client.retry(flaky, backoff=0.5)


# -- ResultCache persistence pins (the death-inheritance substrate) ---


def _cacheable(seed):
    import numpy as np

    return {"partitions": [np.full(8, seed, dtype=np.int32)],
            "n_nodes": 8, "seed": seed}


def test_cache_spill_if_dirty_skips_clean_and_concurrent(tmp_path):
    """The fcfleet periodic-spill contract: dirty -> spill count,
    clean -> 0 without touching disk, concurrent spill holding the
    lock -> -1 plus a counter, and a reload marks the cache dirty (the
    inheritor must re-spill what it inherited or a second death loses
    it)."""
    from fastconsensus_tpu.obs import counters as obs_counters
    from fastconsensus_tpu.serve.cache import ResultCache

    path = str(tmp_path / "spill.npz")
    c = ResultCache(max_entries=8, ttl_seconds=600.0)
    c.put("h1", _cacheable(1))
    assert c.spill_if_dirty(path) == 1
    mtime = os.path.getmtime(path)
    assert c.spill_if_dirty(path) == 0       # clean: no rewrite
    assert os.path.getmtime(path) == mtime
    c.put("h2", _cacheable(2))
    base = obs_counters.get_registry().counters()
    assert c._spill_lock.acquire(blocking=False)
    try:
        assert c.spill_if_dirty(path) == -1  # concurrent writer holds it
    finally:
        c._spill_lock.release()
    since = obs_counters.get_registry().counters_since(base)
    assert since.get("serve.cache.persist_concurrent_skip", 0) == 1
    assert c.spill_if_dirty(path) == 2       # still dirty, spills now

    heir = ResultCache(max_entries=8, ttl_seconds=600.0)
    assert heir.load(path) == 2
    assert heir.spill_if_dirty(str(tmp_path / "re.npz")) == 2


# -- router forwarding over a live replica ----------------------------


@pytest.fixture
def replica():
    """One real loopback replica with its worker NOT started, so queue
    contents are observable and deterministic."""
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig,
                                                make_http_server)
    from fastconsensus_tpu.serve.shaping import ShapingConfig

    svc = ConsensusService(ServeConfig(queue_depth=16, pin_sizing=False,
                                       shaping=ShapingConfig(shed=False)))
    httpd = make_http_server(svc, "127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        yield svc, f"http://127.0.0.1:{port}"
    finally:
        httpd.shutdown()
        svc.queue.close()


def test_router_forwarding_preserves_edf_order(replica):
    """Priority submitted THROUGH the router must come out of the
    replica's admission queue EDF-major exactly as if submitted
    directly — forwarding must not flatten the priority band."""
    import json

    from fastconsensus_tpu.serve.jobs import (PRIORITY_BATCH,
                                              PRIORITY_INTERACTIVE,
                                              PRIORITY_NORMAL)
    from fastconsensus_tpu.serve.router import FleetRouter

    svc, url = replica
    router = FleetRouter({"r0": url}, poll_s=60.0)
    router.poll_once()
    edges = [[i, (i + 1) % 12] for i in range(12)]
    submitted = []
    for seed, prio in enumerate((PRIORITY_BATCH, PRIORITY_NORMAL,
                                 PRIORITY_INTERACTIVE)):
        body = json.dumps({"edges": edges, "n_nodes": 12,
                           "algorithm": "lpm", "n_p": 2,
                           "max_rounds": 2, "seed": seed,
                           "priority": prio}).encode("utf-8")
        status, out, _ = router.submit(body)
        assert status == 202, out
        assert out["fleet_replica"] == "r0"
        submitted.append((prio, out["job_id"]))
    pops = [svc.queue.pop(timeout=5.0) for _ in submitted]
    # PRIORITY_INTERACTIVE=0 < NORMAL=1 < BATCH=2: the heap pops the
    # lowest priority number first
    assert [j.spec.priority for j in pops] == sorted(
        p for p, _ in submitted), \
        "queue must drain interactive -> normal -> batch"
    stats = router.fleet_stats()
    assert stats["jobs_tracked"] == 3
    assert set(stats["assignments"].values()) == {"r0"}


def test_router_cordon_routes_around_dead_replica(replica):
    """A cordoned replica must receive NOTHING (exclusion at lookup),
    and uncordon must restore it without a restart."""
    import json

    from fastconsensus_tpu.serve.router import FleetRouter

    svc, url = replica
    # "ghost" listens nowhere: if routing ever picks it the forward
    # errors out and the counters show it
    router = FleetRouter({"live": url, "ghost": "http://127.0.0.1:9"},
                         poll_s=60.0)
    router.cordon("ghost", "test: known dead")
    for seed in range(6):
        body = json.dumps({"edges": [[0, 1], [1, 2]], "n_nodes": 8,
                           "algorithm": "lpm", "n_p": 2,
                           "max_rounds": 2, "seed": seed,
                           "tau": seed / 10.0}).encode("utf-8")
        status, out, _ = router.submit(body)
        assert status == 202 and out["fleet_replica"] == "live"
    stats = router.fleet_stats()
    assert set(stats["assignments"].values()) == {"live"}
    states = {r["name"]: r["state"] for r in stats["replicas"]}
    assert states == {"live": "up", "ghost": "cordoned"}
    router.uncordon("ghost")
    states = {r["name"]: r["state"]
              for r in router.fleet_stats()["replicas"]}
    assert states["ghost"] == "up"
