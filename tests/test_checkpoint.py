"""Checkpoint / resume + tracing (SURVEY.md §5 rebuild subsystems)."""

import logging
import os

import jax
import numpy as np

from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
from fastconsensus_tpu.graph import pack_edges
from fastconsensus_tpu.models.registry import get_detector
from fastconsensus_tpu.utils.checkpoint import (load_checkpoint,
                                                save_checkpoint)
from fastconsensus_tpu.utils.synth import planted_partition
from fastconsensus_tpu.utils.trace import RoundTracer, phase_timer


def _slab():
    edges, _ = planted_partition(120, 4, 0.35, 0.02, seed=8)
    return pack_edges(edges, 120)


def test_checkpoint_roundtrip(tmp_path):
    slab = _slab()
    path = str(tmp_path / "state.npz")
    key_data = np.asarray(jax.random.key_data(jax.random.key(7)))
    history = [{"round": 1, "n_alive": 3}]
    save_checkpoint(path, slab, 1, key_data, history, extra={"alg": "lpm"})
    slab2, rounds, kd, hist, extra = load_checkpoint(path)
    assert rounds == 1
    assert hist == history
    assert extra == {"alg": "lpm"}
    assert np.array_equal(kd, key_data)
    assert np.array_equal(np.asarray(slab2.src), np.asarray(slab.src))
    assert np.array_equal(np.asarray(slab2.alive), np.asarray(slab.alive))
    assert slab2.n_nodes == slab.n_nodes


def test_resume_matches_uninterrupted_run(tmp_path):
    """A run checkpointed every round and resumed after round 1 must land on
    the same final graph as the same run left alone (same PRNG stream)."""
    slab = _slab()
    detect = get_detector("lpm")
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.0,
                          max_rounds=3, seed=3)

    full = run_consensus(slab, detect, cfg)

    path = str(tmp_path / "ck.npz")
    cfg1 = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.0,
                          max_rounds=1, seed=3)
    run_consensus(slab, detect, cfg1, checkpoint_path=path)
    assert os.path.exists(path)
    resumed = run_consensus(slab, detect, cfg, checkpoint_path=path,
                            resume=True)

    assert resumed.rounds == full.rounds
    assert np.array_equal(np.asarray(resumed.graph.alive),
                          np.asarray(full.graph.alive))
    assert np.allclose(np.asarray(resumed.graph.weight),
                       np.asarray(full.graph.weight))
    for a, b in zip(resumed.partitions, full.partitions):
        assert np.array_equal(a, b)


def test_resume_rejects_mismatched_config(tmp_path):
    import pytest

    slab = _slab()
    detect = get_detector("lpm")
    path = str(tmp_path / "ck.npz")
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.0,
                          max_rounds=1, seed=3)
    run_consensus(slab, detect, cfg, checkpoint_path=path)
    bad = ConsensusConfig(algorithm="lpm", n_p=4, tau=0.5, delta=0.0,
                          max_rounds=2, seed=3)
    with pytest.raises(ValueError, match="different run configuration"):
        run_consensus(slab, detect, bad, checkpoint_path=path, resume=True)


def test_resume_after_convergence_is_a_noop(tmp_path):
    slab = _slab()
    detect = get_detector("lpm")
    path = str(tmp_path / "ck.npz")
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=1.0,
                          max_rounds=4, seed=3)  # delta=1: converges round 1
    first = run_consensus(slab, detect, cfg, checkpoint_path=path)
    assert first.converged and first.rounds == 1
    again = run_consensus(slab, detect, cfg, checkpoint_path=path,
                          resume=True)
    assert again.converged and again.rounds == first.rounds
    assert np.array_equal(np.asarray(again.graph.weight),
                          np.asarray(first.graph.weight))


def test_round_tracer_records_and_logs(tmp_path, caplog):
    slab = _slab()
    tracer = RoundTracer(jsonl_path=str(tmp_path / "trace.jsonl"))
    cfg = ConsensusConfig(algorithm="lpm", n_p=4, tau=0.5, delta=0.02,
                          max_rounds=2, seed=0)
    with caplog.at_level(logging.INFO, logger="fastconsensus_tpu"):
        result = run_consensus(slab, get_detector("lpm"), cfg,
                               on_round=tracer.on_round)
    assert len(tracer.records) == result.rounds
    assert all("round_seconds" in r for r in tracer.records)
    assert any("edges alive" in m for m in caplog.messages)
    with open(tmp_path / "trace.jsonl") as fh:
        assert len(fh.readlines()) == result.rounds


def test_phase_timer_sink():
    sink = {}
    with phase_timer("pack", sink):
        pass
    assert "pack" in sink and sink["pack"] >= 0.0
