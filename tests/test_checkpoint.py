"""Checkpoint / resume + tracing (SURVEY.md §5 rebuild subsystems)."""

import logging
import os

import jax
import numpy as np

from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
from fastconsensus_tpu.graph import pack_edges
from fastconsensus_tpu.models.registry import get_detector
from fastconsensus_tpu.utils.checkpoint import (load_checkpoint,
                                                save_checkpoint)
from fastconsensus_tpu.utils.synth import planted_partition
from fastconsensus_tpu.utils.trace import RoundTracer, phase_timer


def _slab():
    edges, _ = planted_partition(120, 4, 0.35, 0.02, seed=8)
    return pack_edges(edges, 120)


def test_checkpoint_roundtrip(tmp_path):
    slab = _slab()
    path = str(tmp_path / "state.npz")
    key_data = np.asarray(jax.random.key_data(jax.random.key(7)))
    history = [{"round": 1, "n_alive": 3}]
    save_checkpoint(path, slab, 1, key_data, history, extra={"alg": "lpm"})
    slab2, rounds, kd, hist, extra = load_checkpoint(path)
    assert rounds == 1
    assert hist == history
    assert extra == {"alg": "lpm"}
    assert np.array_equal(kd, key_data)
    assert np.array_equal(np.asarray(slab2.src), np.asarray(slab.src))
    assert np.array_equal(np.asarray(slab2.alive), np.asarray(slab.alive))
    assert slab2.n_nodes == slab.n_nodes


def test_resume_matches_uninterrupted_run(tmp_path):
    """A run checkpointed every round and resumed after round 1 must land on
    the same final graph as the same run left alone (same PRNG stream)."""
    slab = _slab()
    detect = get_detector("lpm")
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.0,
                          max_rounds=3, seed=3)

    full = run_consensus(slab, detect, cfg)

    path = str(tmp_path / "ck.npz")
    cfg1 = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.0,
                          max_rounds=1, seed=3)
    run_consensus(slab, detect, cfg1, checkpoint_path=path)
    assert os.path.exists(path)
    resumed = run_consensus(slab, detect, cfg, checkpoint_path=path,
                            resume=True)

    assert resumed.rounds == full.rounds
    assert np.array_equal(np.asarray(resumed.graph.alive),
                          np.asarray(full.graph.alive))
    assert np.allclose(np.asarray(resumed.graph.weight),
                       np.asarray(full.graph.weight))
    for a, b in zip(resumed.partitions, full.partitions):
        assert np.array_equal(a, b)


def _hub_slab():
    """Skewed-degree graph above MATMUL_MAX_N nodes that packs with hybrid
    sizing: a sparse SBM base plus three cross-graph hubs (max degree blows
    the dense d_cap budget; p95 stays narrow, so d_hyb/hub_cap are set)."""
    n = 1400
    edges, _ = planted_partition(n, 8, 0.02, 0.001, seed=5)
    rng = np.random.default_rng(5)
    hubs = []
    for h in range(3):
        nbrs = rng.choice(n, size=900, replace=False)
        nbrs = nbrs[nbrs != h]
        hubs.append(np.stack([np.full(nbrs.size, h), nbrs], 1))
    return pack_edges(np.vstack([edges] + hubs), n)


def test_checkpoint_preserves_hybrid_sizing(tmp_path):
    """Round-trip keeps d_hyb/hub_cap, so select_move_path cannot flip
    hybrid -> hash on resume (round-2 VERDICT Weak #2)."""
    from fastconsensus_tpu.models.louvain import select_move_path

    slab = _hub_slab()
    assert slab.d_hyb > 0 and slab.hub_cap > 0
    assert select_move_path(slab) == "hybrid"
    path = str(tmp_path / "state.npz")
    key_data = np.asarray(jax.random.key_data(jax.random.key(1)))
    save_checkpoint(path, slab, 1, key_data, [])
    slab2 = load_checkpoint(path)[0]
    assert (slab2.d_cap, slab2.cap_hint, slab2.d_hyb, slab2.hub_cap) == \
        (slab.d_cap, slab.cap_hint, slab.d_hyb, slab.hub_cap)
    assert select_move_path(slab2) == "hybrid"


def test_hub_resume_parity(tmp_path):
    """Resume on a hub-heavy slab matches the uninterrupted run bitwise AND
    keeps the hybrid move path across the round-trip."""
    from fastconsensus_tpu.models.louvain import select_move_path

    slab = _hub_slab()
    detect = get_detector("louvain")
    cfg = ConsensusConfig(algorithm="louvain", n_p=4, tau=0.2, delta=0.02,
                          max_rounds=2, seed=1)
    full = run_consensus(slab, detect, cfg)

    path = str(tmp_path / "ck.npz")
    cfg1 = ConsensusConfig(algorithm="louvain", n_p=4, tau=0.2, delta=0.02,
                           max_rounds=1, seed=1)
    run_consensus(slab, detect, cfg1, checkpoint_path=path)
    resumed = run_consensus(slab, detect, cfg, checkpoint_path=path,
                            resume=True)

    assert select_move_path(resumed.graph) == "hybrid"
    assert resumed.rounds == full.rounds
    assert np.array_equal(np.asarray(resumed.graph.alive),
                          np.asarray(full.graph.alive))
    assert np.allclose(np.asarray(resumed.graph.weight),
                       np.asarray(full.graph.weight))
    for a, b in zip(resumed.partitions, full.partitions):
        assert np.array_equal(a, b)


def test_legacy_v1_checkpoint_migrates_hybrid_sizing(tmp_path):
    """A v1 checkpoint (no d_hyb/hub_cap in meta) is migrated on resume:
    the driver re-derives the sizing from the caller's freshly packed slab
    instead of silently dropping to the hash path."""
    import json
    import zipfile

    slab = _hub_slab()
    detect = get_detector("louvain")
    path = str(tmp_path / "ck.npz")
    cfg1 = ConsensusConfig(algorithm="louvain", n_p=4, tau=0.2, delta=0.02,
                           max_rounds=1, seed=1)
    run_consensus(slab, detect, cfg1, checkpoint_path=path)

    # Rewrite the metadata blob as a version-1 checkpoint.
    with np.load(path) as z:
        arrays = {name: z[name].copy() for name in z.files}
    meta = json.loads(bytes(arrays["meta"]).decode())
    meta["version"] = 1
    del meta["d_hyb"], meta["hub_cap"]
    arrays["meta"] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    np.savez(path, **arrays)

    loaded, _, _, _, extra = load_checkpoint(path)
    assert extra.get("_legacy_v1") and loaded.d_hyb == 0

    cfg = ConsensusConfig(algorithm="louvain", n_p=4, tau=0.2, delta=0.02,
                          max_rounds=2, seed=1)
    resumed = run_consensus(slab, detect, cfg, checkpoint_path=path,
                            resume=True)
    # The migration's contract is that the hybrid path survives (not a
    # silent drop to the hash lowering); the exact values may legally
    # move later if densification fires a live budget re-derivation, so
    # assert the path, not the numbers.
    assert resumed.graph.d_hyb > 0
    assert resumed.graph.hub_cap > 0


def test_resume_rejects_mismatched_config(tmp_path):
    import pytest

    slab = _slab()
    detect = get_detector("lpm")
    path = str(tmp_path / "ck.npz")
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.0,
                          max_rounds=1, seed=3)
    run_consensus(slab, detect, cfg, checkpoint_path=path)
    bad = ConsensusConfig(algorithm="lpm", n_p=4, tau=0.5, delta=0.0,
                          max_rounds=2, seed=3)
    from fastconsensus_tpu.obs import get_registry

    get_registry().reset()  # fresh process resuming the wrong config
    with pytest.raises(ValueError, match="different run configuration"):
        run_consensus(slab, detect, bad, checkpoint_path=path, resume=True)
    # the REJECTED resume must not leak the dead run's counters into the
    # live registry (telemetry restore runs only after validation)
    assert get_registry().counters().get("rounds.total", 0) == 0


def test_resume_after_convergence_is_a_noop(tmp_path):
    slab = _slab()
    detect = get_detector("lpm")
    path = str(tmp_path / "ck.npz")
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=1.0,
                          max_rounds=4, seed=3)  # delta=1: converges round 1
    first = run_consensus(slab, detect, cfg, checkpoint_path=path)
    assert first.converged and first.rounds == 1
    again = run_consensus(slab, detect, cfg, checkpoint_path=path,
                          resume=True)
    assert again.converged and again.rounds == first.rounds
    assert np.array_equal(np.asarray(again.graph.weight),
                          np.asarray(first.graph.weight))


def test_resumed_run_reports_cumulative_counters(tmp_path):
    """Telemetry continuity (the ROADMAP "counter deltas in checkpoint
    metadata" item): a checkpoint carries the fcobs counter snapshot, and
    a resumed run in a FRESH process (simulated by resetting the
    process-global registry) delta-restores it — so the resumed run's
    totals are cumulative over the whole run, not just the survivor."""
    from fastconsensus_tpu.obs import get_registry
    from fastconsensus_tpu.utils.checkpoint import load_checkpoint

    registry = get_registry()
    slab = _slab()
    detect = get_detector("lpm")
    path = str(tmp_path / "ck.npz")
    cfg1 = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.0,
                           max_rounds=1, seed=3)
    registry.reset()
    first = run_consensus(slab, detect, cfg1, checkpoint_path=path)
    first_counts = registry.counters()
    assert first.rounds == 1 and first_counts["rounds.total"] == 1

    # the snapshot rode along in the checkpoint metadata
    extra = load_checkpoint(path)[4]
    assert extra["_telemetry"]["rounds.total"] == 1
    # snapshotted at checkpoint time — i.e. before the run's final
    # re-detection added its syncs
    assert 1 <= extra["_telemetry"]["host_sync.total"] <= \
        first_counts["host_sync.total"]

    # "new process": zeroed registry; the resume restores + accumulates
    registry.reset()
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.0,
                          max_rounds=3, seed=3)
    resumed = run_consensus(slab, detect, cfg, checkpoint_path=path,
                            resume=True)
    counts = registry.counters()
    # the resumed process itself only ran rounds 2..N, but its counters
    # report the whole run (round 1 restored from the checkpoint)
    assert resumed.rounds >= 2
    assert counts["rounds.total"] == resumed.rounds == \
        len(resumed.history), \
        "resumed run restarted counters at zero instead of cumulating"
    assert counts["host_sync.total"] > first_counts["host_sync.total"]
    # and the checkpoint written BY the resumed process carries the
    # cumulative totals forward (continuity chains across N restarts)
    extra = load_checkpoint(path)[4]
    assert extra["_telemetry"]["rounds.total"] == resumed.rounds
    registry.reset()


def test_checkpoint_telemetry_is_run_scoped(tmp_path):
    """Counts an unrelated earlier run left in the process-global
    registry must NOT leak into a later run's checkpoint telemetry (the
    library-usage pattern: nobody resets the registry between runs)."""
    from fastconsensus_tpu.obs import get_registry
    from fastconsensus_tpu.utils.checkpoint import load_checkpoint

    registry = get_registry()
    registry.reset()
    slab = _slab()
    detect = get_detector("lpm")
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.0,
                          max_rounds=2, seed=3)
    run_a = run_consensus(slab, detect, cfg)  # no checkpoint
    assert registry.counters()["rounds.total"] == run_a.rounds

    path = str(tmp_path / "ck.npz")
    run_b = run_consensus(slab, detect, cfg, checkpoint_path=path)
    # the registry is (by design) process-cumulative...
    assert registry.counters()["rounds.total"] == \
        run_a.rounds + run_b.rounds
    # ...but run B's checkpoint carries run B's counts only
    extra = load_checkpoint(path)[4]
    assert extra["_telemetry"]["rounds.total"] == run_b.rounds
    registry.reset()


def test_resume_in_same_process_does_not_double_count(tmp_path):
    """The delta restore must be a no-op when the process already holds
    the run's counts (immediate in-process resume after convergence)."""
    from fastconsensus_tpu.obs import get_registry

    registry = get_registry()
    slab = _slab()
    detect = get_detector("lpm")
    path = str(tmp_path / "ck.npz")
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=1.0,
                          max_rounds=4, seed=3)  # delta=1: converges r1
    registry.reset()
    first = run_consensus(slab, detect, cfg, checkpoint_path=path)
    assert first.converged and registry.counters()["rounds.total"] == 1
    again = run_consensus(slab, detect, cfg, checkpoint_path=path,
                          resume=True)
    assert again.rounds == first.rounds
    assert registry.counters()["rounds.total"] == 1, \
        "in-process resume double-counted the restored snapshot"
    registry.reset()


def test_round_tracer_records_and_logs(tmp_path, caplog):
    slab = _slab()
    tracer = RoundTracer(jsonl_path=str(tmp_path / "trace.jsonl"))
    cfg = ConsensusConfig(algorithm="lpm", n_p=4, tau=0.5, delta=0.02,
                          max_rounds=2, seed=0)
    with caplog.at_level(logging.INFO, logger="fastconsensus_tpu"):
        result = run_consensus(slab, get_detector("lpm"), cfg,
                               on_round=tracer.on_round)
    assert len(tracer.records) == result.rounds
    assert all("round_seconds" in r for r in tracer.records)
    assert any("edges alive" in m for m in caplog.messages)
    with open(tmp_path / "trace.jsonl") as fh:
        assert len(fh.readlines()) == result.rounds


def test_phase_timer_sink():
    sink = {}
    with phase_timer("pack", sink):
        pass
    assert "pack" in sink and sink["pack"] >= 0.0
