"""Louvain/Leiden local-move kernels: exactness on planted structure,
modularity quality vs networkx's reference implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fastconsensus_tpu.graph import host_edges, pack_edges
from fastconsensus_tpu.models.leiden import leiden_single
from fastconsensus_tpu.models.louvain import (aggregate, local_move,
                                              louvain_single,
                                              modularity_levels)
from fastconsensus_tpu.utils.metrics import modularity, nmi


def ring_of_cliques(n_cliques=4, k=5):
    edges = []
    for c in range(n_cliques):
        base = c * k
        for a in range(k):
            for b in range(a + 1, k):
                edges.append([base + a, base + b])
        edges.append([base, ((c + 1) % n_cliques) * k])
    truth = np.repeat(np.arange(n_cliques), k)
    return np.array(edges), n_cliques * k, truth


def test_louvain_ring_of_cliques_exact():
    edges, n, truth = ring_of_cliques()
    slab = pack_edges(edges, n)
    labels = np.asarray(louvain_single(slab, jax.random.key(0)))
    assert nmi(labels, truth) == 1.0


def test_louvain_karate_quality(karate_slab, karate_truth):
    u, v, w = host_edges(karate_slab)
    best_q = -1.0
    best_nmi = 0.0
    for s in range(3):
        labels = np.asarray(louvain_single(karate_slab, jax.random.key(s)))
        best_q = max(best_q, modularity(u, v, w, labels))
        best_nmi = max(best_nmi, nmi(labels, karate_truth))
    # python-louvain level-0 typically reaches Q ~ 0.40-0.42 on karate
    assert best_q > 0.32, f"modularity {best_q}"
    assert best_nmi > 0.4


def test_louvain_vs_networkx_quality(karate_slab):
    import networkx as nx

    u, v, w = host_edges(karate_slab)
    g = nx.Graph()
    g.add_nodes_from(range(34))
    g.add_edges_from(zip(u.tolist(), v.tolist()))
    nx_comms = nx.community.louvain_communities(g, seed=1)
    nx_labels = np.zeros(34, int)
    for i, c in enumerate(nx_comms):
        for node in c:
            nx_labels[node] = i
    q_nx = modularity(u, v, w, nx_labels)
    q_tpu = max(
        modularity(u, v, w,
                   np.asarray(modularity_levels(karate_slab,
                                                jax.random.key(s), 2)))
        for s in range(3))
    # multi-level TPU louvain within 90% of networkx louvain modularity
    assert q_tpu > 0.9 * q_nx, f"tpu {q_tpu} vs nx {q_nx}"


def test_louvain_weighted_respects_weights():
    # two triangles joined by a heavy edge: heavy edge dominates when weighted
    edges = np.array([[0, 1], [1, 2], [0, 2], [3, 4], [4, 5], [3, 5], [2, 3]])
    weights = np.array([1, 1, 1, 1, 1, 1, 100.0], np.float32)
    slab = pack_edges(edges, 6, weights=weights)
    labels = np.asarray(louvain_single(slab, jax.random.key(0)))
    assert labels[2] == labels[3]  # heavy edge endpoints co-clustered


def test_aggregate_preserves_weight_mass():
    edges, n, truth = ring_of_cliques()
    slab = pack_edges(edges, n)
    agg = aggregate(slab, jnp.asarray(truth, dtype=jnp.int32))
    # total weight preserved (self-loops hold intra-community mass)
    assert float(jnp.sum(jnp.where(agg.alive, agg.weight, 0.0))) == \
        pytest.approx(float(jnp.sum(jnp.where(slab.alive, slab.weight, 0.0))))
    u, v, w = host_edges(agg)
    loops = {(int(a), int(b)): float(x) for a, b, x in zip(u, v, w)
             if a == b}
    assert all(val == 10.0 for val in loops.values())  # 10 intra edges/clique
    assert len(loops) == 4


def test_leiden_ring_of_cliques_exact_and_seeded():
    edges, n, truth = ring_of_cliques()
    slab = pack_edges(edges, n)
    a = np.asarray(leiden_single(slab, jax.random.key(5)))
    b = np.asarray(leiden_single(slab, jax.random.key(5)))
    assert (a == b).all()  # seeded determinism (fc:123 parity)
    assert nmi(a, truth) == 1.0


def test_leiden_refinement_connectivity():
    """The property leiden is named for (Traag et al. 2019; VERDICT #7):
    refined communities must induce *connected* subgraphs.  Checked on an
    LFR-1k graph, where greedy parallel moves do produce disconnected
    communities without the singleton-accretion constraint."""
    import networkx as nx

    from fastconsensus_tpu.models.leiden import refine
    from fastconsensus_tpu.models.louvain import local_move
    from fastconsensus_tpu.ops import segment as seg
    from fastconsensus_tpu.utils.synth import lfr_graph

    edges, _ = lfr_graph(1000, 0.4, seed=7)
    slab = pack_edges(edges, 1000)
    g = nx.Graph()
    g.add_nodes_from(range(1000))
    g.add_edges_from(edges.tolist())

    for s in range(3):
        k0, k1 = jax.random.split(jax.random.key(s))
        comm = local_move(slab, k0)
        refined = np.asarray(seg.compact_labels(
            refine(slab, comm, k1), 1000))
        for c in np.unique(refined):
            members = np.nonzero(refined == c)[0]
            if len(members) > 1:
                sub = g.subgraph(members.tolist())
                assert nx.is_connected(sub), \
                    f"refined community {c} disconnected (seed {s})"


def test_leiden_refinement_respects_communities():
    """Refinement must never merge across the constraining partition."""
    from fastconsensus_tpu.models.leiden import refine
    from fastconsensus_tpu.models.louvain import local_move
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, _ = planted_partition(300, 4, 0.2, 0.02, seed=2)
    slab = pack_edges(edges, 300)
    k0, k1 = jax.random.split(jax.random.key(0))
    comm = np.asarray(local_move(slab, k0))
    refined = np.asarray(refine(slab, jax.numpy.asarray(comm), k1))
    for c in np.unique(refined):
        parents = np.unique(comm[refined == c])
        assert len(parents) == 1, f"group {c} spans communities {parents}"


def test_leiden_karate_quality(karate_slab, karate_truth):
    u, v, w = host_edges(karate_slab)
    qs = []
    for s in range(3):
        labels = np.asarray(leiden_single(karate_slab, jax.random.key(s)))
        qs.append(modularity(u, v, w, labels))
    assert max(qs) > 0.35, f"leiden modularity {qs}"


def test_hash_totals_exact_without_collisions():
    # tiny candidate set, huge table: collisions impossible -> exact totals
    from fastconsensus_tpu.ops import segment as seg

    node = jnp.array([0, 0, 1, 1, 1, 2, 0], jnp.int32)
    label = jnp.array([5, 5, 5, 7, 7, 9, 9], jnp.int32)
    value = jnp.array([1., 2., 4., 8., 16., 32., 64.], jnp.float32)
    valid = jnp.array([1, 1, 1, 1, 1, 1, 0], bool)  # last entry masked
    tables = seg.build_hash_totals(node, label, value, valid, 1 << 16)
    got = np.asarray(seg.lookup_hash_totals(tables, node, label))
    np.testing.assert_allclose(got[:6], [3., 3., 4., 24., 24., 32.])
    # absent pair reads 0 (both buckets empty at this load)
    absent = seg.lookup_hash_totals(
        tables, jnp.array([3], jnp.int32), jnp.array([5], jnp.int32))
    assert float(absent[0]) == 0.0


def test_scatter_argmax_matches_sorted_argmax():
    from fastconsensus_tpu.ops import segment as seg

    rng = np.random.default_rng(0)
    e, n = 500, 40
    node = jnp.asarray(rng.integers(0, n, e), jnp.int32)
    label = jnp.asarray(rng.integers(0, 17, e), jnp.int32)
    score = jnp.asarray(rng.normal(size=e), jnp.float32)
    valid = jnp.asarray(rng.random(e) < 0.8)
    a_lab, a_sc, a_has = seg.scatter_argmax_label(node, score, label, valid, n)
    b_lab, b_sc, b_has = seg.argmax_label_per_node(node, score, label, valid, n)
    np.testing.assert_array_equal(np.asarray(a_has), np.asarray(b_has))
    np.testing.assert_allclose(np.asarray(a_sc)[np.asarray(a_has)],
                               np.asarray(b_sc)[np.asarray(b_has)])
    np.testing.assert_array_equal(np.asarray(a_lab), np.asarray(b_lab))


def test_move_path_parity(monkeypatch):
    """The approximate hash path must match the exact paths at NMI level
    (models/louvain.py hash-path docstring)."""
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, truth = planted_partition(400, 8, 0.3, 0.01, seed=3)
    slab = pack_edges(edges, 400)
    keys = jax.random.split(jax.random.key(0), 4)
    scores = {}
    for path in ("matmul", "hash", "hybrid", "runs"):
        monkeypatch.setenv("FCTPU_MOVE_PATH", path)
        from fastconsensus_tpu.models import louvain as lv

        assert lv.select_move_path(slab) == path
        labels = np.asarray(jax.vmap(
            lambda k: louvain_single(slab, k))(keys))
        scores[path] = float(np.mean([nmi(l, truth) for l in labels]))
    assert scores["hash"] > 0.9, scores
    assert scores["hybrid"] > 0.9, scores
    assert abs(scores["hash"] - scores["runs"]) < 0.08, scores
    assert abs(scores["hybrid"] - scores["runs"]) < 0.08, scores


def test_hybrid_on_skewed_degrees():
    """Hybrid's regime: a hub-heavy graph (star cores + communities).  The
    hub side (hashed prefix) and dense side must cooperate: quality close
    to the exact sorted-run oracle on the same slab."""
    import os

    from fastconsensus_tpu.models import louvain as lv
    from fastconsensus_tpu.utils.synth import planted_partition

    rng = np.random.default_rng(0)
    edges, truth = planted_partition(600, 6, 0.12, 0.004, seed=5)
    # graft 6 hubs: node h connects to 150 random others
    hubs = rng.choice(600, 6, replace=False)
    extra = np.array([[h, int(o)] for h in hubs
                      for o in rng.choice(600, 150, replace=False)
                      if int(o) != h])
    all_edges = np.vstack([edges, extra])
    slab = pack_edges(all_edges, 600)
    assert slab.d_hyb > 0 and slab.hub_cap > 0
    keys = jax.random.split(jax.random.key(1), 4)

    prev = os.environ.get("FCTPU_MOVE_PATH")
    try:
        os.environ["FCTPU_MOVE_PATH"] = "hybrid"
        hyb = np.asarray(jax.vmap(lambda k: louvain_single(slab, k))(keys))
        os.environ["FCTPU_MOVE_PATH"] = "runs"
        exact = np.asarray(jax.vmap(lambda k: louvain_single(slab, k))(keys))
    finally:
        os.environ.pop("FCTPU_MOVE_PATH", None)
        if prev is not None:
            os.environ["FCTPU_MOVE_PATH"] = prev
    s_h = float(np.mean([nmi(l, truth) for l in hyb]))
    s_e = float(np.mean([nmi(l, truth) for l in exact]))
    assert s_h > 0.8, (s_h, s_e)
    assert s_h > s_e - 0.08, (s_h, s_e)


def test_select_move_path_forced_fallbacks(monkeypatch):
    import dataclasses

    from fastconsensus_tpu.models import louvain as lv

    edges = np.array([[0, 1], [1, 2], [2, 3]])
    slab = pack_edges(edges, 4)
    assert lv.select_move_path(slab) == "matmul"
    nocap = dataclasses.replace(slab, d_cap=0)
    monkeypatch.setenv("FCTPU_MOVE_PATH", "dense")
    assert lv.select_move_path(nocap) == "runs"  # dense impossible
    monkeypatch.setenv("FCTPU_MOVE_PATH", "hash")
    assert lv.select_move_path(nocap) == "hash"
    # forced matmul on a huge-N slab must not materialize N^2 — falls back
    monkeypatch.setenv("FCTPU_MOVE_PATH", "matmul")
    big = dataclasses.replace(slab, n_nodes=100_000, d_cap=0)
    assert lv.select_move_path(big) == "runs"


def test_gamma_resolution_changes_granularity():
    # higher resolution -> more, smaller communities (mc's -g, made to work)
    from fastconsensus_tpu.models.registry import get_detector

    edges, n, truth = ring_of_cliques(6, 5)
    slab = pack_edges(edges, n)
    keys = jax.random.split(jax.random.key(0), 2)
    lo = np.asarray(get_detector("louvain", gamma=0.05)(slab, keys))
    hi = np.asarray(get_detector("louvain", gamma=8.0)(slab, keys))
    assert len(np.unique(hi[0])) > len(np.unique(lo[0]))
    # same (name, gamma) resolves to the same cached function object
    assert get_detector("louvain", gamma=8.0) is \
        get_detector("louvain", gamma=8.0)


def test_fused_dense_step_matches_unfused(monkeypatch):
    """The fused pallas sweep must pick the same moves as the unfused dense
    step up to tie-breaks (different jitter streams): compare want-counts
    and resulting partition quality on a planted graph."""
    import functools

    from fastconsensus_tpu.models import louvain as lv
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, truth = planted_partition(600, 6, 0.25, 0.01, seed=5)
    slab = pack_edges(edges, 600)
    monkeypatch.setenv("FCTPU_MOVE_PATH", "dense")

    monkeypatch.setenv("FCTPU_FUSED", "1")  # interpret-mode pallas on CPU
    lab_f = np.asarray(lv.louvain_single(slab, jax.random.key(0)))
    monkeypatch.setenv("FCTPU_FUSED", "0")
    lab_u = np.asarray(lv.louvain_single(slab, jax.random.key(0)))

    nmi_f, nmi_u = nmi(lab_f, truth), nmi(lab_u, truth)
    assert nmi_f > 0.9, (nmi_f, nmi_u)
    assert abs(nmi_f - nmi_u) < 0.05, (nmi_f, nmi_u)


def test_leiden_agg_compaction_paths(monkeypatch):
    """The compacted aggregate move (GraphSlab.agg_cap, round 5) must keep
    leiden's quality on both lowerings it can take: bit-exact on the
    matmul path (the dense W is built from alive edges only, so
    compaction cannot change it) and exact-recovery on the forced hash
    path (different bucket geometry => different tie noise is allowed,
    the structure is not)."""
    import dataclasses

    edges, n, truth = ring_of_cliques(6, 5)
    slab = pack_edges(edges, n)
    # pack_edges sizes agg_cap by default, but its 4096 floor exceeds this
    # tiny slab's capacity, which disables compaction (the leiden guard is
    # 0 < agg_cap < capacity) — pin a small cap that really compacts:
    # >= the 66 alive edges (lossless) and < the 148-slot capacity.
    assert slab.agg_cap > 0
    assert not 0 < slab.agg_cap < slab.capacity
    slab = dataclasses.replace(slab, agg_cap=80)
    off = dataclasses.replace(slab, agg_cap=0)

    a = np.asarray(leiden_single(slab, jax.random.key(3)))
    b = np.asarray(leiden_single(off, jax.random.key(3)))
    assert (a == b).all()  # matmul agg move: compaction is bit-inert
    assert nmi(a, truth) == 1.0

    monkeypatch.setenv("FCTPU_MOVE_PATH", "hash")
    c = np.asarray(leiden_single(slab, jax.random.key(3)))
    d = np.asarray(leiden_single(off, jax.random.key(3)))
    assert nmi(c, truth) == 1.0
    assert nmi(d, truth) == 1.0
