"""Synthetic generators + CPU reference oracle (SURVEY.md §4 protocol)."""

import numpy as np
import pytest

from fastconsensus_tpu.utils.metrics import nmi
from fastconsensus_tpu.utils.synth import planted_partition


def test_planted_partition_shapes_and_structure():
    edges, labels = planted_partition(400, 8, 0.25, 0.01, seed=0)
    assert labels.shape == (400,)
    assert len(np.unique(labels)) == 8
    assert np.all(edges[:, 0] < edges[:, 1])
    assert edges.max() < 400
    # intra-community edges should dominate at these densities
    intra = (labels[edges[:, 0]] == labels[edges[:, 1]]).mean()
    assert intra > 0.7


def test_planted_partition_is_seed_deterministic():
    a = planted_partition(200, 4, 0.3, 0.02, seed=9)
    b = planted_partition(200, 4, 0.3, 0.02, seed=9)
    assert np.array_equal(a[0], b[0])


@pytest.mark.slow
def test_lfr_graph_has_planted_communities():
    from fastconsensus_tpu.utils.synth import lfr_graph

    edges, labels = lfr_graph(300, 0.2, seed=1)
    assert labels.shape == (300,)
    assert len(np.unique(labels)) > 2
    intra = (labels[edges[:, 0]] == labels[edges[:, 1]]).mean()
    assert intra > 0.6


def test_cpu_reference_oracle_recovers_planted():
    from fastconsensus_tpu.baselines.cpu_reference import cpu_consensus

    edges, truth = planted_partition(250, 5, 0.3, 0.01, seed=4)
    parts, rounds = cpu_consensus(edges, 250, n_p=6, tau=0.2, delta=0.02,
                                  seed=0, max_rounds=8)
    assert len(parts) == 6
    assert rounds >= 1
    assert nmi(parts[0], truth) > 0.85
