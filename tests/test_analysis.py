"""fcheck static-analysis suite: per-rule fixtures, jaxpr audit over the
registered entry points, CLI exit codes, and the recompile guard
(including the 2-round consensus compile-budget pin)."""

import os
import subprocess
import sys

import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def _lint(name):
    from fastconsensus_tpu.analysis.astlint import lint_source

    path = os.path.join(FIXTURES, name)
    with open(path) as fh:
        src = fh.read()
    diags, suppressed = lint_source(src, filename=path)
    return diags, suppressed


RULE_FIXTURES = [
    ("bad_key_reuse.py", "ok_key_split.py", "key-reuse", 2),
    # the cross-function pass: a drawing helper propagates its
    # consumption to call sites; a derive-only helper stops counting as
    # a draw (the ok file is reuse-FLAGGED under intra-only analysis)
    ("bad_key_helper.py", "ok_key_helper.py", "key-reuse", 1),
    ("bad_traced_branch.py", "ok_lax_cond.py", "traced-branch", 2),
    ("bad_sync_loop.py", "ok_sync_outside.py", "sync-in-loop", 4),
    ("bad_f64.py", "ok_f32.py", "f64-dtype", 3),
    ("bad_retrace.py", "ok_retrace_cached.py", "retrace-risk", 1),
    ("bad_kernel_closure.py", "ok_kernel_module.py",
     "kernel-tracer-closure", 1),
    ("bad_mesh_axis.py", "ok_mesh_axis.py", "mesh-axis", 2),
]


@pytest.mark.parametrize("bad,ok,rule,n_bad", RULE_FIXTURES,
                         ids=[r[2] for r in RULE_FIXTURES])
def test_rule_fires_on_bad_and_not_on_ok(bad, ok, rule, n_bad):
    bad_diags, _ = _lint(bad)
    hits = [d for d in bad_diags if d.rule == rule]
    assert len(hits) == n_bad, (rule, [d.format() for d in bad_diags])
    ok_diags, _ = _lint(ok)
    assert not [d for d in ok_diags if d.rule == rule], \
        [d.format() for d in ok_diags]


def test_weak_static_arg_and_module_const_ride_along():
    diags, _ = _lint("bad_retrace.py")
    assert any(d.rule == "weak-static-arg" for d in diags)
    diags, _ = _lint("bad_kernel_closure.py")
    assert any(d.rule == "module-jnp-const" for d in diags)
    diags, _ = _lint("ok_kernel_module.py")
    assert not diags, [d.format() for d in diags]


def test_mesh_axis_silent_without_a_declared_mesh():
    """A module declaring no axis constants and no Mesh has no contract
    to check — bare psum("x") there must not fire (shard_map callees
    see axes their CALLER's mesh declares)."""
    from fastconsensus_tpu.analysis.astlint import lint_source

    diags, _ = lint_source(
        "import jax\n\n\ndef f(x):\n    return jax.lax.psum(x, 'x')\n",
        filename="<anon>")
    assert not [d for d in diags if d.rule == "mesh-axis"], \
        [d.format() for d in diags]


def test_mesh_axis_clean_on_the_real_sharding_modules():
    """The rule's raison d'être: parallel/sharding.py and
    ops/sharded_tail.py declare the ("p", "e") contract and must lint
    clean against it (a typo'd axis in either fails here before it
    fails at runtime on a real mesh)."""
    from fastconsensus_tpu.analysis import Report, lint_paths

    pkg = os.path.join(os.path.dirname(__file__), "..",
                       "fastconsensus_tpu")
    report = lint_paths([os.path.join(pkg, "parallel", "sharding.py"),
                         os.path.join(pkg, "ops", "sharded_tail.py")],
                        Report())
    assert not [d for d in report.diagnostics if d.rule == "mesh-axis"], \
        report.format_human()


def test_key_reuse_summaries_cross_module(tmp_path):
    """lint_paths' two-pass table: a derive-only helper in one module is
    recognized at call sites in ANOTHER module (import-alias
    resolution), and a drawing helper still counts as a draw there."""
    from fastconsensus_tpu.analysis import Report, lint_paths

    (tmp_path / "helpers.py").write_text(
        "import jax\n\n\n"
        "def fan(key, n):\n"
        "    return jax.random.split(key, n)\n\n\n"
        "def draw(key, shape):\n"
        "    return jax.random.uniform(key, shape)\n")
    (tmp_path / "ok_user.py").write_text(
        "import helpers as h\n\n\n"
        "def use(key):\n"
        "    a = h.fan(key, 2)\n"
        "    b = h.fan(key, 3)\n"       # derive-only helper: safe
        "    return a, b\n")
    (tmp_path / "bad_user.py").write_text(
        "from helpers import draw\n\n\n"
        "def use(key):\n"
        "    x = draw(key, (2,))\n"
        "    y = draw(key, (3,))\n"     # two draws on one key
        "    return x, y\n")
    report = lint_paths([str(tmp_path)], Report())
    by_file = {}
    for d in report.diagnostics:
        by_file.setdefault(os.path.basename(d.file), []).append(d.rule)
    assert "ok_user.py" not in by_file, by_file
    assert by_file.get("bad_user.py") == ["key-reuse"], by_file


def test_key_reuse_summaries_resolve_relative_imports(tmp_path):
    """Relative imports anchor against the importing file's package
    path, so `from .helpers import fan` resolves into the summary
    table exactly like its absolute spelling."""
    from fastconsensus_tpu.analysis import Report, lint_paths

    pkg = tmp_path / "fastconsensus_tpu"
    pkg.mkdir()
    (pkg / "helpers.py").write_text(
        "import jax\n\n\n"
        "def fan(key, n):\n"
        "    return jax.random.split(key, n)\n\n\n"
        "def draw(key, shape):\n"
        "    return jax.random.uniform(key, shape)\n")
    (pkg / "ok_rel.py").write_text(
        "from .helpers import fan\n\n\n"
        "def use(key):\n"
        "    return fan(key, 2), fan(key, 3)\n")   # derive-only: safe
    (pkg / "bad_rel.py").write_text(
        "from . import helpers as h\n\n\n"
        "def use(key):\n"
        "    x = h.draw(key, (2,))\n"
        "    return x, h.draw(key, (3,))\n")       # two draws, one key
    report = lint_paths([str(pkg)], Report())
    by_file = {}
    for d in report.diagnostics:
        by_file.setdefault(os.path.basename(d.file), []).append(d.rule)
    assert "ok_rel.py" not in by_file, by_file
    assert by_file.get("bad_rel.py") == ["key-reuse"], by_file


def test_key_reuse_helper_summaries_shapes():
    """summarize_key_params classifies deriver/draw/reuse weights."""
    from fastconsensus_tpu.analysis.astlint import summarize_key_params

    table = summarize_key_params(
        "import jax\n\n\n"
        "def derive(key):\n"
        "    return jax.random.fold_in(key, 1)\n\n\n"
        "def one(key):\n"
        "    return jax.random.bits(key, (2,), 'uint32')\n\n\n"
        "def two(key):\n"
        "    a = jax.random.uniform(key, (2,))\n"
        "    return a + jax.random.normal(key, (2,))\n")
    weights = {k: v["weights"]["key"] for k, v in table.items()}
    assert weights == {"derive": 0, "one": 1, "two": 2}


CONCURRENCY_FIXTURES = [
    ("bad_guarded_field.py", "ok_guarded_field.py", "guarded-field", 1),
    ("bad_lock_order.py", "ok_lock_order.py", "lock-order", 1),
    ("bad_blocking_lock.py", "ok_blocking_lock.py",
     "blocking-under-lock", 4),
    ("bad_notify_outside.py", "ok_notify_inside.py",
     "notify-outside-lock", 1),
    ("bad_root_write.py", "ok_root_write.py", "unguarded-root-write", 2),
]


def _lint_conc(name):
    """Concurrency rules run through lint_paths (the pass is
    whole-program, not per-source)."""
    from fastconsensus_tpu.analysis import Report, lint_paths

    return lint_paths([os.path.join(FIXTURES, name)],
                      Report()).diagnostics


@pytest.mark.parametrize("bad,ok,rule,n_bad", CONCURRENCY_FIXTURES,
                         ids=[r[2] for r in CONCURRENCY_FIXTURES])
def test_concurrency_rule_fires_on_bad_and_not_on_ok(bad, ok, rule,
                                                     n_bad):
    hits = [d for d in _lint_conc(bad) if d.rule == rule]
    assert len(hits) == n_bad, (rule, [d.format() for d in hits])
    assert not [d for d in _lint_conc(ok) if d.rule == rule], \
        [d.format() for d in _lint_conc(ok)]


def test_drain_since_prefix_race_is_caught_by_guarded_field():
    """ISSUE 7 acceptance: the PR 6 ``Tracer.drain_since`` pre-fix
    pattern — snapshot the span buffer outside the lock, clear it under
    the lock — reconstructed as a fixture, must be caught by the
    guarded-field rule at the unlocked snapshot."""
    hits = [d for d in _lint_conc("bad_guarded_field.py")
            if d.rule == "guarded-field"]
    assert len(hits) == 1, [d.format() for d in hits]
    assert "_events" in hits[0].message
    # ...and the fixed shape (one atomic snapshot+clear) is clean
    assert not _lint_conc("ok_guarded_field.py")


def test_concurrency_lock_order_cross_function_edge():
    """The cycle in bad_lock_order.py crosses a call boundary
    (_ledger held -> helper acquires _audit): the finding proves the
    call-table propagation works, not just lexical nesting."""
    hits = [d for d in _lint_conc("bad_lock_order.py")
            if d.rule == "lock-order"]
    assert len(hits) == 1
    assert "_ledger" in hits[0].message and "_audit" in hits[0].message


def test_static_lock_graph_of_the_repo_is_acyclic():
    """The whole package's static acquisition-order digraph must be
    acyclic (the same graph the runtime recorder is checked against in
    tests/test_concurrency_stress.py)."""
    from fastconsensus_tpu.analysis.concurrency import (find_cycle,
                                                        static_lock_graph)

    pkg = os.path.join(os.path.dirname(__file__), "..",
                       "fastconsensus_tpu")
    sources = {}
    for root, dirs, names in os.walk(pkg):
        dirs[:] = [d for d in dirs if d not in ("__pycache__", "build",
                                                "src")]
        for f in names:
            if f.endswith(".py"):
                path = os.path.join(root, f)
                with open(path, encoding="utf-8") as fh:
                    sources[path] = fh.read()
    graph = static_lock_graph(sources)
    assert graph, "expected at least one static lock-order edge"
    assert find_cycle(graph) is None, find_cycle(graph)


def test_find_cycle_detects_and_clears():
    from fastconsensus_tpu.analysis.concurrency import find_cycle

    assert find_cycle({("a", "b"), ("b", "c")}) is None
    cyc = find_cycle({("a", "b"), ("b", "c"), ("c", "a")})
    assert cyc is not None and set(cyc) == {"a", "b", "c"}
    assert find_cycle({("a", "a")}) == ["a"]


def test_cli_only_filters_rules():
    """--only keeps the selected rules (and skips the jaxpr audit when
    none of them is jaxpr-*), so CI can archive per-rule reports and a
    developer can iterate on one rule."""
    import json
    import tempfile

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    root = os.path.join(os.path.dirname(__file__), "..")
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "only.json")
        r = subprocess.run(
            [sys.executable, "-m", "fastconsensus_tpu.analysis",
             FIXTURES, "--quiet", "--only", "lock-order,guarded-field",
             "--json", out],
            cwd=root, env=env, capture_output=True, text=True,
            timeout=120)
        assert r.returncode == 1, r.stdout + r.stderr
        blob = json.loads(open(out).read())
        rules = {d["rule"] for d in blob["diagnostics"]}
        assert rules == {"lock-order", "guarded-field"}, rules
        # a bad fixture filtered down to an unrelated rule exits clean
        r2 = subprocess.run(
            [sys.executable, "-m", "fastconsensus_tpu.analysis",
             os.path.join(FIXTURES, "bad_lock_order.py"), "--quiet",
             "--only", "key-reuse"],
            cwd=root, env=env, capture_output=True, text=True,
            timeout=120)
        assert r2.returncode == 0, r2.stdout + r2.stderr


def test_lockorder_recorder_forced_inversion_is_caught():
    """Unit pin for the runtime half: two package locks acquired in
    both orders under the recorder must fail assert_acyclic, and the
    factories must be restored after the recording block."""
    import threading

    from fastconsensus_tpu.analysis import lockorder

    with lockorder.recording() as rec:
        from fastconsensus_tpu.serve.cache import ResultCache
        from fastconsensus_tpu.serve.queue import AdmissionQueue

        q = AdmissionQueue(4)
        c = ResultCache(max_entries=4)
        with c._lock:
            q.depth()          # cache -> queue
        rec.assert_acyclic()   # one direction alone is fine
        with q._cond:
            c.get("k")         # queue -> cache: the inversion
        with pytest.raises(AssertionError, match="lock-order cycle"):
            rec.assert_acyclic()
    if not lockorder._installed:
        # outside FCTPU_LOCK_ORDER=1 runs the recording block must
        # restore the real factories; under env-install they stay
        # patched by design (the suite-wide recorder keeps going)
        assert threading.Lock is lockorder._REAL["Lock"]


def test_pragma_suppresses_and_is_counted():
    diags, suppressed = _lint("ok_sync_outside.py")
    assert not diags, [d.format() for d in diags]
    assert suppressed == 1  # the documented_driver pragma


def test_diagnostic_json_roundtrip():
    import json

    from fastconsensus_tpu.analysis import Report, lint_paths

    report = lint_paths([FIXTURES], Report())
    blob = json.loads(report.to_json())
    assert blob["tool"] == "fcheck"
    assert blob["n_diagnostics"] == len(report.diagnostics) > 0
    rules = {d["rule"] for d in blob["diagnostics"]}
    assert "key-reuse" in rules and "sync-in-loop" in rules


def test_repo_lints_clean():
    """The package itself must stay clean — new violations fail here
    before they fail CI."""
    from fastconsensus_tpu.analysis import Report, lint_paths

    pkg = os.path.join(os.path.dirname(__file__), "..",
                       "fastconsensus_tpu")
    report = lint_paths([pkg], Report())
    assert not report.diagnostics, report.format_human()


def test_cli_exit_codes():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    root = os.path.join(os.path.dirname(__file__), "..")
    bad = subprocess.run(
        [sys.executable, "-m", "fastconsensus_tpu.analysis", FIXTURES,
         "--quiet"],
        cwd=root, env=env, capture_output=True, text=True, timeout=120)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    ok = subprocess.run(
        [sys.executable, "-m", "fastconsensus_tpu.analysis",
         os.path.join(FIXTURES, "ok_key_split.py"), "--quiet"],
        cwd=root, env=env, capture_output=True, text=True, timeout=120)
    assert ok.returncode == 0, ok.stdout + ok.stderr


def test_jaxpr_audit_passes_on_all_entry_points():
    """Every registered jitted entry point traces at canonical shapes
    with no forbidden primitives."""
    from fastconsensus_tpu.analysis.jaxpr_audit import audit_entry_points

    diags, summary = audit_entry_points()
    assert not diags, [d.format() for d in diags]
    # the canonical surface: ops + engine + the three jax detectors
    names = set(summary)
    for expected in ("ops.comembership_counts", "engine.consensus_tail",
                     "models.louvain", "models.leiden", "models.lpm",
                     "engine.consensus_round[louvain]"):
        assert expected in names, sorted(names)
    # the audit actually inspected real programs (primitive histograms)
    assert any(h for h in summary.values())


def test_jaxpr_audit_flags_f64_and_device_put():
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.analysis.jaxpr_audit import audit_jaxpr

    def leaky(x):
        return jax.device_put(x) * 2

    closed = jax.make_jaxpr(leaky)(jnp.ones((4,)))
    diags, _ = audit_jaxpr(closed, "leaky")
    assert any(d.rule == "jaxpr-device-put" for d in diags)

    jax.config.update("jax_enable_x64", True)
    try:
        def f64(x):
            return x.astype(jnp.float64) + 1.0

        closed = jax.make_jaxpr(f64)(jnp.ones((4,), jnp.float32))
        diags, _ = audit_jaxpr(closed, "f64")
        assert any(d.rule == "jaxpr-f64" for d in diags)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_compile_guard_counts_and_bounds():
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.analysis import (CompileGuard, RecompileError,
                                            assert_max_compiles)

    @jax.jit
    def f(x):
        return x * 3 + 1

    with CompileGuard() as g:
        f(jnp.ones((5,)))
    first = g.count
    assert first >= 1
    with CompileGuard() as g2:
        f(jnp.ones((5,)))  # cached shape: no compile
    assert g2.count == 0
    with pytest.raises(RecompileError):
        with assert_max_compiles(0):
            f(jnp.ones((7,)))  # new shape must breach a zero budget


def test_consensus_two_rounds_compile_budget(karate_slab):
    """Tier-1 pin: a 2-round consensus run stays within its compile
    budget, and an identical second run compiles NOTHING (the
    engine._jitted_round lru-cache contract).  A fresh-wrapper-per-round
    regression fails both."""
    from fastconsensus_tpu.analysis import CompileGuard, assert_max_compiles
    from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
    from fastconsensus_tpu.models.registry import get_detector

    cfg = ConsensusConfig(algorithm="louvain", n_p=6, tau=0.2, delta=0.02,
                          max_rounds=2, seed=0)
    det = get_detector("louvain")
    # measured 15 cold compiles (detect/warm/block/final variants + small
    # utility programs); 24 leaves version headroom without masking a
    # per-round retrace (2 rounds x ~15 would blow it)
    with CompileGuard(max_compiles=24) as g:
        res = run_consensus(karate_slab, det, cfg)
    assert res.rounds >= 1
    assert g.count >= 1  # the guard actually observed the cold compiles
    with assert_max_compiles(0):
        run_consensus(karate_slab, det, cfg)


@pytest.mark.slow
def test_lfr10k_leiden_split_phase_compile_budget(monkeypatch):
    """ROADMAP open item (PR 2): the chunked-detection (split-phase) path
    has its own executable set — detect chunks via _jitted_detect, the
    standalone _jitted_tail, per-variant warm/cold detectors — so the
    2-round karate pin (whole rounds fused in one executable) cannot see
    a retrace there.  Pin it on the lfr10k leiden config, with the member
    count forced below n_p so the split path is taken deterministically.

    Measured 40 cold compiles (incl. the one mid-run budget-rederive
    recompile on this graph); 56 leaves version headroom without masking
    a per-round retrace (2 rounds x ~40 would blow it).  The second
    identical run must compile NOTHING — the same lru-cache contract the
    karate pin enforces, now covering the split-phase executables."""
    from fastconsensus_tpu.analysis import CompileGuard, assert_max_compiles
    from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
    from fastconsensus_tpu.graph import pack_edges
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils import synth

    monkeypatch.setenv("FCTPU_DETECT_CALL_MEMBERS", "4")  # 8 members -> 2
    # chunks per round: the split path, regardless of rate estimates
    edges, _ = synth.lfr_graph(10_000, 0.5, seed=42)
    slab = pack_edges(edges, 10_000)
    cfg = ConsensusConfig(algorithm="leiden", n_p=8, tau=0.2, delta=0.02,
                          max_rounds=2, seed=0, closure_tau=0.2)
    det = get_detector("leiden")
    with CompileGuard(max_compiles=56) as g:
        res = run_consensus(slab, det, cfg)
    assert res.rounds >= 1
    # g.count may be 0 under a warm persistent compile cache (cache hits
    # don't fire the monitoring event) — the budget is the pin, not a
    # minimum.
    with assert_max_compiles(0):
        run_consensus(slab, det, cfg)
