"""Violating fixture for the ``padding-waste`` rule: an edge ladder
with a 128 -> 1024 gap, so a 129-edge graph pads ~6x its payload — the
broken-grid geometry the rule trips on (the real {2^k, 3*2^k} ladder
bounds worst-case padding under 50%).  Pure grid math: no jax."""

FOOTPRINT_SPEC = {
    "grid": [64, 96, 128, 1024],
    "rules": ["padding-waste"],
}
