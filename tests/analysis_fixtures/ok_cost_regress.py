"""Clean counterpart of bad_cost_regress.py: a baseline (1 s) the
mirror's ~52 ms estimate for the same executable sits far below — no
growth, the rule must stay silent."""

COST_SPEC = {
    "baseline": {"rounds[warm]@n64_e96": 1.0},
    "rules": ["cost-roofline-regress"],
}
