"""Fixture: typed client parser vs server emitter wire-schema drift.

The server emitter writes ``busy_s`` but the client dataclass parses
``busy_sec`` — the field silently reads its default forever, and the
emitted ``busy_s`` is silently dropped.  fcheck-contract must flag
both directions as ``schema-drift``: the phantom client key at the
parser, and the dropped emitter key at the dict.
"""

CONTRACT_SPEC = {"rules": ["schema-drift"]}


class DeviceRow:
    """Typed jax-free view of one device-status payload row."""

    @classmethod
    def from_payload(cls, payload):
        return cls(
            device=payload["device"],
            alive=payload["alive"],
            jobs=payload["jobs"],
            busy_sec=payload.get("busy_sec", 0.0),  # server says busy_s
        )


def render_device_row(dev) -> dict:
    return {
        "device": dev.index,
        "alive": not dev.cordoned,
        "jobs": dev.jobs_done,
        "busy_s": dev.busy_seconds,
    }
