"""Fixture: readbacks batched outside the loop (or pragma'd) -> clean."""
import jax
import numpy as np


def bulk_readback(step, state, n):
    states = []
    for _ in range(n):
        state = step(state)
        states.append(state)
    return np.asarray(jax.device_get(states))


def documented_driver(step, state, n):
    for _ in range(n):
        state = step(state)
        # fcheck: ok=sync-in-loop (this loop is the host driver)
        state = jax.device_get(state)
    return state
