"""Violating fixture for the ``cost-duality`` rule: a posture that
demands the batch rung save 90% per job over solo dispatch.  Under the
roofline the batched executable amortizes only the dispatch overhead
(device work scales linearly with the rung), so no ladder bucket comes
near such a saving — the analyzer must price the duality honestly and
fail the demand."""

COST_SPEC = {
    "duality_min_saving": 0.9,
    "rules": ["cost-duality"],
}
