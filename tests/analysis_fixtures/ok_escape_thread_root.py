"""Compliant fixture: the thread root absorbs its raise set.

Same poller as bad_escape_thread_root.py, but the loop wraps the
fallible helper in an ``except Exception`` arm that records the error
as a counted value — the thread survives a poisoned estimate and the
failure is visible.
"""

import threading


class Poller:
    def __init__(self):
        self.estimates = {}
        self.poll_errors = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def _loop(self):
        while True:
            try:
                self._poll_once()
            except Exception:
                self.poll_errors += 1

    def _poll_once(self):
        if not self.estimates:
            raise ValueError("poisoned estimate table")
        return min(self.estimates.values())
