"""Clean fixture: every sharding axis name resolves to a declared mesh
axis (mesh-axis)."""
import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ENSEMBLE_AXIS = "p"
EDGE_AXIS = "e"


def make_mesh(devices):
    return Mesh(np.asarray(devices).reshape(-1, 1),
                (ENSEMBLE_AXIS, EDGE_AXIS))


def good_collective(x):
    return jax.lax.psum(x, EDGE_AXIS)


def good_spec(mesh, x):
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(ENSEMBLE_AXIS, None)))


def good_literal(x):
    return jax.lax.pmax(x, "p")
