"""Violating fixture: an HTTP handler with an unmapped exception.

``do_GET``'s query parser raises ``ValueError`` on a malformed id and
no except arm maps it to a 4xx/5xx response — the client sees a
dropped connection (or a raw-traceback 500) instead of the promised
JSON error body.
"""


class Handler:
    def do_GET(self):
        job_id = self._parse_id()
        self._send(200, {"job_id": job_id})

    def _parse_id(self):
        path = str(getattr(self, "path", ""))
        if not path.startswith("/status/"):
            raise ValueError(f"malformed id in {path!r}")
        return path[len("/status/"):]

    def _send(self, code, payload):
        self.last = (code, payload)
