"""Compliant fixture: every handler-reachable exception maps to a
status code.

Same handler as bad_unmapped_http.py, with the ``ValueError`` the
parser can raise mapped to a 400 JSON error response.
"""


class Handler:
    def do_GET(self):
        try:
            job_id = self._parse_id()
        except ValueError as e:
            self._send(400, {"error": str(e)})
            return
        self._send(200, {"job_id": job_id})

    def _parse_id(self):
        path = str(getattr(self, "path", ""))
        if not path.startswith("/status/"):
            raise ValueError(f"malformed id in {path!r}")
        return path[len("/status/"):]

    def _send(self, code, payload):
        self.last = (code, payload)
