"""Clean counterpart of bad_padding_ladder.py: a gapless {2^k, 3*2^k}
ladder prefix whose worst-case member padding stays under the threshold
— the rule must stay silent."""

FOOTPRINT_SPEC = {
    "grid": [64, 96, 128, 192, 256, 384, 512],
    "rules": ["padding-waste"],
}
