"""Violating fixture: worker-thread roots writing shared state with no
guard at all — a module global and an instance attribute, each also
read from the external (caller) root."""

import threading

progress = 0


def worker_loop():
    global progress
    for i in range(100):
        progress = i               # unguarded write from a thread root


def start():
    t = threading.Thread(target=worker_loop, daemon=True)
    t.start()
    return t


def read_progress():
    global progress
    return progress


class Poller:
    def __init__(self):
        self.last_seen = None
        self._thread = threading.Thread(target=self._poll,
                                        daemon=True)

    def _poll(self):
        while True:
            self.last_seen = object()   # unguarded write from the root

    def status(self):
        return self.last_seen
