"""Clean twin of bad_notify_outside.py: every notify is lexically
inside the owning ``with``."""

import threading


class Mailbox:
    def __init__(self):
        self._cond = threading.Condition()
        self._items = []

    def put(self, item):
        with self._cond:
            self._items += [item]
            self._cond.notify()

    def close(self):
        with self._cond:
            self._cond.notify_all()
