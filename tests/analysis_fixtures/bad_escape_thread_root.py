"""Violating fixture: an exception escapes a thread root.

The poller thread's loop calls a helper whose raise set (inferred and
propagated through the call table by analysis/faults.py) includes
``ValueError``; nothing on the path catches it, so ``Thread.run``
prints a traceback and the thread dies silently — the serving-stack
shape where the dispatcher or watchdog thread evaporates while
/healthz stays green.
"""

import threading


class Poller:
    def __init__(self):
        self.estimates = {}
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def _loop(self):
        while True:
            self._poll_once()

    def _poll_once(self):
        if not self.estimates:
            raise ValueError("poisoned estimate table")
        return min(self.estimates.values())
