"""Violating fixture: a file whose close is skipped on the error path.

The header read sits between ``open`` and ``close`` with no ``with``
and no ``finally`` — an ``OSError`` (or a bad-header ``ValueError``)
leaks the descriptor.  Long-lived servers turn this shape into fd
exhaustion.
"""


def read_header(path):
    fh = open(path, encoding="utf-8")
    line = fh.readline()
    if not line.startswith("#"):
        raise ValueError(f"{path}: missing header line")
    fh.close()
    return line
