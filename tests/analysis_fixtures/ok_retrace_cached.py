"""Fixture: lru-cached jit builders and named statics -> clean."""
import functools

import jax


@functools.lru_cache(maxsize=32)
def jitted_step(cfg):
    return jax.jit(functools.partial(_step, cfg=cfg))


def _step(state, cfg=None):
    return state


@functools.partial(jax.jit, static_argnames=("n",))
def named_static(x, n):
    return x * n
