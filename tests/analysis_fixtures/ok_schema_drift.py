"""Fixture: typed client parser and server emitter agree field-for-field.

Same shape as ``bad_schema_drift.py`` with the parser reading exactly
the keys the emitter writes — fcheck-contract must stay silent.
"""

CONTRACT_SPEC = {"rules": ["schema-drift"]}


class DeviceRow:
    """Typed jax-free view of one device-status payload row."""

    @classmethod
    def from_payload(cls, payload):
        return cls(
            device=payload["device"],
            alive=payload["alive"],
            jobs=payload["jobs"],
            busy_s=payload.get("busy_s", 0.0),
        )


def render_device_row(dev) -> dict:
    return {
        "device": dev.index,
        "alive": not dev.cordoned,
        "jobs": dev.jobs_done,
        "busy_s": dev.busy_seconds,
    }
