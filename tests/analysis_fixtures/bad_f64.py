"""Fixture: float64 reaching jnp arrays -> f64-dtype."""
import jax.numpy as jnp
import numpy as np


def make_weights(n):
    return jnp.zeros((n,), dtype=jnp.float64)


def cast_up(x):
    return x.astype(jnp.float64)


def from_numpy(arr):
    return jnp.asarray(arr, dtype=np.float64)
