"""Violating fixture: Condition.notify outside its own ``with`` — a
RuntimeError on exactly the path nobody tested."""

import threading


class Mailbox:
    def __init__(self):
        self._cond = threading.Condition()
        self._items = []

    def put_racy(self, item):
        with self._cond:
            self._items += [item]
        self._cond.notify()        # the lock is already released

    def put_ok(self, item):
        with self._cond:
            self._items += [item]
            self._cond.notify()
