"""BAD: the same key drawn from through a helper and again directly.

``jitter`` consumes its key (one random.bits draw — the seg.pair_jitter
shape), so the caller's second draw on the same key correlates with the
helper's: the cross-function key-reuse pass weights the helper call by
its summarized consumption and flags the reuse, naming the helper.
"""

import jax


def jitter(key, node):
    salt = jax.random.bits(key, (2,), "uint32")
    return node * salt[0] + salt[1]


def score(key, node):
    noise = jitter(key, node)
    extra = jax.random.normal(key, (4,))  # reuse: jitter already drew
    return noise + extra
