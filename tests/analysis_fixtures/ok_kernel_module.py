"""Fixture: module-level kernel, statics via functools.partial -> clean."""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

SENTINEL = 2**31 - 1  # a Python int, not a device array


def _row_sum_kernel(x_ref, o_ref, *, scale: float):
    o_ref[...] = jnp.sum(x_ref[...] * scale, axis=1, keepdims=True)


def row_sum(x, scale: float):
    return pl.pallas_call(
        functools.partial(_row_sum_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], 1), x.dtype),
    )(x)
