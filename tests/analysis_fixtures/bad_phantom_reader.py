"""Fixture: a CI gate reads a counter name no writer produces.

The writer registers ``serve.fixture.completed`` but the gate greps
``serve.fixture.complete`` — the classic stale-gate bug: the check is
vacuously green forever.  fcheck-contract must flag the read site with
``phantom-reader``.
"""

CONTRACT_SPEC = {"rules": ["phantom-reader"]}


def tick(reg) -> None:
    reg.inc("serve.fixture.completed")
    reg.gauge("serve.fixture.depth", 3)


def check_fixture_gate(counters) -> bool:
    done = counters.get("serve.fixture.complete", 0)  # typo'd reader
    depth = counters.get("serve.fixture.depth", 0)
    return done > 0 and depth < 10
