"""Fixture: a counter is written but nothing ever reads it.

``fixture.ticks.dropped`` has a writer and no gate, client, probe or
documentation row — dead telemetry that silently rots.
fcheck-contract must flag the write site with ``dead-counter``.
"""

CONTRACT_SPEC = {"rules": ["dead-counter"]}


def tick(reg, dropped: bool) -> None:
    reg.inc("fixture.ticks.total")
    if dropped:
        reg.inc("fixture.ticks.dropped")  # no reader anywhere


def check_ticks(counters) -> bool:
    return counters.get("fixture.ticks.total", 0) > 0
