"""Fixture: recorded flight-event kinds and the vocabulary agree.

Same shape as ``bad_event_vocab.py`` with every recorded kind in the
vocabulary and every vocabulary entry recorded — fcheck-contract must
stay silent.
"""

CONTRACT_SPEC = {
    "rules": ["event-vocab"],
    "event_kinds": ["admit", "finish"],
}


def trace(flight, job: str) -> None:
    flight.record("admit", job=job)
    flight.record("finish", job=job)
