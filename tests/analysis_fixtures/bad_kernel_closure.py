"""Fixture: Pallas kernel as local def closing over traced arrays ->
kernel-tracer-closure (plus a module-level jnp constant)."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

SENTINEL = jnp.int32(2**31 - 1)  # module-jnp-const: device array at import


def row_sum(x, scale):
    def kernel(x_ref, o_ref):
        # closes over `scale` (traced!) from the enclosing trace
        o_ref[...] = jnp.sum(x_ref[...] * scale, axis=1, keepdims=True)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((x.shape[0], 1), x.dtype),
    )(x)
