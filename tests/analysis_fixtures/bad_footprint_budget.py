"""Violating fixture for the ``jaxpr-peak-bytes`` rule: a serving
posture whose frontier executables cannot fit the declared per-chip
budget — 200 KB of HBM serves nothing, and the analyzer must say so at
review time instead of OOM-ing on first traffic.  The surface is kept
tiny (max 256 nodes / 512 edges, batch 2) so the rule's trace probes
stay fast in CI."""

FOOTPRINT_SPEC = {
    "max_nodes": 256,
    "max_edges": 512,
    "max_batch": 2,
    "n_p": 4,
    "hbm_bytes": 200_000,
    "rules": ["jaxpr-peak-bytes"],
}
