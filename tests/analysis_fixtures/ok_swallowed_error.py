"""Compliant fixture: the handler records the failure as a value.

Same loader as bad_swallowed_error.py, but the except body assigns the
documented cold-start fallback (an error-value outlet) — callers see
the default and nothing disappears silently.
"""

import json


def load_rates(path):
    try:
        with open(path, encoding="utf-8") as fh:
            loaded = json.load(fh)
    except (OSError, ValueError):
        loaded = {}
    rates = {"default": 1.0}
    rates.update(loaded)
    return rates
