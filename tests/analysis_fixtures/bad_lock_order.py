"""Violating fixture: inconsistent lock acquisition order.

``transfer_ab`` takes _ledger then (through the helper) _audit;
``transfer_ba`` takes _audit then _ledger.  Two threads running one
each deadlock.  The _ledger -> _audit edge crosses a call boundary, so
the rule's call-table propagation is what catches it.
"""

import threading

_ledger = threading.Lock()
_audit = threading.Lock()


def _log_entry(n):
    with _audit:
        return n


def transfer_ab(n):
    with _ledger:
        return _log_entry(n)     # _ledger -> _audit (via the helper)


def transfer_ba(n):
    with _audit:
        with _ledger:            # _audit -> _ledger: the cycle
            return n
