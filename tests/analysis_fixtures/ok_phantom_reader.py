"""Fixture: every gate read names a metric some writer produces.

Same shape as ``bad_phantom_reader.py`` with the reader spelled
correctly — fcheck-contract must stay silent.
"""

CONTRACT_SPEC = {"rules": ["phantom-reader"]}


def tick(reg) -> None:
    reg.inc("serve.fixture.completed")
    reg.gauge("serve.fixture.depth", 3)


def check_fixture_gate(counters) -> bool:
    done = counters.get("serve.fixture.completed", 0)
    depth = counters.get("serve.fixture.depth", 0)
    return done > 0 and depth < 10
