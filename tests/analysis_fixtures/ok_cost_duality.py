"""Clean counterpart of bad_cost_duality.py: the repo default floor
(0.0 — batching must merely never cost MORE per job than solo), which
the dispatch-overhead amortization always clears — the rule must stay
silent."""

COST_SPEC = {
    "duality_min_saving": 0.0,
    "rules": ["cost-duality"],
}
