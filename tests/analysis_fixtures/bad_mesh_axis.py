"""Violating fixture: sharding axis names no mesh in this module
declares (mesh-axis).  The mesh contract here is ("p", "e")."""
import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ENSEMBLE_AXIS = "p"


def make_mesh(devices):
    return Mesh(np.asarray(devices).reshape(-1, 1), (ENSEMBLE_AXIS, "e"))


def bad_collective(x):
    # "q" is a typo: the mesh has axes p/e only — this fails at runtime
    # on a real mesh, which is exactly what the lint preempts
    return jax.lax.psum(x, "q")


def bad_spec(mesh, x):
    # "edge" is not the declared axis name "e"
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P("edge")))
