"""Fixture: Python control flow on traced values -> traced-branch."""
import jax.numpy as jnp


def branchy(x):
    if jnp.any(x > 0):
        return x * 2
    return x


def loopy(x):
    while jnp.sum(x) < 10:
        x = x * 2
    return x
