"""Clean twin of bad_lock_order.py: one global order (_ledger before
_audit) on every path."""

import threading

_ledger = threading.Lock()
_audit = threading.Lock()


def _log_entry(n):
    with _audit:
        return n


def transfer_ab(n):
    with _ledger:
        return _log_entry(n)     # _ledger -> _audit


def transfer_ba(n):
    with _ledger:
        with _audit:             # same order: no cycle
            return n
