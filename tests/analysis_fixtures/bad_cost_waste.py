"""Violating fixture for the ``cost-dead-compute`` rule: a posture
whose pinned waste budget (40%) is tighter than the dead-compute bill
the committed fcqual frontier series actually produces (~61% of the
run's rounds-executable FLOPs on frozen vertices) — the analyzer must
bill it at review time instead of letting the waste ride to the
device."""

COST_SPEC = {
    "waste_budget": 0.4,
    "rules": ["cost-dead-compute"],
}
