"""Violating fixture for the ``surface-count`` rule: the full default
ladder (2^20 nodes x 2^22 edges x batch rungs x engine modes) against a
10-executable budget — the cartesian static-arg explosion the rule
exists to catch at review time, before CompileGuard catches it at
runtime.  Pure grid math: no jax import, no traces."""

FOOTPRINT_SPEC = {
    "surface_budget": 10,
    "rules": ["surface-count"],
}
