"""Compliant fixture: the file is context-managed.

Same header read as bad_resource_leak.py inside ``with`` — the
descriptor closes on every path, error or not.
"""


def read_header(path):
    with open(path, encoding="utf-8") as fh:
        line = fh.readline()
        if not line.startswith("#"):
            raise ValueError(f"{path}: missing header line")
        return line
