"""Fixture: host-device syncs inside a Python loop -> sync-in-loop."""
import jax
import numpy as np


def per_round_readback(step, state, n):
    history = []
    for _ in range(n):
        state = step(state)
        history.append(float(state.loss.item()))
    return state, history


def per_round_block(step, state, n):
    for _ in range(n):
        state = step(state)
        state.block_until_ready()
    return state


def per_round_transfer(step, state, n):
    outs = []
    for _ in range(n):
        state = step(state)
        outs.append(np.asarray(jax.device_get(state)))
    return outs
