"""Violating fixture: blocking calls while a lock is held — a sleep, an
unbounded thread join, a device dispatch reached through a helper, and
a Condition.wait() that drags a foreign lock into the wait."""

import threading
import time

_lock = threading.Lock()
_done = threading.Condition()


def hold_and_sleep():
    with _lock:
        time.sleep(5.0)            # every _lock waiter sleeps too


def hold_and_join(worker_thread):
    with _lock:
        worker_thread.join()       # unbounded join under the lock


def _dispatch(slab, detect, config):
    return run_consensus(slab, detect, config)  # noqa: F821 — AST-only


def hold_and_dispatch(slab):
    with _lock:
        return _dispatch(slab, None, None)  # blocks via the helper


def wait_holding_foreign():
    with _lock:
        with _done:
            _done.wait()           # _lock is held through the wait
