"""Clean twin of bad_root_write.py: both sides of each shared-state
access hold the guarding lock."""

import threading

_lock = threading.Lock()
progress = 0


def worker_loop():
    global progress
    for i in range(100):
        with _lock:
            progress = i


def start():
    t = threading.Thread(target=worker_loop, daemon=True)
    t.start()
    return t


def read_progress():
    global progress
    with _lock:
        return progress


class Poller:
    def __init__(self):
        self._plock = threading.Lock()
        self.last_seen = None
        self._thread = threading.Thread(target=self._poll,
                                        daemon=True)

    def _poll(self):
        while True:
            with self._plock:
                self.last_seen = object()

    def status(self):
        with self._plock:
            return self.last_seen
