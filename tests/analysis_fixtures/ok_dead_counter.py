"""Fixture: every written counter has a reader.

Same shape as ``bad_dead_counter.py`` with the drop counter consumed
by the gate too — fcheck-contract must stay silent.
"""

CONTRACT_SPEC = {"rules": ["dead-counter"]}


def tick(reg, dropped: bool) -> None:
    reg.inc("fixture.ticks.total")
    if dropped:
        reg.inc("fixture.ticks.dropped")


def check_ticks(counters) -> bool:
    total = counters.get("fixture.ticks.total", 0)
    dropped = counters.get("fixture.ticks.dropped", 0)
    return total > 0 and dropped == 0
