"""Clean twin of bad_blocking_lock.py: snapshot under the lock, block
outside it; waiting holds only the condition's own lock."""

import threading
import time

_lock = threading.Lock()
_done = threading.Condition()
_pending = []


def sleep_outside():
    with _lock:
        n = len(_pending)
    time.sleep(0.01)               # the lock is long released
    return n


def join_outside(worker_thread):
    with _lock:
        _pending.append(worker_thread)
    worker_thread.join()


def _dispatch(slab, detect, config):
    return run_consensus(slab, detect, config)  # noqa: F821 — AST-only


def dispatch_outside(slab):
    with _lock:
        job = list(_pending)
    return _dispatch(job or slab, None, None)


def wait_own_lock_only():
    with _done:
        _done.wait()               # the protocol: only the condition's
    return True                    # own lock is held
