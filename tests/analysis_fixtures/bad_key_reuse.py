"""Fixture: the same PRNG key consumed by two draws -> key-reuse."""
import jax


def two_draws(key):
    a = jax.random.uniform(key, (4,))
    b = jax.random.normal(key, (4,))
    return a + b


def loop_reuse(key, xs):
    out = []
    for x in xs:
        out.append(jax.random.bernoulli(key, 0.5, (4,)) * x)
    return out
