"""Clean twin of bad_guarded_field.py: the snapshot and the clear are
one atomic operation under the lock (the ``Tracer.drain_since`` fix)."""

import threading


class SafeSpanBuffer:
    def __init__(self):
        self._lock = threading.Lock()
        self._events = []
        self._thread = threading.Thread(target=self._worker,
                                        daemon=True)

    def _worker(self):
        while True:
            self.record({"name": "span"})

    def record(self, ev):
        with self._lock:
            self._events += [ev]

    def flush(self):
        with self._lock:
            tail = list(self._events)
            self._events = []
        return tail
