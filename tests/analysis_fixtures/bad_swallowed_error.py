"""Violating fixture: an except body that eats the error silently.

The loader's ``except OSError: pass`` neither re-raises, returns an
error value, assigns a fallback, stamps a counter, nor records a
flight event — the one failure mode the observability stack cannot
see.  (``json.load`` inside the ``with`` also pins the builtin-raiser
table: the handler would need ``ValueError`` coverage to absorb it.)
"""

import json


def load_rates(path):
    rates = {"default": 1.0}
    try:
        with open(path, encoding="utf-8") as fh:
            rates.update(json.load(fh))
    except OSError:
        pass
    return rates
