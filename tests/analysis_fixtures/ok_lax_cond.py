"""Fixture: device-side control flow through lax, host predicates via
numpy -> clean."""
import jax
import jax.numpy as jnp
import numpy as np


def branchy(x):
    return jax.lax.cond(jnp.any(x > 0), lambda v: v * 2, lambda v: v, x)


def host_predicate(x_host):
    if np.any(x_host > 0):
        return x_host * 2
    return x_host
