"""Fixture: properly split/folded keys -> clean."""
import jax


def two_draws(key):
    k_a, k_b = jax.random.split(key)
    a = jax.random.uniform(k_a, (4,))
    b = jax.random.normal(k_b, (4,))
    return a + b


def loop_fold(key, xs):
    out = []
    for i, x in enumerate(xs):
        k = jax.random.fold_in(key, i)
        out.append(jax.random.bernoulli(k, 0.5, (4,)) * x)
    return out


def branch_exclusive(key, flag):
    # one consumer per execution path is fine
    if flag:
        return jax.random.uniform(key, (2,))
    return jax.random.normal(key, (2,))
