"""Violating fixture for the ``cost-roofline-regress`` rule: a
committed baseline claiming the floor bucket's solo rounds executable
used to model at 1 ms device time.  The mirror prices it an order of
magnitude above that, so against this baseline the surface has
"regressed" far past the tolerance — the analyzer must name the drift
instead of letting the baseline rot."""

COST_SPEC = {
    "baseline": {"rounds[warm]@n64_e96": 0.001},
    "rules": ["cost-roofline-regress"],
}
