"""Clean counterpart of bad_footprint_budget.py: the same tiny serving
surface under a budget (1 GB) its frontier executables comfortably fit —
the rule must stay silent."""

FOOTPRINT_SPEC = {
    "max_nodes": 256,
    "max_edges": 512,
    "max_batch": 2,
    "n_p": 4,
    "hbm_bytes": 1_000_000_000,
    "rules": ["jaxpr-peak-bytes"],
}
