"""OK: a derive-only helper may be called repeatedly on one key.

``fan_out`` only ever *derives* from its key (random.split), so passing
the same key to it twice correlates nothing — exactly like calling
``jax.random.split`` twice.  The cross-function key-reuse pass
(analysis/astlint.py summaries) classifies the helper as weight-0 from
its body; the old intra-function-only rule counted each helper call as
a draw and flagged this file as reuse.
"""

import jax


def fan_out(key, n):
    return jax.random.split(key, n)


def stream_pairs(key):
    first = fan_out(key, 2)
    second = fan_out(key, 3)  # same key, derive-only helper: safe
    a = jax.random.uniform(first[0], (4,))
    b = jax.random.uniform(second[1], (4,))
    return a + b
