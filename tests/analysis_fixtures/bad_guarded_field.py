"""Violating fixture: snapshot-outside-lock on a guarded field.

This reconstructs the PR 6 ``Tracer.drain_since`` pre-fix pattern: a
worker thread records spans into a buffer under the lock, while the
flusher SNAPSHOTS the buffer without the lock before clearing it under
the lock — a span recorded between the snapshot and the clear vanishes
from memory without ever being streamed.
"""

import threading


class SpanBuffer:
    def __init__(self):
        self._lock = threading.Lock()
        self._events = []
        self._thread = threading.Thread(target=self._worker,
                                        daemon=True)

    def _worker(self):
        while True:
            self.record({"name": "span"})

    def record(self, ev):
        with self._lock:
            self._events += [ev]

    def flush(self):
        tail = list(self._events)   # snapshot WITHOUT the lock
        with self._lock:
            self._events = []       # ...then clear under it: spans
        return tail                 # recorded in between are lost
