"""Fixture: the README counters reference matches the writers exactly.

Same shape as ``bad_doc_drift.py`` with the appendix regenerated —
every written metric documented under its real kind, no stale rows —
so fcheck-contract must stay silent.
"""

CONTRACT_SPEC = {
    "rules": ["doc-drift"],
    "readme": """
## Appendix: counters & series reference

<!-- fcheck-contract: counters begin -->
| name | kind | writers |
|---|---|---|
| `fixture.rounds.total` | counter | ok_doc_drift.py |
| `fixture.rounds.warm` | counter | ok_doc_drift.py |
<!-- fcheck-contract: counters end -->
""",
}


def count_round(reg) -> None:
    reg.inc("fixture.rounds.total")
    reg.inc("fixture.rounds.warm")
