"""Fixture: flight-recorder event kinds drift from the vocabulary.

One ``record(...)`` call uses a typo'd kind (``admitt``) the declared
vocabulary does not know, and the vocabulary still lists ``admit``
which no site records — postmortem kind filters miss the former and
trust a stale entry for the latter.  fcheck-contract must flag both
with ``event-vocab``.
"""

CONTRACT_SPEC = {
    "rules": ["event-vocab"],
    "event_kinds": ["admit", "finish"],
}


def trace(flight, job: str) -> None:
    flight.record("admitt", job=job)  # typo: not in the vocabulary
    flight.record("finish", job=job)
