"""Fixture: fresh jit wrappers per call + positional statics ->
retrace-risk / weak-static-arg."""
import functools

import jax


def run_step(state, cfg):
    step = jax.jit(functools.partial(_step, cfg=cfg))  # fresh every call
    return step(state)


def _step(state, cfg=None):
    return state


@functools.partial(jax.jit, static_argnums=(1,))
def positional_static(x, n):
    return x * n
