"""Fixture: f32/i32 device arrays, host-side np.float64 is fine -> clean."""
import jax.numpy as jnp
import numpy as np


def make_weights(n):
    return jnp.zeros((n,), dtype=jnp.float32)


def host_accumulator(xs):
    # host-side numpy f64 is allowed (e.g. exact NMI accumulation)
    return np.zeros((len(xs),), dtype=np.float64)
