"""Fixture: the README counters reference drifted from the writers.

The appendix documents ``fixture.rounds.cold`` (no writer), misses
``fixture.rounds.warm`` (written, undocumented), and lists
``fixture.rounds.total`` as a gauge where the writer registers a
counter.  fcheck-contract must flag all three with ``doc-drift``.
"""

CONTRACT_SPEC = {
    "rules": ["doc-drift"],
    "readme": """
## Appendix: counters & series reference

<!-- fcheck-contract: counters begin -->
| name | kind | writers |
|---|---|---|
| `fixture.rounds.cold` | counter | bad_doc_drift.py |
| `fixture.rounds.total` | gauge | bad_doc_drift.py |
<!-- fcheck-contract: counters end -->
""",
}


def count_round(reg) -> None:
    reg.inc("fixture.rounds.total")
    reg.inc("fixture.rounds.warm")
