"""Clean counterpart of bad_cost_waste.py: the same posture under the
repo's pinned waste budget (75%), which the measured ~61% dead-compute
bill fits with headroom — the rule must stay silent."""

COST_SPEC = {
    "waste_budget": 0.75,
    "rules": ["cost-dead-compute"],
}
