"""Clean counterpart of bad_surface_budget.py: the same default ladder
under a budget with room to spare — the rule must stay silent."""

FOOTPRINT_SPEC = {
    "surface_budget": 1_000_000,
    "rules": ["surface-count"],
}
