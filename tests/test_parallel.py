"""Multi-chip sharding tests on the 8-device virtual CPU mesh (conftest.py).

Exercises the scale-out design of SURVEY.md §2.24: ensemble ("p") sharding is
the DP analog of the reference's leiden process pool (fast_consensus.py:210),
edge ("e") sharding is the SP/TP analog needed for the 100k-node configs.
"""

import jax
import numpy as np
import pytest

from fastconsensus_tpu import parallel
from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
from fastconsensus_tpu.models.registry import get_detector
from fastconsensus_tpu.utils.metrics import nmi


def test_make_mesh_shapes():
    mesh = parallel.make_mesh()
    assert mesh.shape[parallel.ENSEMBLE_AXIS] == len(jax.devices())
    assert mesh.shape[parallel.EDGE_AXIS] == 1

    mesh2 = parallel.make_mesh(edge=2)
    assert mesh2.shape[parallel.ENSEMBLE_AXIS] == len(jax.devices()) // 2
    assert mesh2.shape[parallel.EDGE_AXIS] == 2

    with pytest.raises(ValueError):
        parallel.make_mesh(ensemble=len(jax.devices()), edge=2)


def test_pad_n_p():
    mesh = parallel.make_mesh()
    p = mesh.shape[parallel.ENSEMBLE_AXIS]
    assert parallel.pad_n_p(1, mesh) == p
    assert parallel.pad_n_p(p, mesh) == p
    assert parallel.pad_n_p(p + 1, mesh) == 2 * p


def test_shard_slab_pads_capacity(karate_slab):
    mesh = parallel.make_mesh(ensemble=2, edge=4)
    sharded = parallel.shard_slab(karate_slab, mesh)
    assert sharded.capacity % 4 == 0
    assert int(sharded.num_alive()) == int(karate_slab.num_alive())


@pytest.mark.parametrize("alg", ["lpm", "louvain"])
def test_ensemble_sharded_consensus_matches_quality(karate_slab, karate_truth,
                                                    alg):
    """Consensus under a p=8 mesh converges and finds the factions."""
    mesh = parallel.make_mesh()
    n_p = parallel.pad_n_p(16, mesh)
    cfg = ConsensusConfig(algorithm=alg, n_p=n_p, tau=0.5, delta=0.1, seed=3)
    result = run_consensus(karate_slab, get_detector(alg), cfg, mesh=mesh)
    assert result.converged
    # modularity's optimum on karate is 4 communities, a refinement of the
    # 2-faction ground truth; NMI vs the factions sits near 0.49 for it.
    scores = [nmi(p, karate_truth) for p in result.partitions]
    assert np.mean(scores) > 0.45


def test_edge_sharded_consensus_runs(karate_slab, karate_truth):
    """2D mesh (p=4, e=2): edge-sharded slab + sharded ensemble."""
    mesh = parallel.make_mesh(ensemble=4, edge=2)
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.1, seed=0)
    result = run_consensus(karate_slab, get_detector("lpm"), cfg, mesh=mesh)
    assert result.converged
    scores = [nmi(p, karate_truth) for p in result.partitions]
    assert np.mean(scores) > 0.4


def test_sharded_matches_unsharded_bitwise(karate_slab):
    """Sharding must not change the math: same seed => same partitions."""
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.1, seed=7)
    det = get_detector("lpm")
    base = run_consensus(karate_slab, det, cfg)
    mesh = parallel.make_mesh()
    sharded = run_consensus(karate_slab, det, cfg, mesh=mesh)
    assert base.rounds == sharded.rounds
    for a, b in zip(base.partitions, sharded.partitions):
        np.testing.assert_array_equal(a, b)
