"""Multi-chip sharding tests on the 8-device virtual CPU mesh (conftest.py).

Exercises the scale-out design of SURVEY.md §2.24: ensemble ("p") sharding is
the DP analog of the reference's leiden process pool (fast_consensus.py:210),
edge ("e") sharding is the SP/TP analog needed for the 100k-node configs.
"""

import jax
import numpy as np
import pytest

from fastconsensus_tpu import parallel
from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
from fastconsensus_tpu.models.registry import get_detector
from fastconsensus_tpu.utils.metrics import nmi


def test_make_mesh_shapes():
    mesh = parallel.make_mesh()
    assert mesh.shape[parallel.ENSEMBLE_AXIS] == len(jax.devices())
    assert mesh.shape[parallel.EDGE_AXIS] == 1

    mesh2 = parallel.make_mesh(edge=2)
    assert mesh2.shape[parallel.ENSEMBLE_AXIS] == len(jax.devices()) // 2
    assert mesh2.shape[parallel.EDGE_AXIS] == 2

    with pytest.raises(ValueError):
        parallel.make_mesh(ensemble=len(jax.devices()), edge=2)


def test_pad_n_p():
    mesh = parallel.make_mesh()
    p = mesh.shape[parallel.ENSEMBLE_AXIS]
    assert parallel.pad_n_p(1, mesh) == p
    assert parallel.pad_n_p(p, mesh) == p
    assert parallel.pad_n_p(p + 1, mesh) == 2 * p


def test_shard_slab_pads_capacity(karate_slab):
    mesh = parallel.make_mesh(ensemble=2, edge=4)
    sharded = parallel.shard_slab(karate_slab, mesh)
    assert sharded.capacity % 4 == 0
    assert int(sharded.num_alive()) == int(karate_slab.num_alive())


@pytest.mark.parametrize("alg", ["lpm", "louvain"])
def test_ensemble_sharded_consensus_matches_quality(karate_slab, karate_truth,
                                                    alg):
    """Consensus under a p=8 mesh converges and finds the factions."""
    mesh = parallel.make_mesh()
    n_p = parallel.pad_n_p(16, mesh)
    cfg = ConsensusConfig(algorithm=alg, n_p=n_p, tau=0.5, delta=0.1, seed=3)
    result = run_consensus(karate_slab, get_detector(alg), cfg, mesh=mesh)
    assert result.converged
    # modularity's optimum on karate is 4 communities, a refinement of the
    # 2-faction ground truth; NMI vs the factions sits near 0.49 for it.
    scores = [nmi(p, karate_truth) for p in result.partitions]
    assert np.mean(scores) > 0.45


def test_edge_sharded_consensus_runs(karate_slab, karate_truth):
    """2D mesh (p=4, e=2): edge-sharded slab + sharded ensemble."""
    mesh = parallel.make_mesh(ensemble=4, edge=2)
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.1, seed=0)
    result = run_consensus(karate_slab, get_detector("lpm"), cfg, mesh=mesh)
    assert result.converged
    scores = [nmi(p, karate_truth) for p in result.partitions]
    assert np.mean(scores) > 0.4


def test_sharded_matches_unsharded_bitwise(karate_slab):
    """Sharding must not change the math: same seed => same partitions.

    closure_sampler pinned to "scatter": the unsharded default is the CSR
    fast path, which draws different (equally valid) wedges than the
    sort-free engine the sharded tail requires (ConsensusConfig)."""
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.1, seed=7,
                          closure_sampler="scatter")
    det = get_detector("lpm")
    base = run_consensus(karate_slab, det, cfg)
    mesh = parallel.make_mesh()
    sharded = run_consensus(karate_slab, det, cfg, mesh=mesh)
    assert base.rounds == sharded.rounds
    for a, b in zip(base.partitions, sharded.partitions):
        np.testing.assert_array_equal(a, b)


def test_edge_sharded_matches_unsharded_bitwise(karate_slab):
    """2D mesh (p=4, e=2) bitwise parity on a small graph — the fast
    guard for the at-scale variant below (slow-marked), so the default
    suite still catches an edge-axis math regression."""
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.1, seed=7,
                          closure_sampler="scatter")
    det = get_detector("lpm")
    base = run_consensus(karate_slab, det, cfg)
    mesh = parallel.make_mesh(ensemble=4, edge=2)
    sharded = run_consensus(karate_slab, det, cfg, mesh=mesh)
    assert base.rounds == sharded.rounds
    np.testing.assert_array_equal(
        np.asarray(base.graph.alive),
        np.asarray(sharded.graph.alive)[:base.graph.capacity])
    for a, b in zip(base.partitions, sharded.partitions):
        np.testing.assert_array_equal(a, b)


def test_non_divisible_n_p_raises(karate_slab):
    """Round 1 warned and silently ran unsharded; now it is an error
    (device_put rejects uneven axes and GSPMD re-shards behind your back)."""
    import pytest

    mesh = parallel.make_mesh()  # p=8
    cfg = ConsensusConfig(algorithm="lpm", n_p=10, tau=0.5, delta=0.1)
    with pytest.raises(ValueError, match="not divisible"):
        run_consensus(karate_slab, get_detector("lpm"), cfg, mesh=mesh)


def _big_skewed_graph():
    from fastconsensus_tpu.graph import pack_edges
    from fastconsensus_tpu.utils.synth import planted_partition

    edges, truth = planted_partition(20_000, 40, 0.025, 0.0002, seed=1)
    assert edges.shape[0] >= 100_000, edges.shape  # the design-scale regime
    return pack_edges(edges, 20_000), truth


@pytest.mark.slow
def test_edge_sharded_parity_at_scale():
    """VERDICT #4: a >=100k-edge graph on a 2D (p=4, e=2) mesh must match
    the unsharded run bitwise (1 full round + final detection)."""
    slab, _ = _big_skewed_graph()
    det = get_detector("lpm")
    cfg = ConsensusConfig(algorithm="lpm", n_p=8, tau=0.5, delta=0.02,
                          max_rounds=1, seed=2, closure_sampler="scatter")
    base = run_consensus(slab, det, cfg)
    mesh = parallel.make_mesh(ensemble=4, edge=2)
    sharded = run_consensus(slab, det, cfg, mesh=mesh)
    assert base.rounds == sharded.rounds
    np.testing.assert_array_equal(
        np.asarray(base.graph.alive),
        np.asarray(sharded.graph.alive)[:base.graph.capacity])
    for a, b in zip(base.partitions, sharded.partitions):
        np.testing.assert_array_equal(a, b)


def test_edge_sharding_hlo_behavior_pinned():
    """Pin the measured partitioning behavior of the round step on a 2D
    mesh: outputs keep their annotated shardings, and slab-sized
    all-gathers stay in single digits — the shard_map tail
    (ops/sharded_tail.py) contributes ZERO; what remains is the lpm
    detection's own directed-view concats + one argsort (measured 5 at
    pinning time, round 3; was 19 with the GSPMD tail)."""
    import functools
    import re

    import jax

    from fastconsensus_tpu.consensus import consensus_round

    slab, _ = _big_skewed_graph()
    mesh = parallel.make_mesh(ensemble=4, edge=2)
    sl = parallel.shard_slab(slab, mesh)
    step = jax.jit(functools.partial(
        consensus_round, detect=get_detector("lpm"), n_p=8, tau=0.5,
        delta=0.02, n_closure=int(slab.num_alive()),
        ensemble_sharding=parallel.keys_sharding(mesh)))
    comp = step.lower(sl, jax.random.key(0)).compile()
    new_slab, labels, _ = step(sl, jax.random.key(0))
    assert new_slab.src.sharding.is_equivalent_to(
        parallel.slab_sharding(mesh), ndim=1)
    assert labels.sharding.is_equivalent_to(
        parallel.labels_sharding(mesh), ndim=2)
    gathers = re.findall(r"all-gather[^\n]*", comp.as_text())
    cap = sl.capacity
    slab_sized = [g for g in gathers
                  if re.search(rf"\[{cap}\]|\[{2 * cap}\]", g)]
    # measured 5 at pinning time (round 3, shard_map tail); headroom to 8
    # so benign XLA drift does not flake, while a tail regression (the
    # GSPMD tail alone added 14) still fails loudly
    assert len(slab_sized) <= 8, len(slab_sized)


@pytest.mark.slow
def test_detect_cache_recovery_under_mesh(tmp_path, monkeypatch):
    """Split-phase detection + chunk cache must work under a mesh (round 1
    disabled it there — VERDICT #4); cached chunks are read back on retry
    and reproduce the identical result."""
    from fastconsensus_tpu.utils.synth import planted_partition
    from fastconsensus_tpu.graph import pack_edges

    edges, _ = planted_partition(300, 6, 0.3, 0.02, seed=4)
    slab = pack_edges(edges, 300)
    det = get_detector("lpm")
    mesh = parallel.make_mesh()  # p=8
    monkeypatch.setenv("FCTPU_DETECT_CALL_MEMBERS", "8")
    cfg = ConsensusConfig(algorithm="lpm", n_p=16, tau=0.5, delta=0.02,
                          max_rounds=3, seed=5)
    d = str(tmp_path / "cache")
    first = run_consensus(slab, det, cfg, mesh=mesh, detect_cache_dir=d)
    files = sorted(p.name for p in (tmp_path / "cache").iterdir())
    assert files, "no detect chunks persisted under the mesh"
    second = run_consensus(slab, det, cfg, mesh=mesh, detect_cache_dir=d)
    assert first.rounds == second.rounds
    for a, b in zip(first.partitions, second.partitions):
        np.testing.assert_array_equal(a, b)
